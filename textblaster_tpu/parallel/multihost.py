"""Multi-host execution: per-host document feed over a global device mesh.

The reference scales across machines by pointing more worker processes at one
RabbitMQ broker (SURVEY.md §2.5); the TPU-native equivalent is a
``jax.distributed`` SPMD job.  Every process joins one coordinator, the
``data`` mesh spans all hosts' devices, each host packs and feeds only its
*local* shard of the document stream
(``jax.make_array_from_process_local_data``), the compiled pipeline executes
once globally per round — cross-host traffic rides DCN exactly where XLA
places it — and each host assembles outcomes for its own documents from its
addressable output shards (the results-queue analogue: outputs land where
the documents came from, ready for per-host Parquet shards).

Lockstep contract: multi-host SPMD requires every process to dispatch the
same programs in the same order.  The per-(bucket) round counts are therefore
**negotiated**: every process allgathers how many rounds each bucket needs for
its local documents, and all processes run the columnwise maximum — hosts
with fewer documents pad with empty batches.  No operator-supplied round
budget is needed (the round-3 ``rounds`` argument survives as an optional
assertion).  ``textblast run --coordinator ... --num-processes N
--process-id i`` is the production entry (:func:`run_multihost`): each
process reads its row stripe of the input Parquet, writes a per-host shard
pair, and host 0 merges the shards into the final kept/excluded files after
a global barrier — the "resharded static fan-out" SURVEY.md §2.5 maps the
reference's competing consumers onto.

On real pods the same code runs unchanged: ``initialize()`` picks up the TPU
coordinator, the mesh spans the slice, and ICI/DCN routing is XLA's choice —
no NCCL/MPI analogue to manage (SURVEY.md §2.5's north-star mapping).

Kernels (PR 8): mesh-sharded programs no longer fall back to the lax scans.
``CompiledPipeline._build_fn`` traces them under ``mesh_tracing(mesh)``
(:mod:`textblaster_tpu.ops.pallas_scan`), which makes every scan kernel —
including the fused per-(bucket, phase) megakernel — dispatch through
``shard_map`` over the ``data`` axis, the same pattern ``pallas_sort.sort2``
has always used: each host's devices scan their own row shards in VMEM, and
rows never cross devices so no collective is inserted.  The host-oracle
degradation rung still runs pure Python and never sees Pallas code.

Resilience (PR 4): each lockstep round resolves under the negotiated guard
(:mod:`textblaster_tpu.resilience.negotiated`) — a retryable fault on any
host triggers a jointly-negotiated retry/degradation so transient device
faults no longer kill the job; per-host dead-letter shards merge like
kept/excluded; and the host-0 merge commits every final atomically
(tmp + fsync + rename via :func:`merge_shard_files`), deleting shards only
after every rename lands.

Elastic membership (PR 6): every KV exchange is deadline-bounded
(``--exchange-deadline-s``) and raises a typed
:class:`~textblaster_tpu.errors.PeerFailure` naming the unposted ranks —
dead-versus-slow resolved against renewable KV liveness leases
(``--lease-ttl-s``) — instead of blocking on the old hardcoded 300 s get;
exchange keys are namespaced by epoch and deleted once drained.  With
``--elastic`` the run leaves the lockstep contract entirely
(:func:`_run_elastic`): membership lives in shared-filesystem leases,
survivors adopt a dead rank's input stripe at the membership-epoch bump,
and a SIGKILLed rank can be relaunched to rejoin in place from its
committed cursor — replaying zero completed chunks, outcomes
byte-identical to a fault-free run.

Overlap (PR 9): lockstep rounds ride a K-deep in-flight window where K is
the **min** over every host's ``OverlapConfig.pipeline_depth``, allgathered
once at shard start (:func:`_negotiate_depth`) — depth is lockstep state,
so it cannot be a per-host choice.  Packing runs ahead on the shared
pack-worker pool (including the next phase's survivor chunks, packed while
the current phase's tail rounds still resolve), launches run up to K ahead
of unresolved verdicts, resolves stay strict FIFO, and a negotiated fault
verdict drains the window so every host re-dispatches the younger rounds
in the identical order — serial and overlapped runs stay byte-identical.
"""

from __future__ import annotations

import json
import math
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config.pipeline import PipelineConfig
from ..data_model import ProcessingOutcome, TextDocument
from ..errors import GangReformed, PeerFailure, ReformationFailed
from ..resilience.membership import (
    DEFAULT_EXCHANGE_DEADLINE_S,
    DEFAULT_LEASE_TTL_S,
    EpochTracker,
    FileMembershipStore,
    KVLeaseStore,
    LeaseHeartbeat,
    _kv_set,
    elect_members,
)
from ..resilience.watchdog import WATCHDOG
from ..utils.events import EVENTS
from ..utils.trace import TRACER
from .mesh import DATA_AXIS, batch_sharding

__all__ = [
    "initialize",
    "global_data_mesh",
    "host_allgather",
    "configure_exchange",
    "bump_exchange_epoch",
    "current_exchange_epoch",
    "ExchangeTransport",
    "KVExchangeTransport",
    "FileLeaseTransport",
    "resolve_exchange_transport",
    "PeerFailure",
    "GangReformed",
    "ReformationFailed",
    "detect_stale_shards",
    "merge_shard_files",
    "run_local_shard",
    "run_multihost",
]


def detect_stale_shards(
    finals: Sequence[str], num_processes: int
) -> List[str]:
    """``*.shard*`` siblings of ``finals`` that THIS run will not produce.

    A prior crashed run with a larger ``--num-processes`` leaves orphan
    ``<final>.shard{j}`` files (j >= num_processes); the old merge silently
    ignored them next to fresh outputs — data loss masquerading as success.
    Returns the sorted offenders so callers can fail fast naming them
    (``--force`` removes them instead).  Expected shards
    (``.shard0..shard{n-1}``) are NOT stale: this run overwrites them.
    """
    import glob

    expected = {
        f"{final}.shard{i}" for final in finals for i in range(num_processes)
    }
    stale = {
        path
        for final in finals
        for path in glob.glob(glob.escape(final) + ".shard*")
        if path not in expected
    }
    return sorted(stale)


def _commit_merged(final: str, shards: Sequence[str]) -> None:
    """Stream the shards' row groups into ``<final>.tmp``, then commit it
    atomically: fsync the tmp, rename over ``final``, fsync the directory —
    the checkpoint-commit discipline (checkpoint.py), so a crash at any
    instant leaves ``final`` either absent or complete, never truncated."""
    import os

    import pyarrow.parquet as pq

    from ..utils.metrics import METRICS

    tmp = final + ".tmp"
    writer = None
    try:
        for s in shards:
            pf = pq.ParquetFile(s)
            if writer is None:
                writer = pq.ParquetWriter(tmp, pf.schema_arrow)
            # Row-group streaming keeps the merge O(row-group) memory
            # however large the global corpus is.
            for g in range(pf.metadata.num_row_groups):
                writer.write_table(pf.read_row_group(g))
    finally:
        if writer is not None:
            writer.close()
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, final)
    dfd = os.open(os.path.dirname(os.path.abspath(final)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    METRICS.inc("multihost_merge_commits_total")


def _commit_concat(final: str, part_paths: Sequence[str], schema) -> None:
    """Concatenate Parquet parts into ``final`` atomically, with an
    **explicit schema**: unlike :func:`_commit_merged` (which infers the
    schema from the first shard), zero parts still commit a well-formed
    empty file — the elastic merge must produce valid finals even when
    every row was filtered or a stripe is empty."""
    import os

    import pyarrow.parquet as pq

    from ..utils.metrics import METRICS

    tmp = final + ".tmp"
    writer = pq.ParquetWriter(tmp, schema)
    try:
        for p in part_paths:
            writer.write_table(pq.read_table(p).cast(schema))
    finally:
        writer.close()
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, final)
    dfd = os.open(os.path.dirname(os.path.abspath(final)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    METRICS.inc("multihost_merge_commits_total")


def merge_shard_files(
    pairs: Sequence[Tuple[str, Sequence[str]]]
) -> None:
    """Commit every ``(final, shards)`` merge atomically, THEN delete shards.

    Deletion only starts after the last rename has landed: a kill anywhere
    mid-merge leaves every input shard intact, so a re-run (with ``--force``
    to clear the re-produced finals' leftover shards if needed) loses
    nothing.  The old in-place merge consumed shards into a final that a
    crash left truncated — unrecoverable."""
    import os

    for final, shards in pairs:
        _commit_merged(final, shards)
    for _final, shards in pairs:
        for s in shards:
            os.remove(s)


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the distributed job (no-op if this process already joined).

    ``coordinator`` is ``host:port`` of process 0 — the moral equivalent of
    the reference's ``--amqp-addr`` (utils/common.rs:15), except the
    connection carries collectives instead of JSON tasks."""
    if _distributed_initialized():
        return
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )


def _distributed_initialized() -> bool:
    """True once this process joined a ``jax.distributed`` job.

    ``jax.distributed.is_initialized`` only exists on newer jax; on older
    versions (this container's 0.4.x included) probe the distributed state's
    client directly instead of raising AttributeError mid-run."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    from jax._src import distributed

    return getattr(distributed.global_state, "client", None) is not None


def global_data_mesh() -> "jax.sharding.Mesh":
    """1-D ``data`` mesh over every device of every process.

    Exception: on a multi-process **CPU** job the mesh covers only this
    process's local devices.  XLA:CPU refuses to execute a computation that
    spans processes (INVALID_ARGUMENT "Multiprocess computations aren't
    implemented on the CPU backend"), and the compiled pipeline programs are
    collective-free, so per-host execution under the negotiated lockstep
    schedule — whose exchanges ride :func:`host_allgather` — is semantically
    identical: each host's "global" batch is simply its own stripe.  On
    accelerator backends the mesh spans the whole job as before and XLA
    routes cross-host traffic over ICI/DCN."""
    from jax.sharding import Mesh

    devices = (
        jax.local_devices()
        if jax.process_count() > 1 and jax.default_backend() == "cpu"
        else jax.devices()
    )
    return Mesh(np.array(devices), (DATA_AXIS,))


class _ExchangeState:
    """Shared round state for the KV-transport lockstep exchanges.

    The old implementation keyed each exchange by a process-local
    ``itertools.count`` — fine while every process lives forever, but a
    relaunched process restarts its counter at 0 and can never re-enter.
    Keys are now namespaced by an **exchange epoch** with the sequence
    number restarting at every epoch boundary, and the epoch advances only
    at points derived from shared round state (:func:`bump_exchange_epoch`
    at each negotiated phase boundary in :func:`run_local_shard`), so any
    process that re-enters at an epoch boundary computes the same key names
    as its peers.  Drained epochs are deleted (see :func:`host_allgather`'s
    hygiene note), so the KV store holds O(1) allgather keys per rank
    instead of growing for the life of the coordinator.
    """

    def __init__(self) -> None:
        self.deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S
        self.epoch: int = 0
        self.seq: int = 0
        self.lease_store = None  # KVLeaseStore or FileMembershipStore
        # Own (epoch, seq) keys whose epoch drained but whose read-proof
        # (a peer completing a later exchange) hadn't landed yet.
        self.pending_delete: List[Tuple[int, int]] = []
        # Active transport override: ``None`` means the default XLA/KV
        # funnel (:class:`KVExchangeTransport`); :func:`run_multihost`
        # installs a :class:`FileLeaseTransport` for ``--exchange-transport
        # file`` runs.
        self.transport: Optional["ExchangeTransport"] = None


_EXCHANGE = _ExchangeState()

#: Timeout for the post-deadline sweep that names EVERY laggard (not just
#: the first): once the budget is spent, each remaining rank gets one short
#: probe instead of the full deadline again.
_PROBE_TIMEOUT_MS = 1000


def configure_exchange(
    deadline_s: Optional[float] = None,
    lease_store=None,
    reset: bool = True,
    transport: Optional["ExchangeTransport"] = None,
) -> None:
    """Configure the exchange deadline / lease table / transport for this
    process and (by default) restart the epoch/sequence counters — called
    by :func:`run_multihost` on every process at run start, so the shared
    round state begins aligned.  ``transport=None`` selects the default
    XLA/KV funnel (:class:`KVExchangeTransport`)."""
    if deadline_s is not None:
        _EXCHANGE.deadline_s = float(deadline_s)
    _EXCHANGE.lease_store = lease_store
    _EXCHANGE.transport = transport
    if reset:
        _EXCHANGE.epoch = 0
        _EXCHANGE.seq = 0
        _EXCHANGE.pending_delete = []


def current_exchange_epoch() -> int:
    """The epoch namespace current exchanges are keyed under (trace/metrics
    labeling; every process in lockstep reports the same value)."""
    return _EXCHANGE.epoch


def bump_exchange_epoch() -> int:
    """Open the next exchange epoch: the sequence restarts at 0 and the
    drained epoch's last own key is queued for deletion (it is removed once
    a completed exchange in the new epoch proves every peer has read it).
    Must be called in lockstep — :func:`run_local_shard` does so at every
    negotiated phase boundary, the shared round state all processes agree
    on without communicating."""
    if _EXCHANGE.seq > 0:
        _EXCHANGE.pending_delete.append((_EXCHANGE.epoch, _EXCHANGE.seq - 1))
    _EXCHANGE.epoch += 1
    _EXCHANGE.seq = 0
    return _EXCHANGE.epoch


def _ag_key(epoch: int, seq: int, rank: int) -> str:
    return f"textblast/allgather/e{epoch}/s{seq}/{rank}"


def _validate_rows(
    rows: Sequence[Sequence[int]], width: int, *, seq: int, epoch: int
) -> None:
    """Ragged-row guard: every peer's row must match this process's lane
    count.  A shorter/empty row previously fed a ragged list-of-lists to
    ``np.asarray`` (an object-dtype array that crashed far from the cause);
    now the offending rank is named in a typed :exc:`PeerFailure`."""
    for r, row in enumerate(rows):
        if len(row) != width:
            from ..utils.metrics import METRICS

            METRICS.inc("multihost_peer_failures_total")
            raise PeerFailure(
                f"exchange e{epoch}/s{seq}: rank {r} posted {len(row)} "
                f"lane(s) where {width} were expected — a desynchronized "
                "or corrupted peer (ragged allgather row)",
                missing_ranks=(r,),
                seq=seq,
                epoch=epoch,
            )


def _raise_peer_failure(
    missing: Sequence[int],
    *,
    seq: int,
    epoch: int,
    deadline_s: float,
    transport_error: str = "",
) -> None:
    """Deadline expired with peers unposted: resolve dead-vs-slow against
    the lease table and raise the typed error naming both lists.
    ``transport_error`` carries the coordination service's own words (a
    heartbeat/UNAVAILABLE teardown reads very differently from a plain
    DEADLINE_EXCEEDED, and operators grep for it)."""
    from ..utils.metrics import METRICS

    dead: List[int] = []
    store = _EXCHANGE.lease_store
    if store is not None:
        try:
            dead, _slow = store.resolve_liveness(missing)
        except Exception:  # pragma: no cover - lease table best-effort
            dead = []
    METRICS.inc("multihost_peer_failures_total")
    TRACER.instant(
        "peer_failure",
        {"seq": seq, "epoch": epoch, "missing": list(missing),
         "dead": list(dead)},
    )
    if EVENTS.enabled:
        EVENTS.emit("peer_failure", missing_ranks=list(missing),
                    dead_ranks=list(dead), seq=seq, epoch=epoch)
    detail = (
        f"; liveness leases mark rank(s) {list(dead)} dead "
        f"(lease older than {store.ttl_s:g}s)"
        if dead and store is not None
        else "; every missing rank still holds a fresh liveness lease "
        "(slow or wedged, not dead)"
        if store is not None
        else ""
    )
    transport = (
        f"; last transport error: {transport_error[:300]}"
        if transport_error
        else ""
    )
    raise PeerFailure(
        f"exchange e{epoch}/s{seq} deadline ({deadline_s:g}s) expired; "
        f"rank(s) {list(missing)} never posted{detail}{transport}",
        missing_ranks=missing,
        dead_ranks=dead,
        seq=seq,
        epoch=epoch,
    )


class ExchangeTransport:
    """Pluggable carrier for the lockstep exchanges (:func:`host_allgather`).

    Two implementations:

    * :class:`KVExchangeTransport` (``kv``, the default) — the XLA
      collective / ``jax.distributed`` coordination-service KV funnel,
      byte-for-byte the pre-seam behavior.  Diagnoses a peer death fast
      (typed :exc:`PeerFailure`) but cannot outlive it: the coordination
      service force-terminates every healthy task ~90-100 s after a peer
      stops heartbeating, regardless of what the survivor does.
    * :class:`FileLeaseTransport` (``file``) — exchange slots on the shared
      filesystem next to :class:`FileMembershipStore`'s liveness leases.
      The gang is not coupled through ``jax.distributed`` at all, so under
      ``--survive-peer-loss`` a peer death triggers gang *reformation*
      (fence → elect → adopt) instead of gang death.
    """

    name: str = "?"

    def members(self) -> Tuple[int, ...]:
        """Current member ranks, in exchange row order."""
        raise NotImplementedError

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        """Exchange one flat int64 row per member; returns
        ``[n_members, len(arr)]`` in :meth:`members` order."""
        raise NotImplementedError


class KVExchangeTransport(ExchangeTransport):
    """The default transport: XLA collective on accelerator backends, the
    ``jax.distributed`` coordination-service key-value store on multi-process
    CPU jobs (where XLA cannot run the collective at all) — the transport
    that already carries barriers and heartbeats.

    KV-path failure semantics (the exchange *deadline*, PR 6): the whole
    exchange gets ``configure_exchange``'s budget (default
    ``DEFAULT_EXCHANGE_DEADLINE_S``; ``--exchange-deadline-s``) instead of
    the old hardcoded 300 s per rank.  On expiry, the remaining ranks are
    each probed briefly so every laggard is identified, peer liveness is
    resolved against the KV lease table, and a typed :exc:`PeerFailure`
    names the exchange coordinates, the missing ranks, and which of them
    hold expired leases (dead) versus fresh ones (slow).  Rows are also
    validated for raggedness (:func:`_validate_rows`).  The accelerator
    path is XLA's collective and carries no host-side deadline — there the
    coordination-service heartbeat teardown remains the backstop.

    Hygiene: completing exchange ``s`` proves every peer has read exchange
    ``s-1`` (each peer posts ``s`` only after fully reading ``s-1``), so
    this process's ``s-1`` key — and any queued keys from drained epochs —
    are deleted after each completed exchange.  The KV table stays O(1) per
    rank for the life of the coordinator."""

    name = "kv"

    def members(self) -> Tuple[int, ...]:
        return tuple(range(jax.process_count()))

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        n = jax.process_count()
        if n == 1:
            return arr.reshape(1, -1)
        if jax.default_backend() != "cpu":
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(arr), dtype=np.int64
            ).reshape(n, -1)
        from jax._src import distributed

        client = distributed.global_state.client
        me = jax.process_index()
        epoch, seq = _EXCHANGE.epoch, _EXCHANGE.seq
        _EXCHANGE.seq += 1
        _kv_set(
            client,
            _ag_key(epoch, seq, me),
            ",".join(str(int(x)) for x in arr),
        )
        deadline_s = _EXCHANGE.deadline_s
        t0 = time.monotonic()
        own_row = [int(x) for x in arr]
        rows: List[List[int]] = []
        missing: List[int] = []
        transport_error = ""
        for r in range(n):
            if r == me:
                rows.append(own_row)
                continue
            remaining_ms = int((deadline_s - (time.monotonic() - t0)) * 1000)
            timeout_ms = (
                remaining_ms if remaining_ms > 0 else _PROBE_TIMEOUT_MS
            )
            try:
                raw = client.blocking_key_value_get(
                    _ag_key(epoch, seq, r), timeout_ms
                )
            except Exception as e:  # DEADLINE_EXCEEDED / service teardown
                missing.append(r)
                rows.append([])
                transport_error = str(e)
                continue
            rows.append([int(x) for x in raw.split(",")] if raw else [])
        if missing:
            _raise_peer_failure(
                missing, seq=seq, epoch=epoch, deadline_s=deadline_s,
                transport_error=transport_error,
            )
        _validate_rows(rows, len(own_row), seq=seq, epoch=epoch)
        drained = [_ag_key(e, s, me) for e, s in _EXCHANGE.pending_delete]
        _EXCHANGE.pending_delete.clear()
        if seq > 0:
            drained.append(_ag_key(epoch, seq - 1, me))
        for key in drained:
            try:
                client.key_value_delete(key)
            except Exception:  # pragma: no cover - hygiene is best-effort
                pass
        return np.asarray(rows, dtype=np.int64)


_KV_TRANSPORT = KVExchangeTransport()


class FileLeaseTransport(ExchangeTransport):
    """File-lease exchange transport: slots on the shared filesystem.

    Each exchange ``(epoch, seq)`` is a directory of per-rank slot files
    under the membership root (``exchange/e{E}/s{S}/rank{r}.json``), posted
    with the same atomic tmp+rename discipline as the liveness leases and
    naming the poster's incarnation so a fenced zombie's late post is
    ignored.  Reads are deadline-bounded polls over the member set; hygiene
    mirrors the KV rules — completing exchange ``s`` proves every member
    read ``s-1``, so the own ``s-1`` slot and any queued drained-epoch
    slots are deleted after each completed exchange.

    With ``survive=True``, a deadline expiry runs the reformation protocol
    (fence the missing ranks' incarnations, elect the survivor set via
    shared-filesystem proposals, bump the membership and exchange epochs)
    and raises :exc:`GangReformed` for the driver to replay the interrupted
    exchange over the survivors; without it, the expiry raises the same
    typed :exc:`PeerFailure` the KV transport does.

    Unlike the KV transport this one never touches ``jax.distributed`` —
    that is the point: the coordination service force-terminates healthy
    tasks ~90-100 s after a peer death, so survivability requires a carrier
    the dead rank cannot take down."""

    name = "file"

    def __init__(
        self,
        store: FileMembershipStore,
        rank: int,
        num_processes: int,
        *,
        survive: bool = False,
        heartbeat: Optional[LeaseHeartbeat] = None,
        poll_s: float = 0.02,
    ) -> None:
        self.store = store
        self.rank = int(rank)
        self._members: Tuple[int, ...] = tuple(range(int(num_processes)))
        self.survive = bool(survive)
        self.heartbeat = heartbeat
        self.poll_s = float(poll_s)
        self.dead_ranks: List[int] = []
        self.reformations = 0
        self.tracker = EpochTracker(rank)
        self.tracker.observe(self._members)

    def members(self) -> Tuple[int, ...]:
        return self._members

    def _self_check(self, epoch: int, seq: int) -> None:
        """Zombie/solo guard, run at every exchange: a rank whose own
        incarnation got fenced (a peer reformed without it), or whose lease
        went stale (heartbeat dead, filesystem gone), must terminate typed —
        on a shrunk gang there may be no peer left to notice, so hanging on
        slots that can never fill is the alternative."""
        if self.store.self_fenced():
            raise ReformationFailed(
                f"rank {self.rank} (incarnation {self.store.incarnation}) "
                f"found itself fenced at exchange e{epoch}/s{seq}: a peer "
                "reformed the gang without it",
                rank=self.rank,
            )
        hb_dead = self.heartbeat is not None and self.heartbeat.failed
        if not hb_dead and not self.store.my_lease_fresh():
            # Stale-but-present lease of this very incarnation: a long
            # GIL hold (an XLA compile) can starve the heartbeat thread
            # past the TTL, and on wake the main thread may reach this
            # check before the overdue renewal lands.  That is a
            # scheduling artifact, not a death — nobody fenced us (checked
            # above) — so renew in place.  Gone, or overwritten by a
            # successor incarnation, stays fatal below; and if a peer
            # fenced us in the same gap, the next exchange's fence check
            # terminates this rank typed.
            d = self.store.read_leases().get(self.rank)
            if (
                d is not None
                and d.get("incarnation") == self.store.incarnation
            ):
                try:
                    self.store.post()
                except OSError:
                    pass  # renewal refused: fall through to the fatal raise
        if hb_dead or not self.store.my_lease_fresh():
            raise ReformationFailed(
                f"rank {self.rank} failed its liveness self-check at "
                f"exchange e{epoch}/s{seq}: "
                + (
                    "the lease heartbeat died"
                    if hb_dead
                    else "its own lease file is stale or gone "
                    f"(ttl {self.store.ttl_s:g}s)"
                )
                + " — no quorum can include this process",
                rank=self.rank,
            )

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        epoch, seq = _EXCHANGE.epoch, _EXCHANGE.seq
        _EXCHANGE.seq += 1
        self._self_check(epoch, seq)
        own_row = [int(x) for x in arr]
        mem = self._members
        if len(mem) == 1:
            # Solo gang: nothing to exchange, but the self-check above still
            # ran — a double-death (reform down to one member, then lose the
            # filesystem lease) fails typed instead of hanging on peers that
            # can never post.
            return np.asarray([own_row], dtype=np.int64)
        self.store.post_exchange_slot(
            epoch, seq, ",".join(str(int(x)) for x in arr)
        )
        deadline_s = _EXCHANGE.deadline_s
        t0 = time.monotonic()
        got = {self.rank: own_row}
        while len(got) < len(mem):
            for r in mem:
                if r in got:
                    continue
                slot = self.store.read_exchange_slot(epoch, seq, r)
                if slot is None:
                    continue
                if self.store.is_fenced(
                    int(slot.get("rank", r)), str(slot.get("incarnation", ""))
                ):
                    continue  # a fenced zombie's late post
                raw = str(slot.get("data", ""))
                got[r] = [int(x) for x in raw.split(",")] if raw else []
            if len(got) == len(mem):
                break
            if time.monotonic() - t0 >= deadline_s:
                missing = [r for r in mem if r not in got]
                if self.survive:
                    self._reform(missing, epoch, seq)  # raises GangReformed
                _raise_peer_failure(
                    missing, seq=seq, epoch=epoch, deadline_s=deadline_s,
                    transport_error=(
                        "file-lease exchange slot(s) never appeared"
                    ),
                )
            self._self_check(epoch, seq)
            time.sleep(self.poll_s)
        rows = [got[r] for r in mem]
        _validate_rows(rows, len(own_row), seq=seq, epoch=epoch)
        for e, s in _EXCHANGE.pending_delete:
            self.store.delete_exchange_slot(e, s)
        _EXCHANGE.pending_delete.clear()
        if seq > 0:
            self.store.delete_exchange_slot(epoch, seq - 1)
        return np.asarray(rows, dtype=np.int64)

    def _reform(self, missing: Sequence[int], epoch: int, seq: int) -> None:
        """The reformation protocol, run by every survivor blocked at the
        same ``(epoch, seq)``: fence the missing ranks' incarnations, elect
        the new member set through shared-filesystem proposals
        (:func:`elect_members`), bump the membership epoch (eviction
        accounting) and the exchange epoch (slot-namespace hygiene — the
        failed exchange's own slot is queued for deletion by the bump), and
        raise :exc:`GangReformed` so the driver replays the interrupted
        exchange over the survivors."""
        from ..utils.metrics import METRICS

        dead: List[int] = []
        try:
            dead, _slow = self.store.resolve_liveness(missing)
        except Exception:  # pragma: no cover - lease table best-effort
            dead = []
        TRACER.instant(
            "gang_reform_start",
            {"epoch": epoch, "seq": seq, "missing": list(missing),
             "dead": list(dead)},
        )
        if EVENTS.enabled:
            # The detection is a peer failure whether or not the gang
            # survives it; the journal names it first so the causal chain
            # reads peer_failure -> gang_reform_start -> gang_reformation.
            EVENTS.emit("peer_failure", missing_ranks=list(missing),
                        dead_ranks=list(dead), epoch=epoch, seq=seq)
            EVENTS.emit("gang_reform_start", epoch=epoch, seq=seq,
                        missing=list(missing), dead=list(dead))
        members, newly_dead = elect_members(
            self.store,
            self._members,
            missing,
            tag=f"e{epoch}s{seq}",
            deadline_s=_EXCHANGE.deadline_s,
        )
        self._members = members
        self.dead_ranks.extend(
            r for r in newly_dead if r not in self.dead_ranks
        )
        self.reformations += 1
        self.tracker.observe(members)
        new_exchange_epoch = bump_exchange_epoch()
        self.store.write_roster(
            members, self.tracker.epoch, new_exchange_epoch
        )
        METRICS.inc("multihost_gang_reformations_total")
        METRICS.set("multihost_reformation_epoch", float(self.tracker.epoch))
        if EVENTS.enabled:
            # Records emitted from here on carry the new gang generation.
            EVENTS.set_incarnation(self.reformations)
        TRACER.instant(
            "gang_reformation",
            {"membership_epoch": self.tracker.epoch,
             "exchange_epoch": new_exchange_epoch,
             "members": list(members), "dead": list(newly_dead)},
        )
        if EVENTS.enabled:
            EVENTS.emit("gang_reformation", epoch=self.tracker.epoch,
                        world_size=len(members), members=list(members),
                        dead=list(newly_dead))
        print(
            f"reform[{self.rank}]: exchange e{epoch}/s{seq} deadline "
            f"({_EXCHANGE.deadline_s:g}s) expired; fenced rank(s) "
            f"{list(newly_dead)} (lease table marked {list(dead)} dead); "
            f"reformed to members {list(members)} at membership epoch "
            f"{self.tracker.epoch}",
            flush=True,
        )
        raise GangReformed(
            f"rank(s) {list(newly_dead)} fenced at exchange e{epoch}/s{seq};"
            f" members now {list(members)} (membership epoch "
            f"{self.tracker.epoch})",
            members=members,
            dead_ranks=newly_dead,
            epoch=self.tracker.epoch,
        )

    def maybe_admit(self) -> None:
        """Phase-boundary admission sweep: observe posted join requests
        and grow the gang through the reformation machinery.

        Called at every negotiated phase boundary (via
        :func:`maybe_admit_joiners` from :func:`run_local_shard`) — the one
        point where no rounds are in flight, so growing the member set
        cannot strand a launched chunk.  The sweep is collective: a
        joiner's request file may be visible to some members before others
        (shared-filesystem propagation), so members first allgather the
        join ranks each observed and act on the **union** — either every
        member runs the admission election or none does.  Success bumps
        the membership and exchange epochs, publishes the grown roster
        (``roster.json`` — how the joiner learns it is in), clears the
        handled requests, and raises :exc:`GangReformed` so the driver
        replays from the phase boundary with the window depth re-negotiated
        over the grown gang.  A joiner that died mid-admission is fenced by
        the election and the gang proceeds un-grown (no raise); a *member*
        death during the sweep folds into the ordinary reformation retry
        inside :func:`elect_members`."""
        lanes = self.collect_join_lanes()
        if lanes is None:
            return
        if len(self._members) == 1:
            # Solo gang: nobody to agree with, the local view is the union.
            union = [r for r in lanes if r >= 0]
        else:
            merged = self.allgather(np.asarray(lanes, dtype=np.int64))
            union = [int(x) for x in np.asarray(merged).ravel()]
        self.admit_union(union)

    def collect_join_lanes(self) -> Optional[List[int]]:
        """Local half of the admission sweep: the fixed-width join-lane row
        this rank would post (observed joiner ranks, ``-1`` padding to
        ``_JOIN_LANES``), split out of :meth:`maybe_admit` so the
        speculative phase barrier can piggyback it on the combined
        barrier exchange instead of spending a dedicated allgather.
        Returns ``None`` when admission is off (no ``--survive-peer-loss``
        — the caller then posts no admission lanes at all, keeping the
        vector width identical on every host)."""
        if not self.survive:
            return None
        local = sorted(
            r for r in self.store.read_join_requests()
            if r not in self._members
        )[:_JOIN_LANES]
        return local + [-1] * (_JOIN_LANES - len(local))

    def admit_union(self, ranks) -> None:
        """Gang half of the admission sweep: act on the agreed joiner set.

        ``ranks`` is the flattened merge of every member's join lanes
        (``-1`` padding and already-member ranks are filtered here, so
        callers hand over raw allgather rows).  Every member reaches this
        with the identical union — from :meth:`maybe_admit`'s own
        allgather or from lanes piggybacked on the barrier exchange — so
        either every member runs the admission election or none does.
        Raises :exc:`GangReformed` on successful admission, exactly as
        :meth:`maybe_admit` always did."""
        if not self.survive:
            return
        from ..resilience.faults import FAULTS
        from ..utils.metrics import METRICS

        epoch = _EXCHANGE.epoch
        union = sorted(
            {int(x) for x in ranks if int(x) >= 0} - set(self._members)
        )
        if not union:
            return
        FAULTS.fire("multihost.join.admit")
        TRACER.instant(
            "gang_admission_start",
            {"exchange_epoch": epoch, "joiners": list(union)},
        )
        if EVENTS.enabled:
            EVENTS.emit("gang_admission_start", epoch=epoch,
                        joiners=list(union))
        members, newly_dead = elect_members(
            self.store,
            self._members,
            (),
            tag=f"join.e{epoch}",
            deadline_s=_EXCHANGE.deadline_s,
            joiners=union,
        )
        admitted = [r for r in members if r not in self._members]
        for r in union:
            # Handled either way: the roster supersedes an admitted
            # request, and a fenced joiner's request must not re-trigger
            # the sweep at every subsequent boundary.
            self.store.clear_join_request(r)
        if not admitted and not newly_dead:
            print(
                f"admit[{self.rank}]: joiner(s) {list(union)} fenced "
                "mid-admission; gang proceeds un-grown",
                flush=True,
            )
            return
        self._members = members
        self.dead_ranks.extend(
            r for r in newly_dead if r not in self.dead_ranks
        )
        if newly_dead:
            # A member died during the admission sweep: that is a
            # reformation folded into the same election.
            self.reformations += 1
            METRICS.inc("multihost_gang_reformations_total")
        self.tracker.observe(members)
        new_exchange_epoch = bump_exchange_epoch()
        METRICS.set("multihost_reformation_epoch", float(self.tracker.epoch))
        self.store.write_roster(
            members, self.tracker.epoch, new_exchange_epoch
        )
        TRACER.instant(
            "gang_admission",
            {"membership_epoch": self.tracker.epoch,
             "exchange_epoch": new_exchange_epoch,
             "members": list(members), "admitted": admitted,
             "dead": list(newly_dead)},
        )
        if EVENTS.enabled:
            EVENTS.emit("gang_admission", epoch=self.tracker.epoch,
                        world_size=len(members), admitted=list(admitted),
                        dead=list(newly_dead))
        print(
            f"admit[{self.rank}]: admitted rank(s) {admitted} at phase "
            f"boundary (exchange epoch {epoch}); members now "
            f"{list(members)} at membership epoch {self.tracker.epoch}",
            flush=True,
        )
        raise GangReformed(
            f"rank(s) {admitted} admitted at exchange epoch {epoch}; "
            f"members now {list(members)} (membership epoch "
            f"{self.tracker.epoch})",
            members=members,
            dead_ranks=tuple(newly_dead),
            epoch=self.tracker.epoch,
        )


#: Admission fan-in per phase boundary: the union allgather carries a
#: fixed-width vector of observed joiner ranks (-1 padding), so at most
#: this many joiners are admitted per boundary — later requests simply
#: wait for the next one.
_JOIN_LANES = 4


def maybe_admit_joiners() -> None:
    """Phase-boundary hook for :func:`run_local_shard`: run the admission
    sweep when the active exchange transport supports one (the file-lease
    transport under ``--survive-peer-loss``); a no-op everywhere else, so
    the KV path's exchange sequence is untouched."""
    admit = getattr(_EXCHANGE.transport, "maybe_admit", None)
    if admit is not None:
        admit()


def request_admission(
    store: FileMembershipStore,
    *,
    deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S,
    poll_s: float = 0.05,
) -> dict:
    """Joiner-side half of the admission protocol (file-lease transport).

    Renews this rank's liveness lease, posts an incarnation-stamped join
    request next to it, and waits for the running gang to admit it at a
    phase boundary.  The joiner deliberately does NOT drive the election
    (:func:`elect_members` fences silent candidates — a joiner running the
    full driver could fence healthy members on its own deadline); it
    **echoes**: whenever a gang member's ``join.*`` proposal includes this
    rank, the joiner posts the identical proposal, making itself a
    unanimous candidate without ever suspecting anyone.  Admission is
    learned from ``roster.json`` (published by every admitting member
    after the epoch bump); the returned roster dict carries ``members``,
    ``membership_epoch`` and ``exchange_epoch``, so the caller can align
    its exchange state with the gang before its first collective.

    Raises :exc:`ReformationFailed` when the gang fenced this incarnation
    (the died-mid-admission verdict, seen from the inside: the gang
    proceeded un-grown) or when nothing admits it within ``deadline_s``.
    """
    store.post()
    store.post_join_request()
    t0 = time.monotonic()
    while True:
        roster = store.read_roster()
        if roster is not None and store.rank in {
            int(r) for r in roster.get("members", ())
        }:
            return roster
        if store.self_fenced():
            raise ReformationFailed(
                f"rank {store.rank} (incarnation {store.incarnation}) was "
                "fenced while awaiting admission: the gang proceeded "
                "un-grown",
                rank=store.rank,
            )
        for tag, proposed in store.peer_proposals("join.").items():
            if store.rank in proposed and (
                store.read_proposal(tag, store.rank) is None
            ):
                store.post_proposal(tag, proposed)
        if time.monotonic() - t0 >= deadline_s:
            raise ReformationFailed(
                f"rank {store.rank}'s join request was not admitted within "
                f"{deadline_s:g}s (no phase boundary reached, or the gang "
                "is gone)",
                rank=store.rank,
            )
        store.post()  # keep the lease fresh: a stale joiner is invisible
        time.sleep(poll_s)


def resolve_exchange_transport(choice: str, survive_peer_loss: bool) -> str:
    """Resolve ``--exchange-transport {auto,kv,file}`` to a concrete name.

    ``auto`` picks ``file`` when ``--survive-peer-loss`` is set (reformation
    needs a carrier that outlives the coordination service) and ``kv``
    otherwise (lowest exchange latency; XLA collective on accelerators).
    Explicit ``kv`` + survive is a contradiction and fails fast."""
    from ..errors import PipelineError

    c = str(choice or "auto").lower()
    if c not in ("auto", "kv", "file"):
        raise PipelineError(
            f"exchange transport must be one of auto/kv/file, got {choice!r}"
        )
    if c == "auto":
        c = "file" if survive_peer_loss else "kv"
    if survive_peer_loss and c != "file":
        raise PipelineError(
            "--survive-peer-loss requires the file-lease exchange transport"
            " (the kv transport rides the jax coordination service, which "
            "force-terminates survivors ~90-100s after a peer death); pass "
            "--exchange-transport file or auto"
        )
    return c


def host_allgather(vec: np.ndarray) -> np.ndarray:
    """Allgather one small int vector per process; returns ``[n_proc, len]``.

    Every lockstep exchange in this module (round schedules, fault verdicts,
    merged histograms, the totals barrier) funnels through here, and from
    here through the configured :class:`ExchangeTransport` — the XLA/KV
    funnel by default (:class:`KVExchangeTransport`, byte-for-byte the
    pre-seam behavior), or :class:`FileLeaseTransport` when
    :func:`run_multihost` installed one via :func:`configure_exchange`.
    Callers must invoke it in lockstep (the contract this module enforces
    anyway): slots are ``(epoch, seq, rank)`` tuples from the shared round
    state (:class:`_ExchangeState`), and the blocking read doubles as the
    barrier — no process proceeds until every member has posted its row."""
    arr = np.asarray(vec, dtype=np.int64).ravel()
    transport = _EXCHANGE.transport
    if transport is None:
        transport = _KV_TRANSPORT
    # One post per process per call, whatever the vector width — the
    # counter the batched verdict exchange drives down (a piggybacked
    # K-flag verdict vector is ONE post where K per-round flags were K).
    from ..utils.metrics import METRICS
    from ..utils.telemetry import TELEMETRY

    METRICS.inc("multihost_exchange_posts_total")
    t0 = time.perf_counter()
    try:
        return transport.allgather(arr)
    finally:
        dt = time.perf_counter() - t0
        METRICS.inc("multihost_exchange_post_seconds_total", dt)
        if TELEMETRY.enabled:
            METRICS.observe_hdr(
                "exchange_post_latency_seconds", int(dt * 1e6)
            )


def host_allgather_obj(obj) -> list:
    """Allgather one small JSON-serializable object per process.

    Rides :func:`host_allgather` (the only transport this module trusts):
    the object is JSON-encoded to UTF-8 bytes, lengths are exchanged first
    so every process can pad its byte vector to the common width, then the
    padded vectors are exchanged and each row decoded back.  Two collectives
    per call — callers must invoke it in lockstep, like every other
    exchange here.  Sized for metrics snapshots (a few KiB), not bulk data:
    each byte travels as an int64 lane.  The row count follows the active
    transport's member set, not ``jax.process_count()`` — on a reformed
    file-transport gang only survivors contribute rows (a reformation
    *between* the two collectives raises :exc:`GangReformed` from the
    second, so callers replay the whole closure, never decode with stale
    lengths)."""
    data = json.dumps(obj, sort_keys=True).encode("utf-8")
    lens = host_allgather(np.array([len(data)]))[:, 0]
    width = max(1, int(lens.max()))
    buf = np.zeros(width, dtype=np.int64)
    if data:
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    rows = host_allgather(buf)
    return [
        json.loads(
            bytes(rows[i, : int(lens[i])].astype(np.uint8)).decode("utf-8")
        )
        for i in range(rows.shape[0])
    ]


def _local_stats(out: dict) -> dict:
    """This process's rows of every ``data``-sharded output, in row order,
    moved in ONE bundled transfer (per-key np.asarray is a synchronous round
    trip each on remote-tunnel backends — see assemble_batch)."""
    shard_tree = {
        k: [
            s.data
            for s in sorted(
                v.addressable_shards, key=lambda s: s.index[0].start or 0
            )
        ]
        for k, v in out.items()
    }
    if WATCHDOG.enabled:
        # Deadline-bounded readiness poll before the blocking transfer: a
        # wedged lockstep dispatch raises StallError here, which the
        # negotiated guard converts to a local fault verdict — the gang
        # jointly drains/retries instead of riding the exchange deadline.
        WATCHDOG.wait_device_ready(
            "device_fetch",
            (s for parts in shard_tree.values() for s in parts),
        )
    host_tree = jax.device_get(shard_tree)
    return {
        k: (np.concatenate(parts, axis=0) if parts else np.empty((0,)))
        for k, parts in host_tree.items()
    }


def _timed_stats(out: dict, bucket: int, phase: int, rows: int) -> dict:
    """``_local_stats`` with device-wait attribution.

    The lockstep path fetches shard trees directly — it never goes through
    the single-host ``_device_fetch`` seam — so this wrapper is where its
    blocked-on-device time lands in ``stage_device_wait_seconds`` (the
    counter the window decomposition subtracts from window stall) and,
    when profiling is on, in the per-(bucket, phase) device-time
    histograms.  A faulted fetch still books the wait (matching
    ``_device_fetch``'s ``finally``) but records no dispatch sample."""
    from ..utils.metrics import METRICS
    from ..utils.profiler import PROFILER

    t0 = time.perf_counter()
    ok = False
    try:
        stats = _local_stats(out)
        ok = True
    finally:
        dt = time.perf_counter() - t0
        METRICS.inc("stage_device_wait_seconds", dt)
        if ok and PROFILER.enabled:
            PROFILER.record_dispatch(bucket, phase, rows, dt)
    return stats


def _negotiate_max(needed_local: np.ndarray) -> np.ndarray:
    """Columnwise max of every process's per-bucket round counts.

    Lockstep safety: EVERY process must run the same number of rounds per
    bucket — a unilateral decision while peers enter ``fn()`` would hang the
    job until the coordinator heartbeat tears it down.  One small allgather
    makes the schedule global and deterministic."""
    return host_allgather(needed_local).max(axis=0).astype(np.int32)


def _negotiate_depth(local_depth: int, local_spec_depth: Optional[int] = None):
    """Joint in-flight window depth: the MIN over every host's configured
    ``OverlapConfig.pipeline_depth`` (one extra startup allgather, zero
    per-round exchanges).

    Depth is lockstep state: every host must launch and resolve the
    identical round sequence with the identical interleave, so a host
    configured shallower than its peers pulls the whole gang down to what
    it can sustain — min, not max, because depth K means K launches may
    run ahead of unresolved verdicts and the most conservative host bounds
    what all hosts may assume about each other's dispatch order.  A
    mismatch is legal (hosts merely negotiate down) but surfaced in the
    trace so an operator can see which rank capped the window.

    With ``local_spec_depth`` the post carries a second lane — the
    speculative cross-phase dispatch depth — negotiated by the same min
    rule in the same allgather, and the return becomes ``(depth, spec)``.
    Speculation is lockstep state for the same reason depth is: the
    combined barrier exchange replaces the classic three-post phase
    boundary, so every host must agree whether the protocol is on (joint
    spec > 0) before the first barrier.  One host running with
    ``TEXTBLAST_SPECULATE=off`` (local spec 0) therefore pins the whole
    gang to the classic barrier.  The 1-arg form stays a 1-lane post
    returning a bare int — existing call sites and their wire traffic are
    untouched."""
    from ..utils.metrics import METRICS

    lanes = [max(1, int(local_depth))]
    if local_spec_depth is not None:
        lanes.append(max(0, int(local_spec_depth)))
    merged = host_allgather(np.array(lanes, dtype=np.int32))
    depths = merged[:, 0]
    joint = max(1, int(depths.min()))
    METRICS.set("multihost_negotiated_depth", float(joint))
    if int(depths.max()) != joint:
        TRACER.instant(
            "window_depth_mismatch",
            {"host_depths": [int(d) for d in depths], "joint": joint},
        )
        if EVENTS.enabled:
            EVENTS.emit("window_depth_mismatch", joint=joint,
                        host_depths=[int(d) for d in depths])
    if local_spec_depth is None:
        return joint
    spec = max(0, int(merged[:, 1].min()))
    METRICS.set("multihost_speculate_depth", float(spec))
    return joint, spec


def _align_trace_clocks() -> None:
    """Cross-host trace clock handshake (one allgather at run start).

    Each process's tracer stamps events from a private ``perf_counter``
    origin, so per-host trace files loaded into one Perfetto session show
    hosts skewed by their process start times.  Every process allgathers
    the wall-clock time of its tracer origin; the **minimum** becomes the
    run's shared origin and each tracer shifts its timestamps by
    ``own_wall - min_wall`` (recording the offset and every host's wall in
    a ``trace_clock_offset`` metadata event).  The exchange is
    unconditional — it is a collective, and a host without ``--trace``
    still must participate or the gang desynchronizes; only the local
    ``align`` is gated on tracing being enabled.  Alignment is as good as
    the hosts' wall clocks (NTP-grade), which is what a cross-host
    timeline needs — spans are still *timed* by each host's monotonic
    clock."""
    wall = TRACER.wall_at_origin_us()
    walls = host_allgather(np.array([wall], dtype=np.int64))[:, 0]
    if TRACER.enabled:
        origin = int(walls.min())
        TRACER.align(
            wall - origin,
            args={
                "origin_wall_us": origin,
                "host_walls_us": [int(w) for w in walls],
            },
        )


def run_local_shard(
    config: PipelineConfig,
    docs: Sequence[TextDocument],
    bucket: Optional[int] = None,
    rounds: Optional[int] = None,
    mesh=None,
    pipeline=None,
    buckets: Optional[Sequence[int]] = None,
    fault_guard: bool = True,
) -> List[ProcessingOutcome]:
    """Run this host's documents through the globally-sharded pipeline.

    Every participating process must call this with the same ``config`` and
    bucket set (lockstep).  The number of rounds per bucket is negotiated by
    allgather (:func:`_negotiate_max`), so hosts never need a pre-agreed
    budget; passing ``rounds`` turns it into an assertion (ValueError if the
    negotiated schedule exceeds it — the round-3 interface).  Documents
    longer than every bucket run the host oracle locally (the usual counted
    fallback).

    Returns outcomes for **this host's** documents only.

    Phased short-circuit, lockstep-safe (VERDICT r3 item 3): for EVERY phase
    the per-bucket round counts are renegotiated over allgather from the
    hosts' surviving document counts, so all processes dispatch the identical
    program sequence while later phases run on shrinking, repacked survivor
    batches — the device analogue of the executor short-circuit that the
    single-controller path already had.

    With ``fault_guard`` (default) every round resolves under the
    :class:`~textblaster_tpu.resilience.negotiated.NegotiatedGuard`: a
    retryable fault on ANY host triggers a jointly-negotiated retry of the
    round on EVERY host (shared zero-jitter backoff), then a
    jointly-negotiated degradation of the round's documents to the host
    oracle; a per-bucket breaker latches persistently bad buckets onto the
    oracle for the rest of the run.  The guard's only lockstep addition is
    one 1-int allgather per round resolution — the fault-free program
    sequence is unchanged.

    Overlap (PR 9): rounds ride a K-deep in-flight window, where K is the
    min over every host's ``OverlapConfig.pipeline_depth``, allgathered
    once at shard start (:func:`_negotiate_depth` — depth is lockstep
    state, so it cannot be a per-host choice).  Packing runs ahead on the
    shared pack pool (rounds r+1..r+K pack while round r executes, and the
    next phase's full survivor chunks pack while this phase's tail rounds
    still resolve), launches run up to K ahead of unresolved verdicts, and
    resolves stay strict FIFO — so serial (depth 1 / ``--no-overlap``) and
    overlapped runs produce byte-identical outcome streams.  A negotiated
    fault verdict drains the window: every host discards its launched-ahead
    results and the younger rounds re-dispatch fresh at their own resolve,
    keeping the post-verdict global program order identical on every host.

    Speculative cross-phase dispatch (this PR): at each non-final phase
    barrier, up to ``spec_depth`` next-phase rounds launch before the tail
    verdicts resolve (``launch_speculative``), and the tail verdict batch,
    join-admission sweep, and next-phase schedule negotiation collapse
    into ONE exchange post (``resolve_barrier`` — two on phases a badwords
    step keeps from previewing).  The joint speculation depth is the min
    over every host's local value (``--speculate-depth``, default the
    window depth; ``TEXTBLAST_SPECULATE=off`` posts 0 and pins the whole
    gang to the classic barrier).  Any joint fault voids the speculated
    launches and the piggybacked freight identically on every host —
    speculation moves launches, never outcomes, so on/off runs stay
    byte-identical.
    """
    import os
    from collections import deque

    from ..ops.pipeline import CompiledPipeline, maybe_warmup, record_occupancy
    from ..orchestration import execute_processing_pipeline
    from ..resilience.negotiated import NegotiatedGuard
    from ..resilience.retry import classify_error
    from ..utils.metrics import METRICS
    from ..utils.overlap import shared_pack_pool

    from ..ops.packing import PACK_MARGIN

    if buckets is None:
        buckets = (bucket,) if bucket is not None else (2048,)
    buckets = tuple(sorted(buckets))
    mesh = mesh if mesh is not None else global_data_mesh()
    # How many processes the program's mesh spans: jax.process_count() on
    # accelerators, 1 under the multi-process-CPU local-mesh fallback
    # (global_data_mesh) where each host runs its own full-width program.
    n_proc = len({d.process_index for d in mesh.devices.flat})
    if pipeline is None:
        pipeline = CompiledPipeline(config, buckets=buckets, mesh=mesh)
        # Warm before the first lockstep round: every host compiles (or AOT-
        # cache-loads) the identical program set up front, so no host hits a
        # first-dispatch compile stall mid-round while its peers wait at the
        # allgather.
        maybe_warmup(pipeline)
    # Per-bucket local row counts: each host feeds its 1/n_proc stripe of the
    # bucket's global batch.  Under uniform geometry every bucket resolves to
    # the old single ``pipeline.batch_size // n_proc``.
    geo = pipeline.geometry
    local_for = {
        b: max(1, geo.batch_for(b) // n_proc) if b in geo.buckets
        else max(1, pipeline.batch_size // n_proc)
        for b in buckets
    }

    def partition(ds: Sequence[TextDocument]):
        by_bucket: dict = {b: [] for b in buckets}
        over: List[TextDocument] = []
        for d in ds:
            for b in buckets:
                if len(d.content) <= b - PACK_MARGIN:
                    by_bucket[b].append(d)
                    break
            else:
                over.append(d)
        return by_bucket, over

    if pipeline._route_dict_scripts:
        # Dictionary-script docs take the host oracle (ops/pipeline.py
        # __init__ note); they join the local fallback list, which runs
        # outside the lockstep schedule and so needs no negotiation.
        # Single pass: ``docs`` may be any iterable, and one content scan
        # per document suffices.
        from ..utils.cjk import has_dict_script

        routed, kept = [], []
        for d in docs:
            (routed if has_dict_script(d.content) else kept).append(d)
        docs = kept
    else:
        routed = []
    current, fallback = partition(docs)
    fallback.extend(routed)

    sh2 = batch_sharding(mesh, 2)
    sh1 = batch_sharding(mesh, 1)

    guard = NegotiatedGuard(config.resilience, buckets=buckets) if fault_guard else None
    degraded: List[TextDocument] = []

    # Joint window depth: a collective, so EVERY host negotiates it even
    # when its own overlap is off (its local depth is then 1, pulling the
    # whole gang to serial — min rule).
    overlap_cfg = getattr(config, "overlap", None)
    overlapped = (
        overlap_cfg is not None
        and overlap_cfg.enabled
        and os.environ.get("TEXTBLAST_NO_OVERLAP") != "1"
    )
    local_depth = max(1, overlap_cfg.pipeline_depth) if overlapped else 1
    # Local speculative cross-phase dispatch depth: how many next-phase
    # rounds this host is willing to launch at a phase barrier before the
    # tail verdicts resolve.  Defaults to the window depth; capped per-host
    # by --speculate-depth and killed by TEXTBLAST_SPECULATE=off (or a
    # single-phase pipeline, where there is no barrier to speculate
    # across).  The joint value is min-negotiated alongside the window
    # depth — one host opting out pins the whole gang to the classic
    # three-post barrier, because the barrier protocol itself is lockstep
    # state.
    spec_env = os.environ.get("TEXTBLAST_SPECULATE", "").strip().lower()
    spec_cfg = getattr(overlap_cfg, "speculate_depth", None)
    if (
        not overlapped
        or spec_env in ("off", "0", "false")
        or len(pipeline.phases) < 2
    ):
        local_spec = 0
    elif spec_cfg is None:
        local_spec = local_depth
    else:
        local_spec = max(0, int(spec_cfg))
    while True:
        try:
            depth, spec_depth = _negotiate_depth(local_depth, local_spec)
            break
        except GangReformed:
            # The reformation already bumped the exchange epoch; just
            # replay the negotiation over the survivor set.
            continue
    # Pack off the critical path: the process-wide pool (shared with the
    # single-host packers) packs rounds ahead of the launch cursor and the
    # next phase's survivor chunks behind the resolve cursor.  Serial mode
    # (--no-overlap) packs inline on this thread, exactly as before.
    pool = shared_pack_pool(max(1, overlap_cfg.pack_workers)) if overlapped else None

    def launch(local, ph, speculative=False):
        """Guarded async launch.  Returns ``(out, launch_fault)``: a
        retryable launch failure is captured, not raised — the verdict has
        to convene at resolve time so every host takes the same branch.
        ``speculative`` marks a cross-phase launch fired at a phase
        barrier before the tail verdicts resolved (its own chaos seam,
        ``multihost.speculate``)."""
        from ..resilience.faults import FAULTS

        if guard is None:
            if speculative:
                FAULTS.fire("multihost.speculate")
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        try:
            if speculative:
                FAULTS.fire("multihost.speculate")
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        except BaseException as e:  # noqa: BLE001 — classifier decides
            if classify_error(e) != "retryable":
                raise
            WATCHDOG.escalated(e)
            return None, True

    def phase_rewrites(ph: int) -> bool:
        # Only C4QualityFilter rewrites survivor content mid-phase (line
        # drops); every other device step decides and stamps.  Phases
        # without it preserve lengths, so each survivor's bucket is its
        # round's bucket and the re-partition length scan is skipped.
        return any(
            pipeline.device_steps[i].type == "C4QualityFilter"
            for i in pipeline.phases[ph]
        )

    outcomes: List[ProcessingOutcome] = []
    n_phases = len(pipeline.phases)
    lockstep_t0 = time.perf_counter()
    # Cross-phase pre-pack handoff: pack futures for the next phase's full
    # survivor chunks, keyed (bucket, round), built while this phase's tail
    # rounds are still resolving.
    prepack_next: dict = {}
    # Speculative cross-phase dispatch (joint spec_depth > 0): entries
    # ``{"batch", "out", "fault"}`` keyed (bucket, round) for next-phase
    # rounds LAUNCHED at this phase's barrier, before the tail verdicts
    # resolved.  Chunks are only speculated once fully confirmed (a full
    # next_current chunk exists ⇒ its documents' phase membership is
    # resolved); the optimism lives in the piggybacked round COUNTS, which
    # include still-pending tail survivors and are voided with the
    # launches on any joint fault.  ``carried_schedule`` hands the
    # barrier-negotiated next-phase schedule across the phase edge.
    spec_next: dict = {}
    carried_schedule = None
    for phase in range(n_phases):
        # Exchange epochs advance with the negotiated phase sequence — a
        # piece of round state every process derives identically without
        # communicating (phases are negotiated in lockstep), which is what
        # lets KV exchange keys be namespaced deterministically instead of
        # by a process-local counter (see _ExchangeState).
        bump_exchange_epoch()
        last = phase == n_phases - 1
        rewrites = (not last) and phase_rewrites(phase)
        # State that must survive a gang reformation re-entry of this phase:
        # resolved rounds' outcomes/survivors stand (outcomes, next_current,
        # degraded only ever grow), and the pre-pack handoff for the NEXT
        # phase keys on next_current chunk indexes, which are persistent.
        next_current: dict = {b: [] for b in buckets}
        next_over: List[TextDocument] = []
        prepack_done = {b: 0 for b in buckets}
        inherited = prepack_next  # this phase's pre-packed chunks
        prepack_next = {}
        # Speculative launches made FOR this phase at the previous barrier,
        # and the schedule negotiated there (piggybacked on the combined
        # barrier exchange) — both None'd out by a reformation, which
        # replays through the classic negotiation instead.
        spec_inflight = spec_next
        spec_next = {}
        carried = carried_schedule
        carried_schedule = None
        reformed = False
        while True:
            plan: Optional[List[tuple]] = None
            consumed: List[bool] = []
            try:
                # Admission sweep before any round launches: a posted join
                # request is observed here, at the phase boundary — the one
                # point with no rounds in flight — and a successful
                # admission raises GangReformed into the handler below, so
                # the re-entry re-negotiates the window depth over the
                # grown gang exactly as a shrink reformation would.
                if carried is not None:
                    # The previous phase's speculative barrier already
                    # negotiated this phase's schedule (round counts
                    # piggybacked on the tail verdict post) and ran the
                    # admission sweep off the same vector — re-posting
                    # either here would break the lockstep exchange
                    # sequence, since peers carried too.
                    schedule = carried
                    carried = None
                else:
                    maybe_admit_joiners()
                    if reformed:
                        # Survivor re-entry: re-negotiate the window depth
                        # (and speculation depth) over the reformed gang (a
                        # member with a different local depth may have
                        # died).  Fault-free runs never take this branch,
                        # so the exchange sequence they emit is unchanged;
                        # the reformation itself already bumped the
                        # exchange epoch, so no re-bump here.
                        depth, spec_depth = _negotiate_depth(
                            local_depth, local_spec
                        )
                        reformed = False
                    needed_local = np.array(
                        [
                            math.ceil(len(current[b]) / local_for[b])
                            for b in buckets
                        ],
                        dtype=np.int32,
                    )
                    schedule = _negotiate_max(needed_local)
                if (
                    phase == 0
                    and rounds is not None
                    and int(schedule.sum()) > rounds
                ):
                    raise ValueError(
                        f"shard needs {int(schedule.sum())} rounds "
                        f"(local {int(needed_local.sum())}), got {rounds}"
                    )

                # The phase's launch plan, in the negotiated (bucket,
                # round) order every host shares.  The negotiated count
                # covers the local ceil by construction; a violation would
                # silently strand a tail chunk once launches run ahead of
                # resolves, so fail loudly instead.
                plan = []
                for b, n_rounds in zip(buckets, schedule):
                    local_batch = local_for[b]
                    assert int(n_rounds) * local_batch >= len(current[b]), (
                        f"bucket {b}: negotiated {int(n_rounds)} round(s) "
                        f"of {local_batch} rows cannot cover "
                        f"{len(current[b])} local documents — geometry "
                        "round-up stranded a tail chunk"
                    )
                    for r in range(int(n_rounds)):
                        plan.append(
                            (
                                b,
                                r,
                                current[b][
                                    r * local_batch : (r + 1) * local_batch
                                ],
                            )
                        )
                consumed = [False] * len(plan)
                packs: dict = {}  # plan index -> PackedBatch (or future)

                def ensure_packed(j, plan=plan, packs=packs):
                    """Keep rounds j..j+K packed (or packing) ahead of the
                    launch cursor; cross-phase pre-packed chunks are
                    adopted as-is."""
                    for k in range(j, min(j + depth + 1, len(plan))):
                        if k in packs:
                            continue
                        kb, kr, kchunk = plan[k]
                        if (kb, kr) in spec_inflight:
                            # Speculatively launched at the previous
                            # barrier: the packed batch lives in the spec
                            # entry and is adopted at this round's launch
                            # slot — packing it again would be pure waste.
                            continue
                        pre = inherited.pop((kb, kr), None)
                        if pre is not None:
                            packs[k] = pre
                        elif pool is not None:
                            packs[k] = pool.submit(
                                pipeline._timed_pack, kchunk,
                                batch_size=local_for[kb], max_len=kb,
                            )
                        else:
                            packs[k] = pipeline._timed_pack(
                                kchunk, batch_size=local_for[kb], max_len=kb
                            )

                def absorb(src_bucket, alive):
                    """Fold one resolved round's survivors into the next
                    phase — incrementally, in resolve order (== the old
                    flat-list partition order), so full next-phase chunks
                    can pack while this phase still has rounds in flight
                    (the next ``_negotiate_max`` needs only the final
                    counts, exchanged after the drain as before)."""
                    if last:
                        return
                    if rewrites:
                        # Survivor content may have been rewritten (C4) —
                        # re-route by current length.  Growth past every
                        # bucket is impossible (rewrites only drop chars),
                        # but route defensively anyway.
                        for d in alive:
                            for nb in buckets:
                                if len(d.content) <= nb - PACK_MARGIN:
                                    next_current[nb].append(d)
                                    break
                            else:
                                next_over.append(d)
                    else:
                        next_current[src_bucket].extend(alive)
                    if pool is None:
                        return
                    for nb in buckets if rewrites else (src_bucket,):
                        lb = local_for[nb]
                        k = prepack_done[nb]
                        # A full chunk's document prefix is final once
                        # appended (later resolves only extend the list),
                        # so it can pack now.
                        while (k + 1) * lb <= len(next_current[nb]):
                            prepack_next[(nb, k)] = pool.submit(
                                pipeline._timed_pack,
                                next_current[nb][k * lb : (k + 1) * lb],
                                batch_size=lb, max_len=nb,
                            )
                            k += 1
                        prepack_done[nb] = k

                window: deque = deque()

                def void_speculation():
                    """Joint rollback of every speculative launch: this
                    phase's not-yet-adopted entries and the next phase's
                    barrier launches discard their results (the packed
                    batches stay — chunk contents are final) and
                    re-dispatch fresh, on every host identically, because
                    the verdict that triggers the void is allgathered.
                    The cross-barrier extension of the window drain's
                    first-fault-authoritative contract."""
                    n = sum(
                        1
                        for e in list(spec_inflight.values())
                        + list(spec_next.values())
                        if e["out"] is not None or e["fault"]
                    )
                    for e in list(spec_inflight.values()) + list(
                        spec_next.values()
                    ):
                        e["out"] = None
                        e["fault"] = False
                    if n:
                        METRICS.inc("multihost_voided_rounds_total", n)
                        TRACER.instant(
                            "window_drained",
                            {"replayed": 0, "pending": 0, "voided": n,
                             "phase": phase, "cause": "speculation_void"},
                        )
                        if EVENTS.enabled:
                            EVENTS.emit("speculation_void", voided=n,
                                        phase=phase, cause="drain")

                def drain_window():
                    """Joint fault verdict convened at the window front:
                    discard this host's launched-ahead results so every
                    host's program order after the verdict is the same
                    ``[retry(r), r+1, ...]`` — the younger rounds
                    re-dispatch fresh at their own resolve.  Speculative
                    launches are part of the launched-ahead state and void
                    with the window."""
                    n = sum(
                        1 for e in window if e["out"] is not None or e["fault"]
                    )
                    for e in window:
                        e["out"] = None
                        e["fault"] = False
                    if n:
                        METRICS.inc(
                            "multihost_window_replayed_rounds_total", n
                        )
                    TRACER.instant(
                        "window_drained",
                        {"replayed": n, "pending": len(window),
                         "phase": phase, "cause": "fault"},
                    )
                    void_speculation()

                def resolve_front():
                    """Block for the OLDEST in-flight round and assemble it
                    — under the negotiated verdict protocol when the guard
                    is on.  Strict FIFO at every depth: the window moves
                    waits, never sequence."""
                    entry = window.popleft()
                    TRACER.counter("lockstep_window", len(window))
                    local, ph, eb = (
                        entry["batch"], entry["phase"], entry["bucket"]
                    )
                    t0 = time.perf_counter()
                    try:
                        with TRACER.span(
                            "lockstep_resolve", {"bucket": eb, "phase": ph}
                        ):
                            rows = local.batch_size
                            if guard is None:
                                stats = _timed_stats(
                                    entry["out"], eb, ph, rows
                                )
                            else:
                                stats = guard.run_round(
                                    eb,
                                    dispatch=lambda: (
                                        pipeline.dispatch_lockstep(
                                            local, ph, sh2, sh1
                                        )
                                    ),
                                    fetch=lambda out: _timed_stats(
                                        out, eb, ph, rows
                                    ),
                                    inflight=entry["out"],
                                    launch_fault=entry["fault"],
                                    on_fault=drain_window,
                                )
                                if stats is None:
                                    # Jointly degraded: every host routes
                                    # this round's chunk to the host
                                    # oracle; none re-enters the program.
                                    degraded.extend(local.docs)
                                    consumed[entry["plan_idx"]] = True
                                    return
                            po, alive = pipeline.assemble_phase(
                                local, stats, ph
                            )
                            outcomes.extend(po)
                            absorb(eb, alive)
                            consumed[entry["plan_idx"]] = True
                    finally:
                        METRICS.inc(
                            "multihost_window_stall_seconds_total",
                            time.perf_counter() - t0,
                        )

                def resolve_batch(n):
                    """Drain the ``n`` oldest in-flight rounds under ONE
                    batched verdict post (``NegotiatedGuard.
                    negotiate_batch``): every round's local flag is fetched
                    first, then all flags ride a single allgather vector
                    instead of one scalar post each.  ``n`` is derived from
                    the negotiated plan and depth, so every host batches
                    the identical rounds.  With no guard or a single round
                    this IS ``resolve_front`` — depth-1 behavior stays
                    byte-identical by construction.  On the first joint
                    fault the younger rounds' piggybacked flags are void
                    (measured on launched-ahead state the drain discards):
                    they return to the window, the faulted round re-enters
                    the serial retry protocol with its verdict pre-resolved
                    (``prior_fault``), and the remainder resolves
                    round-at-a-time — the exact drain ordering of the
                    unbatched path."""
                    if guard is None or n <= 1:
                        for _ in range(n):
                            resolve_front()
                        return
                    entries = [window.popleft() for _ in range(n)]
                    TRACER.counter("lockstep_window", len(window))
                    t0 = time.perf_counter()
                    faults, stats_list = [], []
                    for entry in entries:
                        fault, st = bool(entry["fault"]), None
                        if not fault:
                            try:
                                if entry["out"] is None:
                                    # Voided by a mid-phase drain: nothing
                                    # is in flight, so re-dispatch fresh at
                                    # the resolve — the batched analogue of
                                    # resolve_front's ``inflight=None``
                                    # path (the voided set is joint, so
                                    # every host re-dispatches the same
                                    # rounds here, in the same order).
                                    entry["out"] = pipeline.dispatch_lockstep(
                                        entry["batch"], entry["phase"],
                                        sh2, sh1,
                                    )
                                st = _timed_stats(
                                    entry["out"],
                                    entry["bucket"],
                                    entry["phase"],
                                    entry["batch"].batch_size,
                                )
                            except BaseException as e:  # noqa: BLE001
                                if classify_error(e) != "retryable":
                                    raise
                                WATCHDOG.escalated(e)
                                fault = True
                        faults.append(fault)
                        stats_list.append(st)
                    verdicts = guard.negotiate_batch(faults)
                    METRICS.inc(
                        "multihost_window_stall_seconds_total",
                        time.perf_counter() - t0,
                    )
                    for i, entry in enumerate(entries):
                        local, ph, eb = (
                            entry["batch"], entry["phase"], entry["bucket"]
                        )
                        if verdicts[i]:
                            # Younger rounds rejoin the window BEFORE the
                            # drain hook fires, so the joint drain clears
                            # exactly the launched-ahead set the unbatched
                            # path would have cleared.
                            for e in reversed(entries[i + 1:]):
                                window.appendleft(e)
                            TRACER.counter("lockstep_window", len(window))
                            with TRACER.span(
                                "lockstep_resolve",
                                {"bucket": eb, "phase": ph},
                            ):
                                stats = guard.run_round(
                                    eb,
                                    dispatch=lambda local=local, ph=ph: (
                                        pipeline.dispatch_lockstep(
                                            local, ph, sh2, sh1
                                        )
                                    ),
                                    fetch=lambda out, eb=eb, ph=ph, rows=(
                                        local.batch_size
                                    ): _timed_stats(out, eb, ph, rows),
                                    on_fault=drain_window,
                                    prior_fault=True,
                                    prior_local_fault=faults[i],
                                )
                                if stats is None:
                                    degraded.extend(local.docs)
                                else:
                                    po, alive = pipeline.assemble_phase(
                                        local, stats, ph
                                    )
                                    outcomes.extend(po)
                                    absorb(eb, alive)
                                consumed[entry["plan_idx"]] = True
                            while window:
                                resolve_front()
                            return
                        with TRACER.span(
                            "lockstep_resolve", {"bucket": eb, "phase": ph}
                        ):
                            guard.record_round_success(eb)
                            po, alive = pipeline.assemble_phase(
                                local, stats_list[i], ph
                            )
                            outcomes.extend(po)
                            absorb(eb, alive)
                            consumed[entry["plan_idx"]] = True

                def launch_speculative():
                    """Launch up to ``spec_depth`` of the NEXT phase's
                    confirmed survivor chunks while this phase's tail
                    verdicts are still unresolved — the device computes
                    phase p+1 rounds across the barrier instead of idling
                    through the drain.

                    Only fully-confirmed chunks launch: a complete
                    ``next_current`` chunk exists only once every document
                    in it resolved its phase-p membership, so the LAUNCHED
                    work is never optimistic — the optimism lives in the
                    piggybacked round counts, which include still-pending
                    tail survivors.  Per-host launch counts may differ
                    (chunk confirmation progress is local); that is sound
                    for the collective-free programs this build compiles,
                    the same residual-risk stance resilience/negotiated.py
                    documents for fetches.  Voided entries (``out=None``)
                    re-launch here on the barrier's next pass, after the
                    joint drain."""
                    if spec_depth <= 0 or pool is None:
                        return
                    in_flight = sum(
                        1 for e in spec_next.values()
                        if e["out"] is not None or e["fault"]
                    )
                    for nb in buckets:
                        if guard is not None and guard.bucket_degraded(nb):
                            continue
                        for k in range(prepack_done[nb]):
                            if in_flight >= spec_depth:
                                return
                            key = (nb, k)
                            e = spec_next.get(key)
                            if e is None:
                                fut = prepack_next.pop(key, None)
                                if fut is None:
                                    continue
                                if hasattr(fut, "result"):
                                    if WATCHDOG.enabled:
                                        WATCHDOG.wait("pack_wait", fut.done)
                                    fut = fut.result()
                                e = {
                                    "batch": fut,
                                    "out": None,
                                    "fault": False,
                                }
                                spec_next[key] = e
                            elif e["out"] is not None or e["fault"]:
                                continue
                            with TRACER.span(
                                "lockstep_speculate",
                                {"bucket": nb, "round": k,
                                 "phase": phase + 1},
                            ):
                                out, fault = launch(
                                    e["batch"], phase + 1, speculative=True
                                )
                            e["out"], e["fault"] = out, fault
                            METRICS.inc(
                                "multihost_speculated_rounds_total"
                            )
                            in_flight += 1

                def resolve_barrier():
                    """Speculative phase barrier: resolve the tail rounds,
                    sweep join admission, and negotiate the next phase's
                    schedule — all on ONE exchange post — with up to
                    ``spec_depth`` next-phase rounds launched before the
                    tail verdicts convene.

                    The combined vector is ``[tail fault flags | join
                    lanes | next-phase round counts]``; every section's
                    presence is derived from shared state (guard
                    configured, transport admission-capable, phase
                    previewable), so the width is identical on every host.
                    The counts are optimistic — each host projects its
                    tail survivors via ``preview_phase_survivors`` — and
                    the first-fault-authoritative contract extends across
                    the barrier: ANY fault verdict voids the speculative
                    launches AND the freight on every host, the faulted
                    round re-enters the serial retry protocol
                    (``prior_fault``), the remainder drains
                    round-at-a-time, and the barrier re-posts fresh.
                    Returns the negotiated next-phase schedule, carried
                    into the next phase instead of its classic
                    ``maybe_admit_joiners`` + ``_negotiate_max`` posts.
                    Phases without a batch verdict mask (badwords) cannot
                    preview: the schedule then posts separately after
                    assembly — two posts instead of one, still never
                    three."""
                    previewable = (
                        not rewrites and pipeline.phase_previewable(phase)
                    )
                    collect = getattr(
                        _EXCHANGE.transport, "collect_join_lanes", None
                    )
                    while True:
                        launch_speculative()
                        n_tail = len(window)
                        entries = [window.popleft() for _ in range(n_tail)]
                        TRACER.counter("lockstep_window", 0)
                        t0 = time.perf_counter()
                        faults, stats_list = [], []
                        for entry in entries:
                            fault, st = bool(entry["fault"]), None
                            if not fault:
                                if guard is None:
                                    st = _timed_stats(
                                        entry["out"], entry["bucket"],
                                        entry["phase"],
                                        entry["batch"].batch_size,
                                    )
                                else:
                                    try:
                                        if entry["out"] is None:
                                            # Voided by a mid-phase drain:
                                            # re-dispatch fresh, jointly
                                            # (see resolve_batch).
                                            entry["out"] = (
                                                pipeline.dispatch_lockstep(
                                                    entry["batch"],
                                                    entry["phase"],
                                                    sh2, sh1,
                                                )
                                            )
                                        st = _timed_stats(
                                            entry["out"], entry["bucket"],
                                            entry["phase"],
                                            entry["batch"].batch_size,
                                        )
                                    except BaseException as e:  # noqa: BLE001
                                        if classify_error(e) != "retryable":
                                            raise
                                        WATCHDOG.escalated(e)
                                        fault = True
                            faults.append(fault)
                            stats_list.append(st)
                        proj = None
                        counts = None
                        if previewable:
                            proj = {
                                b: len(next_current[b]) for b in buckets
                            }
                            for i, entry in enumerate(entries):
                                if not faults[i]:
                                    proj[entry["bucket"]] += (
                                        pipeline.preview_phase_survivors(
                                            entry["batch"],
                                            stats_list[i],
                                            phase,
                                        )
                                    )
                            counts = [
                                math.ceil(proj[b] / local_for[b])
                                for b in buckets
                            ]
                        lanes = collect() if collect is not None else None
                        freight = (
                            list(lanes) if lanes is not None else []
                        ) + (counts if counts is not None else [])
                        if guard is not None:
                            verdicts, rows = guard.negotiate_freight(
                                faults, freight
                            )
                            posts = 1
                        elif freight:
                            rows = host_allgather(
                                np.asarray(freight, dtype=np.int64)
                            )
                            verdicts = [False] * n_tail
                            posts = 1
                        else:
                            rows, verdicts, posts = None, [], 0
                        METRICS.inc(
                            "multihost_window_stall_seconds_total",
                            time.perf_counter() - t0,
                        )
                        first = next(
                            (i for i, v in enumerate(verdicts) if v), None
                        )
                        if first is not None:
                            # Joint rollback: speculative launches and
                            # piggybacked freight void together, on every
                            # host (the counts were measured on tail state
                            # the drain is about to discard).
                            void_speculation()
                            for k in range(first):
                                entry = entries[k]
                                eb = entry["bucket"]
                                with TRACER.span(
                                    "lockstep_resolve",
                                    {"bucket": eb, "phase": phase},
                                ):
                                    guard.record_round_success(eb)
                                    po, alive = pipeline.assemble_phase(
                                        entry["batch"], stats_list[k],
                                        phase,
                                    )
                                    outcomes.extend(po)
                                    absorb(eb, alive)
                                    consumed[entry["plan_idx"]] = True
                            for e in reversed(entries[first + 1:]):
                                window.appendleft(e)
                            TRACER.counter("lockstep_window", len(window))
                            entry = entries[first]
                            local, eb = entry["batch"], entry["bucket"]
                            with TRACER.span(
                                "lockstep_resolve",
                                {"bucket": eb, "phase": phase},
                            ):
                                stats = guard.run_round(
                                    eb,
                                    dispatch=lambda local=local: (
                                        pipeline.dispatch_lockstep(
                                            local, phase, sh2, sh1
                                        )
                                    ),
                                    fetch=lambda out, eb=eb, rows_n=(
                                        local.batch_size
                                    ): _timed_stats(
                                        out, eb, phase, rows_n
                                    ),
                                    on_fault=drain_window,
                                    prior_fault=True,
                                    prior_local_fault=faults[first],
                                )
                                if stats is None:
                                    degraded.extend(local.docs)
                                else:
                                    po, alive = pipeline.assemble_phase(
                                        local, stats, phase
                                    )
                                    outcomes.extend(po)
                                    absorb(eb, alive)
                                consumed[entry["plan_idx"]] = True
                            while window:
                                resolve_front()
                            # Re-post a fresh barrier exchange: voided
                            # speculative launches re-dispatch first, and
                            # lanes/counts re-measure post-drain.
                            continue
                        for k, entry in enumerate(entries):
                            eb = entry["bucket"]
                            with TRACER.span(
                                "lockstep_resolve",
                                {"bucket": eb, "phase": phase},
                            ):
                                if guard is not None:
                                    guard.record_round_success(eb)
                                po, alive = pipeline.assemble_phase(
                                    entry["batch"], stats_list[k], phase
                                )
                                outcomes.extend(po)
                                absorb(eb, alive)
                                consumed[entry["plan_idx"]] = True
                        TRACER.instant(
                            "window_drained",
                            {"replayed": 0, "pending": 0, "phase": phase,
                             "cause": "barrier"},
                        )
                        if proj is not None:
                            for b in buckets:
                                assert len(next_current[b]) == proj[b], (
                                    f"bucket {b}: barrier preview "
                                    f"projected {proj[b]} next-phase "
                                    f"documents, assembly produced "
                                    f"{len(next_current[b])} — "
                                    "preview_phase_survivors drifted from "
                                    "assemble_phase"
                                )
                        off = _JOIN_LANES if lanes is not None else 0
                        if lanes is not None:
                            # May raise GangReformed (admission) into the
                            # phase handler — safe here: every tail round
                            # above is consumed, so the replayed plan is
                            # empty and the barrier re-runs over the grown
                            # gang with fresh lanes.
                            _EXCHANGE.transport.admit_union(
                                [int(x) for x in rows[:, :off].ravel()]
                            )
                        if counts is not None:
                            sched = (
                                rows[:, off:off + len(buckets)]
                                .max(axis=0)
                                .astype(np.int32)
                            )
                        else:
                            # A step without a batch verdict mask
                            # (badwords) blocks the survivor preview: the
                            # schedule needs post-assembly counts — one
                            # extra post, still fewer than the classic
                            # three.
                            sched = _negotiate_max(
                                np.array(
                                    [
                                        math.ceil(
                                            len(next_current[b])
                                            / local_for[b]
                                        )
                                        for b in buckets
                                    ],
                                    dtype=np.int32,
                                )
                            )
                            posts += 1
                        # Posts the classic barrier would have made: the
                        # tail verdict batch, the admission sweep (only
                        # when a multi-member gang runs one), and the
                        # next-phase schedule.
                        baseline = (
                            (1 if guard is not None and n_tail >= 1 else 0)
                            + (
                                1
                                if lanes is not None
                                and rows is not None
                                and rows.shape[0] > 1
                                else 0
                            )
                            + 1
                        )
                        if baseline > posts:
                            METRICS.inc(
                                "multihost_barrier_elisions_total",
                                baseline - posts,
                            )
                        return sched

                for j, (b, r, chunk) in enumerate(plan):
                    if guard is not None and guard.bucket_degraded(b):
                        # Breaker latched on negotiated verdicts, so every
                        # host reaches the same conclusion at the same
                        # round and the dispatch is skipped jointly —
                        # lockstep preserved without touching the device.
                        METRICS.inc(
                            "resilience_negotiated_degraded_rounds_total"
                        )
                        TRACER.instant(
                            "negotiated_bucket_latched",
                            {"bucket": b, "round": r, "phase": phase},
                        )
                        packs.pop(j, None)
                        se = spec_inflight.pop((b, r), None)
                        if se is not None and (
                            se["out"] is not None or se["fault"]
                        ):
                            # Bucket latched between the speculative launch
                            # and its adoption slot (a tail degradation at
                            # the same barrier): the result is discarded
                            # jointly, like any other voided speculation.
                            METRICS.inc("multihost_voided_rounds_total")
                            TRACER.instant(
                                "window_drained",
                                {"replayed": 0, "pending": 0, "voided": 1,
                                 "phase": phase,
                                 "cause": "speculation_void"},
                            )
                            if EVENTS.enabled:
                                EVENTS.emit("speculation_void", voided=1,
                                            phase=phase,
                                            cause="bucket_latch")
                        degraded.extend(chunk)
                        consumed[j] = True
                        continue
                    ensure_packed(j)
                    with TRACER.span(
                        "lockstep_round",
                        {"bucket": b, "round": r, "phase": phase,
                         "rows": len(chunk)},
                    ):
                        se = spec_inflight.pop((b, r), None)
                        if se is not None:
                            # Adopt the speculative launch at its plan
                            # slot: occupancy books here (once per round,
                            # like every round), and a voided entry simply
                            # re-dispatches fresh — byte-identical either
                            # way, the speculation only moved the launch.
                            local = se["batch"]
                            record_occupancy(local)
                            if se["out"] is None and not se["fault"]:
                                out, fault = launch(local, phase)
                            else:
                                out, fault = se["out"], se["fault"]
                        else:
                            item = packs.pop(j)
                            if hasattr(item, "result"):
                                if WATCHDOG.enabled:
                                    WATCHDOG.wait("pack_wait", item.done)
                                local = item.result()
                            else:
                                local = item
                            record_occupancy(local)
                            out, fault = launch(local, phase)
                    window.append({
                        "batch": local, "bucket": b, "phase": phase,
                        "out": out, "fault": fault, "plan_idx": j,
                    })
                    TRACER.counter("lockstep_window", len(window))
                    while len(window) > depth:
                        resolve_front()
                if not last and spec_depth > 0:
                    carried_schedule = resolve_barrier()
                else:
                    resolve_batch(len(window))
                    TRACER.instant(
                        "window_drained",
                        {"replayed": 0, "pending": 0, "phase": phase,
                         "cause": "barrier"},
                    )
                break
            except GangReformed:
                # Resume at the next round boundary over the survivor set:
                # every resolved round stands (its outcomes and survivors
                # are already folded), and the unconsumed plan chunks — in
                # flight, launched ahead, or never launched — are stitched
                # back into ``current`` in plan order, so the replayed plan
                # re-chunks them at identical boundaries (consumed rounds
                # form a plan-order prefix per bucket; breaker-latched
                # skips route to the host oracle either way).
                if plan is not None:
                    for b in buckets:
                        current[b] = []
                    for j, (b, _r, chunk) in enumerate(plan):
                        if not consumed[j]:
                            current[b].extend(chunk)
                # Pre-packs inherited from the previous phase key on the
                # abandoned plan's round numbering — drop them and pack
                # fresh (futures are pure; unused results are garbage).
                inherited = {}
                # Speculative launches do not survive a reformation: the
                # exchange epoch moved and the replayed plan renumbers its
                # rounds.  Entries for THIS phase (keyed on the abandoned
                # plan) drop entirely and re-pack fresh; entries for the
                # next phase (keyed on persistent next_current chunk
                # indexes) keep their packed batches and re-dispatch at
                # the replayed barrier.
                n_void = sum(
                    1
                    for e in list(spec_inflight.values())
                    + list(spec_next.values())
                    if e["out"] is not None or e["fault"]
                )
                if n_void:
                    METRICS.inc("multihost_voided_rounds_total", n_void)
                    TRACER.instant(
                        "window_drained",
                        {"replayed": 0, "pending": 0, "voided": n_void,
                         "phase": phase, "cause": "speculation_void"},
                    )
                    if EVENTS.enabled:
                        EVENTS.emit("speculation_void", voided=n_void,
                                    phase=phase, cause="reformation")
                spec_inflight = {}
                for e in spec_next.values():
                    e["out"] = None
                    e["fault"] = False
                carried = None
                reformed = True
        if last:
            break
        fallback.extend(next_over)
        current = next_current
    METRICS.inc(
        "multihost_lockstep_seconds_total",
        time.perf_counter() - lockstep_t0,
    )

    for d in fallback:
        METRICS.inc("worker_host_fallback_total")
        o = execute_processing_pipeline(pipeline.host_executor, d)
        if o is not None:
            outcomes.append(o)
    if degraded:
        # Degraded rounds re-run start to finish on the bit-exact host
        # oracle (mid-phase re-stamp contract, ops/pipeline.py _host_rerun),
        # so outcomes stay byte-identical to a fault-free run.
        outcomes.extend(pipeline._host_rerun(degraded))
    return outcomes


def run_multihost(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    *,
    coordinator: str,
    num_processes: int,
    process_id: int,
    text_column: str = "text",
    id_column: str = "id",
    buckets: Sequence[int] = (512, 2048, 8192),
    read_batch_size: int = 1024,
    device_batch: Optional[int] = None,
    auto_geometry: bool = False,
    errors_file: Optional[str] = None,
    force: bool = False,
    run_report: Optional[str] = None,
    provenance: Optional[dict] = None,
    exchange_deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S,
    lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
    elastic: bool = False,
    exchange_transport: str = "auto",
    survive_peer_loss: bool = False,
    autoscale: Optional[str] = None,
):
    """Production multi-host entry (``textblast run --coordinator ...``).

    ``run_report`` (must be passed on EVERY process or on none — the
    snapshot exchange is a collective) makes each process contribute its
    metrics-delta snapshot over :func:`host_allgather_obj` after the totals
    barrier; process 0 writes a merged run report to that path with both
    the per-host snapshots and the summed totals.  ``provenance`` is the
    config-provenance dict embedded in the report.

    Each process reads its contiguous row stripe of ``input_file`` (the
    static shard assignment SURVEY.md §2.5 maps the task queue onto), runs
    the negotiated lockstep schedule, and writes a per-host
    ``<output>.shard<i>`` / ``<excluded>.shard<i>`` Parquet pair (plus an
    ``<errors>.shard<i>`` dead-letter shard when ``errors_file`` is given —
    the per-host slice of PR 1's sink).  After a global barrier, process 0
    merges each shard set into its final file **atomically**
    (:func:`merge_shard_files`: tmp + fsync + rename, shards deleted only
    after every rename lands) — the results-queue aggregation analogue,
    producer_logic.rs:109-196.  Stale ``*.shard*`` leftovers from a crashed
    run with different ``--num-processes`` fail the run fast on every
    process unless ``force`` removes them.

    Returns an ``AggregationResult``: global totals on process 0 (after the
    merge), local totals elsewhere.

    Failure behavior (measured, tests/test_multihost.py +
    tests/test_multihost_chaos.py + tests/test_elastic_membership.py): a
    *retryable device fault* on any host no longer kills the job —
    ``run_local_shard``'s negotiated guard retries the round jointly on
    every host and, past the budget, degrades it to the host oracle jointly
    (outcomes stay byte-identical).  If a process *dies* mid-run, survivors
    do not wait forever on the next exchange: every KV-transport allgather
    is bounded by ``exchange_deadline_s`` and on expiry raises a typed
    :exc:`PeerFailure` naming the exchange coordinates and every rank that
    never posted, with dead-versus-slow resolved against the renewable KV
    liveness leases each process maintains (TTL ``lease_ttl_s``, renewed by
    a daemon heartbeat at TTL/3).  The accelerator collective path carries
    no host-side deadline — there, and for deadlines configured beyond it,
    the jax coordination-service heartbeat teardown (~90 s, UNAVAILABLE to
    every healthy task) remains the backstop.  After a ``PeerFailure`` the
    lockstep run is re-launched whole — the lockstep contract cannot
    reshape a live gang.

    ``elastic=True`` trades the lockstep contract for membership that can
    shrink, grow, and restart in place (:func:`_run_elastic`): processes
    coordinate through renewable leases and per-stripe checkpoint cursors
    on the shared filesystem instead of ``jax.distributed`` collectives,
    survivors adopt a dead rank's stripe at the membership-epoch bump, and
    a relaunched rank rejoins mid-run resuming from the committed cursor —
    replaying zero completed chunks, with outcomes byte-identical to a
    fault-free run.  A brand-new rank (``process_id >= num_processes``)
    scales the gang OUT mid-run: it posts a join request next to its
    lease, the members admit it on observation, and
    :func:`~textblaster_tpu.resilience.membership.assign_stripes` moves a
    pending stripe to it (the donor fences at its next committed chunk,
    the joiner adopts the cursor — dead-stripe adoption in reverse).
    ``run_report`` is supported (the merging rank folds per-rank report
    shards into the merged v4 report; an aborted run leaves a partial
    report, like the kv path); ``auto_geometry`` stays incompatible (a
    full-gang collective with no lockstep exchange to ride).
    ``autoscale="MIN:MAX"`` arms the supervisor loop on the lowest live
    home rank: joiners are spawned under sustained backlog and drain
    (fence-and-leave) at idle.

    ``exchange_transport`` / ``survive_peer_loss`` (PR 10): with the
    ``file`` transport (:class:`FileLeaseTransport`; ``auto`` resolves to
    it iff ``survive_peer_loss``) the lockstep exchanges ride shared-
    filesystem slots next to the membership leases instead of
    ``jax.distributed`` — which is never initialized on this path, because
    the coordination service force-terminates every healthy task ~90-100 s
    after a peer death and would undercut survival from below.  Each
    process then runs its full-width local-device mesh (exactly the
    multi-process CPU fallback :func:`global_data_mesh` already takes; the
    compiled programs are collective-free either way — on accelerator pods
    this trades the cross-host XLA mesh for survivability).  Under
    ``survive_peer_loss`` a peer death mid-exchange triggers gang
    reformation instead of gang death: survivors fence the dead rank's
    incarnation, elect the new member set at a bumped membership epoch,
    replay the interrupted exchange, and the lowest live rank adopts the
    dead rank's stripe through :meth:`CheckpointState.adopt` — the final
    merged outputs stay byte-identical to a fault-free run.  Keeps the
    lockstep contract (unlike ``elastic``) and therefore keeps
    ``run_report``/``auto_geometry``.
    """
    import os
    from itertools import islice

    import pyarrow.parquet as pq

    from ..errors import PipelineError
    from ..orchestration import (
        AggregationResult,
        aggregate_results_from_stream,
        read_documents,
    )
    from ..resilience import DeadLetterSink
    from ..resilience.faults import arm_from_env
    from ..utils.metrics import (
        METRICS,
        build_run_report,
        metrics_snapshot,
        write_run_report,
    )

    finals = [output_file, excluded_file]
    if errors_file is not None:
        finals.append(errors_file)
    stale = detect_stale_shards(finals, num_processes)
    if stale:
        if not force:
            # Checked on EVERY process before joining the coordinator, so
            # the whole gang exits fast instead of one host discovering the
            # problem after the run.
            raise PipelineError(
                "stale shard files from a previous run would be ignored by "
                f"the merge: {', '.join(stale)} — remove them or pass "
                "--force to overwrite"
            )
        for s in stale:
            try:
                os.remove(s)
            except FileNotFoundError:
                pass  # a peer on a shared filesystem got there first
            else:
                METRICS.inc("multihost_stale_shards_removed_total")

    transport_name = resolve_exchange_transport(
        exchange_transport, survive_peer_loss
    )
    if elastic and (survive_peer_loss or transport_name == "file"):
        raise PipelineError(
            "--elastic is incompatible with --survive-peer-loss and "
            "--exchange-transport file: elastic membership deliberately has "
            "no lockstep exchanges for the transport to carry"
        )
    if transport_name == "file" and exchange_deadline_s <= lease_ttl_s:
        raise PipelineError(
            f"--exchange-deadline-s ({exchange_deadline_s:g}s) must exceed "
            f"--lease-ttl-s ({lease_ttl_s:g}s): with the exchange deadline "
            "at or under the lease TTL, every slow lease renewal is "
            "misclassified as a peer death"
        )

    if autoscale is not None and not elastic:
        raise PipelineError(
            "--autoscale requires --elastic: the supervisor spawns and "
            "drains joiner ranks through the elastic membership protocol"
        )
    if elastic:
        if auto_geometry:
            raise PipelineError(
                "--elastic is incompatible with --auto-geometry: geometry "
                "negotiation is a full-gang collective, and elastic "
                "membership deliberately has no lockstep exchanges to "
                "carry it"
            )
        return _run_elastic(
            config,
            input_file,
            output_file,
            excluded_file,
            num_processes=num_processes,
            process_id=process_id,
            text_column=text_column,
            id_column=id_column,
            buckets=buckets,
            read_batch_size=read_batch_size,
            device_batch=device_batch,
            errors_file=errors_file,
            lease_ttl_s=lease_ttl_s,
            force=force,
            run_report=run_report,
            provenance=provenance,
            autoscale=autoscale,
        )

    heartbeat = None
    file_transport = None
    membership_store = None
    membership_root = f"{output_file}.membership"
    if transport_name == "file":
        # The file transport deliberately does NOT initialize
        # jax.distributed: the coordination service force-terminates every
        # healthy task ~90-100 s after a peer stops heartbeating (measured
        # on this stack — the motivation for _run_elastic's identical
        # choice), which would undercut --survive-peer-loss from below.
        # The gang is coupled only through the membership dir on the shared
        # filesystem; jax.process_count() stays 1, so global_data_mesh()
        # hands every process its full-width local mesh — exactly the
        # multi-process CPU fallback, with collective-free programs.
        import shutil

        if force and os.path.isdir(membership_root):
            shutil.rmtree(membership_root, ignore_errors=True)
        membership_store = FileMembershipStore(
            membership_root, process_id, lease_ttl_s
        )
        membership_store.register()
        heartbeat = LeaseHeartbeat(
            membership_store, max(0.05, lease_ttl_s / 3.0)
        )
        heartbeat.start()
        file_transport = FileLeaseTransport(
            membership_store,
            process_id,
            num_processes,
            survive=survive_peer_loss,
            heartbeat=heartbeat,
        )
        arm_from_env(process_id=process_id)
        configure_exchange(
            deadline_s=exchange_deadline_s,
            lease_store=membership_store,
            transport=file_transport,
        )
        # Publish the launch roster (idempotent across ranks — every
        # writer posts identical content atomically): the membership view
        # a prospective joiner echoes in its admission election.
        membership_store.write_roster(
            file_transport.members(),
            file_transport.tracker.epoch,
            current_exchange_epoch(),
        )
        print(
            f"coordinated[{process_id}]: file-lease exchange transport at "
            f"{membership_root} (survive_peer_loss={survive_peer_loss}, "
            f"deadline {exchange_deadline_s:g}s, lease ttl {lease_ttl_s:g}s)",
            flush=True,
        )
    else:
        initialize(coordinator, num_processes, process_id)
        if jax.process_count() != num_processes:
            # Without this, a topology mismatch (typically jax.distributed
            # already initialized with different numbers) surfaces as a
            # hang or a shape error deep inside the first allgather.
            raise PipelineError(
                f"--num-processes {num_processes} does not match the "
                f"initialized distributed runtime "
                f"(jax.process_count()={jax.process_count()}); all "
                "processes must be launched with the same topology, and an "
                "existing jax.distributed initialization cannot be "
                "re-shaped"
            )
        arm_from_env(process_id=process_id)
        configure_exchange(deadline_s=exchange_deadline_s)
        if jax.process_count() > 1 and _distributed_initialized():
            # Liveness leases ride the same coordination-service KV store
            # the exchanges do, so an expired exchange deadline can tell
            # the user WHICH missing ranks are dead (lease expired) vs
            # merely slow.
            from jax._src import distributed

            client = getattr(distributed.global_state, "client", None)
            if client is not None:
                store = KVLeaseStore(client, process_id, lease_ttl_s)
                store.post()
                heartbeat = LeaseHeartbeat(
                    store, max(0.05, lease_ttl_s / 3.0)
                )
                heartbeat.start()
                configure_exchange(
                    deadline_s=exchange_deadline_s,
                    lease_store=store,
                    reset=False,
                )

    def _ride_reformations(fn):
        """Replay a lockstep closure until it completes without a gang
        reformation (at most num_processes-1 replays — each reformation
        permanently shrinks the member set).  On the kv transport
        GangReformed is never raised, so this is a transparent wrapper."""
        while True:
            try:
                return fn()
            except GangReformed:
                continue

    try:
        mesh = global_data_mesh()
        _ride_reformations(_align_trace_clocks)

        import time as _time

        # Run-report scope starts here: everything after distributed init is
        # this run's work, so the snapshot deltas attribute only it.
        values_before = metrics_snapshot() if run_report is not None else {}
        wall_t0 = _time.perf_counter()

        n_rows = pq.ParquetFile(input_file).metadata.num_rows
        stride = math.ceil(n_rows / max(num_processes, 1))
        skip = min(process_id * stride, n_rows)
        take = max(0, min(stride, n_rows - skip))

        # Per-host dead-letter shard, merged by process 0 exactly like
        # kept/excluded.  Created eagerly (DeadLetterSink writes the empty
        # file up front) so the merge never races a host that recorded
        # nothing.
        deadletter = (
            DeadLetterSink(f"{errors_file}.shard{process_id}")
            if errors_file is not None
            else None
        )

        read_errors = 0
        docs: List[TextDocument] = []
        stream = read_documents(
            input_file,
            text_column=text_column,
            id_column=id_column,
            batch_size=read_batch_size,
            skip_rows=skip,
        )
        for item in islice(stream, take):  # one stream item per Parquet row
            if isinstance(item, PipelineError):
                read_errors += 1
                if deadletter is not None:
                    deadletter.record_read_error(item)
            else:
                docs.append(item)

        from ..ops.pipeline import CompiledPipeline

        geometry = None
        if auto_geometry:
            # Geometry negotiation: each host histograms ITS shard's
            # document lengths over the fixed shape-stable bin edges, the
            # histograms are allgathered and summed elementwise, and every
            # host derives the geometry from the identical merged histogram
            # — so the lockstep round schedule (which depends on buckets
            # and batch sizes) stays in agreement without shipping raw
            # lengths across hosts.
            from ..ops.geometry import (
                geometry_from_histogram,
                length_histogram,
            )

            hist = length_histogram([len(d.content) for d in docs])
            folded_stripes: set = set()

            def _merged_hist():
                # Reformation during geometry negotiation: the adopter-to-
                # be (lowest live rank) folds each newly-dead stripe's
                # length histogram into its own before the replay, so the
                # merged histogram — and the geometry derived from it — is
                # identical to the fault-free gang's.
                nonlocal hist
                if file_transport is not None and file_transport.dead_ranks:
                    if process_id == min(file_transport.members()):
                        for r in sorted(set(file_transport.dead_ranks)):
                            if r in folded_stripes:
                                continue
                            folded_stripes.add(r)
                            skip_r = min(r * stride, n_rows)
                            take_r = max(0, min(stride, n_rows - skip_r))
                            lens = [
                                len(d.content)
                                for d in islice(
                                    read_documents(
                                        input_file,
                                        text_column=text_column,
                                        id_column=id_column,
                                        batch_size=read_batch_size,
                                        skip_rows=skip_r,
                                    ),
                                    take_r,
                                )
                                if not isinstance(d, PipelineError)
                            ]
                            hist = hist + length_histogram(lens)
                return host_allgather(hist).sum(axis=0)

            hist = _ride_reformations(_merged_hist)
            if hist.sum() > 0:
                geometry = geometry_from_histogram(
                    hist, backend=jax.default_backend()
                )

        pipeline = CompiledPipeline(
            config, buckets=tuple(sorted(buckets)), batch_size=device_batch,
            mesh=mesh, geometry=geometry,
        )
        from ..ops.pipeline import maybe_warmup

        # Warm ahead of the lockstep rounds (see run_local_shard): compile
        # stalls must not land mid-round where peers wait at the allgather.
        maybe_warmup(pipeline)
        try:
            outcomes = run_local_shard(
                config, docs, buckets=pipeline.geometry.buckets, mesh=mesh,
                pipeline=pipeline,
            )

            shard_out = f"{output_file}.shard{process_id}"
            shard_exc = f"{excluded_file}.shard{process_id}"
            result = aggregate_results_from_stream(
                iter(outcomes), shard_out, shard_exc, deadletter=deadletter
            )
        finally:
            # The shard must be complete on disk before the totals barrier
            # releases process 0 into the merge.
            if deadletter is not None:
                deadletter.close()
        result.read_errors = read_errors

        if file_transport is not None:
            return _finish_file_coordinated(
                config=config,
                input_file=input_file,
                output_file=output_file,
                excluded_file=excluded_file,
                errors_file=errors_file,
                finals=finals,
                text_column=text_column,
                id_column=id_column,
                read_batch_size=read_batch_size,
                num_processes=num_processes,
                process_id=process_id,
                n_rows=n_rows,
                stride=stride,
                mesh=mesh,
                pipeline=pipeline,
                result=result,
                file_transport=file_transport,
                membership_store=membership_store,
                membership_root=membership_root,
                run_report=run_report,
                provenance=provenance,
                values_before=values_before,
                wall_t0=wall_t0,
            )

        totals = np.array(
            [result.received, result.success, result.filtered,
             result.errors, result.read_errors],
            dtype=np.int64,
        )
        # Barrier doubling as the totals exchange: every process must have
        # closed its shard files before process 0 merges (host_allgather's
        # blocking gets release only once every peer has posted).
        all_totals = host_allgather(totals).reshape(-1, 5)

        # Cross-host metrics aggregation: one more lockstep exchange
        # carrying each process's metrics-delta snapshot (a few KiB of
        # JSON), so host 0's report survives the other processes' exit.
        # Runs on EVERY process or on none — see the docstring contract.
        host_reports = None
        if run_report is not None:
            from ..utils.metrics import snapshot_delta

            now = metrics_snapshot()
            local_delta = snapshot_delta(values_before, now)
            host_reports = host_allgather_obj(
                {
                    "process": process_id,
                    "wall_time_s": round(
                        _time.perf_counter() - wall_t0, 3
                    ),
                    "counts": {
                        "received": result.received,
                        "success": result.success,
                        "filtered": result.filtered,
                        "errors": result.errors,
                        "read_errors": result.read_errors,
                    },
                    "metrics": local_delta,
                }
            )

        if process_id == 0:
            merge_shard_files(
                [
                    (
                        final,
                        [f"{final}.shard{i}" for i in range(num_processes)],
                    )
                    for final in finals
                ]
            )
            g = all_totals.sum(axis=0)
            merged = AggregationResult()
            merged.received, merged.success, merged.filtered = (
                int(g[0]), int(g[1]), int(g[2])
            )
            merged.errors, merged.read_errors = int(g[3]), int(g[4])
            if host_reports is not None:
                from ..utils.metrics import is_merge_gauge

                summed: dict = {}
                for h in host_reports:
                    for k, v in h["metrics"].items():
                        # Counters sum across hosts; gauges (gang-agreed
                        # values like the negotiated window depth) merge
                        # by max so the report shows the value, not n x it.
                        if is_merge_gauge(k):
                            summed[k] = max(summed.get(k, v), v)
                        else:
                            summed[k] = summed.get(k, 0.0) + v
                report = build_run_report(
                    values=summed,
                    wall_time_s=max(
                        h["wall_time_s"] for h in host_reports
                    ),
                    counts={
                        "received": merged.received,
                        "success": merged.success,
                        "filtered": merged.filtered,
                        "errors": merged.errors,
                        "read_errors": merged.read_errors,
                    },
                    provenance=provenance,
                    hosts=host_reports,
                )
                write_run_report(run_report, report)
            return merged
        return result
    except PeerFailure:
        # A peer is gone: the coordination service's shutdown barrier can
        # never complete, and jax's atexit hook would hold this process
        # hostage until the service's own heartbeat teardown (~95 s on this
        # stack).  Abandon the distributed client so the survivor's exit is
        # as fast as its diagnosis.
        _abandon_distributed()
        raise
    finally:
        if heartbeat is not None:
            heartbeat.stop()


def _finish_file_coordinated(
    *,
    config,
    input_file: str,
    output_file: str,
    excluded_file: str,
    errors_file: Optional[str],
    finals: Sequence[str],
    text_column: str,
    id_column: str,
    read_batch_size: int,
    num_processes: int,
    process_id: int,
    n_rows: int,
    stride: int,
    mesh,
    pipeline,
    result,
    file_transport: FileLeaseTransport,
    membership_store: FileMembershipStore,
    membership_root: str,
    run_report: Optional[str],
    provenance: Optional[dict],
    values_before: dict,
    wall_t0: float,
):
    """Completion protocol for the file-transport coordinated path: adopt
    dead ranks' stripes, exchange totals/report over the (possibly
    reformed) member set, and have the lowest live rank merge.

    Adoption is a *deferred completion phase*, not mid-stream surgery: a
    dead rank committed nothing durable (shard files are written only after
    its ``run_local_shard`` returned), so the lowest live rank reproduces
    the whole stripe — a collective pass in which the adopter feeds the
    stripe's documents and every other member feeds zero documents, keeping
    the negotiated lockstep schedule identical on all survivors.  The
    adopter then writes ``<final>.shard{r}`` exactly as rank ``r`` would
    have and commits a completed per-stripe cursor
    (:meth:`CheckpointState.adopt` + ``complete=True``), so if the adopter
    itself dies the NEXT adopter skips finished stripes instead of
    repeating them.  Every decision that could diverge (is the stripe done?
    which stripes are dead?) is exchanged, never inferred locally, and the
    whole protocol rides the same GangReformed-replay loop as the run
    itself — a second death during adoption reforms again and resumes.

    The merge and run-report write move from rank 0 to ``min(members)``
    (rank 0 may be the dead one); shard files for ALL of
    ``range(num_processes)`` exist by then — survivors' own plus adopted
    ones — so the merged outputs are byte-identical to a fault-free run."""
    from itertools import islice

    from ..checkpoint import (
        CheckpointState,
        _config_fingerprint,
        _input_fingerprint,
    )
    from ..errors import PipelineError
    from ..orchestration import (
        AggregationResult,
        aggregate_results_from_stream,
        read_documents,
    )
    from ..resilience import DeadLetterSink
    from ..utils.metrics import (
        METRICS,
        build_run_report,
        is_merge_gauge,
        metrics_snapshot,
        write_run_report,
    )

    fingerprint = _input_fingerprint(input_file)
    config_hash = _config_fingerprint(config)
    my_token = {
        "rank": process_id,
        "incarnation": membership_store.incarnation,
    }
    adopted_done: set = set()

    def _adopt_stripe(r: int, adopter: int) -> None:
        skip_r = min(r * stride, n_rows)
        take_r = max(0, min(stride, n_rows - skip_r))
        adopt_docs: List[TextDocument] = []
        dl = None
        st = None
        adopt_read_errors = 0
        if process_id == adopter:
            METRICS.inc("multihost_adopted_stripes_total")
            TRACER.instant(
                "stripe_adopted",
                {"stripe": r, "epoch": file_transport.tracker.epoch},
            )
            if EVENTS.enabled:
                EVENTS.emit("stripe_adopted", stripe=r, adopter=process_id,
                            epoch=file_transport.tracker.epoch)
            print(
                f"reform[{process_id}]: adopting dead rank {r}'s stripe "
                f"({take_r} row(s))",
                flush=True,
            )
            st = CheckpointState.adopt(
                membership_store.stripe_dir(r),
                my_token,
                input_fingerprint=fingerprint,
                config_hash=config_hash,
            )
            dl = (
                DeadLetterSink(f"{errors_file}.shard{r}")
                if errors_file is not None
                else None
            )
            for item in islice(
                read_documents(
                    input_file,
                    text_column=text_column,
                    id_column=id_column,
                    batch_size=read_batch_size,
                    skip_rows=skip_r,
                ),
                take_r,
            ):
                if isinstance(item, PipelineError):
                    adopt_read_errors += 1
                    if dl is not None:
                        dl.record_read_error(item)
                else:
                    adopt_docs.append(item)
        try:
            # Collective: every member runs the pass (non-adopters with
            # zero documents still negotiate/launch the identical padded
            # schedule), so the lockstep contract holds during adoption.
            outcomes_r = run_local_shard(
                config, adopt_docs, buckets=pipeline.geometry.buckets,
                mesh=mesh, pipeline=pipeline,
            )
            if process_id == adopter:
                res_r = aggregate_results_from_stream(
                    iter(outcomes_r),
                    f"{output_file}.shard{r}",
                    f"{excluded_file}.shard{r}",
                    deadletter=dl,
                )
        finally:
            if dl is not None:
                dl.close()
        if process_id == adopter:
            st.rows_consumed = take_r
            st.read_errors = adopt_read_errors
            st.received = res_r.received
            st.success = res_r.success
            st.filtered = res_r.filtered
            st.errors = res_r.errors
            st.complete = True
            st.save(membership_store.stripe_dir(r))

    all_totals = None
    host_reports = None
    while True:
        try:
            members = file_transport.members()
            pending = [
                r
                for r in sorted(set(file_transport.dead_ranks))
                if r not in adopted_done
            ]
            if pending:
                r = pending[0]
                adopter = min(members)
                done = 0
                if process_id == adopter:
                    st = CheckpointState.load(membership_store.stripe_dir(r))
                    done = int(st is not None and bool(st.complete))
                # Joint decision, not a local read: if the adopter saw a
                # completed cursor the commit is durable — every member
                # agrees to skip; otherwise every member joins the pass.
                joint = int(
                    host_allgather(np.array([done], dtype=np.int64)).max()
                )
                if joint:
                    adopted_done.add(r)
                else:
                    _adopt_stripe(r, adopter)
                continue

            # Totals barrier over the (possibly reformed) member set; the
            # current adoption leader folds every dead stripe's committed
            # counts in — recomputed fresh from the cursors on every replay
            # so the fold stays idempotent — and the global sums match a
            # fault-free gang's.
            totals = np.array(
                [result.received, result.success, result.filtered,
                 result.errors, result.read_errors],
                dtype=np.int64,
            )
            if file_transport.dead_ranks and process_id == min(
                file_transport.members()
            ):
                for r in sorted(set(file_transport.dead_ranks)):
                    st = CheckpointState.load(membership_store.stripe_dir(r))
                    if st is not None:
                        totals += np.array(
                            [st.received, st.success, st.filtered,
                             st.errors, st.read_errors],
                            dtype=np.int64,
                        )
            all_totals = host_allgather(totals).reshape(-1, 5)

            if run_report is not None:
                from ..utils.metrics import snapshot_delta

                now = metrics_snapshot()
                local_delta = snapshot_delta(values_before, now)
                host_reports = host_allgather_obj(
                    {
                        "process": process_id,
                        "wall_time_s": round(
                            time.perf_counter() - wall_t0, 3
                        ),
                        "counts": {
                            "received": result.received,
                            "success": result.success,
                            "filtered": result.filtered,
                            "errors": result.errors,
                            "read_errors": result.read_errors,
                        },
                        "metrics": local_delta,
                    }
                )
            break
        except GangReformed:
            continue

    merger = min(file_transport.members())
    if process_id != merger:
        # Heartbeat first, withdraw second: a renewal landing after the
        # withdraw would resurrect the lease file (and the membership dir
        # after the merger's cleanup).  stop() is idempotent — the outer
        # finally's call is then a no-op.
        if file_transport.heartbeat is not None:
            file_transport.heartbeat.stop()
        membership_store.withdraw()
        return result

    merge_shard_files(
        [
            (final, [f"{final}.shard{i}" for i in range(num_processes)])
            for final in finals
        ]
    )
    g = all_totals.sum(axis=0)
    merged = AggregationResult()
    merged.received, merged.success, merged.filtered = (
        int(g[0]), int(g[1]), int(g[2])
    )
    merged.errors, merged.read_errors = int(g[3]), int(g[4])
    if host_reports is not None:
        summed: dict = {}
        for h in host_reports:
            for k, v in h["metrics"].items():
                # Counters sum across hosts; gauges merge by max (same
                # rule as the kv-path report).
                if is_merge_gauge(k):
                    summed[k] = max(summed.get(k, v), v)
                else:
                    summed[k] = summed.get(k, 0.0) + v
        report = build_run_report(
            values=summed,
            wall_time_s=max(h["wall_time_s"] for h in host_reports),
            counts={
                "received": merged.received,
                "success": merged.success,
                "filtered": merged.filtered,
                "errors": merged.errors,
                "read_errors": merged.read_errors,
            },
            provenance=provenance,
            hosts=host_reports,
        )
        write_run_report(run_report, report)
    if file_transport.heartbeat is not None:
        file_transport.heartbeat.stop()
    membership_store.withdraw()
    import shutil

    # Bounded wait for every peer's withdraw before removing the dir: a
    # peer withdraws only AFTER its final exchange read completes, so the
    # leases going away proves nobody is still polling the last report
    # slots.  Removing eagerly races a peer that posted its final row but
    # has not yet read the merger's (a ~10 ms window this merger can win
    # under load): the peer's next liveness self-check then finds its own
    # lease gone and dies typed on an otherwise healthy run.  The timeout
    # covers peers that crashed mid-run and left a stale lease behind.
    peers = [r for r in file_transport.members() if r != process_id]
    deadline = time.monotonic() + min(membership_store.ttl_s, 10.0)
    while peers and time.monotonic() < deadline:
        leases = membership_store.read_leases()
        if not any(r in leases for r in peers):
            break
        time.sleep(0.02)
    shutil.rmtree(membership_root, ignore_errors=True)
    return merged


def _abandon_distributed() -> None:
    """Drop the ``jax.distributed`` client without the shutdown barrier.

    ``DistributedRuntimeClient.shutdown()`` is a full-gang barrier — with a
    dead rank it blocks until the coordination service force-terminates the
    survivors.  After a :class:`PeerFailure` the gang is known-broken, so
    the only useful exit is a non-graceful one: null the client reference
    (jax's atexit ``clean_up`` then skips the barrier) and leave the
    service (if this host runs it) to die with the process."""
    try:
        from jax._src import distributed

        distributed.global_state.client = None
        distributed.global_state.preemption_sync_manager = None
    except Exception as e:  # pragma: no cover - jax internals moved
        import sys

        print(
            f"warning: could not abandon distributed client ({e}); exit may "
            "stall until the coordination service tears the gang down",
            file=sys.stderr,
            flush=True,
        )


def _run_elastic(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    *,
    num_processes: int,
    process_id: int,
    text_column: str,
    id_column: str,
    buckets: Sequence[int],
    read_batch_size: int,
    device_batch: Optional[int],
    errors_file: Optional[str],
    lease_ttl_s: float,
    force: bool,
    run_report: Optional[str] = None,
    provenance: Optional[dict] = None,
    autoscale: Optional[str] = None,
):
    """Elastic membership execution (``--elastic``) — no lockstep, no gang.

    Processes are deliberately NOT coupled through ``jax.distributed``:
    on this container's jax the coordination service force-terminates every
    healthy task ~90-100 s after a peer stops heartbeating, which is the
    opposite of elasticity.  Coordination instead lives entirely on the
    shared filesystem under ``<output>.membership/`` (the same filesystem
    the shard merge already assumes): per-rank lease files
    (:class:`FileMembershipStore`), and one checkpoint directory per input
    *stripe* with a fenced, owner-tokened cursor
    (:func:`~textblaster_tpu.checkpoint.run_stripe_checkpointed`).
    ``--coordinator`` is accepted but unused.

    The protocol, per heartbeat interval:

    1. **Self-fence** — a process whose own lease went stale (or was taken
       over by a newer incarnation of its rank) stops committing and dies;
       its last unfenced commit races the adopter only within the lease
       TTL, and lineage-scoped part files + the single atomic cursor
       rename make any interleaving converge (worst case: one chunk is
       reprocessed, committed once).
    2. **Observe membership** — live set changes bump the membership epoch
       (:class:`EpochTracker`), printing eviction/rejoin transitions.
    3. **Own and advance stripes** — stripe ``s`` belongs to live rank
       ``s``, orphans to the lowest live rank (:func:`stripe_owner`).
       Claiming rewrites the cursor's owner token
       (:meth:`CheckpointState.adopt`); committed work transfers verbatim,
       so adoption and restart-in-place replay **zero completed chunks**.
       A relaunched rank simply re-registers a lease under a fresh
       incarnation and reclaims its cursor; its zombie predecessor (if
       any) loses ownership at its next fence.
    4. **Merge** — when every stripe's cursor shows its window consumed,
       the lowest live rank (merge duty fails over exactly like stripe
       ownership) concatenates all stripes' part files — in stripe order,
       so output order is independent of which ranks did the work — into
       the final kept/excluded (and dead-letter) files atomically with an
       explicit schema (:func:`_commit_concat`), then removes the
       membership directory.

    Byte parity: chunk boundaries are device-batch flush barriers and the
    stripe windows are the same contiguous row ranges the lockstep path
    uses, so outputs are byte-identical to an uninterrupted (or
    single-host) run regardless of kills, adoptions, or rejoins.

    Returns an ``AggregationResult``: global totals on the merging rank,
    this rank's local contribution elsewhere.
    """
    import os
    import shutil

    from ..checkpoint import (
        CheckpointState,
        StripeLost,
        _config_fingerprint,
        _input_fingerprint,
        run_stripe_checkpointed,
    )
    from ..errors import PipelineError
    from ..io.parquet_writer import OUTPUT_SCHEMA
    from ..ops.geometry import DeviceGeometry
    from ..ops.pipeline import CompiledPipeline, process_documents_device
    from ..orchestration import AggregationResult
    from ..resilience.deadletter import DEADLETTER_SCHEMA
    from ..resilience.faults import FAULTS, arm_from_env
    from ..resilience.membership import (
        EpochTracker,
        FileMembershipStore,
        assign_stripes,
    )
    from ..utils.metrics import (
        METRICS,
        build_run_report,
        is_merge_gauge,
        metrics_snapshot,
        write_run_report,
    )
    from .mesh import data_mesh

    import pyarrow.parquet as pq

    root = f"{output_file}.membership"

    def say(msg: str) -> None:
        # stdout + flush: the chaos tests stream these lines to time their
        # SIGKILLs, and operators of a 2-terminal run read them live.
        print(f"elastic[{process_id}]: {msg}", flush=True)

    if force and os.path.isdir(root):
        shutil.rmtree(root)
        say(f"removed leftover membership dir {root} (--force)")

    fingerprint = _input_fingerprint(input_file)
    config_hash = _config_fingerprint(config)
    arm_from_env(process_id=process_id)

    # Run-report scope starts here (mirrors the coordinated path): the
    # metrics delta attributes only this run's work.
    values_before = metrics_snapshot() if run_report is not None else {}
    wall_t0 = time.perf_counter()

    store = FileMembershipStore(root, process_id, lease_ttl_s)
    store.register()
    joiner = process_id >= num_processes
    if joiner:
        # A joiner exists to help a RUNNING gang.  Without a live home
        # rank there is nothing to join — most likely the run already
        # finished and the merger tore the membership directory down, in
        # which case claiming work here would silently re-execute the
        # whole job from virgin cursors (and re-merge over the published
        # outputs).  Bounded grace covers a gang that is still starting.
        grace = max(2.0, 2.0 * lease_ttl_s)
        t_grace = time.monotonic() + grace
        while not any(
            r < num_processes for r in store.live_ranks()
        ):
            if time.monotonic() >= t_grace:
                store.withdraw()
                say(
                    f"no live gang to join (no home-rank lease within "
                    f"{grace:g}s); exiting without work"
                )
                return AggregationResult()
            store.post()  # a stale joiner lease is invisible to the gang
            time.sleep(min(0.1, lease_ttl_s / 10.0))
        # A scale-out joiner (rank beyond the stripe count) is admitted on
        # the strength of an incarnation-stamped join request posted next
        # to its lease.  The request is only valid while the lease stays
        # fresh, so a joiner dying right here (the ``multihost.join.post``
        # fault site) is never assigned work — the gang proceeds un-grown.
        store.post_join_request()
        say(f"posted join request (incarnation {store.incarnation})")
    if TRACER.enabled:
        # File-backend analogue of _align_trace_clocks: the first process
        # to register wrote the run's wall-clock origin; every tracer
        # shifts onto it, no collective needed.
        t0 = store.t0_us()
        if t0 is not None:
            TRACER.align(
                TRACER.wall_at_origin_us() - t0,
                args={"origin_wall_us": t0, "backend": "file"},
            )
    interval = max(0.05, lease_ttl_s / 3.0)
    heartbeat = LeaseHeartbeat(store, interval).start()

    mesh = data_mesh() if len(jax.devices()) > 1 else None
    pipeline = CompiledPipeline(
        config, buckets=tuple(sorted(buckets)), batch_size=device_batch,
        mesh=mesh,
    )
    from ..ops.pipeline import maybe_warmup

    # Warm (or AOT-cache-load) the program set before claiming a stripe —
    # a restarted-in-place elastic member re-enters with warm executables
    # instead of re-paying the cold compile inside its adopted stripe.
    maybe_warmup(pipeline)

    n_rows = pq.ParquetFile(input_file).metadata.num_rows
    stride = math.ceil(n_rows / max(num_processes, 1))

    # Overlapped stripe residue (PR 9): reuse the window config so each
    # process keeps pipeline_depth stripe chunks in flight — one being
    # processed/committed, the rest decoding on the prefetch thread.  Reads
    # are side-effect-free, so fence/commit semantics are untouched and
    # chunk boundaries stay at stripe order.
    oc = getattr(config, "overlap", None)
    read_ahead = 0
    if (
        oc is not None
        and oc.enabled
        and os.environ.get("TEXTBLAST_NO_OVERLAP") != "1"
    ):
        read_ahead = max(1, oc.pipeline_depth - 1)

    def window(s: int) -> Tuple[int, int]:
        # Identical striping to the lockstep path, computed from the input
        # alone — every process (and every relaunch) derives the same
        # windows without communicating.
        skip = min(s * stride, n_rows)
        return skip, max(0, min(stride, n_rows - skip))

    def stripe_done(s: int, st: Optional[CheckpointState] = None) -> bool:
        _skip, take = window(s)
        if take <= 0:
            return True
        if st is None:
            st = CheckpointState.load(store.stripe_dir(s))
        return st is not None and st.rows_consumed >= take

    my_token = {"rank": process_id, "incarnation": store.incarnation}
    lineage = f"-r{process_id}x{store.incarnation}"
    tracker = EpochTracker(process_id)
    local = AggregationResult()
    say(
        f"joined membership (incarnation {store.incarnation}, "
        f"{num_processes} stripe(s), lease ttl {lease_ttl_s:g}s)"
    )

    seen_joiners: set = set()

    def assignable(live):
        # A rank beyond the stripe count is assignable only while its join
        # request is valid (request present + fresh lease of the same
        # incarnation, unfenced): a joiner that died before/at its request
        # post never receives a stripe, and one that dies later drops out
        # with its lease exactly like a home rank.
        reqs = store.read_join_requests()
        picked = sorted(r for r in live if r < num_processes or r in reqs)
        for r in picked:
            if r >= num_processes and r not in seen_joiners:
                seen_joiners.add(r)
                if r != process_id:
                    # First observation of a valid join request IS the
                    # admission on this path (``multihost.join.admit``).
                    FAULTS.fire("multihost.join.admit")
                    say(f"admitting joiner rank {r} (epoch {tracker.epoch})")
        return picked

    def owners_now(live):
        pending = [s for s in range(num_processes) if not stripe_done(s)]
        return assign_stripes(pending, assignable(live), num_processes)

    supervisor = None
    if autoscale is not None:
        from .autoscale import AutoscaleSupervisor

        cfg_path = (provenance or {}).get("pipeline_config")
        if cfg_path is None:
            raise PipelineError(
                "--autoscale needs the pipeline-config path in the run "
                "provenance to respawn joiners (both CLI entries provide "
                "it)"
            )

        def backlog_rows() -> int:
            total = 0
            for s in range(num_processes):
                _sk, tk = window(s)
                if tk <= 0:
                    continue
                st = CheckpointState.load(store.stripe_dir(s))
                total += tk - (st.rows_consumed if st is not None else 0)
            return max(0, total)

        def spawn_command(jid: int):
            import sys as _sys

            cmd = [
                _sys.executable, "-m",
                "textblaster_tpu.parallel.multihost",
                "--coordinator", "autoscale:0",
                "--num-processes", str(num_processes),
                "--process-id", str(jid),
                "--pipeline-config", str(cfg_path),
                "-i", input_file,
                "-o", output_file,
                "-e", excluded_file,
                "--elastic",
                "--lease-ttl-s", str(lease_ttl_s),
                "--read-batch-size", str(read_batch_size),
                "--buckets", ",".join(str(b) for b in sorted(buckets)),
                "--text-column", text_column,
                "--id-column", id_column,
            ]
            if device_batch is not None:
                cmd += ["--device-batch", str(device_batch)]
            if errors_file is not None:
                cmd += ["--errors-file", errors_file]
            return cmd

        supervisor = AutoscaleSupervisor(
            autoscale,
            num_stripes=num_processes,
            rank=process_id,
            live_ranks=store.live_ranks,
            backlog_rows=backlog_rows,
            spawn_command=spawn_command,
            say=say,
        )

    def self_fence() -> None:
        if heartbeat.failed or not store.my_lease_fresh():
            raise PipelineError(
                f"rank {process_id} self-fenced: its liveness lease went "
                f"stale (ttl {lease_ttl_s:g}s) or a newer incarnation of "
                "this rank took over; committing now could race the "
                "stripe's adopter, so this process stops instead"
            )

    # A joiner may only START working while a home rank is live (the
    # pre-compile grace check above, re-verified here because the gang can
    # finish and tear down during this process's pipeline compile).  Once
    # latched it is an ordinary member: if the home ranks die later it
    # keeps its adopted work and can even inherit merge duty.
    gang_seen = not joiner
    try:
        while True:
            self_fence()
            live = store.live_ranks()
            if not gang_seen:
                if any(r < num_processes for r in live):
                    gang_seen = True
                else:
                    say(
                        "gang disappeared before this joiner was "
                        "assigned work; exiting without work"
                    )
                    store.clear_join_request(process_id)
                    store.withdraw()
                    return local
            for msg in tracker.observe(live):
                say(msg)
            if supervisor is not None:
                supervisor.tick()
            progressed = False
            owners = owners_now(live)
            for s in range(num_processes):
                _skip, take = window(s)
                if take <= 0 or stripe_done(s):
                    continue
                if owners.get(s) != process_id:
                    continue
                st_dir = store.stripe_dir(s)
                cur = CheckpointState.load(st_dir)
                if cur is None or cur.owner != my_token:
                    st = CheckpointState.adopt(
                        st_dir, my_token,
                        input_fingerprint=fingerprint,
                        config_hash=config_hash,
                    )
                    if s != process_id:
                        METRICS.inc("multihost_adopted_stripes_total")
                        TRACER.instant(
                            "stripe_adopted",
                            {"stripe": s, "epoch": tracker.epoch},
                        )
                        if EVENTS.enabled:
                            EVENTS.emit("stripe_adopted", stripe=s,
                                        adopter=process_id,
                                        epoch=tracker.epoch)
                        say(
                            f"adopted stripe {s} at row {st.rows_consumed}"
                            f"/{take} (epoch {tracker.epoch})"
                        )
                    elif st.rows_consumed > 0:
                        say(
                            f"stripe {s} resume at row {st.rows_consumed}"
                            f"/{take} (epoch {tracker.epoch})"
                        )
                else:
                    st = cur
                recorded = (
                    DeviceGeometry.from_dict(st.geometry)
                    if st.geometry is not None
                    else None
                )
                if recorded is not None:
                    if (
                        recorded.fingerprint()
                        != pipeline.geometry.fingerprint()
                    ):
                        # Chunk boundaries are batch flush barriers; a
                        # different geometry would batch the remainder
                        # differently than the original owner did.
                        raise PipelineError(
                            f"stripe {s} cursor was created with device "
                            f"geometry {recorded.describe()}, but this "
                            "process resolves to "
                            f"{pipeline.geometry.describe()}; every "
                            "elastic participant must run the identical "
                            "--buckets/--device-batch"
                        )
                else:
                    st.geometry = pipeline.geometry.to_dict()

                skip, take = window(s)
                before = (
                    st.received, st.success, st.filtered, st.errors,
                    st.read_errors,
                )

                def fence(s=s, st_dir=st_dir) -> None:
                    self_fence()
                    if owners_now(store.live_ranks()).get(s) != process_id:
                        raise StripeLost(
                            f"stripe {s} ownership moved (membership "
                            "changed)"
                        )
                    reloaded = CheckpointState.load(st_dir)
                    if reloaded is not None and reloaded.owner != my_token:
                        raise StripeLost(
                            f"stripe {s} cursor claimed by "
                            f"{reloaded.owner}"
                        )

                def on_chunk(state: CheckpointState, s=s, take=take) -> None:
                    say(
                        f"stripe {s} committed rows "
                        f"{state.rows_consumed}/{take} "
                        f"(epoch {tracker.epoch})"
                    )
                    if supervisor is not None:
                        # The supervising rank spends most of the run
                        # inside its own stripe; committed chunk
                        # boundaries are its scaling cadence.
                        supervisor.tick()

                done = run_stripe_checkpointed(
                    input_file,
                    st_dir,
                    state=st,
                    skip_rows=skip,
                    take_rows=take,
                    chunk_size=read_batch_size,
                    process_chunk=lambda items, on_err: (
                        process_documents_device(
                            config, items, on_read_error=on_err,
                            pipeline=pipeline,
                        )
                    ),
                    fence=fence,
                    lineage=lineage,
                    text_column=text_column,
                    id_column=id_column,
                    record_dead=errors_file is not None,
                    on_chunk=on_chunk,
                    read_ahead=read_ahead,
                )
                local.received += st.received - before[0]
                local.success += st.success - before[1]
                local.filtered += st.filtered - before[2]
                local.errors += st.errors - before[3]
                local.read_errors += st.read_errors - before[4]
                progressed = True
                if not done:
                    say(f"stripe {s} lost to another owner; moving on")
            if all(stripe_done(s) for s in range(num_processes)):
                break
            if not progressed:
                time.sleep(interval)
    except BaseException as exc:
        # Aborted elastic run: still leave a machine-readable partial
        # report (this rank's contribution, flagged) — the same contract
        # the kv path keeps on a PeerFailure abort.
        if run_report is not None and not isinstance(exc, GeneratorExit):
            from ..utils.metrics import snapshot_delta

            now = metrics_snapshot()
            delta = snapshot_delta(values_before, now)
            partial = build_run_report(
                values=delta,
                wall_time_s=round(time.perf_counter() - wall_t0, 3),
                counts={
                    "received": local.received,
                    "success": local.success,
                    "filtered": local.filtered,
                    "errors": local.errors,
                    "read_errors": local.read_errors,
                },
                provenance=provenance,
            )
            partial["aborted"] = True
            partial["abort_reason"] = f"{type(exc).__name__}: {exc}"
            try:
                write_run_report(run_report, partial)
            except OSError:
                pass  # the abort itself stays the headline
        raise
    finally:
        heartbeat.stop()

    report_dir = os.path.join(root, "report")
    if run_report is not None:
        # Post this rank's report shard before withdrawing: the merging
        # rank folds whatever shards the (possibly churned) membership
        # left behind — counts stay exact either way, they come from the
        # stripe cursors.
        from ..utils.metrics import snapshot_delta

        now = metrics_snapshot()
        delta = snapshot_delta(values_before, now)
        os.makedirs(report_dir, exist_ok=True)
        path = os.path.join(report_dir, f"rank{process_id}.json")
        tmp = f"{path}.tmp.{store.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "process": process_id,
                    "wall_time_s": round(
                        time.perf_counter() - wall_t0, 3
                    ),
                    "counts": {
                        "received": local.received,
                        "success": local.success,
                        "filtered": local.filtered,
                        "errors": local.errors,
                        "read_errors": local.read_errors,
                    },
                    "metrics": delta,
                },
                f,
            )
        os.replace(tmp, path)

    live = store.live_ranks()
    merger = min(live) if live else process_id
    if process_id != merger:
        store.withdraw()
        say(f"all stripes consumed; rank {merger} merges; local done")
        return local

    host_reports: List[dict] = []
    if run_report is not None:
        # Bounded wait for the other live ranks' report shards: each posts
        # before withdrawing, so every rank either reports or lets its
        # lease lapse.
        deadline = time.monotonic() + max(2.0, 2.0 * lease_ttl_s)
        while time.monotonic() < deadline:
            try:
                posted = {
                    int(n[len("rank"):-len(".json")])
                    for n in os.listdir(report_dir)
                    if n.startswith("rank") and n.endswith(".json")
                }
            except (FileNotFoundError, ValueError):
                posted = set()
            if not [
                r for r in store.live_ranks()
                if r != process_id and r not in posted
            ]:
                break
            time.sleep(0.05)
        try:
            names = sorted(os.listdir(report_dir))
        except FileNotFoundError:
            names = []
        for n in names:
            if not (n.startswith("rank") and n.endswith(".json")):
                continue
            try:
                with open(
                    os.path.join(report_dir, n), encoding="utf-8"
                ) as f:
                    host_reports.append(json.load(f))
            except (OSError, ValueError):
                continue
        host_reports.sort(key=lambda h: int(h.get("process", 0)))

    # Merge duty: lowest live rank (fails over like stripe ownership —
    # if the merger dies here, any relaunched/surviving rank re-enters,
    # finds every stripe done, and repeats this idempotent, atomic merge).
    cursors = [
        CheckpointState.load(store.stripe_dir(s))
        for s in range(num_processes)
    ]

    def parts(attr: str) -> List[str]:
        return [
            os.path.join(store.stripe_dir(s), name)
            for s, cur in enumerate(cursors)
            if cur is not None
            for name in getattr(cur, attr)
        ]

    _commit_concat(output_file, parts("out_parts"), OUTPUT_SCHEMA)
    _commit_concat(excluded_file, parts("excl_parts"), OUTPUT_SCHEMA)
    if errors_file is not None:
        _commit_concat(errors_file, parts("err_parts"), DEADLETTER_SCHEMA)
    merged = AggregationResult()
    for cur in cursors:
        if cur is None:
            continue
        merged.received += cur.received
        merged.success += cur.success
        merged.filtered += cur.filtered
        merged.errors += cur.errors
        merged.read_errors += cur.read_errors
    if run_report is not None:
        summed: dict = {}
        for h in host_reports:
            for k, v in h.get("metrics", {}).items():
                # Same merge rule as the coordinated path: counters sum
                # across ranks, gauges (gang-agreed values like the
                # membership epoch) merge by max.
                if is_merge_gauge(k):
                    summed[k] = max(summed.get(k, v), v)
                else:
                    summed[k] = summed.get(k, 0.0) + v
        report = build_run_report(
            values=summed,
            wall_time_s=max(
                [h.get("wall_time_s", 0.0) for h in host_reports]
                or [round(time.perf_counter() - wall_t0, 3)]
            ),
            counts={
                "received": merged.received,
                "success": merged.success,
                "filtered": merged.filtered,
                "errors": merged.errors,
                "read_errors": merged.read_errors,
            },
            provenance=provenance,
            hosts=host_reports,
        )
        write_run_report(run_report, report)
    if supervisor is not None:
        # Joiners leave on their own once every stripe is consumed
        # (fence-and-leave: report shard, lease withdrawal, clean exit);
        # reap them before the membership dir disappears under them.
        supervisor.drain(timeout_s=max(2.0, 4.0 * lease_ttl_s))
    store.withdraw()
    shutil.rmtree(root, ignore_errors=True)
    say(
        f"merged {num_processes} stripe(s): {merged.received} outcomes "
        f"({merged.success} kept, {merged.filtered} excluded, "
        f"{merged.errors} errors, {merged.read_errors} read errors)"
    )
    return merged


def _main(argv: Optional[Sequence[str]] = None) -> int:
    """Per-process module entry — a thin alias for
    ``textblast run --coordinator ...`` (the production path, `cli.py`)."""
    import argparse

    from ..config.pipeline import load_pipeline_config
    from ..utils.metrics import setup_prometheus_metrics

    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--pipeline-config", required=True)
    ap.add_argument("-i", "--input-file", required=True)
    ap.add_argument("-o", "--output-file", required=True)
    ap.add_argument("-e", "--excluded-file", required=True)
    ap.add_argument("--errors-file", default=None)
    ap.add_argument("--text-column", default="text")
    ap.add_argument("--id-column", default="id")
    ap.add_argument("--read-batch-size", type=int, default=1024)
    ap.add_argument("--buckets", default="512,2048,8192")
    ap.add_argument("--device-batch", type=int, default=None)
    ap.add_argument("--auto-geometry", action="store_true")
    ap.add_argument("--force", action="store_true")
    ap.add_argument(
        "--exchange-deadline-s", type=float,
        default=DEFAULT_EXCHANGE_DEADLINE_S,
        help="budget for each lockstep KV exchange; on expiry a typed "
        "PeerFailure names the rank(s) that never posted",
    )
    ap.add_argument(
        "--lease-ttl-s", type=float, default=DEFAULT_LEASE_TTL_S,
        help="liveness-lease TTL (renewed at TTL/3); a rank whose lease "
        "is older is classified dead",
    )
    ap.add_argument(
        "--elastic", action="store_true",
        help="elastic membership: shared-filesystem leases + per-stripe "
        "checkpoint cursors; survivors adopt dead ranks' stripes, "
        "relaunched ranks rejoin in place, and new ranks "
        "(--process-id >= --num-processes) join live via an admission "
        "request",
    )
    ap.add_argument(
        "--autoscale", default=None, metavar="MIN:MAX",
        help="elastic-only supervisor: the lowest live home rank spawns "
        "joiner ranks (ids >= --num-processes) while backlog persists, "
        "up to MAX total workers; joiners drain (fence-and-leave) at "
        "idle",
    )
    ap.add_argument(
        "--exchange-transport", choices=("auto", "kv", "file"),
        default="auto",
        help="lockstep exchange carrier: kv = the XLA/coordination-service "
        "funnel, file = shared-filesystem slots riding the membership "
        "leases (required for --survive-peer-loss); auto picks file iff "
        "--survive-peer-loss",
    )
    ap.add_argument(
        "--survive-peer-loss", action="store_true",
        help="gang reformation on the coordinated path: on a peer death "
        "the survivors fence the dead rank's incarnation, re-elect the "
        "member set, adopt its stripe, and finish the run (file exchange "
        "transport only)",
    )
    ap.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="in-flight lockstep round window for THIS host; the joint "
        "depth is the min over every host's value, allgathered once at "
        "run start (cli.py run exposes the same flag)",
    )
    ap.add_argument(
        "--no-overlap", action="store_true",
        help="disable the overlapped pipeline on this host (negotiates "
        "the whole gang down to serial depth 1)",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve /metrics on this port + process-id (the offset keeps "
        "co-located processes from colliding on the bind)",
    )
    ap.add_argument(
        "--trace", default=None, metavar="OUT.JSON",
        help="record a Chrome trace (process 0 writes OUT.JSON, process i "
        "writes OUT.JSON.host<i>)",
    )
    ap.add_argument(
        "--run-report", default=None, metavar="REPORT.JSON",
        help="process 0 writes a merged machine-readable run report "
        "(pass on every process — the snapshot exchange is a collective)",
    )
    ap.add_argument(
        "--doc-sample-rate", type=int, default=0, metavar="N",
        help="sample 1-in-N documents for per-doc tail-latency lineage "
        "(deterministic on the doc id, so every host samples the same "
        "docs; 0 = off)",
    )
    args = ap.parse_args(argv)

    if args.exchange_deadline_s <= args.lease_ttl_s:
        ap.error(
            f"--exchange-deadline-s ({args.exchange_deadline_s:g}) must "
            f"exceed --lease-ttl-s ({args.lease_ttl_s:g}): with the "
            "exchange deadline at or under the lease TTL, every slow lease "
            "renewal is misclassified as a peer death"
        )
    if args.survive_peer_loss and args.exchange_transport == "kv":
        ap.error(
            "--survive-peer-loss requires the file-lease exchange "
            "transport; pass --exchange-transport file or auto"
        )
    if args.elastic and (
        args.survive_peer_loss or args.exchange_transport == "file"
    ):
        ap.error(
            "--elastic is incompatible with --survive-peer-loss / "
            "--exchange-transport file: elastic membership has no lockstep "
            "exchanges for the transport to carry"
        )

    if args.metrics_port is not None:
        setup_prometheus_metrics(args.metrics_port + args.process_id)
    if args.trace:
        trace_path = (
            args.trace if args.process_id == 0
            else f"{args.trace}.host{args.process_id}"
        )
        TRACER.configure(
            trace_path,
            process_name=f"textblast-host{args.process_id}",
            pid=args.process_id,
        )
    if args.doc_sample_rate > 0:
        from ..utils.telemetry import TELEMETRY

        TELEMETRY.configure(args.doc_sample_rate)

    config = load_pipeline_config(args.pipeline_config)
    if args.no_overlap:
        config.overlap.enabled = False
    if args.pipeline_depth is not None:
        config.overlap.pipeline_depth = max(1, args.pipeline_depth)
    try:
        result = run_multihost(
            config,
            args.input_file,
            args.output_file,
            args.excluded_file,
            coordinator=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
            text_column=args.text_column,
            id_column=args.id_column,
            read_batch_size=args.read_batch_size,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            device_batch=args.device_batch,
            auto_geometry=args.auto_geometry,
            errors_file=args.errors_file,
            force=args.force,
            run_report=args.run_report,
            exchange_deadline_s=args.exchange_deadline_s,
            lease_ttl_s=args.lease_ttl_s,
            elastic=args.elastic,
            exchange_transport=args.exchange_transport,
            survive_peer_loss=args.survive_peer_loss,
            autoscale=args.autoscale,
            provenance={
                "entry": "textblaster_tpu.parallel.multihost",
                "pipeline_config": args.pipeline_config,
                "steps": [s.type for s in config.pipeline],
                "input_file": args.input_file,
                "num_processes": args.num_processes,
                "buckets": args.buckets,
                "auto_geometry": args.auto_geometry,
                "doc_sample_rate": args.doc_sample_rate,
            },
        )
    finally:
        TRACER.close()
        if args.doc_sample_rate > 0:
            from ..utils.telemetry import TELEMETRY

            TELEMETRY.close()
    print(
        f"process {args.process_id}: {result.received} outcomes "
        f"({result.success} kept, {result.filtered} excluded)"
    )
    from ..utils.metrics import METRICS

    reformations = int(METRICS.get("multihost_gang_reformations_total"))
    if reformations:
        print(
            f"process {args.process_id}: survived {reformations} gang "
            "reformation(s); "
            f"{int(METRICS.get('multihost_adopted_stripes_total'))} "
            "stripe(s) adopted"
        )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
