"""Multi-host execution: per-host document feed over a global device mesh.

The reference scales across machines by pointing more worker processes at one
RabbitMQ broker (SURVEY.md §2.5); the TPU-native equivalent is a
``jax.distributed`` SPMD job.  Every process joins one coordinator, the
``data`` mesh spans all hosts' devices, each host packs and feeds only its
*local* shard of the document stream
(``jax.make_array_from_process_local_data``), the compiled pipeline executes
once globally per round — cross-host traffic rides DCN exactly where XLA
places it — and each host assembles outcomes for its own documents from its
addressable output shards (the results-queue analogue: outputs land where
the documents came from, ready for per-host Parquet shards).

Lockstep contract: multi-host SPMD requires every process to dispatch the
same programs in the same order.  The per-(bucket) round counts are therefore
**negotiated**: every process allgathers how many rounds each bucket needs for
its local documents, and all processes run the columnwise maximum — hosts
with fewer documents pad with empty batches.  No operator-supplied round
budget is needed (the round-3 ``rounds`` argument survives as an optional
assertion).  ``textblast run --coordinator ... --num-processes N
--process-id i`` is the production entry (:func:`run_multihost`): each
process reads its row stripe of the input Parquet, writes a per-host shard
pair, and host 0 merges the shards into the final kept/excluded files after
a global barrier — the "resharded static fan-out" SURVEY.md §2.5 maps the
reference's competing consumers onto.

On real pods the same code runs unchanged: ``initialize()`` picks up the TPU
coordinator, the mesh spans the slice, and ICI/DCN routing is XLA's choice —
no NCCL/MPI analogue to manage (SURVEY.md §2.5's north-star mapping).

Resilience (PR 4): each lockstep round resolves under the negotiated guard
(:mod:`textblaster_tpu.resilience.negotiated`) — a retryable fault on any
host triggers a jointly-negotiated retry/degradation so transient device
faults no longer kill the job; per-host dead-letter shards merge like
kept/excluded; and the host-0 merge commits every final atomically
(tmp + fsync + rename via :func:`merge_shard_files`), deleting shards only
after every rename lands.
"""

from __future__ import annotations

import itertools
import json
import math
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config.pipeline import PipelineConfig
from ..data_model import ProcessingOutcome, TextDocument
from ..ops.packing import pack_documents
from ..utils.trace import TRACER
from .mesh import DATA_AXIS, batch_sharding

__all__ = [
    "initialize",
    "global_data_mesh",
    "host_allgather",
    "detect_stale_shards",
    "merge_shard_files",
    "run_local_shard",
    "run_multihost",
]


def detect_stale_shards(
    finals: Sequence[str], num_processes: int
) -> List[str]:
    """``*.shard*`` siblings of ``finals`` that THIS run will not produce.

    A prior crashed run with a larger ``--num-processes`` leaves orphan
    ``<final>.shard{j}`` files (j >= num_processes); the old merge silently
    ignored them next to fresh outputs — data loss masquerading as success.
    Returns the sorted offenders so callers can fail fast naming them
    (``--force`` removes them instead).  Expected shards
    (``.shard0..shard{n-1}``) are NOT stale: this run overwrites them.
    """
    import glob

    expected = {
        f"{final}.shard{i}" for final in finals for i in range(num_processes)
    }
    stale = {
        path
        for final in finals
        for path in glob.glob(glob.escape(final) + ".shard*")
        if path not in expected
    }
    return sorted(stale)


def _commit_merged(final: str, shards: Sequence[str]) -> None:
    """Stream the shards' row groups into ``<final>.tmp``, then commit it
    atomically: fsync the tmp, rename over ``final``, fsync the directory —
    the checkpoint-commit discipline (checkpoint.py), so a crash at any
    instant leaves ``final`` either absent or complete, never truncated."""
    import os

    import pyarrow.parquet as pq

    from ..utils.metrics import METRICS

    tmp = final + ".tmp"
    writer = None
    try:
        for s in shards:
            pf = pq.ParquetFile(s)
            if writer is None:
                writer = pq.ParquetWriter(tmp, pf.schema_arrow)
            # Row-group streaming keeps the merge O(row-group) memory
            # however large the global corpus is.
            for g in range(pf.metadata.num_row_groups):
                writer.write_table(pf.read_row_group(g))
    finally:
        if writer is not None:
            writer.close()
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, final)
    dfd = os.open(os.path.dirname(os.path.abspath(final)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    METRICS.inc("multihost_merge_commits_total")


def merge_shard_files(
    pairs: Sequence[Tuple[str, Sequence[str]]]
) -> None:
    """Commit every ``(final, shards)`` merge atomically, THEN delete shards.

    Deletion only starts after the last rename has landed: a kill anywhere
    mid-merge leaves every input shard intact, so a re-run (with ``--force``
    to clear the re-produced finals' leftover shards if needed) loses
    nothing.  The old in-place merge consumed shards into a final that a
    crash left truncated — unrecoverable."""
    import os

    for final, shards in pairs:
        _commit_merged(final, shards)
    for _final, shards in pairs:
        for s in shards:
            os.remove(s)


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the distributed job (no-op if this process already joined).

    ``coordinator`` is ``host:port`` of process 0 — the moral equivalent of
    the reference's ``--amqp-addr`` (utils/common.rs:15), except the
    connection carries collectives instead of JSON tasks."""
    if _distributed_initialized():
        return
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )


def _distributed_initialized() -> bool:
    """True once this process joined a ``jax.distributed`` job.

    ``jax.distributed.is_initialized`` only exists on newer jax; on older
    versions (this container's 0.4.x included) probe the distributed state's
    client directly instead of raising AttributeError mid-run."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    from jax._src import distributed

    return getattr(distributed.global_state, "client", None) is not None


def global_data_mesh() -> "jax.sharding.Mesh":
    """1-D ``data`` mesh over every device of every process.

    Exception: on a multi-process **CPU** job the mesh covers only this
    process's local devices.  XLA:CPU refuses to execute a computation that
    spans processes (INVALID_ARGUMENT "Multiprocess computations aren't
    implemented on the CPU backend"), and the compiled pipeline programs are
    collective-free, so per-host execution under the negotiated lockstep
    schedule — whose exchanges ride :func:`host_allgather` — is semantically
    identical: each host's "global" batch is simply its own stripe.  On
    accelerator backends the mesh spans the whole job as before and XLA
    routes cross-host traffic over ICI/DCN."""
    from jax.sharding import Mesh

    devices = (
        jax.local_devices()
        if jax.process_count() > 1 and jax.default_backend() == "cpu"
        else jax.devices()
    )
    return Mesh(np.array(devices), (DATA_AXIS,))


_AG_SEQ = itertools.count()


def host_allgather(vec: np.ndarray) -> np.ndarray:
    """Allgather one small int vector per process; returns ``[n_proc, len]``.

    Every lockstep exchange in this module (round schedules, fault verdicts,
    merged histograms, the totals barrier) funnels through here.  On
    accelerator backends it is ``multihost_utils.process_allgather``; on a
    multi-process CPU job — where XLA cannot run the collective at all — the
    same exchange rides the ``jax.distributed`` coordination-service
    key-value store, the transport that already carries barriers and
    heartbeats.  Callers must invoke it in lockstep (the contract this
    module enforces anyway): a per-process sequence number keys each
    exchange, and the blocking gets double as the barrier — no process
    proceeds until every peer has posted its row."""
    arr = np.asarray(vec, dtype=np.int64).ravel()
    n = jax.process_count()
    if n == 1:
        return arr.reshape(1, -1)
    if jax.default_backend() != "cpu":
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(arr), dtype=np.int64
        ).reshape(n, -1)
    from jax._src import distributed

    client = distributed.global_state.client
    seq = next(_AG_SEQ)
    client.key_value_set(
        f"textblast/allgather/{seq}/{jax.process_index()}",
        ",".join(str(int(x)) for x in arr),
    )
    rows = []
    for r in range(n):
        raw = client.blocking_key_value_get(
            f"textblast/allgather/{seq}/{r}", 300_000
        )
        rows.append([int(x) for x in raw.split(",")] if raw else [])
    return np.asarray(rows, dtype=np.int64)


def host_allgather_obj(obj) -> list:
    """Allgather one small JSON-serializable object per process.

    Rides :func:`host_allgather` (the only transport this module trusts):
    the object is JSON-encoded to UTF-8 bytes, lengths are exchanged first
    so every process can pad its byte vector to the common width, then the
    padded vectors are exchanged and each row decoded back.  Two collectives
    per call — callers must invoke it in lockstep, like every other
    exchange here.  Sized for metrics snapshots (a few KiB), not bulk data:
    each byte travels as an int64 lane."""
    data = json.dumps(obj, sort_keys=True).encode("utf-8")
    n = jax.process_count()
    lens = host_allgather(np.array([len(data)]))[:, 0]
    width = max(1, int(lens.max()))
    buf = np.zeros(width, dtype=np.int64)
    if data:
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    rows = host_allgather(buf)
    return [
        json.loads(
            bytes(rows[i, : int(lens[i])].astype(np.uint8)).decode("utf-8")
        )
        for i in range(n)
    ]


def _local_stats(out: dict) -> dict:
    """This process's rows of every ``data``-sharded output, in row order,
    moved in ONE bundled transfer (per-key np.asarray is a synchronous round
    trip each on remote-tunnel backends — see assemble_batch)."""
    shard_tree = {
        k: [
            s.data
            for s in sorted(
                v.addressable_shards, key=lambda s: s.index[0].start or 0
            )
        ]
        for k, v in out.items()
    }
    host_tree = jax.device_get(shard_tree)
    return {
        k: (np.concatenate(parts, axis=0) if parts else np.empty((0,)))
        for k, parts in host_tree.items()
    }


def _negotiate_max(needed_local: np.ndarray) -> np.ndarray:
    """Columnwise max of every process's per-bucket round counts.

    Lockstep safety: EVERY process must run the same number of rounds per
    bucket — a unilateral decision while peers enter ``fn()`` would hang the
    job until the coordinator heartbeat tears it down.  One small allgather
    makes the schedule global and deterministic."""
    return host_allgather(needed_local).max(axis=0).astype(np.int32)


def run_local_shard(
    config: PipelineConfig,
    docs: Sequence[TextDocument],
    bucket: Optional[int] = None,
    rounds: Optional[int] = None,
    mesh=None,
    pipeline=None,
    buckets: Optional[Sequence[int]] = None,
    fault_guard: bool = True,
) -> List[ProcessingOutcome]:
    """Run this host's documents through the globally-sharded pipeline.

    Every participating process must call this with the same ``config`` and
    bucket set (lockstep).  The number of rounds per bucket is negotiated by
    allgather (:func:`_negotiate_max`), so hosts never need a pre-agreed
    budget; passing ``rounds`` turns it into an assertion (ValueError if the
    negotiated schedule exceeds it — the round-3 interface).  Documents
    longer than every bucket run the host oracle locally (the usual counted
    fallback).

    Returns outcomes for **this host's** documents only.

    Phased short-circuit, lockstep-safe (VERDICT r3 item 3): for EVERY phase
    the per-bucket round counts are renegotiated over allgather from the
    hosts' surviving document counts, so all processes dispatch the identical
    program sequence while later phases run on shrinking, repacked survivor
    batches — the device analogue of the executor short-circuit that the
    single-controller path already had.

    With ``fault_guard`` (default) every round resolves under the
    :class:`~textblaster_tpu.resilience.negotiated.NegotiatedGuard`: a
    retryable fault on ANY host triggers a jointly-negotiated retry of the
    round on EVERY host (shared zero-jitter backoff), then a
    jointly-negotiated degradation of the round's documents to the host
    oracle; a per-bucket breaker latches persistently bad buckets onto the
    oracle for the rest of the run.  The guard's only lockstep addition is
    one 1-int allgather per round resolution — the fault-free program
    sequence is unchanged.
    """
    from ..ops.pipeline import CompiledPipeline, record_occupancy
    from ..orchestration import execute_processing_pipeline
    from ..resilience.negotiated import NegotiatedGuard
    from ..resilience.retry import classify_error
    from ..utils.metrics import METRICS

    from ..ops.packing import PACK_MARGIN

    if buckets is None:
        buckets = (bucket,) if bucket is not None else (2048,)
    buckets = tuple(sorted(buckets))
    mesh = mesh if mesh is not None else global_data_mesh()
    # How many processes the program's mesh spans: jax.process_count() on
    # accelerators, 1 under the multi-process-CPU local-mesh fallback
    # (global_data_mesh) where each host runs its own full-width program.
    n_proc = len({d.process_index for d in mesh.devices.flat})
    if pipeline is None:
        pipeline = CompiledPipeline(config, buckets=buckets, mesh=mesh)
    # Per-bucket local row counts: each host feeds its 1/n_proc stripe of the
    # bucket's global batch.  Under uniform geometry every bucket resolves to
    # the old single ``pipeline.batch_size // n_proc``.
    geo = pipeline.geometry
    local_for = {
        b: max(1, geo.batch_for(b) // n_proc) if b in geo.buckets
        else max(1, pipeline.batch_size // n_proc)
        for b in buckets
    }

    def partition(ds: Sequence[TextDocument]):
        by_bucket: dict = {b: [] for b in buckets}
        over: List[TextDocument] = []
        for d in ds:
            for b in buckets:
                if len(d.content) <= b - PACK_MARGIN:
                    by_bucket[b].append(d)
                    break
            else:
                over.append(d)
        return by_bucket, over

    if pipeline._route_dict_scripts:
        # Dictionary-script docs take the host oracle (ops/pipeline.py
        # __init__ note); they join the local fallback list, which runs
        # outside the lockstep schedule and so needs no negotiation.
        # Single pass: ``docs`` may be any iterable, and one content scan
        # per document suffices.
        from ..utils.cjk import has_dict_script

        routed, kept = [], []
        for d in docs:
            (routed if has_dict_script(d.content) else kept).append(d)
        docs = kept
    else:
        routed = []
    current, fallback = partition(docs)
    fallback.extend(routed)

    sh2 = batch_sharding(mesh, 2)
    sh1 = batch_sharding(mesh, 1)

    guard = NegotiatedGuard(config.resilience, buckets=buckets) if fault_guard else None
    degraded: List[TextDocument] = []

    def launch(local, ph):
        """Guarded async launch.  Returns ``(out, launch_fault)``: a
        retryable launch failure is captured, not raised — the verdict has
        to convene at resolve time so every host takes the same branch."""
        if guard is None:
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        try:
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        except BaseException as e:  # noqa: BLE001 — classifier decides
            if classify_error(e) != "retryable":
                raise
            return None, True

    def resolve(entry, outcomes, survivors):
        """Block for one in-flight round and assemble it — under the
        negotiated verdict protocol when the guard is on."""
        local, ph = entry["batch"], entry["phase"]
        with TRACER.span(
            "lockstep_resolve", {"bucket": entry["bucket"], "phase": ph}
        ):
            if guard is None:
                stats = _local_stats(entry["out"])
            else:
                b = entry["bucket"]
                stats = guard.run_round(
                    b,
                    dispatch=lambda: pipeline.dispatch_lockstep(
                        local, ph, sh2, sh1
                    ),
                    fetch=_local_stats,
                    inflight=entry["out"],
                    launch_fault=entry["fault"],
                )
                if stats is None:
                    # Jointly degraded: every host routes this round's chunk
                    # to the host oracle; none re-enters the program.
                    degraded.extend(local.docs)
                    return
            po, alive = pipeline.assemble_phase(local, stats, ph)
            outcomes.extend(po)
            survivors.extend(alive)

    outcomes: List[ProcessingOutcome] = []
    n_phases = len(pipeline.phases)
    for phase in range(n_phases):
        needed_local = np.array(
            [math.ceil(len(current[b]) / local_for[b]) for b in buckets],
            dtype=np.int32,
        )
        schedule = _negotiate_max(needed_local)
        if phase == 0 and rounds is not None and int(schedule.sum()) > rounds:
            raise ValueError(
                f"shard needs {int(schedule.sum())} rounds "
                f"(local {int(needed_local.sum())}), got {rounds}"
            )

        survivors: List[TextDocument] = []
        pending = None  # one guarded round in flight (dict entry)
        for b, n_rounds in zip(buckets, schedule):
            local_batch = local_for[b]
            for r in range(int(n_rounds)):
                chunk = current[b][r * local_batch : (r + 1) * local_batch]
                if guard is not None and guard.bucket_degraded(b):
                    # Breaker latched on negotiated verdicts, so every host
                    # reaches the same conclusion at the same round and the
                    # dispatch is skipped jointly — lockstep preserved
                    # without touching the device.
                    METRICS.inc("resilience_negotiated_degraded_rounds_total")
                    TRACER.instant(
                        "negotiated_bucket_latched",
                        {"bucket": b, "round": r, "phase": phase},
                    )
                    degraded.extend(chunk)
                    continue
                with TRACER.span(
                    "lockstep_round",
                    {"bucket": b, "round": r, "phase": phase,
                     "rows": len(chunk)},
                ):
                    local = pack_documents(
                        chunk, batch_size=local_batch, max_len=b
                    )
                    record_occupancy(local)
                    out, fault = launch(local, phase)
                if pending is not None:
                    resolve(pending, outcomes, survivors)
                pending = {
                    "batch": local, "bucket": b, "phase": phase,
                    "out": out, "fault": fault,
                }
        if pending is not None:
            resolve(pending, outcomes, survivors)
        if phase == n_phases - 1:
            break
        # Survivor content may have been rewritten (C4) — repack by the
        # current length.  Growth past every bucket is impossible (rewrites
        # only drop chars), but route defensively anyway.
        current, over = partition(survivors)
        fallback.extend(over)

    for d in fallback:
        METRICS.inc("worker_host_fallback_total")
        o = execute_processing_pipeline(pipeline.host_executor, d)
        if o is not None:
            outcomes.append(o)
    if degraded:
        # Degraded rounds re-run start to finish on the bit-exact host
        # oracle (mid-phase re-stamp contract, ops/pipeline.py _host_rerun),
        # so outcomes stay byte-identical to a fault-free run.
        outcomes.extend(pipeline._host_rerun(degraded))
    return outcomes


def run_multihost(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    *,
    coordinator: str,
    num_processes: int,
    process_id: int,
    text_column: str = "text",
    id_column: str = "id",
    buckets: Sequence[int] = (512, 2048, 8192),
    read_batch_size: int = 1024,
    device_batch: Optional[int] = None,
    auto_geometry: bool = False,
    errors_file: Optional[str] = None,
    force: bool = False,
    run_report: Optional[str] = None,
    provenance: Optional[dict] = None,
):
    """Production multi-host entry (``textblast run --coordinator ...``).

    ``run_report`` (must be passed on EVERY process or on none — the
    snapshot exchange is a collective) makes each process contribute its
    metrics-delta snapshot over :func:`host_allgather_obj` after the totals
    barrier; process 0 writes a merged run report to that path with both
    the per-host snapshots and the summed totals.  ``provenance`` is the
    config-provenance dict embedded in the report.

    Each process reads its contiguous row stripe of ``input_file`` (the
    static shard assignment SURVEY.md §2.5 maps the task queue onto), runs
    the negotiated lockstep schedule, and writes a per-host
    ``<output>.shard<i>`` / ``<excluded>.shard<i>`` Parquet pair (plus an
    ``<errors>.shard<i>`` dead-letter shard when ``errors_file`` is given —
    the per-host slice of PR 1's sink).  After a global barrier, process 0
    merges each shard set into its final file **atomically**
    (:func:`merge_shard_files`: tmp + fsync + rename, shards deleted only
    after every rename lands) — the results-queue aggregation analogue,
    producer_logic.rs:109-196.  Stale ``*.shard*`` leftovers from a crashed
    run with different ``--num-processes`` fail the run fast on every
    process unless ``force`` removes them.

    Returns an ``AggregationResult``: global totals on process 0 (after the
    merge), local totals elsewhere.

    Failure behavior (measured, tests/test_multihost.py +
    tests/test_multihost_chaos.py): a *retryable device fault* on any host
    no longer kills the job — ``run_local_shard``'s negotiated guard retries
    the round jointly on every host and, past the budget, degrades it to the
    host oracle jointly (outcomes stay byte-identical).  If a process *dies*
    mid-run, survivors do NOT hang on the next allgather — the jax
    coordination service detects the missed heartbeats (~90 s) and
    propagates UNAVAILABLE to every healthy task, which exits nonzero with
    the dead task named in the error.  The run is then re-launched whole;
    per-process restart-in-place is not supported (matches the reference's
    worker model, where a dead worker's unacked queue messages are simply
    redelivered to a fresh worker).
    """
    import os
    from itertools import islice

    import pyarrow.parquet as pq

    from ..errors import PipelineError
    from ..orchestration import (
        AggregationResult,
        aggregate_results_from_stream,
        read_documents,
    )
    from ..resilience import DeadLetterSink
    from ..resilience.faults import arm_from_env
    from ..utils.metrics import (
        METRICS,
        build_run_report,
        metrics_snapshot,
        write_run_report,
    )

    finals = [output_file, excluded_file]
    if errors_file is not None:
        finals.append(errors_file)
    stale = detect_stale_shards(finals, num_processes)
    if stale:
        if not force:
            # Checked on EVERY process before joining the coordinator, so
            # the whole gang exits fast instead of one host discovering the
            # problem after the run.
            raise PipelineError(
                "stale shard files from a previous run would be ignored by "
                f"the merge: {', '.join(stale)} — remove them or pass "
                "--force to overwrite"
            )
        for s in stale:
            try:
                os.remove(s)
            except FileNotFoundError:
                pass  # a peer on a shared filesystem got there first
            else:
                METRICS.inc("multihost_stale_shards_removed_total")

    initialize(coordinator, num_processes, process_id)
    if jax.process_count() != num_processes:
        # Without this, a topology mismatch (typically jax.distributed
        # already initialized with different numbers) surfaces as a hang or
        # a shape error deep inside the first allgather.
        raise PipelineError(
            f"--num-processes {num_processes} does not match the "
            f"initialized distributed runtime "
            f"(jax.process_count()={jax.process_count()}); all processes "
            "must be launched with the same topology, and an existing "
            "jax.distributed initialization cannot be re-shaped"
        )
    arm_from_env(process_id=process_id)
    mesh = global_data_mesh()

    import time as _time

    # Run-report scope starts here: everything after distributed init is
    # this run's work, so the snapshot deltas attribute only it.
    values_before = metrics_snapshot() if run_report is not None else {}
    wall_t0 = _time.perf_counter()

    n_rows = pq.ParquetFile(input_file).metadata.num_rows
    stride = math.ceil(n_rows / max(num_processes, 1))
    skip = min(process_id * stride, n_rows)
    take = max(0, min(stride, n_rows - skip))

    # Per-host dead-letter shard, merged by process 0 exactly like
    # kept/excluded.  Created eagerly (DeadLetterSink writes the empty file
    # up front) so the merge never races a host that recorded nothing.
    deadletter = (
        DeadLetterSink(f"{errors_file}.shard{process_id}")
        if errors_file is not None
        else None
    )

    read_errors = 0
    docs: List[TextDocument] = []
    stream = read_documents(
        input_file,
        text_column=text_column,
        id_column=id_column,
        batch_size=read_batch_size,
        skip_rows=skip,
    )
    for item in islice(stream, take):  # one stream item per Parquet row
        if isinstance(item, PipelineError):
            read_errors += 1
            if deadletter is not None:
                deadletter.record_read_error(item)
        else:
            docs.append(item)

    from ..ops.pipeline import CompiledPipeline

    geometry = None
    if auto_geometry:
        # Geometry negotiation: each host histograms ITS shard's document
        # lengths over the fixed shape-stable bin edges, the histograms are
        # allgathered and summed elementwise, and every host derives the
        # geometry from the identical merged histogram — so the lockstep
        # round schedule (which depends on buckets and batch sizes) stays in
        # agreement without shipping raw lengths across hosts.
        from ..ops.geometry import (
            geometry_from_histogram,
            length_histogram,
        )

        hist = length_histogram([len(d.content) for d in docs])
        hist = host_allgather(hist).sum(axis=0)
        if hist.sum() > 0:
            geometry = geometry_from_histogram(
                hist, backend=jax.default_backend()
            )

    pipeline = CompiledPipeline(
        config, buckets=tuple(sorted(buckets)), batch_size=device_batch,
        mesh=mesh, geometry=geometry,
    )
    try:
        outcomes = run_local_shard(
            config, docs, buckets=pipeline.geometry.buckets, mesh=mesh,
            pipeline=pipeline,
        )

        shard_out = f"{output_file}.shard{process_id}"
        shard_exc = f"{excluded_file}.shard{process_id}"
        result = aggregate_results_from_stream(
            iter(outcomes), shard_out, shard_exc, deadletter=deadletter
        )
    finally:
        # The shard must be complete on disk before the totals barrier
        # releases process 0 into the merge.
        if deadletter is not None:
            deadletter.close()
    result.read_errors = read_errors

    totals = np.array(
        [result.received, result.success, result.filtered, result.errors,
         result.read_errors],
        dtype=np.int64,
    )
    # Barrier doubling as the totals exchange: every process must have
    # closed its shard files before process 0 merges (host_allgather's
    # blocking gets release only once every peer has posted).
    all_totals = host_allgather(totals).reshape(-1, 5)

    # Cross-host metrics aggregation: one more lockstep exchange carrying
    # each process's metrics-delta snapshot (a few KiB of JSON), so host
    # 0's report survives the other processes' exit.  Runs on EVERY
    # process or on none — see the docstring contract.
    host_reports = None
    if run_report is not None:
        now = metrics_snapshot()
        local_delta = {
            k: round(now.get(k, 0.0) - values_before.get(k, 0.0), 6)
            for k in set(now) | set(values_before)
            if now.get(k, 0.0) != values_before.get(k, 0.0)
        }
        host_reports = host_allgather_obj(
            {
                "process": process_id,
                "wall_time_s": round(_time.perf_counter() - wall_t0, 3),
                "counts": {
                    "received": result.received,
                    "success": result.success,
                    "filtered": result.filtered,
                    "errors": result.errors,
                    "read_errors": result.read_errors,
                },
                "metrics": local_delta,
            }
        )

    if process_id == 0:
        merge_shard_files(
            [
                (final, [f"{final}.shard{i}" for i in range(num_processes)])
                for final in finals
            ]
        )
        g = all_totals.sum(axis=0)
        merged = AggregationResult()
        merged.received, merged.success, merged.filtered = int(g[0]), int(g[1]), int(g[2])
        merged.errors, merged.read_errors = int(g[3]), int(g[4])
        if host_reports is not None:
            summed: dict = {}
            for h in host_reports:
                for k, v in h["metrics"].items():
                    summed[k] = summed.get(k, 0.0) + v
            report = build_run_report(
                values=summed,
                wall_time_s=max(h["wall_time_s"] for h in host_reports),
                counts={
                    "received": merged.received,
                    "success": merged.success,
                    "filtered": merged.filtered,
                    "errors": merged.errors,
                    "read_errors": merged.read_errors,
                },
                provenance=provenance,
                hosts=host_reports,
            )
            write_run_report(run_report, report)
        return merged
    return result


def _main(argv: Optional[Sequence[str]] = None) -> int:
    """Per-process module entry — a thin alias for
    ``textblast run --coordinator ...`` (the production path, `cli.py`)."""
    import argparse

    from ..config.pipeline import load_pipeline_config
    from ..utils.metrics import setup_prometheus_metrics

    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--pipeline-config", required=True)
    ap.add_argument("-i", "--input-file", required=True)
    ap.add_argument("-o", "--output-file", required=True)
    ap.add_argument("-e", "--excluded-file", required=True)
    ap.add_argument("--errors-file", default=None)
    ap.add_argument("--text-column", default="text")
    ap.add_argument("--id-column", default="id")
    ap.add_argument("--read-batch-size", type=int, default=1024)
    ap.add_argument("--buckets", default="512,2048,8192")
    ap.add_argument("--device-batch", type=int, default=None)
    ap.add_argument("--auto-geometry", action="store_true")
    ap.add_argument("--force", action="store_true")
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve /metrics on this port + process-id (the offset keeps "
        "co-located processes from colliding on the bind)",
    )
    ap.add_argument(
        "--trace", default=None, metavar="OUT.JSON",
        help="record a Chrome trace (process 0 writes OUT.JSON, process i "
        "writes OUT.JSON.host<i>)",
    )
    ap.add_argument(
        "--run-report", default=None, metavar="REPORT.JSON",
        help="process 0 writes a merged machine-readable run report "
        "(pass on every process — the snapshot exchange is a collective)",
    )
    args = ap.parse_args(argv)

    if args.metrics_port is not None:
        setup_prometheus_metrics(args.metrics_port + args.process_id)
    if args.trace:
        trace_path = (
            args.trace if args.process_id == 0
            else f"{args.trace}.host{args.process_id}"
        )
        TRACER.configure(
            trace_path,
            process_name=f"textblast-host{args.process_id}",
            pid=args.process_id,
        )

    config = load_pipeline_config(args.pipeline_config)
    try:
        result = run_multihost(
            config,
            args.input_file,
            args.output_file,
            args.excluded_file,
            coordinator=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
            text_column=args.text_column,
            id_column=args.id_column,
            read_batch_size=args.read_batch_size,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            device_batch=args.device_batch,
            auto_geometry=args.auto_geometry,
            errors_file=args.errors_file,
            force=args.force,
            run_report=args.run_report,
            provenance={
                "entry": "textblaster_tpu.parallel.multihost",
                "pipeline_config": args.pipeline_config,
                "steps": [s.type for s in config.pipeline],
                "input_file": args.input_file,
                "num_processes": args.num_processes,
                "buckets": args.buckets,
                "auto_geometry": args.auto_geometry,
            },
        )
    finally:
        TRACER.close()
    print(
        f"process {args.process_id}: {result.received} outcomes "
        f"({result.success} kept, {result.filtered} excluded)"
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
