"""Multi-host execution: per-host document feed over a global device mesh.

The reference scales across machines by pointing more worker processes at one
RabbitMQ broker (SURVEY.md §2.5); the TPU-native equivalent is a
``jax.distributed`` SPMD job.  Every process joins one coordinator, the
``data`` mesh spans all hosts' devices, each host packs and feeds only its
*local* shard of the document stream
(``jax.make_array_from_process_local_data``), the compiled pipeline executes
once globally per round — cross-host traffic rides DCN exactly where XLA
places it — and each host assembles outcomes for its own documents from its
addressable output shards (the results-queue analogue: outputs land where
the documents came from, ready for per-host Parquet shards).

Lockstep contract: multi-host SPMD requires every process to dispatch the
same programs in the same order.  The per-(bucket) round counts are therefore
**negotiated**: every process allgathers how many rounds each bucket needs for
its local documents, and all processes run the columnwise maximum — hosts
with fewer documents pad with empty batches.  No operator-supplied round
budget is needed (the round-3 ``rounds`` argument survives as an optional
assertion).  ``textblast run --coordinator ... --num-processes N
--process-id i`` is the production entry (:func:`run_multihost`): each
process reads its row stripe of the input Parquet, writes a per-host shard
pair, and host 0 merges the shards into the final kept/excluded files after
a global barrier — the "resharded static fan-out" SURVEY.md §2.5 maps the
reference's competing consumers onto.

On real pods the same code runs unchanged: ``initialize()`` picks up the TPU
coordinator, the mesh spans the slice, and ICI/DCN routing is XLA's choice —
no NCCL/MPI analogue to manage (SURVEY.md §2.5's north-star mapping).

Kernels (PR 8): mesh-sharded programs no longer fall back to the lax scans.
``CompiledPipeline._build_fn`` traces them under ``mesh_tracing(mesh)``
(:mod:`textblaster_tpu.ops.pallas_scan`), which makes every scan kernel —
including the fused per-(bucket, phase) megakernel — dispatch through
``shard_map`` over the ``data`` axis, the same pattern ``pallas_sort.sort2``
has always used: each host's devices scan their own row shards in VMEM, and
rows never cross devices so no collective is inserted.  The host-oracle
degradation rung still runs pure Python and never sees Pallas code.

Resilience (PR 4): each lockstep round resolves under the negotiated guard
(:mod:`textblaster_tpu.resilience.negotiated`) — a retryable fault on any
host triggers a jointly-negotiated retry/degradation so transient device
faults no longer kill the job; per-host dead-letter shards merge like
kept/excluded; and the host-0 merge commits every final atomically
(tmp + fsync + rename via :func:`merge_shard_files`), deleting shards only
after every rename lands.

Elastic membership (PR 6): every KV exchange is deadline-bounded
(``--exchange-deadline-s``) and raises a typed
:class:`~textblaster_tpu.errors.PeerFailure` naming the unposted ranks —
dead-versus-slow resolved against renewable KV liveness leases
(``--lease-ttl-s``) — instead of blocking on the old hardcoded 300 s get;
exchange keys are namespaced by epoch and deleted once drained.  With
``--elastic`` the run leaves the lockstep contract entirely
(:func:`_run_elastic`): membership lives in shared-filesystem leases,
survivors adopt a dead rank's input stripe at the membership-epoch bump,
and a SIGKILLed rank can be relaunched to rejoin in place from its
committed cursor — replaying zero completed chunks, outcomes
byte-identical to a fault-free run.

Overlap (PR 9): lockstep rounds ride a K-deep in-flight window where K is
the **min** over every host's ``OverlapConfig.pipeline_depth``, allgathered
once at shard start (:func:`_negotiate_depth`) — depth is lockstep state,
so it cannot be a per-host choice.  Packing runs ahead on the shared
pack-worker pool (including the next phase's survivor chunks, packed while
the current phase's tail rounds still resolve), launches run up to K ahead
of unresolved verdicts, resolves stay strict FIFO, and a negotiated fault
verdict drains the window so every host re-dispatches the younger rounds
in the identical order — serial and overlapped runs stay byte-identical.
"""

from __future__ import annotations

import json
import math
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..config.pipeline import PipelineConfig
from ..data_model import ProcessingOutcome, TextDocument
from ..errors import PeerFailure
from ..resilience.membership import (
    DEFAULT_EXCHANGE_DEADLINE_S,
    DEFAULT_LEASE_TTL_S,
    KVLeaseStore,
    LeaseHeartbeat,
    _kv_set,
)
from ..utils.trace import TRACER
from .mesh import DATA_AXIS, batch_sharding

__all__ = [
    "initialize",
    "global_data_mesh",
    "host_allgather",
    "configure_exchange",
    "bump_exchange_epoch",
    "current_exchange_epoch",
    "PeerFailure",
    "detect_stale_shards",
    "merge_shard_files",
    "run_local_shard",
    "run_multihost",
]


def detect_stale_shards(
    finals: Sequence[str], num_processes: int
) -> List[str]:
    """``*.shard*`` siblings of ``finals`` that THIS run will not produce.

    A prior crashed run with a larger ``--num-processes`` leaves orphan
    ``<final>.shard{j}`` files (j >= num_processes); the old merge silently
    ignored them next to fresh outputs — data loss masquerading as success.
    Returns the sorted offenders so callers can fail fast naming them
    (``--force`` removes them instead).  Expected shards
    (``.shard0..shard{n-1}``) are NOT stale: this run overwrites them.
    """
    import glob

    expected = {
        f"{final}.shard{i}" for final in finals for i in range(num_processes)
    }
    stale = {
        path
        for final in finals
        for path in glob.glob(glob.escape(final) + ".shard*")
        if path not in expected
    }
    return sorted(stale)


def _commit_merged(final: str, shards: Sequence[str]) -> None:
    """Stream the shards' row groups into ``<final>.tmp``, then commit it
    atomically: fsync the tmp, rename over ``final``, fsync the directory —
    the checkpoint-commit discipline (checkpoint.py), so a crash at any
    instant leaves ``final`` either absent or complete, never truncated."""
    import os

    import pyarrow.parquet as pq

    from ..utils.metrics import METRICS

    tmp = final + ".tmp"
    writer = None
    try:
        for s in shards:
            pf = pq.ParquetFile(s)
            if writer is None:
                writer = pq.ParquetWriter(tmp, pf.schema_arrow)
            # Row-group streaming keeps the merge O(row-group) memory
            # however large the global corpus is.
            for g in range(pf.metadata.num_row_groups):
                writer.write_table(pf.read_row_group(g))
    finally:
        if writer is not None:
            writer.close()
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, final)
    dfd = os.open(os.path.dirname(os.path.abspath(final)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    METRICS.inc("multihost_merge_commits_total")


def _commit_concat(final: str, part_paths: Sequence[str], schema) -> None:
    """Concatenate Parquet parts into ``final`` atomically, with an
    **explicit schema**: unlike :func:`_commit_merged` (which infers the
    schema from the first shard), zero parts still commit a well-formed
    empty file — the elastic merge must produce valid finals even when
    every row was filtered or a stripe is empty."""
    import os

    import pyarrow.parquet as pq

    from ..utils.metrics import METRICS

    tmp = final + ".tmp"
    writer = pq.ParquetWriter(tmp, schema)
    try:
        for p in part_paths:
            writer.write_table(pq.read_table(p).cast(schema))
    finally:
        writer.close()
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, final)
    dfd = os.open(os.path.dirname(os.path.abspath(final)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    METRICS.inc("multihost_merge_commits_total")


def merge_shard_files(
    pairs: Sequence[Tuple[str, Sequence[str]]]
) -> None:
    """Commit every ``(final, shards)`` merge atomically, THEN delete shards.

    Deletion only starts after the last rename has landed: a kill anywhere
    mid-merge leaves every input shard intact, so a re-run (with ``--force``
    to clear the re-produced finals' leftover shards if needed) loses
    nothing.  The old in-place merge consumed shards into a final that a
    crash left truncated — unrecoverable."""
    import os

    for final, shards in pairs:
        _commit_merged(final, shards)
    for _final, shards in pairs:
        for s in shards:
            os.remove(s)


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the distributed job (no-op if this process already joined).

    ``coordinator`` is ``host:port`` of process 0 — the moral equivalent of
    the reference's ``--amqp-addr`` (utils/common.rs:15), except the
    connection carries collectives instead of JSON tasks."""
    if _distributed_initialized():
        return
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )


def _distributed_initialized() -> bool:
    """True once this process joined a ``jax.distributed`` job.

    ``jax.distributed.is_initialized`` only exists on newer jax; on older
    versions (this container's 0.4.x included) probe the distributed state's
    client directly instead of raising AttributeError mid-run."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    from jax._src import distributed

    return getattr(distributed.global_state, "client", None) is not None


def global_data_mesh() -> "jax.sharding.Mesh":
    """1-D ``data`` mesh over every device of every process.

    Exception: on a multi-process **CPU** job the mesh covers only this
    process's local devices.  XLA:CPU refuses to execute a computation that
    spans processes (INVALID_ARGUMENT "Multiprocess computations aren't
    implemented on the CPU backend"), and the compiled pipeline programs are
    collective-free, so per-host execution under the negotiated lockstep
    schedule — whose exchanges ride :func:`host_allgather` — is semantically
    identical: each host's "global" batch is simply its own stripe.  On
    accelerator backends the mesh spans the whole job as before and XLA
    routes cross-host traffic over ICI/DCN."""
    from jax.sharding import Mesh

    devices = (
        jax.local_devices()
        if jax.process_count() > 1 and jax.default_backend() == "cpu"
        else jax.devices()
    )
    return Mesh(np.array(devices), (DATA_AXIS,))


class _ExchangeState:
    """Shared round state for the KV-transport lockstep exchanges.

    The old implementation keyed each exchange by a process-local
    ``itertools.count`` — fine while every process lives forever, but a
    relaunched process restarts its counter at 0 and can never re-enter.
    Keys are now namespaced by an **exchange epoch** with the sequence
    number restarting at every epoch boundary, and the epoch advances only
    at points derived from shared round state (:func:`bump_exchange_epoch`
    at each negotiated phase boundary in :func:`run_local_shard`), so any
    process that re-enters at an epoch boundary computes the same key names
    as its peers.  Drained epochs are deleted (see :func:`host_allgather`'s
    hygiene note), so the KV store holds O(1) allgather keys per rank
    instead of growing for the life of the coordinator.
    """

    def __init__(self) -> None:
        self.deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S
        self.epoch: int = 0
        self.seq: int = 0
        self.lease_store: Optional[KVLeaseStore] = None
        # Own (epoch, seq) keys whose epoch drained but whose read-proof
        # (a peer completing a later exchange) hadn't landed yet.
        self.pending_delete: List[Tuple[int, int]] = []


_EXCHANGE = _ExchangeState()

#: Timeout for the post-deadline sweep that names EVERY laggard (not just
#: the first): once the budget is spent, each remaining rank gets one short
#: probe instead of the full deadline again.
_PROBE_TIMEOUT_MS = 1000


def configure_exchange(
    deadline_s: Optional[float] = None,
    lease_store: Optional[KVLeaseStore] = None,
    reset: bool = True,
) -> None:
    """Configure the exchange deadline / lease table for this process and
    (by default) restart the epoch/sequence counters — called by
    :func:`run_multihost` on every process at run start, so the shared
    round state begins aligned."""
    if deadline_s is not None:
        _EXCHANGE.deadline_s = float(deadline_s)
    _EXCHANGE.lease_store = lease_store
    if reset:
        _EXCHANGE.epoch = 0
        _EXCHANGE.seq = 0
        _EXCHANGE.pending_delete = []


def current_exchange_epoch() -> int:
    """The epoch namespace current exchanges are keyed under (trace/metrics
    labeling; every process in lockstep reports the same value)."""
    return _EXCHANGE.epoch


def bump_exchange_epoch() -> int:
    """Open the next exchange epoch: the sequence restarts at 0 and the
    drained epoch's last own key is queued for deletion (it is removed once
    a completed exchange in the new epoch proves every peer has read it).
    Must be called in lockstep — :func:`run_local_shard` does so at every
    negotiated phase boundary, the shared round state all processes agree
    on without communicating."""
    if _EXCHANGE.seq > 0:
        _EXCHANGE.pending_delete.append((_EXCHANGE.epoch, _EXCHANGE.seq - 1))
    _EXCHANGE.epoch += 1
    _EXCHANGE.seq = 0
    return _EXCHANGE.epoch


def _ag_key(epoch: int, seq: int, rank: int) -> str:
    return f"textblast/allgather/e{epoch}/s{seq}/{rank}"


def _validate_rows(
    rows: Sequence[Sequence[int]], width: int, *, seq: int, epoch: int
) -> None:
    """Ragged-row guard: every peer's row must match this process's lane
    count.  A shorter/empty row previously fed a ragged list-of-lists to
    ``np.asarray`` (an object-dtype array that crashed far from the cause);
    now the offending rank is named in a typed :exc:`PeerFailure`."""
    for r, row in enumerate(rows):
        if len(row) != width:
            from ..utils.metrics import METRICS

            METRICS.inc("multihost_peer_failures_total")
            raise PeerFailure(
                f"exchange e{epoch}/s{seq}: rank {r} posted {len(row)} "
                f"lane(s) where {width} were expected — a desynchronized "
                "or corrupted peer (ragged allgather row)",
                missing_ranks=(r,),
                seq=seq,
                epoch=epoch,
            )


def _raise_peer_failure(
    missing: Sequence[int],
    *,
    seq: int,
    epoch: int,
    deadline_s: float,
    transport_error: str = "",
) -> None:
    """Deadline expired with peers unposted: resolve dead-vs-slow against
    the lease table and raise the typed error naming both lists.
    ``transport_error`` carries the coordination service's own words (a
    heartbeat/UNAVAILABLE teardown reads very differently from a plain
    DEADLINE_EXCEEDED, and operators grep for it)."""
    from ..utils.metrics import METRICS

    dead: List[int] = []
    store = _EXCHANGE.lease_store
    if store is not None:
        try:
            dead, _slow = store.resolve_liveness(missing)
        except Exception:  # pragma: no cover - lease table best-effort
            dead = []
    METRICS.inc("multihost_peer_failures_total")
    TRACER.instant(
        "peer_failure",
        {"seq": seq, "epoch": epoch, "missing": list(missing),
         "dead": list(dead)},
    )
    detail = (
        f"; liveness leases mark rank(s) {list(dead)} dead "
        f"(lease older than {store.ttl_s:g}s)"
        if dead and store is not None
        else "; every missing rank still holds a fresh liveness lease "
        "(slow or wedged, not dead)"
        if store is not None
        else ""
    )
    transport = (
        f"; last transport error: {transport_error[:300]}"
        if transport_error
        else ""
    )
    raise PeerFailure(
        f"exchange e{epoch}/s{seq} deadline ({deadline_s:g}s) expired; "
        f"rank(s) {list(missing)} never posted{detail}{transport}",
        missing_ranks=missing,
        dead_ranks=dead,
        seq=seq,
        epoch=epoch,
    )


def host_allgather(vec: np.ndarray) -> np.ndarray:
    """Allgather one small int vector per process; returns ``[n_proc, len]``.

    Every lockstep exchange in this module (round schedules, fault verdicts,
    merged histograms, the totals barrier) funnels through here.  On
    accelerator backends it is ``multihost_utils.process_allgather``; on a
    multi-process CPU job — where XLA cannot run the collective at all — the
    same exchange rides the ``jax.distributed`` coordination-service
    key-value store, the transport that already carries barriers and
    heartbeats.  Callers must invoke it in lockstep (the contract this
    module enforces anyway): keys are ``(epoch, seq, rank)`` tuples from the
    shared round state (:class:`_ExchangeState`), and the blocking gets
    double as the barrier — no process proceeds until every peer has posted
    its row.

    KV-path failure semantics (the exchange *deadline*, PR 6): the whole
    exchange gets ``configure_exchange``'s budget (default
    ``DEFAULT_EXCHANGE_DEADLINE_S``; ``--exchange-deadline-s``) instead of
    the old hardcoded 300 s per rank.  On expiry, the remaining ranks are
    each probed briefly so every laggard is identified, peer liveness is
    resolved against the KV lease table, and a typed :exc:`PeerFailure`
    names the exchange coordinates, the missing ranks, and which of them
    hold expired leases (dead) versus fresh ones (slow).  Rows are also
    validated for raggedness (:func:`_validate_rows`).  The accelerator
    path is XLA's collective and carries no host-side deadline — there the
    coordination-service heartbeat teardown remains the backstop.

    Hygiene: completing exchange ``s`` proves every peer has read exchange
    ``s-1`` (each peer posts ``s`` only after fully reading ``s-1``), so
    this process's ``s-1`` key — and any queued keys from drained epochs —
    are deleted after each completed exchange.  The KV table stays O(1) per
    rank for the life of the coordinator."""
    arr = np.asarray(vec, dtype=np.int64).ravel()
    n = jax.process_count()
    if n == 1:
        return arr.reshape(1, -1)
    if jax.default_backend() != "cpu":
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(arr), dtype=np.int64
        ).reshape(n, -1)
    from jax._src import distributed

    client = distributed.global_state.client
    me = jax.process_index()
    epoch, seq = _EXCHANGE.epoch, _EXCHANGE.seq
    _EXCHANGE.seq += 1
    _kv_set(
        client,
        _ag_key(epoch, seq, me),
        ",".join(str(int(x)) for x in arr),
    )
    deadline_s = _EXCHANGE.deadline_s
    t0 = time.monotonic()
    own_row = [int(x) for x in arr]
    rows: List[List[int]] = []
    missing: List[int] = []
    transport_error = ""
    for r in range(n):
        if r == me:
            rows.append(own_row)
            continue
        remaining_ms = int((deadline_s - (time.monotonic() - t0)) * 1000)
        timeout_ms = remaining_ms if remaining_ms > 0 else _PROBE_TIMEOUT_MS
        try:
            raw = client.blocking_key_value_get(
                _ag_key(epoch, seq, r), timeout_ms
            )
        except Exception as e:  # DEADLINE_EXCEEDED / service teardown
            missing.append(r)
            rows.append([])
            transport_error = str(e)
            continue
        rows.append([int(x) for x in raw.split(",")] if raw else [])
    if missing:
        _raise_peer_failure(
            missing, seq=seq, epoch=epoch, deadline_s=deadline_s,
            transport_error=transport_error,
        )
    _validate_rows(rows, len(own_row), seq=seq, epoch=epoch)
    drained = [_ag_key(e, s, me) for e, s in _EXCHANGE.pending_delete]
    _EXCHANGE.pending_delete.clear()
    if seq > 0:
        drained.append(_ag_key(epoch, seq - 1, me))
    for key in drained:
        try:
            client.key_value_delete(key)
        except Exception:  # pragma: no cover - hygiene is best-effort
            pass
    return np.asarray(rows, dtype=np.int64)


def host_allgather_obj(obj) -> list:
    """Allgather one small JSON-serializable object per process.

    Rides :func:`host_allgather` (the only transport this module trusts):
    the object is JSON-encoded to UTF-8 bytes, lengths are exchanged first
    so every process can pad its byte vector to the common width, then the
    padded vectors are exchanged and each row decoded back.  Two collectives
    per call — callers must invoke it in lockstep, like every other
    exchange here.  Sized for metrics snapshots (a few KiB), not bulk data:
    each byte travels as an int64 lane."""
    data = json.dumps(obj, sort_keys=True).encode("utf-8")
    n = jax.process_count()
    lens = host_allgather(np.array([len(data)]))[:, 0]
    width = max(1, int(lens.max()))
    buf = np.zeros(width, dtype=np.int64)
    if data:
        buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    rows = host_allgather(buf)
    return [
        json.loads(
            bytes(rows[i, : int(lens[i])].astype(np.uint8)).decode("utf-8")
        )
        for i in range(n)
    ]


def _local_stats(out: dict) -> dict:
    """This process's rows of every ``data``-sharded output, in row order,
    moved in ONE bundled transfer (per-key np.asarray is a synchronous round
    trip each on remote-tunnel backends — see assemble_batch)."""
    shard_tree = {
        k: [
            s.data
            for s in sorted(
                v.addressable_shards, key=lambda s: s.index[0].start or 0
            )
        ]
        for k, v in out.items()
    }
    host_tree = jax.device_get(shard_tree)
    return {
        k: (np.concatenate(parts, axis=0) if parts else np.empty((0,)))
        for k, parts in host_tree.items()
    }


def _negotiate_max(needed_local: np.ndarray) -> np.ndarray:
    """Columnwise max of every process's per-bucket round counts.

    Lockstep safety: EVERY process must run the same number of rounds per
    bucket — a unilateral decision while peers enter ``fn()`` would hang the
    job until the coordinator heartbeat tears it down.  One small allgather
    makes the schedule global and deterministic."""
    return host_allgather(needed_local).max(axis=0).astype(np.int32)


def _negotiate_depth(local_depth: int) -> int:
    """Joint in-flight window depth: the MIN over every host's configured
    ``OverlapConfig.pipeline_depth`` (one extra startup allgather, zero
    per-round exchanges).

    Depth is lockstep state: every host must launch and resolve the
    identical round sequence with the identical interleave, so a host
    configured shallower than its peers pulls the whole gang down to what
    it can sustain — min, not max, because depth K means K launches may
    run ahead of unresolved verdicts and the most conservative host bounds
    what all hosts may assume about each other's dispatch order.  A
    mismatch is legal (hosts merely negotiate down) but surfaced in the
    trace so an operator can see which rank capped the window."""
    from ..utils.metrics import METRICS

    depths = host_allgather(
        np.array([max(1, int(local_depth))], dtype=np.int32)
    )[:, 0]
    joint = max(1, int(depths.min()))
    METRICS.set("multihost_negotiated_depth", float(joint))
    if int(depths.max()) != joint:
        TRACER.instant(
            "window_depth_mismatch",
            {"host_depths": [int(d) for d in depths], "joint": joint},
        )
    return joint


def _align_trace_clocks() -> None:
    """Cross-host trace clock handshake (one allgather at run start).

    Each process's tracer stamps events from a private ``perf_counter``
    origin, so per-host trace files loaded into one Perfetto session show
    hosts skewed by their process start times.  Every process allgathers
    the wall-clock time of its tracer origin; the **minimum** becomes the
    run's shared origin and each tracer shifts its timestamps by
    ``own_wall - min_wall`` (recording the offset and every host's wall in
    a ``trace_clock_offset`` metadata event).  The exchange is
    unconditional — it is a collective, and a host without ``--trace``
    still must participate or the gang desynchronizes; only the local
    ``align`` is gated on tracing being enabled.  Alignment is as good as
    the hosts' wall clocks (NTP-grade), which is what a cross-host
    timeline needs — spans are still *timed* by each host's monotonic
    clock."""
    wall = TRACER.wall_at_origin_us()
    walls = host_allgather(np.array([wall], dtype=np.int64))[:, 0]
    if TRACER.enabled:
        origin = int(walls.min())
        TRACER.align(
            wall - origin,
            args={
                "origin_wall_us": origin,
                "host_walls_us": [int(w) for w in walls],
            },
        )


def run_local_shard(
    config: PipelineConfig,
    docs: Sequence[TextDocument],
    bucket: Optional[int] = None,
    rounds: Optional[int] = None,
    mesh=None,
    pipeline=None,
    buckets: Optional[Sequence[int]] = None,
    fault_guard: bool = True,
) -> List[ProcessingOutcome]:
    """Run this host's documents through the globally-sharded pipeline.

    Every participating process must call this with the same ``config`` and
    bucket set (lockstep).  The number of rounds per bucket is negotiated by
    allgather (:func:`_negotiate_max`), so hosts never need a pre-agreed
    budget; passing ``rounds`` turns it into an assertion (ValueError if the
    negotiated schedule exceeds it — the round-3 interface).  Documents
    longer than every bucket run the host oracle locally (the usual counted
    fallback).

    Returns outcomes for **this host's** documents only.

    Phased short-circuit, lockstep-safe (VERDICT r3 item 3): for EVERY phase
    the per-bucket round counts are renegotiated over allgather from the
    hosts' surviving document counts, so all processes dispatch the identical
    program sequence while later phases run on shrinking, repacked survivor
    batches — the device analogue of the executor short-circuit that the
    single-controller path already had.

    With ``fault_guard`` (default) every round resolves under the
    :class:`~textblaster_tpu.resilience.negotiated.NegotiatedGuard`: a
    retryable fault on ANY host triggers a jointly-negotiated retry of the
    round on EVERY host (shared zero-jitter backoff), then a
    jointly-negotiated degradation of the round's documents to the host
    oracle; a per-bucket breaker latches persistently bad buckets onto the
    oracle for the rest of the run.  The guard's only lockstep addition is
    one 1-int allgather per round resolution — the fault-free program
    sequence is unchanged.

    Overlap (PR 9): rounds ride a K-deep in-flight window, where K is the
    min over every host's ``OverlapConfig.pipeline_depth``, allgathered
    once at shard start (:func:`_negotiate_depth` — depth is lockstep
    state, so it cannot be a per-host choice).  Packing runs ahead on the
    shared pack pool (rounds r+1..r+K pack while round r executes, and the
    next phase's full survivor chunks pack while this phase's tail rounds
    still resolve), launches run up to K ahead of unresolved verdicts, and
    resolves stay strict FIFO — so serial (depth 1 / ``--no-overlap``) and
    overlapped runs produce byte-identical outcome streams.  A negotiated
    fault verdict drains the window: every host discards its launched-ahead
    results and the younger rounds re-dispatch fresh at their own resolve,
    keeping the post-verdict global program order identical on every host.
    """
    import os
    from collections import deque

    from ..ops.pipeline import CompiledPipeline, maybe_warmup, record_occupancy
    from ..orchestration import execute_processing_pipeline
    from ..resilience.negotiated import NegotiatedGuard
    from ..resilience.retry import classify_error
    from ..utils.metrics import METRICS
    from ..utils.overlap import shared_pack_pool

    from ..ops.packing import PACK_MARGIN

    if buckets is None:
        buckets = (bucket,) if bucket is not None else (2048,)
    buckets = tuple(sorted(buckets))
    mesh = mesh if mesh is not None else global_data_mesh()
    # How many processes the program's mesh spans: jax.process_count() on
    # accelerators, 1 under the multi-process-CPU local-mesh fallback
    # (global_data_mesh) where each host runs its own full-width program.
    n_proc = len({d.process_index for d in mesh.devices.flat})
    if pipeline is None:
        pipeline = CompiledPipeline(config, buckets=buckets, mesh=mesh)
        # Warm before the first lockstep round: every host compiles (or AOT-
        # cache-loads) the identical program set up front, so no host hits a
        # first-dispatch compile stall mid-round while its peers wait at the
        # allgather.
        maybe_warmup(pipeline)
    # Per-bucket local row counts: each host feeds its 1/n_proc stripe of the
    # bucket's global batch.  Under uniform geometry every bucket resolves to
    # the old single ``pipeline.batch_size // n_proc``.
    geo = pipeline.geometry
    local_for = {
        b: max(1, geo.batch_for(b) // n_proc) if b in geo.buckets
        else max(1, pipeline.batch_size // n_proc)
        for b in buckets
    }

    def partition(ds: Sequence[TextDocument]):
        by_bucket: dict = {b: [] for b in buckets}
        over: List[TextDocument] = []
        for d in ds:
            for b in buckets:
                if len(d.content) <= b - PACK_MARGIN:
                    by_bucket[b].append(d)
                    break
            else:
                over.append(d)
        return by_bucket, over

    if pipeline._route_dict_scripts:
        # Dictionary-script docs take the host oracle (ops/pipeline.py
        # __init__ note); they join the local fallback list, which runs
        # outside the lockstep schedule and so needs no negotiation.
        # Single pass: ``docs`` may be any iterable, and one content scan
        # per document suffices.
        from ..utils.cjk import has_dict_script

        routed, kept = [], []
        for d in docs:
            (routed if has_dict_script(d.content) else kept).append(d)
        docs = kept
    else:
        routed = []
    current, fallback = partition(docs)
    fallback.extend(routed)

    sh2 = batch_sharding(mesh, 2)
    sh1 = batch_sharding(mesh, 1)

    guard = NegotiatedGuard(config.resilience, buckets=buckets) if fault_guard else None
    degraded: List[TextDocument] = []

    # Joint window depth: a collective, so EVERY host negotiates it even
    # when its own overlap is off (its local depth is then 1, pulling the
    # whole gang to serial — min rule).
    overlap_cfg = getattr(config, "overlap", None)
    overlapped = (
        overlap_cfg is not None
        and overlap_cfg.enabled
        and os.environ.get("TEXTBLAST_NO_OVERLAP") != "1"
    )
    depth = _negotiate_depth(
        max(1, overlap_cfg.pipeline_depth) if overlapped else 1
    )
    # Pack off the critical path: the process-wide pool (shared with the
    # single-host packers) packs rounds ahead of the launch cursor and the
    # next phase's survivor chunks behind the resolve cursor.  Serial mode
    # (--no-overlap) packs inline on this thread, exactly as before.
    pool = shared_pack_pool(max(1, overlap_cfg.pack_workers)) if overlapped else None

    def launch(local, ph):
        """Guarded async launch.  Returns ``(out, launch_fault)``: a
        retryable launch failure is captured, not raised — the verdict has
        to convene at resolve time so every host takes the same branch."""
        if guard is None:
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        try:
            return pipeline.dispatch_lockstep(local, ph, sh2, sh1), False
        except BaseException as e:  # noqa: BLE001 — classifier decides
            if classify_error(e) != "retryable":
                raise
            return None, True

    def phase_rewrites(ph: int) -> bool:
        # Only C4QualityFilter rewrites survivor content mid-phase (line
        # drops); every other device step decides and stamps.  Phases
        # without it preserve lengths, so each survivor's bucket is its
        # round's bucket and the re-partition length scan is skipped.
        return any(
            pipeline.device_steps[i].type == "C4QualityFilter"
            for i in pipeline.phases[ph]
        )

    outcomes: List[ProcessingOutcome] = []
    n_phases = len(pipeline.phases)
    lockstep_t0 = time.perf_counter()
    # Cross-phase pre-pack handoff: pack futures for the next phase's full
    # survivor chunks, keyed (bucket, round), built while this phase's tail
    # rounds are still resolving.
    prepack_next: dict = {}
    for phase in range(n_phases):
        # Exchange epochs advance with the negotiated phase sequence — a
        # piece of round state every process derives identically without
        # communicating (phases are negotiated in lockstep), which is what
        # lets KV exchange keys be namespaced deterministically instead of
        # by a process-local counter (see _ExchangeState).
        bump_exchange_epoch()
        needed_local = np.array(
            [math.ceil(len(current[b]) / local_for[b]) for b in buckets],
            dtype=np.int32,
        )
        schedule = _negotiate_max(needed_local)
        if phase == 0 and rounds is not None and int(schedule.sum()) > rounds:
            raise ValueError(
                f"shard needs {int(schedule.sum())} rounds "
                f"(local {int(needed_local.sum())}), got {rounds}"
            )

        # The phase's launch plan, in the negotiated (bucket, round) order
        # every host shares.  The negotiated count covers the local ceil by
        # construction; a violation would silently strand a tail chunk once
        # launches run ahead of resolves, so fail loudly instead.
        plan: List[tuple] = []
        for b, n_rounds in zip(buckets, schedule):
            local_batch = local_for[b]
            assert int(n_rounds) * local_batch >= len(current[b]), (
                f"bucket {b}: negotiated {int(n_rounds)} round(s) of "
                f"{local_batch} rows cannot cover {len(current[b])} local "
                "documents — geometry round-up stranded a tail chunk"
            )
            for r in range(int(n_rounds)):
                plan.append(
                    (b, r, current[b][r * local_batch : (r + 1) * local_batch])
                )

        inherited = prepack_next  # this phase's pre-packed chunks
        prepack_next = {}
        packs: dict = {}  # plan index -> PackedBatch (or its future)

        def ensure_packed(j):
            """Keep rounds j..j+K packed (or packing) ahead of the launch
            cursor; cross-phase pre-packed chunks are adopted as-is."""
            for k in range(j, min(j + depth + 1, len(plan))):
                if k in packs:
                    continue
                kb, kr, kchunk = plan[k]
                pre = inherited.pop((kb, kr), None)
                if pre is not None:
                    packs[k] = pre
                elif pool is not None:
                    packs[k] = pool.submit(
                        pipeline._timed_pack, kchunk,
                        batch_size=local_for[kb], max_len=kb,
                    )
                else:
                    packs[k] = pipeline._timed_pack(
                        kchunk, batch_size=local_for[kb], max_len=kb
                    )

        last = phase == n_phases - 1
        rewrites = (not last) and phase_rewrites(phase)
        next_current: dict = {b: [] for b in buckets}
        next_over: List[TextDocument] = []
        prepack_done = {b: 0 for b in buckets}

        def absorb(src_bucket, alive):
            """Fold one resolved round's survivors into the next phase —
            incrementally, in resolve order (== the old flat-list partition
            order), so full next-phase chunks can pack while this phase
            still has rounds in flight (the next ``_negotiate_max`` needs
            only the final counts, exchanged after the drain as before)."""
            if last:
                return
            if rewrites:
                # Survivor content may have been rewritten (C4) — re-route
                # by current length.  Growth past every bucket is
                # impossible (rewrites only drop chars), but route
                # defensively anyway.
                for d in alive:
                    for nb in buckets:
                        if len(d.content) <= nb - PACK_MARGIN:
                            next_current[nb].append(d)
                            break
                    else:
                        next_over.append(d)
            else:
                next_current[src_bucket].extend(alive)
            if pool is None:
                return
            for nb in buckets if rewrites else (src_bucket,):
                lb = local_for[nb]
                k = prepack_done[nb]
                # A full chunk's document prefix is final once appended
                # (later resolves only extend the list), so it can pack now.
                while (k + 1) * lb <= len(next_current[nb]):
                    prepack_next[(nb, k)] = pool.submit(
                        pipeline._timed_pack,
                        next_current[nb][k * lb : (k + 1) * lb],
                        batch_size=lb, max_len=nb,
                    )
                    k += 1
                prepack_done[nb] = k

        window: deque = deque()

        def drain_window():
            """Joint fault verdict convened at the window front: discard
            this host's launched-ahead results so every host's program
            order after the verdict is the same ``[retry(r), r+1, ...]`` —
            the younger rounds re-dispatch fresh at their own resolve."""
            n = sum(1 for e in window if e["out"] is not None or e["fault"])
            for e in window:
                e["out"] = None
                e["fault"] = False
            if n:
                METRICS.inc("multihost_window_replayed_rounds_total", n)
            TRACER.instant(
                "window_drained",
                {"replayed": n, "pending": len(window), "phase": phase},
            )

        def resolve_front():
            """Block for the OLDEST in-flight round and assemble it — under
            the negotiated verdict protocol when the guard is on.  Strict
            FIFO at every depth: the window moves waits, never sequence."""
            entry = window.popleft()
            TRACER.counter("lockstep_window", len(window))
            local, ph, eb = entry["batch"], entry["phase"], entry["bucket"]
            t0 = time.perf_counter()
            try:
                with TRACER.span(
                    "lockstep_resolve", {"bucket": eb, "phase": ph}
                ):
                    if guard is None:
                        stats = _local_stats(entry["out"])
                    else:
                        stats = guard.run_round(
                            eb,
                            dispatch=lambda: pipeline.dispatch_lockstep(
                                local, ph, sh2, sh1
                            ),
                            fetch=_local_stats,
                            inflight=entry["out"],
                            launch_fault=entry["fault"],
                            on_fault=drain_window,
                        )
                        if stats is None:
                            # Jointly degraded: every host routes this
                            # round's chunk to the host oracle; none
                            # re-enters the program.
                            degraded.extend(local.docs)
                            return
                    po, alive = pipeline.assemble_phase(local, stats, ph)
                    outcomes.extend(po)
                    absorb(eb, alive)
            finally:
                METRICS.inc(
                    "multihost_window_stall_seconds_total",
                    time.perf_counter() - t0,
                )

        for j, (b, r, chunk) in enumerate(plan):
            if guard is not None and guard.bucket_degraded(b):
                # Breaker latched on negotiated verdicts, so every host
                # reaches the same conclusion at the same round and the
                # dispatch is skipped jointly — lockstep preserved
                # without touching the device.
                METRICS.inc("resilience_negotiated_degraded_rounds_total")
                TRACER.instant(
                    "negotiated_bucket_latched",
                    {"bucket": b, "round": r, "phase": phase},
                )
                packs.pop(j, None)
                degraded.extend(chunk)
                continue
            ensure_packed(j)
            with TRACER.span(
                "lockstep_round",
                {"bucket": b, "round": r, "phase": phase,
                 "rows": len(chunk)},
            ):
                item = packs.pop(j)
                local = item.result() if hasattr(item, "result") else item
                record_occupancy(local)
                out, fault = launch(local, phase)
            window.append({
                "batch": local, "bucket": b, "phase": phase,
                "out": out, "fault": fault,
            })
            TRACER.counter("lockstep_window", len(window))
            while len(window) > depth:
                resolve_front()
        while window:
            resolve_front()
        if last:
            break
        fallback.extend(next_over)
        current = next_current
    METRICS.inc(
        "multihost_lockstep_seconds_total",
        time.perf_counter() - lockstep_t0,
    )

    for d in fallback:
        METRICS.inc("worker_host_fallback_total")
        o = execute_processing_pipeline(pipeline.host_executor, d)
        if o is not None:
            outcomes.append(o)
    if degraded:
        # Degraded rounds re-run start to finish on the bit-exact host
        # oracle (mid-phase re-stamp contract, ops/pipeline.py _host_rerun),
        # so outcomes stay byte-identical to a fault-free run.
        outcomes.extend(pipeline._host_rerun(degraded))
    return outcomes


def run_multihost(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    *,
    coordinator: str,
    num_processes: int,
    process_id: int,
    text_column: str = "text",
    id_column: str = "id",
    buckets: Sequence[int] = (512, 2048, 8192),
    read_batch_size: int = 1024,
    device_batch: Optional[int] = None,
    auto_geometry: bool = False,
    errors_file: Optional[str] = None,
    force: bool = False,
    run_report: Optional[str] = None,
    provenance: Optional[dict] = None,
    exchange_deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S,
    lease_ttl_s: float = DEFAULT_LEASE_TTL_S,
    elastic: bool = False,
):
    """Production multi-host entry (``textblast run --coordinator ...``).

    ``run_report`` (must be passed on EVERY process or on none — the
    snapshot exchange is a collective) makes each process contribute its
    metrics-delta snapshot over :func:`host_allgather_obj` after the totals
    barrier; process 0 writes a merged run report to that path with both
    the per-host snapshots and the summed totals.  ``provenance`` is the
    config-provenance dict embedded in the report.

    Each process reads its contiguous row stripe of ``input_file`` (the
    static shard assignment SURVEY.md §2.5 maps the task queue onto), runs
    the negotiated lockstep schedule, and writes a per-host
    ``<output>.shard<i>`` / ``<excluded>.shard<i>`` Parquet pair (plus an
    ``<errors>.shard<i>`` dead-letter shard when ``errors_file`` is given —
    the per-host slice of PR 1's sink).  After a global barrier, process 0
    merges each shard set into its final file **atomically**
    (:func:`merge_shard_files`: tmp + fsync + rename, shards deleted only
    after every rename lands) — the results-queue aggregation analogue,
    producer_logic.rs:109-196.  Stale ``*.shard*`` leftovers from a crashed
    run with different ``--num-processes`` fail the run fast on every
    process unless ``force`` removes them.

    Returns an ``AggregationResult``: global totals on process 0 (after the
    merge), local totals elsewhere.

    Failure behavior (measured, tests/test_multihost.py +
    tests/test_multihost_chaos.py + tests/test_elastic_membership.py): a
    *retryable device fault* on any host no longer kills the job —
    ``run_local_shard``'s negotiated guard retries the round jointly on
    every host and, past the budget, degrades it to the host oracle jointly
    (outcomes stay byte-identical).  If a process *dies* mid-run, survivors
    do not wait forever on the next exchange: every KV-transport allgather
    is bounded by ``exchange_deadline_s`` and on expiry raises a typed
    :exc:`PeerFailure` naming the exchange coordinates and every rank that
    never posted, with dead-versus-slow resolved against the renewable KV
    liveness leases each process maintains (TTL ``lease_ttl_s``, renewed by
    a daemon heartbeat at TTL/3).  The accelerator collective path carries
    no host-side deadline — there, and for deadlines configured beyond it,
    the jax coordination-service heartbeat teardown (~90 s, UNAVAILABLE to
    every healthy task) remains the backstop.  After a ``PeerFailure`` the
    lockstep run is re-launched whole — the lockstep contract cannot
    reshape a live gang.

    ``elastic=True`` trades the lockstep contract for membership that can
    shrink, grow, and restart in place (:func:`_run_elastic`): processes
    coordinate through renewable leases and per-stripe checkpoint cursors
    on the shared filesystem instead of ``jax.distributed`` collectives,
    survivors adopt a dead rank's stripe at the membership-epoch bump, and
    a relaunched rank rejoins mid-run resuming from the committed cursor —
    replaying zero completed chunks, with outcomes byte-identical to a
    fault-free run.  Incompatible with ``run_report``/``auto_geometry``
    (both are defined in terms of full-gang collectives).
    """
    import os
    from itertools import islice

    import pyarrow.parquet as pq

    from ..errors import PipelineError
    from ..orchestration import (
        AggregationResult,
        aggregate_results_from_stream,
        read_documents,
    )
    from ..resilience import DeadLetterSink
    from ..resilience.faults import arm_from_env
    from ..utils.metrics import (
        METRICS,
        build_run_report,
        metrics_snapshot,
        write_run_report,
    )

    finals = [output_file, excluded_file]
    if errors_file is not None:
        finals.append(errors_file)
    stale = detect_stale_shards(finals, num_processes)
    if stale:
        if not force:
            # Checked on EVERY process before joining the coordinator, so
            # the whole gang exits fast instead of one host discovering the
            # problem after the run.
            raise PipelineError(
                "stale shard files from a previous run would be ignored by "
                f"the merge: {', '.join(stale)} — remove them or pass "
                "--force to overwrite"
            )
        for s in stale:
            try:
                os.remove(s)
            except FileNotFoundError:
                pass  # a peer on a shared filesystem got there first
            else:
                METRICS.inc("multihost_stale_shards_removed_total")

    if elastic:
        if run_report is not None or auto_geometry:
            raise PipelineError(
                "--elastic is incompatible with --run-report and "
                "--auto-geometry: both are full-gang collectives, and "
                "elastic membership deliberately has no lockstep exchanges "
                "to carry them"
            )
        return _run_elastic(
            config,
            input_file,
            output_file,
            excluded_file,
            num_processes=num_processes,
            process_id=process_id,
            text_column=text_column,
            id_column=id_column,
            buckets=buckets,
            read_batch_size=read_batch_size,
            device_batch=device_batch,
            errors_file=errors_file,
            lease_ttl_s=lease_ttl_s,
            force=force,
        )

    initialize(coordinator, num_processes, process_id)
    if jax.process_count() != num_processes:
        # Without this, a topology mismatch (typically jax.distributed
        # already initialized with different numbers) surfaces as a hang or
        # a shape error deep inside the first allgather.
        raise PipelineError(
            f"--num-processes {num_processes} does not match the "
            f"initialized distributed runtime "
            f"(jax.process_count()={jax.process_count()}); all processes "
            "must be launched with the same topology, and an existing "
            "jax.distributed initialization cannot be re-shaped"
        )
    arm_from_env(process_id=process_id)
    configure_exchange(deadline_s=exchange_deadline_s)
    heartbeat = None
    if jax.process_count() > 1 and _distributed_initialized():
        # Liveness leases ride the same coordination-service KV store the
        # exchanges do, so an expired exchange deadline can tell the user
        # WHICH missing ranks are dead (lease expired) vs merely slow.
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
        if client is not None:
            store = KVLeaseStore(client, process_id, lease_ttl_s)
            store.post()
            heartbeat = LeaseHeartbeat(
                store, max(0.05, lease_ttl_s / 3.0)
            )
            heartbeat.start()
            configure_exchange(
                deadline_s=exchange_deadline_s,
                lease_store=store,
                reset=False,
            )
    try:
        mesh = global_data_mesh()
        _align_trace_clocks()

        import time as _time

        # Run-report scope starts here: everything after distributed init is
        # this run's work, so the snapshot deltas attribute only it.
        values_before = metrics_snapshot() if run_report is not None else {}
        wall_t0 = _time.perf_counter()

        n_rows = pq.ParquetFile(input_file).metadata.num_rows
        stride = math.ceil(n_rows / max(num_processes, 1))
        skip = min(process_id * stride, n_rows)
        take = max(0, min(stride, n_rows - skip))

        # Per-host dead-letter shard, merged by process 0 exactly like
        # kept/excluded.  Created eagerly (DeadLetterSink writes the empty
        # file up front) so the merge never races a host that recorded
        # nothing.
        deadletter = (
            DeadLetterSink(f"{errors_file}.shard{process_id}")
            if errors_file is not None
            else None
        )

        read_errors = 0
        docs: List[TextDocument] = []
        stream = read_documents(
            input_file,
            text_column=text_column,
            id_column=id_column,
            batch_size=read_batch_size,
            skip_rows=skip,
        )
        for item in islice(stream, take):  # one stream item per Parquet row
            if isinstance(item, PipelineError):
                read_errors += 1
                if deadletter is not None:
                    deadletter.record_read_error(item)
            else:
                docs.append(item)

        from ..ops.pipeline import CompiledPipeline

        geometry = None
        if auto_geometry:
            # Geometry negotiation: each host histograms ITS shard's
            # document lengths over the fixed shape-stable bin edges, the
            # histograms are allgathered and summed elementwise, and every
            # host derives the geometry from the identical merged histogram
            # — so the lockstep round schedule (which depends on buckets
            # and batch sizes) stays in agreement without shipping raw
            # lengths across hosts.
            from ..ops.geometry import (
                geometry_from_histogram,
                length_histogram,
            )

            hist = length_histogram([len(d.content) for d in docs])
            hist = host_allgather(hist).sum(axis=0)
            if hist.sum() > 0:
                geometry = geometry_from_histogram(
                    hist, backend=jax.default_backend()
                )

        pipeline = CompiledPipeline(
            config, buckets=tuple(sorted(buckets)), batch_size=device_batch,
            mesh=mesh, geometry=geometry,
        )
        from ..ops.pipeline import maybe_warmup

        # Warm ahead of the lockstep rounds (see run_local_shard): compile
        # stalls must not land mid-round where peers wait at the allgather.
        maybe_warmup(pipeline)
        try:
            outcomes = run_local_shard(
                config, docs, buckets=pipeline.geometry.buckets, mesh=mesh,
                pipeline=pipeline,
            )

            shard_out = f"{output_file}.shard{process_id}"
            shard_exc = f"{excluded_file}.shard{process_id}"
            result = aggregate_results_from_stream(
                iter(outcomes), shard_out, shard_exc, deadletter=deadletter
            )
        finally:
            # The shard must be complete on disk before the totals barrier
            # releases process 0 into the merge.
            if deadletter is not None:
                deadletter.close()
        result.read_errors = read_errors

        totals = np.array(
            [result.received, result.success, result.filtered,
             result.errors, result.read_errors],
            dtype=np.int64,
        )
        # Barrier doubling as the totals exchange: every process must have
        # closed its shard files before process 0 merges (host_allgather's
        # blocking gets release only once every peer has posted).
        all_totals = host_allgather(totals).reshape(-1, 5)

        # Cross-host metrics aggregation: one more lockstep exchange
        # carrying each process's metrics-delta snapshot (a few KiB of
        # JSON), so host 0's report survives the other processes' exit.
        # Runs on EVERY process or on none — see the docstring contract.
        host_reports = None
        if run_report is not None:
            now = metrics_snapshot()
            local_delta = {
                k: round(now.get(k, 0.0) - values_before.get(k, 0.0), 6)
                for k in set(now) | set(values_before)
                if now.get(k, 0.0) != values_before.get(k, 0.0)
            }
            host_reports = host_allgather_obj(
                {
                    "process": process_id,
                    "wall_time_s": round(
                        _time.perf_counter() - wall_t0, 3
                    ),
                    "counts": {
                        "received": result.received,
                        "success": result.success,
                        "filtered": result.filtered,
                        "errors": result.errors,
                        "read_errors": result.read_errors,
                    },
                    "metrics": local_delta,
                }
            )

        if process_id == 0:
            merge_shard_files(
                [
                    (
                        final,
                        [f"{final}.shard{i}" for i in range(num_processes)],
                    )
                    for final in finals
                ]
            )
            g = all_totals.sum(axis=0)
            merged = AggregationResult()
            merged.received, merged.success, merged.filtered = (
                int(g[0]), int(g[1]), int(g[2])
            )
            merged.errors, merged.read_errors = int(g[3]), int(g[4])
            if host_reports is not None:
                from ..utils.metrics import _SPECS

                summed: dict = {}
                for h in host_reports:
                    for k, v in h["metrics"].items():
                        # Counters sum across hosts; gauges (gang-agreed
                        # values like the negotiated window depth) merge
                        # by max so the report shows the value, not n x it.
                        if _SPECS.get(k, ("counter",))[0] == "gauge":
                            summed[k] = max(summed.get(k, v), v)
                        else:
                            summed[k] = summed.get(k, 0.0) + v
                report = build_run_report(
                    values=summed,
                    wall_time_s=max(
                        h["wall_time_s"] for h in host_reports
                    ),
                    counts={
                        "received": merged.received,
                        "success": merged.success,
                        "filtered": merged.filtered,
                        "errors": merged.errors,
                        "read_errors": merged.read_errors,
                    },
                    provenance=provenance,
                    hosts=host_reports,
                )
                write_run_report(run_report, report)
            return merged
        return result
    except PeerFailure:
        # A peer is gone: the coordination service's shutdown barrier can
        # never complete, and jax's atexit hook would hold this process
        # hostage until the service's own heartbeat teardown (~95 s on this
        # stack).  Abandon the distributed client so the survivor's exit is
        # as fast as its diagnosis.
        _abandon_distributed()
        raise
    finally:
        if heartbeat is not None:
            heartbeat.stop()


def _abandon_distributed() -> None:
    """Drop the ``jax.distributed`` client without the shutdown barrier.

    ``DistributedRuntimeClient.shutdown()`` is a full-gang barrier — with a
    dead rank it blocks until the coordination service force-terminates the
    survivors.  After a :class:`PeerFailure` the gang is known-broken, so
    the only useful exit is a non-graceful one: null the client reference
    (jax's atexit ``clean_up`` then skips the barrier) and leave the
    service (if this host runs it) to die with the process."""
    try:
        from jax._src import distributed

        distributed.global_state.client = None
        distributed.global_state.preemption_sync_manager = None
    except Exception as e:  # pragma: no cover - jax internals moved
        import sys

        print(
            f"warning: could not abandon distributed client ({e}); exit may "
            "stall until the coordination service tears the gang down",
            file=sys.stderr,
            flush=True,
        )


def _run_elastic(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    *,
    num_processes: int,
    process_id: int,
    text_column: str,
    id_column: str,
    buckets: Sequence[int],
    read_batch_size: int,
    device_batch: Optional[int],
    errors_file: Optional[str],
    lease_ttl_s: float,
    force: bool,
):
    """Elastic membership execution (``--elastic``) — no lockstep, no gang.

    Processes are deliberately NOT coupled through ``jax.distributed``:
    on this container's jax the coordination service force-terminates every
    healthy task ~90-100 s after a peer stops heartbeating, which is the
    opposite of elasticity.  Coordination instead lives entirely on the
    shared filesystem under ``<output>.membership/`` (the same filesystem
    the shard merge already assumes): per-rank lease files
    (:class:`FileMembershipStore`), and one checkpoint directory per input
    *stripe* with a fenced, owner-tokened cursor
    (:func:`~textblaster_tpu.checkpoint.run_stripe_checkpointed`).
    ``--coordinator`` is accepted but unused.

    The protocol, per heartbeat interval:

    1. **Self-fence** — a process whose own lease went stale (or was taken
       over by a newer incarnation of its rank) stops committing and dies;
       its last unfenced commit races the adopter only within the lease
       TTL, and lineage-scoped part files + the single atomic cursor
       rename make any interleaving converge (worst case: one chunk is
       reprocessed, committed once).
    2. **Observe membership** — live set changes bump the membership epoch
       (:class:`EpochTracker`), printing eviction/rejoin transitions.
    3. **Own and advance stripes** — stripe ``s`` belongs to live rank
       ``s``, orphans to the lowest live rank (:func:`stripe_owner`).
       Claiming rewrites the cursor's owner token
       (:meth:`CheckpointState.adopt`); committed work transfers verbatim,
       so adoption and restart-in-place replay **zero completed chunks**.
       A relaunched rank simply re-registers a lease under a fresh
       incarnation and reclaims its cursor; its zombie predecessor (if
       any) loses ownership at its next fence.
    4. **Merge** — when every stripe's cursor shows its window consumed,
       the lowest live rank (merge duty fails over exactly like stripe
       ownership) concatenates all stripes' part files — in stripe order,
       so output order is independent of which ranks did the work — into
       the final kept/excluded (and dead-letter) files atomically with an
       explicit schema (:func:`_commit_concat`), then removes the
       membership directory.

    Byte parity: chunk boundaries are device-batch flush barriers and the
    stripe windows are the same contiguous row ranges the lockstep path
    uses, so outputs are byte-identical to an uninterrupted (or
    single-host) run regardless of kills, adoptions, or rejoins.

    Returns an ``AggregationResult``: global totals on the merging rank,
    this rank's local contribution elsewhere.
    """
    import os
    import shutil

    from ..checkpoint import (
        CheckpointState,
        StripeLost,
        _config_fingerprint,
        _input_fingerprint,
        run_stripe_checkpointed,
    )
    from ..errors import PipelineError
    from ..io.parquet_writer import OUTPUT_SCHEMA
    from ..ops.geometry import DeviceGeometry
    from ..ops.pipeline import CompiledPipeline, process_documents_device
    from ..orchestration import AggregationResult
    from ..resilience.deadletter import DEADLETTER_SCHEMA
    from ..resilience.faults import arm_from_env
    from ..resilience.membership import EpochTracker, FileMembershipStore
    from ..resilience.membership import stripe_owner as owner_of
    from ..utils.metrics import METRICS
    from .mesh import data_mesh

    import pyarrow.parquet as pq

    root = f"{output_file}.membership"

    def say(msg: str) -> None:
        # stdout + flush: the chaos tests stream these lines to time their
        # SIGKILLs, and operators of a 2-terminal run read them live.
        print(f"elastic[{process_id}]: {msg}", flush=True)

    if force and os.path.isdir(root):
        shutil.rmtree(root)
        say(f"removed leftover membership dir {root} (--force)")

    fingerprint = _input_fingerprint(input_file)
    config_hash = _config_fingerprint(config)
    arm_from_env(process_id=process_id)

    store = FileMembershipStore(root, process_id, lease_ttl_s)
    store.register()
    if TRACER.enabled:
        # File-backend analogue of _align_trace_clocks: the first process
        # to register wrote the run's wall-clock origin; every tracer
        # shifts onto it, no collective needed.
        t0 = store.t0_us()
        if t0 is not None:
            TRACER.align(
                TRACER.wall_at_origin_us() - t0,
                args={"origin_wall_us": t0, "backend": "file"},
            )
    interval = max(0.05, lease_ttl_s / 3.0)
    heartbeat = LeaseHeartbeat(store, interval).start()

    mesh = data_mesh() if len(jax.devices()) > 1 else None
    pipeline = CompiledPipeline(
        config, buckets=tuple(sorted(buckets)), batch_size=device_batch,
        mesh=mesh,
    )
    from ..ops.pipeline import maybe_warmup

    # Warm (or AOT-cache-load) the program set before claiming a stripe —
    # a restarted-in-place elastic member re-enters with warm executables
    # instead of re-paying the cold compile inside its adopted stripe.
    maybe_warmup(pipeline)

    n_rows = pq.ParquetFile(input_file).metadata.num_rows
    stride = math.ceil(n_rows / max(num_processes, 1))

    # Overlapped stripe residue (PR 9): reuse the window config so each
    # process keeps pipeline_depth stripe chunks in flight — one being
    # processed/committed, the rest decoding on the prefetch thread.  Reads
    # are side-effect-free, so fence/commit semantics are untouched and
    # chunk boundaries stay at stripe order.
    oc = getattr(config, "overlap", None)
    read_ahead = 0
    if (
        oc is not None
        and oc.enabled
        and os.environ.get("TEXTBLAST_NO_OVERLAP") != "1"
    ):
        read_ahead = max(1, oc.pipeline_depth - 1)

    def window(s: int) -> Tuple[int, int]:
        # Identical striping to the lockstep path, computed from the input
        # alone — every process (and every relaunch) derives the same
        # windows without communicating.
        skip = min(s * stride, n_rows)
        return skip, max(0, min(stride, n_rows - skip))

    def stripe_done(s: int, st: Optional[CheckpointState] = None) -> bool:
        _skip, take = window(s)
        if take <= 0:
            return True
        if st is None:
            st = CheckpointState.load(store.stripe_dir(s))
        return st is not None and st.rows_consumed >= take

    my_token = {"rank": process_id, "incarnation": store.incarnation}
    lineage = f"-r{process_id}x{store.incarnation}"
    tracker = EpochTracker(process_id)
    local = AggregationResult()
    say(
        f"joined membership (incarnation {store.incarnation}, "
        f"{num_processes} stripe(s), lease ttl {lease_ttl_s:g}s)"
    )

    def self_fence() -> None:
        if heartbeat.failed or not store.my_lease_fresh():
            raise PipelineError(
                f"rank {process_id} self-fenced: its liveness lease went "
                f"stale (ttl {lease_ttl_s:g}s) or a newer incarnation of "
                "this rank took over; committing now could race the "
                "stripe's adopter, so this process stops instead"
            )

    try:
        while True:
            self_fence()
            live = store.live_ranks()
            for msg in tracker.observe(live):
                say(msg)
            progressed = False
            for s in range(num_processes):
                _skip, take = window(s)
                if take <= 0 or stripe_done(s):
                    continue
                if owner_of(s, live) != process_id:
                    continue
                st_dir = store.stripe_dir(s)
                cur = CheckpointState.load(st_dir)
                if cur is None or cur.owner != my_token:
                    st = CheckpointState.adopt(
                        st_dir, my_token,
                        input_fingerprint=fingerprint,
                        config_hash=config_hash,
                    )
                    if s != process_id:
                        METRICS.inc("multihost_adopted_stripes_total")
                        TRACER.instant(
                            "stripe_adopted",
                            {"stripe": s, "epoch": tracker.epoch},
                        )
                        say(
                            f"adopted stripe {s} at row {st.rows_consumed}"
                            f"/{take} (epoch {tracker.epoch})"
                        )
                    elif st.rows_consumed > 0:
                        say(
                            f"stripe {s} resume at row {st.rows_consumed}"
                            f"/{take} (epoch {tracker.epoch})"
                        )
                else:
                    st = cur
                recorded = (
                    DeviceGeometry.from_dict(st.geometry)
                    if st.geometry is not None
                    else None
                )
                if recorded is not None:
                    if (
                        recorded.fingerprint()
                        != pipeline.geometry.fingerprint()
                    ):
                        # Chunk boundaries are batch flush barriers; a
                        # different geometry would batch the remainder
                        # differently than the original owner did.
                        raise PipelineError(
                            f"stripe {s} cursor was created with device "
                            f"geometry {recorded.describe()}, but this "
                            "process resolves to "
                            f"{pipeline.geometry.describe()}; every "
                            "elastic participant must run the identical "
                            "--buckets/--device-batch"
                        )
                else:
                    st.geometry = pipeline.geometry.to_dict()

                skip, take = window(s)
                before = (
                    st.received, st.success, st.filtered, st.errors,
                    st.read_errors,
                )

                def fence(s=s, st_dir=st_dir) -> None:
                    self_fence()
                    if owner_of(s, store.live_ranks()) != process_id:
                        raise StripeLost(
                            f"stripe {s} ownership moved (membership "
                            "changed)"
                        )
                    reloaded = CheckpointState.load(st_dir)
                    if reloaded is not None and reloaded.owner != my_token:
                        raise StripeLost(
                            f"stripe {s} cursor claimed by "
                            f"{reloaded.owner}"
                        )

                def on_chunk(state: CheckpointState, s=s, take=take) -> None:
                    say(
                        f"stripe {s} committed rows "
                        f"{state.rows_consumed}/{take} "
                        f"(epoch {tracker.epoch})"
                    )

                done = run_stripe_checkpointed(
                    input_file,
                    st_dir,
                    state=st,
                    skip_rows=skip,
                    take_rows=take,
                    chunk_size=read_batch_size,
                    process_chunk=lambda items, on_err: (
                        process_documents_device(
                            config, items, on_read_error=on_err,
                            pipeline=pipeline,
                        )
                    ),
                    fence=fence,
                    lineage=lineage,
                    text_column=text_column,
                    id_column=id_column,
                    record_dead=errors_file is not None,
                    on_chunk=on_chunk,
                    read_ahead=read_ahead,
                )
                local.received += st.received - before[0]
                local.success += st.success - before[1]
                local.filtered += st.filtered - before[2]
                local.errors += st.errors - before[3]
                local.read_errors += st.read_errors - before[4]
                progressed = True
                if not done:
                    say(f"stripe {s} lost to another owner; moving on")
            if all(stripe_done(s) for s in range(num_processes)):
                break
            if not progressed:
                time.sleep(interval)
    finally:
        heartbeat.stop()

    live = store.live_ranks()
    merger = min(live) if live else process_id
    if process_id != merger:
        store.withdraw()
        say(f"all stripes consumed; rank {merger} merges; local done")
        return local

    # Merge duty: lowest live rank (fails over like stripe ownership —
    # if the merger dies here, any relaunched/surviving rank re-enters,
    # finds every stripe done, and repeats this idempotent, atomic merge).
    cursors = [
        CheckpointState.load(store.stripe_dir(s))
        for s in range(num_processes)
    ]

    def parts(attr: str) -> List[str]:
        return [
            os.path.join(store.stripe_dir(s), name)
            for s, cur in enumerate(cursors)
            if cur is not None
            for name in getattr(cur, attr)
        ]

    _commit_concat(output_file, parts("out_parts"), OUTPUT_SCHEMA)
    _commit_concat(excluded_file, parts("excl_parts"), OUTPUT_SCHEMA)
    if errors_file is not None:
        _commit_concat(errors_file, parts("err_parts"), DEADLETTER_SCHEMA)
    merged = AggregationResult()
    for cur in cursors:
        if cur is None:
            continue
        merged.received += cur.received
        merged.success += cur.success
        merged.filtered += cur.filtered
        merged.errors += cur.errors
        merged.read_errors += cur.read_errors
    store.withdraw()
    shutil.rmtree(root, ignore_errors=True)
    say(
        f"merged {num_processes} stripe(s): {merged.received} outcomes "
        f"({merged.success} kept, {merged.filtered} excluded, "
        f"{merged.errors} errors, {merged.read_errors} read errors)"
    )
    return merged


def _main(argv: Optional[Sequence[str]] = None) -> int:
    """Per-process module entry — a thin alias for
    ``textblast run --coordinator ...`` (the production path, `cli.py`)."""
    import argparse

    from ..config.pipeline import load_pipeline_config
    from ..utils.metrics import setup_prometheus_metrics

    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--pipeline-config", required=True)
    ap.add_argument("-i", "--input-file", required=True)
    ap.add_argument("-o", "--output-file", required=True)
    ap.add_argument("-e", "--excluded-file", required=True)
    ap.add_argument("--errors-file", default=None)
    ap.add_argument("--text-column", default="text")
    ap.add_argument("--id-column", default="id")
    ap.add_argument("--read-batch-size", type=int, default=1024)
    ap.add_argument("--buckets", default="512,2048,8192")
    ap.add_argument("--device-batch", type=int, default=None)
    ap.add_argument("--auto-geometry", action="store_true")
    ap.add_argument("--force", action="store_true")
    ap.add_argument(
        "--exchange-deadline-s", type=float,
        default=DEFAULT_EXCHANGE_DEADLINE_S,
        help="budget for each lockstep KV exchange; on expiry a typed "
        "PeerFailure names the rank(s) that never posted",
    )
    ap.add_argument(
        "--lease-ttl-s", type=float, default=DEFAULT_LEASE_TTL_S,
        help="liveness-lease TTL (renewed at TTL/3); a rank whose lease "
        "is older is classified dead",
    )
    ap.add_argument(
        "--elastic", action="store_true",
        help="elastic membership: shared-filesystem leases + per-stripe "
        "checkpoint cursors; survivors adopt dead ranks' stripes and "
        "relaunched ranks rejoin in place",
    )
    ap.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="in-flight lockstep round window for THIS host; the joint "
        "depth is the min over every host's value, allgathered once at "
        "run start (cli.py run exposes the same flag)",
    )
    ap.add_argument(
        "--no-overlap", action="store_true",
        help="disable the overlapped pipeline on this host (negotiates "
        "the whole gang down to serial depth 1)",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve /metrics on this port + process-id (the offset keeps "
        "co-located processes from colliding on the bind)",
    )
    ap.add_argument(
        "--trace", default=None, metavar="OUT.JSON",
        help="record a Chrome trace (process 0 writes OUT.JSON, process i "
        "writes OUT.JSON.host<i>)",
    )
    ap.add_argument(
        "--run-report", default=None, metavar="REPORT.JSON",
        help="process 0 writes a merged machine-readable run report "
        "(pass on every process — the snapshot exchange is a collective)",
    )
    args = ap.parse_args(argv)

    if args.metrics_port is not None:
        setup_prometheus_metrics(args.metrics_port + args.process_id)
    if args.trace:
        trace_path = (
            args.trace if args.process_id == 0
            else f"{args.trace}.host{args.process_id}"
        )
        TRACER.configure(
            trace_path,
            process_name=f"textblast-host{args.process_id}",
            pid=args.process_id,
        )

    config = load_pipeline_config(args.pipeline_config)
    if args.no_overlap:
        config.overlap.enabled = False
    if args.pipeline_depth is not None:
        config.overlap.pipeline_depth = max(1, args.pipeline_depth)
    try:
        result = run_multihost(
            config,
            args.input_file,
            args.output_file,
            args.excluded_file,
            coordinator=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
            text_column=args.text_column,
            id_column=args.id_column,
            read_batch_size=args.read_batch_size,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            device_batch=args.device_batch,
            auto_geometry=args.auto_geometry,
            errors_file=args.errors_file,
            force=args.force,
            run_report=args.run_report,
            exchange_deadline_s=args.exchange_deadline_s,
            lease_ttl_s=args.lease_ttl_s,
            elastic=args.elastic,
            provenance={
                "entry": "textblaster_tpu.parallel.multihost",
                "pipeline_config": args.pipeline_config,
                "steps": [s.type for s in config.pipeline],
                "input_file": args.input_file,
                "num_processes": args.num_processes,
                "buckets": args.buckets,
                "auto_geometry": args.auto_geometry,
            },
        )
    finally:
        TRACER.close()
    print(
        f"process {args.process_id}: {result.received} outcomes "
        f"({result.success} kept, {result.filtered} excluded)"
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
