"""Device mesh + sharding for the compiled pipeline.

The reference scales by adding competing consumers on a RabbitMQ queue
(SURVEY.md §2.5); here the equivalent is SPMD data parallelism over a
``jax.sharding.Mesh``: packed batches are sharded along the ``data`` axis, the
compiled filter program runs identically on every chip over its shard, and
the (small) integer stat outputs are gathered back to the host — the
"all-gather keep/drop masks over ICI" of the BASELINE.json north star.  The
per-document kernels have no cross-document dependencies, so XLA partitions
them without inserting any collectives until the output gather; scaling is
linear in chips modulo input-feed bandwidth.

Multi-host: :mod:`textblaster_tpu.parallel.multihost` — every process joins a
``jax.distributed`` coordinator, the mesh spans all hosts' devices, each host
feeds its local shard (``jax.make_array_from_process_local_data``) and
assembles outcomes from its addressable output rows; cross-host traffic rides
DCN where XLA places it.  Exercised by ``tests/test_multihost.py`` as a
2-process CPU job.  Single-host multi-chip needs no extra code.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["data_mesh", "shard_batch", "batch_sharding"]

DATA_AXIS = "data"


def data_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices along the ``data`` axis."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (DATA_AXIS,))


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (documents) across the mesh; other axes replicated."""
    spec = P(DATA_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def shard_batch(mesh: Mesh, cps: np.ndarray, lengths: np.ndarray):
    """Place a packed batch on the mesh, sharded along the document axis."""
    cps_s = jax.device_put(cps, batch_sharding(mesh, 2))
    len_s = jax.device_put(lengths, batch_sharding(mesh, 1))
    return cps_s, len_s
