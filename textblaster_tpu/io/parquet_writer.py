"""Parquet writer with the reference's fixed output schema.

Re-implementation of ``ParquetWriter``
(``/root/reference/src/pipeline/writers/parquet_writer.rs:17-165``):

* schema: ``id`` Utf8 (non-null), ``source`` Utf8 (non-null), ``text`` Utf8
  (non-null), ``added`` Date32 (nullable), ``created``
  Struct{start,end: Timestamp(us)} (nullable), ``metadata`` Utf8 JSON-or-null;
* empty metadata maps write as null (rs:104-111, SURVEY.md §7 quirk #3);
* explicit :meth:`close` finalizes the file footer (rs:159-164).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from ..data_model import TextDocument
from ..errors import ParquetError
from ..utils.metrics import METRICS
from ..utils.telemetry import TELEMETRY
from ..utils.trace import TRACER
from .base import BaseWriter

__all__ = ["ParquetWriter", "OUTPUT_SCHEMA"]

_TS = pa.timestamp("us")

OUTPUT_SCHEMA = pa.schema(
    [
        pa.field("id", pa.string(), nullable=False),
        pa.field("source", pa.string(), nullable=False),
        pa.field("text", pa.string(), nullable=False),
        pa.field("added", pa.date32(), nullable=True),
        pa.field(
            "created",
            pa.struct(
                [pa.field("start", _TS, nullable=True), pa.field("end", _TS, nullable=True)]
            ),
            nullable=True,
        ),
        pa.field("metadata", pa.string(), nullable=True),
    ]
)


class ParquetWriter(BaseWriter):
    def __init__(self, path: str) -> None:
        try:
            self._writer: Optional[pq.ParquetWriter] = pq.ParquetWriter(
                path, OUTPUT_SCHEMA
            )
        except Exception as e:
            raise ParquetError(str(e)) from e
        self.path = path

    def write_batch(self, documents: Sequence[TextDocument]) -> None:
        if not documents:
            return
        if TELEMETRY.enabled:
            TELEMETRY.mark("write", (d.id for d in documents))
        t0 = time.perf_counter()
        try:
            with TRACER.span("write", {"rows": len(documents)}):
                self._write_batch_inner(documents)
        finally:
            # Timed here (not in callers) so every write path — runner,
            # checkpoint parts, the threaded writer — lands in the stage
            # counter exactly once.
            METRICS.inc("stage_write_seconds", time.perf_counter() - t0)
        if TELEMETRY.enabled:
            # The single seam every persisted document passes through:
            # close sampled lineages here, and feed the chars/s rollup.
            METRICS.inc(
                "writer_chars_total", sum(len(d.content) for d in documents)
            )
            TELEMETRY.complete(documents)

    def _write_batch_inner(self, documents: Sequence[TextDocument]) -> None:
        ids: List[str] = []
        sources: List[str] = []
        texts: List[str] = []
        added: List = []
        created: List = []
        metadata: List[Optional[str]] = []
        for doc in documents:
            ids.append(doc.id)
            sources.append(doc.source)
            texts.append(doc.content)
            added.append(doc.added)
            created.append(
                {"start": doc.created[0], "end": doc.created[1]}
                if doc.created
                else None
            )
            metadata.append(
                json.dumps(doc.metadata, ensure_ascii=False, separators=(",", ":"))
                if doc.metadata
                else None  # empty map -> null (rs:104-111)
            )
        batch = pa.record_batch(
            [
                pa.array(ids, pa.string()),
                pa.array(sources, pa.string()),
                pa.array(texts, pa.string()),
                pa.array(added, pa.date32()),
                pa.array(created, OUTPUT_SCHEMA.field("created").type),
                pa.array(metadata, pa.string()),
            ],
            schema=OUTPUT_SCHEMA,
        )
        if self._writer is None:
            raise ParquetError(f"writer for '{self.path}' is closed")
        try:
            self._writer.write_batch(batch)
        except Exception as e:
            raise ParquetError(str(e)) from e

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
