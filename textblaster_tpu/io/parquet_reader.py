"""Parquet reader with the reference's schema handling and quirks.

Re-implementation of ``ParquetReader``
(``/root/reference/src/pipeline/readers/parquet_reader.rs:18-252``) on
pyarrow.  Reproduces:

* required, configurable text + id columns — missing column is a
  ``ConfigError``; text must be a UTF-8 type (parquet_reader.rs:27-41);
* optional fixed-name columns: ``source`` (fallback = file path,
  rs:181-190), ``added`` (Date32 or microsecond timestamp -> date,
  rs:43-63), ``created`` (struct of two timestamps; both must be non-null,
  rs:197-213), ``metadata`` (JSON string -> dict; parse errors -> warn +
  empty map, rs:215-230);
* null text/id rows yield per-row errors, not a failed read (rs:159-173);
* the text column is **HTML-entity-decoded** at read time (rs:177-179).

For the TPU feed path the reader also exposes :meth:`read_batches`, which
yields raw Arrow record batches so the packer can build device byte tensors
straight from Arrow's offsets+data buffers without per-document Python
objects.
"""

from __future__ import annotations

import html
import json
import logging
from dataclasses import dataclass
from typing import Iterator, Optional, Union

import pyarrow as pa
import pyarrow.parquet as pq

from ..data_model import TextDocument
from ..errors import ConfigError, ParquetError, PipelineError, UnexpectedError
from .base import BaseReader

logger = logging.getLogger(__name__)

__all__ = ["ParquetInputConfig", "ParquetReader"]


@dataclass
class ParquetInputConfig:
    """Reference ``config/parquet.rs:5-11``."""

    path: str
    text_column: str
    id_column: str
    batch_size: Optional[int] = None


def _to_date(value):
    """Date32 / timestamp cell -> date (parquet_reader.rs:43-63)."""
    if value is None:
        return None
    import datetime as _dt

    if isinstance(value, _dt.datetime):
        return value.date()
    if isinstance(value, _dt.date):
        return value
    return None


def _to_datetime(value):
    if value is None:
        return None
    import datetime as _dt

    if isinstance(value, _dt.datetime):
        return value
    if isinstance(value, _dt.date):
        return _dt.datetime(value.year, value.month, value.day)
    return None


class ParquetReader(BaseReader):
    def __init__(self, config: ParquetInputConfig) -> None:
        self.config = config

    def _open(self) -> pq.ParquetFile:
        try:
            return pq.ParquetFile(self.config.path)
        except FileNotFoundError as e:
            raise ParquetError(str(e)) from e
        except Exception as e:
            raise ParquetError(str(e)) from e

    def _validate_schema(self, schema: pa.Schema) -> None:
        for name in (self.config.text_column, self.config.id_column):
            if schema.get_field_index(name) == -1:
                raise ConfigError(f"Required column '{name}' not found in schema.")
        text_type = schema.field(self.config.text_column).type
        if text_type not in (pa.string(), pa.large_string()):
            raise ConfigError(
                f"Column '{self.config.text_column}' must be Utf8 or LargeUtf8, "
                f"found: {text_type}"
            )

    def read_batches(self, skip_rows: int = 0) -> Iterator[pa.RecordBatch]:
        """Raw Arrow record batches (the zero-copy path for the TPU packer).

        ``skip_rows`` seeks past the first N rows without decoding them:
        fully-consumed row groups are never read (their ``num_rows`` come
        from the footer), and only the partially-consumed group is sliced —
        the row-group cursor the checkpoint subsystem resumes from.
        """
        pf = self._open()
        self._validate_schema(pf.schema_arrow)
        batch_size = self.config.batch_size or 1024

        if skip_rows <= 0:
            yield from pf.iter_batches(batch_size=batch_size)
            return

        md = pf.metadata
        groups = list(range(md.num_row_groups))
        while groups and skip_rows >= md.row_group(groups[0]).num_rows:
            skip_rows -= md.row_group(groups[0]).num_rows
            groups.pop(0)
        for batch in pf.iter_batches(batch_size=batch_size, row_groups=groups):
            if skip_rows:
                if batch.num_rows <= skip_rows:
                    skip_rows -= batch.num_rows
                    continue
                batch = batch.slice(skip_rows)
                skip_rows = 0
            yield batch

    def read_documents(
        self, skip_rows: int = 0
    ) -> Iterator[Union[TextDocument, PipelineError]]:
        pf = self._open()
        schema = pf.schema_arrow
        self._validate_schema(schema)

        has = {name: schema.get_field_index(name) != -1 for name in
               ("source", "added", "created", "metadata")}
        # metadata column must be a string type to be used (rs:92-97).
        if has["metadata"]:
            md_type = schema.field("metadata").type
            if md_type not in (pa.string(), pa.large_string()):
                has["metadata"] = False

        for batch in self.read_batches(skip_rows=skip_rows):
            cols = {name: batch.column(i) for i, name in enumerate(batch.schema.names)}
            text_col = cols[self.config.text_column]
            id_col = cols[self.config.id_column]
            n = batch.num_rows

            source_col = cols.get("source") if has["source"] else None
            added_col = cols.get("added") if has["added"] else None
            created_col = cols.get("created") if has["created"] else None
            metadata_col = cols.get("metadata") if has["metadata"] else None

            for i in range(n):
                if not text_col[i].is_valid:
                    yield UnexpectedError(
                        f"Row {i} has null text column '{self.config.text_column}'"
                    )
                    continue
                if not id_col[i].is_valid:
                    yield UnexpectedError(
                        f"Row {i} has null id column '{self.config.id_column}'"
                    )
                    continue

                doc_id = id_col[i].as_py()
                # HTML-entity decode at ingest (rs:177-179).
                content = html.unescape(text_col[i].as_py())

                source = None
                if source_col is not None and source_col[i].is_valid:
                    source = source_col[i].as_py()
                if source is None:
                    source = self.config.path  # fallback (rs:181-190)

                added = None
                if added_col is not None and added_col[i].is_valid:
                    added = _to_date(added_col[i].as_py())

                created = None
                if created_col is not None and created_col[i].is_valid:
                    cell = created_col[i].as_py()
                    if isinstance(cell, dict) and len(cell) >= 2:
                        vals = list(cell.values())
                        start = _to_datetime(vals[0])
                        end = _to_datetime(vals[1])
                        if start is not None and end is not None:
                            created = (start, end)
                    else:
                        logger.warning("'created' column is not a struct.")

                metadata = {}
                if metadata_col is not None and metadata_col[i].is_valid:
                    raw = metadata_col[i].as_py()
                    try:
                        parsed = json.loads(raw)
                        metadata = (
                            {str(k): str(v) for k, v in parsed.items()}
                            if isinstance(parsed, dict)
                            else {}
                        )
                    except (json.JSONDecodeError, AttributeError) as e:
                        logger.warning(
                            "Failed to parse metadata JSON. id=%s err=%s", doc_id, e
                        )
                        metadata = {}

                yield TextDocument(
                    id=str(doc_id),
                    content=content,
                    source=str(source),
                    added=added,
                    created=created,
                    metadata=metadata,
                )
