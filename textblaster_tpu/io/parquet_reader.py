"""Parquet reader with the reference's schema handling and quirks.

Re-implementation of ``ParquetReader``
(``/root/reference/src/pipeline/readers/parquet_reader.rs:18-252``) on
pyarrow.  Reproduces:

* required, configurable text + id columns — missing column is a
  ``ConfigError``; text must be a UTF-8 type (parquet_reader.rs:27-41);
* optional fixed-name columns: ``source`` (fallback = file path,
  rs:181-190), ``added`` (Date32 or microsecond timestamp -> date,
  rs:43-63), ``created`` (struct of two timestamps; both must be non-null,
  rs:197-213), ``metadata`` (JSON string -> dict; parse errors -> warn +
  empty map, rs:215-230);
* null text/id rows yield per-row errors, not a failed read (rs:159-173);
* the text column is **HTML-entity-decoded** at read time (rs:177-179).

For the TPU feed path the reader also exposes :meth:`read_batches`, which
yields raw Arrow record batches so the packer can build device byte tensors
straight from Arrow's offsets+data buffers without per-document Python
objects.
"""

from __future__ import annotations

import html
import json
import logging
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Union

import pyarrow as pa
import pyarrow.parquet as pq

from ..data_model import TextDocument
from ..errors import ConfigError, ParquetError, PipelineError, UnexpectedError
from ..resilience.faults import FAULTS
from ..resilience.retry import RetryPolicy
from ..utils.metrics import METRICS
from ..utils.telemetry import TELEMETRY
from ..utils.trace import TRACER
from .base import BaseReader

logger = logging.getLogger(__name__)

__all__ = ["ParquetInputConfig", "ParquetReader"]


@dataclass
class ParquetInputConfig:
    """Reference ``config/parquet.rs:5-11``."""

    path: str
    text_column: str
    id_column: str
    batch_size: Optional[int] = None


def _to_date(value):
    """Date32 / timestamp cell -> date (parquet_reader.rs:43-63)."""
    if value is None:
        return None
    import datetime as _dt

    if isinstance(value, _dt.datetime):
        return value.date()
    if isinstance(value, _dt.date):
        return value
    return None


def _to_datetime(value):
    if value is None:
        return None
    import datetime as _dt

    if isinstance(value, _dt.datetime):
        return value
    if isinstance(value, _dt.date):
        return _dt.datetime(value.year, value.month, value.day)
    return None


# Module-default policy for the read seam: every reader is guarded even when
# the caller didn't thread an explicit policy through (run_pipeline does).
_DEFAULT_READ_RETRY: Optional[RetryPolicy] = None


def _default_read_retry() -> RetryPolicy:
    global _DEFAULT_READ_RETRY
    if _DEFAULT_READ_RETRY is None:
        _DEFAULT_READ_RETRY = RetryPolicy()
    return _DEFAULT_READ_RETRY


class _QuarantinedGroup:
    """Sentinel for a row group that stayed unreadable through the retry
    budget: carries how many input rows it held so consumers can keep the
    item<->row accounting exact (the checkpoint cursor depends on it)."""

    __slots__ = ("group", "num_rows", "error")

    def __init__(self, group: int, num_rows: int, error: BaseException) -> None:
        self.group = group
        self.num_rows = num_rows
        self.error = error


class ParquetReader(BaseReader):
    def __init__(
        self,
        config: ParquetInputConfig,
        retry_policy: Optional[RetryPolicy] = None,
    ) -> None:
        self.config = config
        self.retry_policy = retry_policy

    def _open(self) -> pq.ParquetFile:
        try:
            return pq.ParquetFile(self.config.path)
        except FileNotFoundError as e:
            raise ParquetError(str(e)) from e
        except Exception as e:
            raise ParquetError(str(e)) from e

    def _validate_schema(self, schema: pa.Schema) -> None:
        for name in (self.config.text_column, self.config.id_column):
            if schema.get_field_index(name) == -1:
                raise ConfigError(f"Required column '{name}' not found in schema.")
        text_type = schema.field(self.config.text_column).type
        if text_type not in (pa.string(), pa.large_string()):
            raise ConfigError(
                f"Column '{self.config.text_column}' must be Utf8 or LargeUtf8, "
                f"found: {text_type}"
            )

    def _fetch_group(self, pf: pq.ParquetFile, group: int) -> pa.Table:
        """One row group off disk — the guarded read seam.  The fault site
        fires *inside* the retried callable so chaos tests drive the retry
        layer through real control flow."""
        import time

        policy = self.retry_policy or _default_read_retry()

        def fetch() -> pa.Table:
            FAULTS.fire("read.batch")
            return pf.read_row_group(group)

        t0 = time.perf_counter()
        try:
            with TRACER.span("read", {"kind": "fetch", "group": group}):
                return policy.run(fetch, seam="read")
        finally:
            METRICS.inc("stage_read_seconds", time.perf_counter() - t0)

    def _iter_group_batches(
        self, skip_rows: int = 0, on_quarantine=None
    ) -> Iterator[Union[pa.RecordBatch, _QuarantinedGroup]]:
        """Record batches row-group by row-group, each group fetched under
        the read RetryPolicy.

        ``skip_rows`` seeks past the first N rows without decoding them:
        fully-consumed row groups are never read (their ``num_rows`` come
        from the footer), and only the partially-consumed group is sliced —
        the row-group cursor the checkpoint subsystem resumes from.

        A group that stays unreadable through the retry budget is yielded as
        a :class:`_QuarantinedGroup` when ``on_quarantine`` is truthy
        (reading continues at the next group); otherwise the error
        propagates as :class:`ParquetError`.
        """
        pf = self._open()
        self._validate_schema(pf.schema_arrow)
        batch_size = self.config.batch_size or 1024

        md = pf.metadata
        groups = list(range(md.num_row_groups))
        while groups and skip_rows >= md.row_group(groups[0]).num_rows:
            skip_rows -= md.row_group(groups[0]).num_rows
            groups.pop(0)

        for g in groups:
            n_rows = md.row_group(g).num_rows
            try:
                table = self._fetch_group(pf, g)
            except Exception as e:  # noqa: BLE001 — budget already spent
                if not on_quarantine:
                    if isinstance(e, ParquetError):
                        raise
                    raise ParquetError(
                        f"failed to read row group {g} of "
                        f"'{self.config.path}': {e}"
                    ) from e
                # Quarantine: account every not-yet-consumed row of the
                # group so item<->row bookkeeping stays exact.
                lost = n_rows - skip_rows
                skip_rows = 0
                METRICS.inc("resilience_quarantined_rows_total", lost)
                logger.error(
                    "Quarantined row group %d of '%s' (%d rows): %s",
                    g, self.config.path, lost, e,
                )
                yield _QuarantinedGroup(g, lost, e)
                continue
            if skip_rows:
                table = table.slice(skip_rows)
                skip_rows = 0
            for batch in table.to_batches(max_chunksize=batch_size):
                if batch.num_rows:
                    yield batch

    def read_batches(self, skip_rows: int = 0) -> Iterator[pa.RecordBatch]:
        """Raw Arrow record batches (the zero-copy path for the TPU packer).

        Reads are guarded by the retry policy; an unreadable row group
        raises :class:`ParquetError` here (use :meth:`read_documents` for
        the quarantining form)."""
        yield from self._iter_group_batches(skip_rows=skip_rows)

    def read_documents(
        self, skip_rows: int = 0
    ) -> Iterator[Union[TextDocument, PipelineError]]:
        pf = self._open()
        schema = pf.schema_arrow
        self._validate_schema(schema)

        has = {name: schema.get_field_index(name) != -1 for name in
               ("source", "added", "created", "metadata")}
        # metadata column must be a string type to be used (rs:92-97).
        if has["metadata"]:
            md_type = schema.field("metadata").type
            if md_type not in (pa.string(), pa.large_string()):
                has["metadata"] = False

        for batch in self._iter_group_batches(
            skip_rows=skip_rows, on_quarantine=True
        ):
            if isinstance(batch, _QuarantinedGroup):
                # One error item PER LOST ROW, not per group: the stream's
                # item count must equal the input row count for the
                # checkpoint cursor's row-exact resume skip.
                q = batch
                for _ in range(q.num_rows):
                    yield ParquetError(
                        f"row quarantined: row group {q.group} of "
                        f"'{self.config.path}' unreadable: {q.error}"
                    )
                continue
            # Decode the whole batch into a list before yielding: the decode
            # wall time must exclude consumer time (a generator suspends at
            # every yield), or the read-stage counter would absorb the rest
            # of the pipeline.
            import time

            t0 = time.perf_counter()
            with TRACER.span("read", {"kind": "decode", "rows": batch.num_rows}):
                items = self._decode_batch(batch, has)
            METRICS.inc("stage_read_seconds", time.perf_counter() - t0)
            if TELEMETRY.enabled:
                TELEMETRY.mark(
                    "read",
                    (d.id for d in items if isinstance(d, TextDocument)),
                )
            yield from items

    def _decode_batch(
        self, batch: pa.RecordBatch, has: dict
    ) -> list:
        """Arrow record batch -> list of documents / per-row errors."""
        items: list = []
        cols = {name: batch.column(i) for i, name in enumerate(batch.schema.names)}
        text_col = cols[self.config.text_column]
        id_col = cols[self.config.id_column]
        n = batch.num_rows

        source_col = cols.get("source") if has["source"] else None
        added_col = cols.get("added") if has["added"] else None
        created_col = cols.get("created") if has["created"] else None
        metadata_col = cols.get("metadata") if has["metadata"] else None

        for i in range(n):
            if not text_col[i].is_valid:
                items.append(UnexpectedError(
                    f"Row {i} has null text column '{self.config.text_column}'"
                ))
                continue
            if not id_col[i].is_valid:
                items.append(UnexpectedError(
                    f"Row {i} has null id column '{self.config.id_column}'"
                ))
                continue

            doc_id = id_col[i].as_py()
            # HTML-entity decode at ingest (rs:177-179).
            content = html.unescape(text_col[i].as_py())

            source = None
            if source_col is not None and source_col[i].is_valid:
                source = source_col[i].as_py()
            if source is None:
                source = self.config.path  # fallback (rs:181-190)

            added = None
            if added_col is not None and added_col[i].is_valid:
                added = _to_date(added_col[i].as_py())

            created = None
            if created_col is not None and created_col[i].is_valid:
                cell = created_col[i].as_py()
                if isinstance(cell, dict) and len(cell) >= 2:
                    vals = list(cell.values())
                    start = _to_datetime(vals[0])
                    end = _to_datetime(vals[1])
                    if start is not None and end is not None:
                        created = (start, end)
                else:
                    logger.warning("'created' column is not a struct.")

            metadata = {}
            if metadata_col is not None and metadata_col[i].is_valid:
                raw = metadata_col[i].as_py()
                try:
                    parsed = json.loads(raw)
                    metadata = (
                        {str(k): str(v) for k, v in parsed.items()}
                        if isinstance(parsed, dict)
                        else {}
                    )
                except (json.JSONDecodeError, AttributeError) as e:
                    logger.warning(
                        "Failed to parse metadata JSON. id=%s err=%s", doc_id, e
                    )
                    metadata = {}

            items.append(TextDocument(
                id=str(doc_id),
                content=content,
                source=str(source),
                added=added,
                created=created,
                metadata=metadata,
            ))
        return items
