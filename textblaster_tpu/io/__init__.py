"""Columnar I/O: Parquet reader/writer with the reference schema
(``/root/reference/src/pipeline/readers/``, ``writers/``)."""

from .base import BaseReader, BaseWriter
from .parquet_reader import ParquetInputConfig, ParquetReader
from .parquet_writer import OUTPUT_SCHEMA, ParquetWriter

__all__ = [
    "BaseReader",
    "BaseWriter",
    "ParquetInputConfig",
    "ParquetReader",
    "ParquetWriter",
    "OUTPUT_SCHEMA",
]
