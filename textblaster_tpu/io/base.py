"""Reader/writer interfaces (reference ``readers/base_reader.rs:4-6`` and
``writers/base_writer.rs:5-11``)."""

from __future__ import annotations

from typing import Iterator, Sequence, Union

from ..data_model import TextDocument
from ..errors import PipelineError

__all__ = ["BaseReader", "BaseWriter"]


class BaseReader:
    """Yields per-row ``TextDocument`` or ``PipelineError`` results —
    mirroring the reference's ``Iterator<Item = Result<TextDocument>>``."""

    def read_documents(self) -> Iterator[Union[TextDocument, PipelineError]]:
        raise NotImplementedError


class BaseWriter:
    def write_batch(self, documents: Sequence[TextDocument]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError
