"""Checkpoint / resume for pipeline runs.

The reference has **no** checkpointing: producer state is in-memory and a
crash restarts the whole shard (an explicit roadmap gap — SURVEY.md §5
"Checkpoint / resume: None ... The TPU build should do better (resumable
row-group cursor)").  This subsystem closes that gap:

* the run is processed in **chunks** of documents; after each chunk the kept
  and excluded rows land in per-chunk Parquet part files and a JSON cursor
  (consumed-row count, outcome counts, part list, input + config
  fingerprints) is committed atomically (tmp + rename);
* a restart after a crash re-opens the cursor, verifies the fingerprints,
  skips the consumed prefix of the reader stream, and continues from the
  next chunk — completed work is never recomputed (and with the persistent
  XLA compilation cache the restart does not even recompile);
* at stream end the parts concatenate into the reference-shaped single
  kept/excluded Parquet pair (parquet_writer.rs:17-44 schema) and the
  checkpoint directory is removed.

Chunk boundaries are also device-batch flush barriers, so the consumed
prefix exactly matches the set of produced outcomes — the property the
cursor relies on (the bucketed packer holds partial batches *within* a
chunk, never across a checkpoint).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
from dataclasses import dataclass, field
from itertools import islice
from typing import Callable, Iterator, List, Optional

import pyarrow.parquet as pq

from .config.pipeline import PipelineConfig
from .data_model import ProcessingOutcome
from .errors import CheckpointError, PipelineError
from .io.parquet_writer import OUTPUT_SCHEMA, ParquetWriter
from .orchestration import (
    PARQUET_WRITE_BATCH_SIZE,
    AggregationResult,
    read_documents,
)
from .resilience.deadletter import (
    DEADLETTER_SCHEMA,
    DeadLetterSink,
    outcome_row,
    read_error_row,
)
from .resilience.faults import FAULTS
from .utils.events import EVENTS
from .resilience.retry import RetryPolicy
from .utils.metrics import METRICS

logger = logging.getLogger(__name__)

__all__ = [
    "CheckpointState",
    "run_checkpointed",
    "run_stripe_checkpointed",
    "StripeLost",
    "CHECKPOINT_FILE",
]

CHECKPOINT_FILE = "checkpoint.json"
_VERSION = 1

_DEFAULT_COMMIT_RETRY: Optional[RetryPolicy] = None


def _default_commit_retry() -> RetryPolicy:
    global _DEFAULT_COMMIT_RETRY
    if _DEFAULT_COMMIT_RETRY is None:
        _DEFAULT_COMMIT_RETRY = RetryPolicy()
    return _DEFAULT_COMMIT_RETRY


def _input_fingerprint(path: str) -> dict:
    st = os.stat(path)
    meta = pq.read_metadata(path)
    return {
        "path": os.path.abspath(path),
        "size": st.st_size,
        "mtime_ns": st.st_mtime_ns,
        "num_rows": meta.num_rows,
    }


def _config_fingerprint(config: PipelineConfig) -> str:
    spec = [
        {"type": s.type, "params": dataclasses.asdict(s.params)}
        for s in config.pipeline
    ]
    blob = json.dumps(spec, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


@dataclass
class CheckpointState:
    """The resumable cursor, serialized to ``<dir>/checkpoint.json``."""

    input: dict
    config_hash: str
    rows_consumed: int = 0
    read_errors: int = 0
    received: int = 0
    success: int = 0
    filtered: int = 0
    errors: int = 0
    out_parts: List[str] = field(default_factory=list)
    excl_parts: List[str] = field(default_factory=list)
    # Dead-letter part files (only populated when the run has an
    # ``errors_file``); absent in pre-resilience checkpoints, so the default
    # keeps old cursors loadable.
    err_parts: List[str] = field(default_factory=list)
    # The device geometry the run was started with (DeviceGeometry.to_dict()).
    # Chunk boundaries are batch flush barriers, so resuming with a different
    # geometry would silently reshuffle batches — resume verifies this field
    # and fails fast on mismatch.  Absent in pre-geometry cursors (None), so
    # the default keeps old cursors loadable.
    geometry: Optional[dict] = None
    # Elastic-membership owner token ({"rank", "incarnation"}) for per-rank
    # stripe cursors (parallel/multihost.py --elastic): the process named
    # here is the only one allowed to advance the cursor, and adoption
    # rewrites it (:meth:`adopt`).  Absent in single-host cursors (None), so
    # the default keeps old cursors loadable.
    owner: Optional[dict] = None
    # Coordinated-path adoption marker (parallel/multihost.py
    # --survive-peer-loss): True once an adopter has fully reproduced a
    # dead rank's stripe and committed its shard files, so a later
    # re-adoption (the adopter itself died) skips the stripe instead of
    # repeating it.  Absent in older cursors (False), so the default keeps
    # them loadable.
    complete: bool = False
    version: int = _VERSION

    def save(
        self, ckpt_dir: str, retry_policy: Optional["RetryPolicy"] = None
    ) -> None:
        """Commit the cursor atomically AND durably.

        tmp + fsync(file) + rename + fsync(parent dir): without the
        directory fsync the rename itself can be lost on power failure, and
        a kill test could observe a missing-or-truncated ``checkpoint.json``
        after the commit reported success.  The whole commit is one guarded
        seam — transient IO faults are retried (the tmp file is rewritten
        from scratch each attempt, so a half-written tmp never survives
        into the rename).
        """
        policy = retry_policy or _default_commit_retry()

        def commit() -> None:
            FAULTS.fire("checkpoint.commit")
            tmp = os.path.join(ckpt_dir, CHECKPOINT_FILE + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(dataclasses.asdict(self), f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(ckpt_dir, CHECKPOINT_FILE))
            dir_fd = os.open(ckpt_dir, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

        policy.run(commit, seam="checkpoint")
        if EVENTS.enabled:
            EVENTS.emit("checkpoint_commit", chunk=len(self.out_parts),
                        rows_consumed=self.rows_consumed)

    @classmethod
    def load(cls, ckpt_dir: str) -> Optional["CheckpointState"]:
        path = os.path.join(ckpt_dir, CHECKPOINT_FILE)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        if d.get("version") != _VERSION:
            raise CheckpointError(
                f"checkpoint version {d.get('version')} is not supported"
            )
        return cls(**d)

    @classmethod
    def adopt(
        cls,
        ckpt_dir: str,
        owner: dict,
        *,
        input_fingerprint: dict,
        config_hash: str,
        retry_policy: Optional["RetryPolicy"] = None,
    ) -> "CheckpointState":
        """Claim (or create) a stripe cursor for ``owner`` and commit it.

        The elastic-membership claim point (``--elastic``): a process takes
        over a stripe — its own on a fresh start or rejoin, an evicted
        peer's on adoption — by rewriting the cursor's ``owner`` token.
        Work committed by the previous owner (``rows_consumed``, parts,
        counts) is kept verbatim, so the new owner resumes at the next
        chunk, replaying nothing.  Fingerprints are validated exactly like
        a single-host resume; a mismatch means the directory belongs to a
        different input or config and the caller must remove it.
        """
        FAULTS.fire("multihost.rejoin")
        state = cls.load(ckpt_dir)
        if state is None:
            state = cls(input=input_fingerprint, config_hash=config_hash)
        else:
            if state.input != input_fingerprint:
                raise CheckpointError(
                    f"stripe cursor in '{ckpt_dir}' was created for a "
                    f"different input ({state.input.get('path')}, "
                    f"{state.input.get('num_rows')} rows); remove the "
                    "membership directory to start over"
                )
            if state.config_hash != config_hash:
                raise CheckpointError(
                    f"stripe cursor in '{ckpt_dir}' was created with a "
                    "different pipeline config; remove the membership "
                    "directory to start over"
                )
        state.owner = dict(owner)
        if EVENTS.enabled:
            EVENTS.emit("checkpoint_adopted", owner=dict(owner),
                        rows_consumed=state.rows_consumed)
        state.save(ckpt_dir, retry_policy)
        return state


class _PartWriter:
    """Lazily-created Parquet part files, one per checkpointed chunk.

    Documents buffer to ``PARQUET_WRITE_BATCH_SIZE`` before hitting the
    writer (producer_logic.rs:21 parity) so each part gets a few large row
    groups instead of one per document.
    """

    def __init__(self, ckpt_dir: str, prefix: str, existing: List[str]) -> None:
        self.ckpt_dir = ckpt_dir
        self.prefix = prefix
        self.parts = list(existing)
        self._writer: Optional[ParquetWriter] = None
        self._current: Optional[str] = None
        self._buffer: List = []

    def append(self, doc) -> None:
        self._buffer.append(doc)
        if len(self._buffer) >= PARQUET_WRITE_BATCH_SIZE:
            self._flush()

    def _flush(self) -> None:
        if not self._buffer:
            return
        if self._writer is None:
            name = f"{self.prefix}-{len(self.parts):05d}.parquet"
            self._current = name
            self._writer = ParquetWriter(os.path.join(self.ckpt_dir, name))
        self._writer.write_batch(self._buffer)
        self._buffer.clear()

    def roll(self) -> None:
        """Flush and close the current part (if any) at a chunk boundary."""
        self._flush()
        if self._writer is not None:
            self._writer.close()
            self.parts.append(self._current)
            self._writer = None
            self._current = None

    def abort(self) -> None:
        self._buffer.clear()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            # The part is NOT recorded: a crash mid-chunk discards it and the
            # resume reprocesses the whole chunk.


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def _concat_parts(
    ckpt_dir: str, parts: List[str], out_path: str, schema=None
) -> None:
    schema = OUTPUT_SCHEMA if schema is None else schema
    parent = os.path.dirname(out_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    writer = pq.ParquetWriter(out_path, schema)
    try:
        for name in parts:
            table = pq.read_table(os.path.join(ckpt_dir, name))
            if table.num_rows:
                writer.write_table(table.cast(schema))
    finally:
        writer.close()


class StripeLost(Exception):
    """Control-flow signal for the elastic stripe loop: the stripe this
    process was advancing no longer belongs to it (its preferred owner
    rejoined, or another rank's claim landed first).  Raised by the caller's
    ``fence`` callback inside :func:`run_stripe_checkpointed`; the chunk in
    flight is discarded (never committed) and the function returns
    ``False`` so the caller can move on.  Deliberately NOT a
    :class:`~textblaster_tpu.errors.PipelineError`: nothing failed."""


def run_stripe_checkpointed(
    input_file: str,
    ckpt_dir: str,
    *,
    state: CheckpointState,
    skip_rows: int,
    take_rows: int,
    chunk_size: int,
    process_chunk: Callable[[Iterator, Callable], Iterator[ProcessingOutcome]],
    fence: Optional[Callable[[], None]] = None,
    lineage: str = "",
    text_column: str = "text",
    id_column: str = "id",
    record_dead: bool = False,
    retry_policy: Optional[RetryPolicy] = None,
    on_chunk: Optional[Callable[[CheckpointState], None]] = None,
    read_ahead: int = 0,
) -> bool:
    """Advance one input stripe's cursor chunk by chunk (``--elastic``).

    The stripe is the row window ``[skip_rows, skip_rows + take_rows)`` of
    ``input_file``; ``state`` is its (already adopted, fingerprint-verified)
    cursor.  Each iteration reads one chunk past ``state.rows_consumed``,
    runs ``process_chunk(items, on_read_error)``, commits the kept/excluded
    (and, with ``record_dead``, dead-letter) part files, then the cursor —
    the same commit discipline as :func:`run_checkpointed`, minus the
    finalize: parts stay in ``ckpt_dir`` for the run-level merge.

    Two differences carry the elastic-membership semantics:

    * ``fence`` runs before each chunk and again immediately before each
      cursor commit.  It may raise :class:`StripeLost` (ownership moved —
      the in-flight chunk is discarded, never committed, and the function
      returns ``False``) or any error (propagated; a self-fenced process
      uses this to die rather than double-commit).
    * ``lineage`` scopes the part-file prefixes (``out{lineage}-NNNNN``)
      to one (rank, incarnation), so a zombie owner racing its adopter in
      the lease-TTL window writes to *different* files — the cursor, with
      its single atomic writer-wins rename, is the only commit point, and
      an unrecorded part from the loser is a stray file, not corruption.

    ``read_ahead`` > 0 overlaps reading with processing: a prefetch thread
    decodes up to that many chunk-sized blocks ahead, keeping
    ``read_ahead + 1`` stripe chunks in flight per process while commit
    semantics are untouched — the reader only runs AHEAD of consumption,
    ``rows_consumed`` still counts exactly the items drained into chunks,
    and commits stay at chunk boundaries in stripe order.

    Returns ``True`` when the stripe is fully consumed, ``False`` on
    :class:`StripeLost`.  Counts fold into ``state`` only at commit, so a
    discarded chunk leaves the cursor's totals exact.
    """
    policy = retry_policy or _default_commit_retry()
    if take_rows - state.rows_consumed <= 0:
        return True

    out_parts = _PartWriter(ckpt_dir, f"out{lineage}", state.out_parts)
    excl_parts = _PartWriter(ckpt_dir, f"excl{lineage}", state.excl_parts)
    dead_rows: List[dict] = []
    read_errors_box = [0]

    def on_read_error(err) -> None:
        read_errors_box[0] += 1
        if record_dead:
            dead_rows.append(read_error_row(err))

    raw = islice(
        read_documents(
            input_file,
            text_column=text_column,
            id_column=id_column,
            batch_size=chunk_size,
            skip_rows=skip_rows + state.rows_consumed,
            retry_policy=policy,
        ),
        take_rows - state.rows_consumed,
    )
    raw_close = None
    if read_ahead > 0:
        from .utils.overlap import prefetch_iter

        raw = prefetch_iter(raw, depth=read_ahead, block=chunk_size)
        raw_close = raw.close
    try:
        while True:
            if fence is not None:
                fence()
            chunk = list(islice(raw, chunk_size))
            if not chunk:
                return True
            counts = {"received": 0, "success": 0, "filtered": 0, "errors": 0}
            for outcome in process_chunk(iter(chunk), on_read_error):
                counts["received"] += 1
                if outcome.kind == ProcessingOutcome.SUCCESS:
                    counts["success"] += 1
                    METRICS.inc("producer_results_success_total")
                    out_parts.append(outcome.document)
                elif outcome.kind == ProcessingOutcome.FILTERED:
                    counts["filtered"] += 1
                    METRICS.inc("producer_results_filtered_total")
                    excl_parts.append(outcome.document)
                else:
                    counts["errors"] += 1
                    METRICS.inc("producer_results_error_total")
                    if record_dead:
                        dead_rows.append(outcome_row(outcome))
                METRICS.inc("producer_results_received_total")

            if fence is not None:
                fence()  # self-fence: last check before anything commits
            out_parts.roll()
            excl_parts.roll()
            if dead_rows:
                name = f"err{lineage}-{len(state.err_parts):05d}.parquet"
                with DeadLetterSink(os.path.join(ckpt_dir, name)) as sink:
                    for row in dead_rows:
                        sink.record_row(row)
                state.err_parts.append(name)
            dead_rows.clear()
            state.rows_consumed += len(chunk)
            state.read_errors += read_errors_box[0]
            read_errors_box[0] = 0
            state.received += counts["received"]
            state.success += counts["success"]
            state.filtered += counts["filtered"]
            state.errors += counts["errors"]
            state.out_parts = out_parts.parts
            state.excl_parts = excl_parts.parts
            state.save(ckpt_dir, policy)
            if on_chunk is not None:
                on_chunk(state)
    except StripeLost:
        out_parts.abort()
        excl_parts.abort()
        return False
    except BaseException:
        out_parts.abort()
        excl_parts.abort()
        raise
    finally:
        if raw_close is not None:
            raw_close()


def run_checkpointed(
    config: PipelineConfig,
    input_file: str,
    output_file: str,
    excluded_file: str,
    ckpt_dir: str,
    chunk_size: int = 8192,
    text_column: str = "text",
    id_column: str = "id",
    backend: str = "tpu",
    read_batch_size: int = 1024,
    device_batch: Optional[int] = None,
    buckets=None,
    auto_geometry: bool = False,
    mesh=None,
    progress: Optional[Callable[[AggregationResult], None]] = None,
    stop_after_chunks: Optional[int] = None,
    errors_file: Optional[str] = None,
    warmup: Optional[bool] = None,
) -> AggregationResult:
    """Run the pipeline with chunk-level checkpointing (resume by default).

    ``stop_after_chunks`` aborts the run after N committed chunks — the fault
    -injection hook the crash/resume tests drive (see also the finer-grained
    :data:`~textblaster_tpu.resilience.FAULTS` sites at the read / device /
    commit seams).

    ``errors_file`` opts into the dead-letter sink.  Dead-letter rows are
    committed as per-chunk part files inside ``ckpt_dir`` (recorded in the
    cursor) and concatenated at finalize, so a crash/resume cycle loses no
    quarantine records and re-records none twice.
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    fingerprint = _input_fingerprint(input_file)
    config_hash = _config_fingerprint(config)

    # Resilience knobs are deliberately outside the config fingerprint, so
    # tuning them between a crash and its resume never invalidates the cursor.
    rc = getattr(config, "resilience", None)
    retry_policy = RetryPolicy.from_config(rc) if rc is not None else RetryPolicy()

    state = CheckpointState.load(ckpt_dir)
    resumed = state is not None
    if state is None and os.listdir(ckpt_dir):
        # A non-empty directory without a cursor is not ours: finalization
        # deletes the subsystem's artifacts, and starting a run inside e.g.
        # `--checkpoint-dir .` must never end with user files removed.
        raise CheckpointError(
            f"checkpoint directory '{ckpt_dir}' is not empty and contains no "
            f"{CHECKPOINT_FILE}; use an empty (or new) directory"
        )
    if state is not None:
        if state.input != fingerprint:
            raise CheckpointError(
                f"checkpoint in '{ckpt_dir}' was created for a different input "
                f"({state.input.get('path')}, {state.input.get('num_rows')} rows); "
                "remove the checkpoint directory to start over"
            )
        if state.config_hash != config_hash:
            raise CheckpointError(
                f"checkpoint in '{ckpt_dir}' was created with a different "
                "pipeline config; remove the checkpoint directory to start over"
            )
        logger.info(
            "Resuming from checkpoint: %d rows consumed, %d outcomes",
            state.rows_consumed,
            state.received,
        )
    else:
        state = CheckpointState(input=fingerprint, config_hash=config_hash)

    out_parts = _PartWriter(ckpt_dir, "out", state.out_parts)
    excl_parts = _PartWriter(ckpt_dir, "excl", state.excl_parts)

    read_errors_box = [state.read_errors]
    # Dead-letter rows buffer per chunk and are committed as an err-part at
    # the same boundary as the kept/excluded parts: a crash mid-chunk
    # discards the buffer with the chunk (the resume re-derives it), so no
    # row is ever recorded twice or lost.
    dead_rows: List[dict] = []

    def on_read_error(err) -> None:
        read_errors_box[0] += 1
        if errors_file is not None:
            dead_rows.append(read_error_row(err))

    # The raw reader stream yields one item per row (document or per-row
    # error) — `rows_consumed` counts items, so the skip is exact.  The
    # consumed prefix is skipped at row-group granularity (never decoded).
    raw = read_documents(
        input_file,
        text_column=text_column,
        id_column=id_column,
        batch_size=read_batch_size,
        skip_rows=state.rows_consumed,
        retry_policy=retry_policy,
    )
    # Overlapped read-ahead (device backend): the reader thread only runs
    # AHEAD of consumption — `rows_consumed` still counts exactly the items
    # drained into chunks, part writes stay synchronous at chunk boundaries,
    # and commit semantics are untouched.
    raw_close = None
    oc = getattr(config, "overlap", None)
    if (
        backend == "tpu"
        and oc is not None
        and oc.enabled
        and os.environ.get("TEXTBLAST_NO_OVERLAP") != "1"
    ):
        from .utils.overlap import prefetch_iter

        raw = prefetch_iter(
            raw, depth=oc.read_ahead, block=max(64, read_batch_size // 4)
        )
        raw_close = raw.close

    # Chunk processor: host executor or a single CompiledPipeline reused
    # across chunks (compiled programs cached between calls).
    if backend == "tpu":
        import jax

        from .ops.geometry import DeviceGeometry
        from .ops.pipeline import CompiledPipeline, process_documents_device
        from .parallel.mesh import data_mesh

        if mesh is None and len(jax.devices()) > 1:
            mesh = data_mesh()  # same sharding as the non-checkpointed runner
        pkw = {} if buckets is None else {"buckets": buckets}
        recorded = (
            DeviceGeometry.from_dict(state.geometry)
            if state.geometry is not None
            else None
        )
        if resumed and recorded is not None:
            # The cursor's geometry is authoritative: chunk boundaries are
            # batch flush barriers, and a different geometry would batch the
            # remaining rows differently than the original run would have.
            # Verify the flags resolve to the recorded geometry (or, for an
            # auto run, that --auto-geometry is passed again) and fail fast
            # otherwise.
            if auto_geometry:
                if recorded.source != "auto":
                    raise CheckpointError(
                        f"checkpoint in '{ckpt_dir}' was created WITHOUT "
                        f"--auto-geometry (device geometry "
                        f"{recorded.describe()}); resume without the flag, "
                        "or remove the checkpoint directory to start over"
                    )
                pipeline = CompiledPipeline(config, mesh=mesh, geometry=recorded)
            else:
                candidate = CompiledPipeline(
                    config, batch_size=device_batch, mesh=mesh, **pkw
                )
                if candidate.geometry.fingerprint() != recorded.fingerprint():
                    hint = (
                        "pass --auto-geometry again"
                        if recorded.source == "auto"
                        else "resume with the original --buckets/--device-batch"
                    )
                    raise CheckpointError(
                        f"checkpoint in '{ckpt_dir}' was created with device "
                        f"geometry {recorded.describe()}, but this invocation "
                        f"resolves to {candidate.geometry.describe()}; {hint}, "
                        "or remove the checkpoint directory to start over"
                    )
                pipeline = candidate
        else:
            if resumed and auto_geometry:
                # Pre-geometry cursor: the original batching cannot be
                # reconstructed under a freshly calibrated geometry.
                raise CheckpointError(
                    f"checkpoint in '{ckpt_dir}' predates geometry recording "
                    "and cannot be resumed with --auto-geometry; resume "
                    "without the flag, or remove the checkpoint directory "
                    "to start over"
                )
            geometry = None
            if auto_geometry:
                # Fresh run: calibrate from the head of the stream, then
                # replay the head ahead of the rest.  The result is recorded
                # in the cursor so a resume dispatches identical batches.
                from itertools import chain

                from .ops.geometry import CALIBRATION_SAMPLE, calibrate_geometry

                head = list(islice(raw, CALIBRATION_SAMPLE))
                lengths = [
                    len(d.content)
                    for d in head
                    if not isinstance(d, PipelineError)
                ]
                if lengths:
                    geometry = calibrate_geometry(
                        lengths, backend=jax.default_backend()
                    )
                    logger.info(
                        "Auto-calibrated device geometry from %d sampled "
                        "documents: %s",
                        len(lengths),
                        geometry.describe(),
                    )
                raw = chain(head, raw)
            pipeline = CompiledPipeline(
                config,
                batch_size=device_batch,
                mesh=mesh,
                geometry=geometry,
                **pkw,
            )
        # Recorded from the constructed pipeline (mesh rounding included) so
        # the resume check compares like with like.
        state.geometry = pipeline.geometry.to_dict()

        from .ops.pipeline import maybe_warmup

        maybe_warmup(pipeline, warmup)

        def process_chunk(items) -> Iterator[ProcessingOutcome]:
            return process_documents_device(
                config, items, on_read_error=on_read_error, pipeline=pipeline
            )

    else:
        from .orchestration import process_documents_host
        from .pipeline_builder import build_pipeline_from_config

        executor = build_pipeline_from_config(config)

        def process_chunk(items) -> Iterator[ProcessingOutcome]:
            return process_documents_host(
                executor, items, on_read_error=on_read_error
            )

    result = AggregationResult(
        received=state.received,
        success=state.success,
        filtered=state.filtered,
        errors=state.errors,
    )

    chunks_done = 0
    try:
        while True:
            chunk = list(islice(raw, chunk_size))
            if not chunk:
                break
            for outcome in process_chunk(iter(chunk)):
                result.received += 1
                if outcome.kind == ProcessingOutcome.SUCCESS:
                    result.success += 1
                    METRICS.inc("producer_results_success_total")
                    out_parts.append(outcome.document)
                elif outcome.kind == ProcessingOutcome.FILTERED:
                    result.filtered += 1
                    METRICS.inc("producer_results_filtered_total")
                    excl_parts.append(outcome.document)
                else:
                    result.errors += 1
                    METRICS.inc("producer_results_error_total")
                    if errors_file is not None:
                        dead_rows.append(outcome_row(outcome))
                METRICS.inc("producer_results_received_total")
                if progress is not None:
                    progress(result)

            # Chunk boundary: commit parts, then the cursor.
            out_parts.roll()
            excl_parts.roll()
            if dead_rows:
                # Same index scheme as out/excl parts.  A crash between the
                # part write and the cursor commit re-creates the same name
                # on resume (err_parts length is unchanged), so the orphan
                # is overwritten, never duplicated.
                name = f"err-{len(state.err_parts):05d}.parquet"
                with DeadLetterSink(os.path.join(ckpt_dir, name)) as sink:
                    for row in dead_rows:
                        sink.record_row(row)
                state.err_parts.append(name)
                dead_rows.clear()
            state.rows_consumed += len(chunk)
            state.read_errors = read_errors_box[0]
            state.received = result.received
            state.success = result.success
            state.filtered = result.filtered
            state.errors = result.errors
            state.out_parts = out_parts.parts
            state.excl_parts = excl_parts.parts
            state.save(ckpt_dir, retry_policy)

            chunks_done += 1
            if stop_after_chunks is not None and chunks_done >= stop_after_chunks:
                raise CheckpointError(
                    f"aborted after {chunks_done} chunks (fault injection)"
                )
    except BaseException:
        out_parts.abort()
        excl_parts.abort()
        raise
    finally:
        if raw_close is not None:
            raw_close()  # stop the read-ahead thread on every exit path

    # Finalize: single kept/excluded pair with the reference's schema.  Only
    # artifacts this subsystem created are deleted — the directory itself is
    # removed only if that leaves it empty (it may pre-exist, e.g. ".").
    _concat_parts(ckpt_dir, state.out_parts, output_file)
    _concat_parts(ckpt_dir, state.excl_parts, excluded_file)
    if errors_file is not None:
        # Empty parts list still yields a well-formed (empty) dead-letter
        # file — "no errors" stays distinguishable from "sink not wired".
        _concat_parts(
            ckpt_dir, state.err_parts, errors_file, schema=DEADLETTER_SCHEMA
        )
    for name in state.out_parts + state.excl_parts + state.err_parts:
        _unlink_quiet(os.path.join(ckpt_dir, name))
    _unlink_quiet(os.path.join(ckpt_dir, CHECKPOINT_FILE))
    _unlink_quiet(os.path.join(ckpt_dir, CHECKPOINT_FILE + ".tmp"))
    try:
        os.rmdir(ckpt_dir)
    except OSError:
        logger.warning(
            "checkpoint directory '%s' not removed (not empty)", ckpt_dir
        )

    result.read_errors = read_errors_box[0]
    return result
