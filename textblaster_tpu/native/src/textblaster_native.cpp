// textblaster_tpu native host runtime.
//
// The reference's entire runtime is native (Rust; SURVEY.md §2 "the entire
// codebase is the native component").  This library is the TPU build's native
// host-side core: the pieces that sit between storage and the XLA device
// program and that must run at memory bandwidth, not interpreter speed —
//
//   * UTF-8 → packed codepoint-tensor decoding (the host→HBM feed;
//     reference analogue: the producer's serialize loop,
//     src/producer_logic.rs:48-98),
//   * UAX#29-lite word segmentation over codepoint arrays (reference
//     analogue: ICU4X segmentation, src/utils/text.rs:103-181),
//   * n-gram duplicate scans (src/utils/text.rs:197-259),
//   * byte-level BPE token counting (reference analogue: HF tokenizers'
//     native core behind src/pipeline/token/token_counter.rs:8-43).
//
// Semantics deliberately mirror textblaster_tpu/utils/text.py — that file is
// the single source of truth for segmentation rules; this is the compiled
// fast path, and tests assert bit-identical outputs between the two.
//
// C ABI only (loaded via ctypes): no Python.h dependency, buffers are
// caller-allocated numpy arrays.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Char-class bit flags — must match textblaster_tpu/utils/chartables.py.
constexpr uint8_t kAlnum = 1 << 0;
constexpr uint8_t kAlpha = 1 << 1;
constexpr uint8_t kDigit = 1 << 2;
constexpr uint8_t kWs = 1 << 3;
constexpr uint8_t kPunct = 1 << 4;
constexpr uint8_t kExtend = 1 << 7;  // UAX#29 WB4 attachers (chartables.EXTEND)

// UAX#29 word-joining characters — mirrors _MID_LETTER/_MID_NUM/_MID_NUM_LET
// in textblaster_tpu/utils/text.py (UAX#29-lite rule set).
inline bool is_mid_letter(uint32_t cp) {
  switch (cp) {
    case 0x003a: case 0x00b7: case 0x05f4: case 0x2027: case 0xfe13:
    case 0xfe55: case 0xff1a:  // MidLetter
    case 0x002e: case 0x0027: case 0x2019: case 0x2024: case 0xfe52:
    case 0xff07: case 0xff0e:  // MidNumLet
      return true;
    default:
      return false;
  }
}

inline bool is_mid_num(uint32_t cp) {
  switch (cp) {
    case 0x002c: case 0x003b: case 0x037e: case 0x0589: case 0x066c:
    case 0xfe10: case 0xfe14: case 0xff0c: case 0xff1b:  // MidNum
    case 0x002e: case 0x0027: case 0x2019: case 0x2024: case 0xfe52:
    case 0xff07: case 0xff0e:  // MidNumLet
      return true;
    default:
      return false;
  }
}

inline bool is_mid_any(uint32_t cp) { return is_mid_letter(cp) || is_mid_num(cp); }

inline int utf8_width(uint32_t cp) {
  if (cp < 0x80) return 1;
  if (cp < 0x800) return 2;
  if (cp < 0x10000) return 3;
  return 4;
}

// Decode one UTF-8 sequence at p (end e); invalid bytes decode as U+FFFD one
// byte at a time (Python str round-trips never produce invalid input; this is
// belt-and-braces for raw Arrow buffers).
inline const uint8_t* utf8_next(const uint8_t* p, const uint8_t* e, uint32_t* out) {
  uint8_t b0 = *p;
  if (b0 < 0x80) {
    *out = b0;
    return p + 1;
  }
  int n;
  uint32_t cp;
  if ((b0 & 0xe0) == 0xc0) {
    n = 1;
    cp = b0 & 0x1f;
  } else if ((b0 & 0xf0) == 0xe0) {
    n = 2;
    cp = b0 & 0x0f;
  } else if ((b0 & 0xf8) == 0xf0) {
    n = 3;
    cp = b0 & 0x07;
  } else {
    *out = 0xfffd;
    return p + 1;
  }
  const uint8_t* q = p + 1;
  for (int i = 0; i < n; ++i) {
    if (q >= e || (*q & 0xc0) != 0x80) {
      *out = 0xfffd;
      return p + 1;
    }
    cp = (cp << 6) | (*q & 0x3f);
    ++q;
  }
  *out = cp;
  return q;
}

// FNV-1a over a range of 32-bit values.
inline uint64_t fnv1a_step(uint64_t h, uint32_t v) {
  for (int i = 0; i < 4; ++i) {
    h ^= (v >> (8 * i)) & 0xff;
    h *= 0x100000001b3ULL;
  }
  return h;
}
constexpr uint64_t kFnvInit = 0xcbf29ce484222325ULL;

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// Batch UTF-8 decode + pack.
//
// Document i is bytes[offsets[i] .. offsets[i+1]) (Arrow string-array layout;
// parquet_reader.rs:159-179 analogue without the per-row Rust String).  Row i
// of out_cps (stride row_stride int32s) receives its codepoints zero-padded;
// out_lengths[i] = codepoint count, or the negative count if the document
// exceeds max_len (row untouched — caller routes it to the host-fallback
// path, SURVEY.md §5 "ragged data on fixed shapes").
void tb_pack_utf8(const uint8_t* bytes, const int64_t* offsets, int64_t n_docs,
                  int32_t* out_cps, int32_t* out_lengths, int64_t max_len,
                  int64_t row_stride) {
  for (int64_t i = 0; i < n_docs; ++i) {
    const uint8_t* p = bytes + offsets[i];
    const uint8_t* e = bytes + offsets[i + 1];
    int32_t* row = out_cps + i * row_stride;
    int64_t n = 0;
    uint32_t cp;
    bool overflow = false;
    while (p < e) {
      p = utf8_next(p, e, &cp);
      if (n < max_len) {
        row[n] = static_cast<int32_t>(cp);
      } else {
        overflow = true;
      }
      ++n;
    }
    if (overflow) {
      std::memset(row, 0, sizeof(int32_t) * static_cast<size_t>(max_len));
      out_lengths[i] = static_cast<int32_t>(-n);
    } else {
      out_lengths[i] = static_cast<int32_t>(n);
    }
  }
}

// Codepoint counts only (for length-bucketing before any decode).
void tb_utf8_lengths(const uint8_t* bytes, const int64_t* offsets,
                     int64_t n_docs, int32_t* out) {
  for (int64_t i = 0; i < n_docs; ++i) {
    const uint8_t* p = bytes + offsets[i];
    const uint8_t* e = bytes + offsets[i + 1];
    int64_t n = 0;
    // Count = bytes that are not UTF-8 continuation bytes.
    while (p < e) {
      n += ((*p & 0xc0) != 0x80);
      ++p;
    }
    out[i] = static_cast<int32_t>(n);
  }
}

// ---------------------------------------------------------------------------
// UAX#29-lite word segmentation (mirror of utils/text.py word_spans; the
// reference's rule source is ICU4X WordSegmenter, src/utils/text.rs:103-181).
//
// cps/cls: codepoints and their chartables classification.  Writes (start,
// end) pairs into out_spans; returns the span count, or -1 if more than
// max_spans words were found (caller falls back to Python).
int64_t tb_word_spans(const int32_t* cps, int64_t n, const uint8_t* cls,
                      int32_t* out_spans, int64_t max_spans) {
  std::vector<uint8_t> word(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) {
    word[i] = ((cls[i] & kAlnum) != 0) || (cps[i] == '_');
  }
  if (n >= 3) {
    for (int64_t i = 1; i + 1 < n; ++i) {
      if (word[i]) continue;
      uint32_t cp = static_cast<uint32_t>(cps[i]);
      if (!is_mid_any(cp)) continue;
      bool letter_ok = is_mid_letter(cp) && (cls[i - 1] & kAlpha) &&
                       (cls[i + 1] & kAlpha);
      bool num_ok = is_mid_num(cp) && (cls[i - 1] & kDigit) &&
                    (cls[i + 1] & kDigit);
      if (letter_ok || num_ok) word[i] = 2;  // joined, not a run starter class
    }
  }
  // UAX#29 WB4 (lite): Extend/Format chars inherit the wordness of the
  // nearest preceding non-Extend char (utils.text._attach_extend twin).
  // Left-to-right, so marks chain through a run of Extends.
  for (int64_t i = 1; i < n; ++i) {
    if ((cls[i] & kExtend) != 0 && !word[i]) {
      word[i] = word[i - 1] ? 1 : 0;
    }
  }
  int64_t count = 0;
  int64_t i = 0;
  while (i < n) {
    if (word[i]) {
      int64_t j = i;
      bool non_punct = false;
      while (j < n && word[j]) {
        if ((cls[j] & kPunct) == 0) non_punct = true;
        ++j;
      }
      // Reject punctuation-only segments (text.rs:139-157 parity).
      if (non_punct) {
        if (count >= max_spans) return -1;
        out_spans[2 * count] = static_cast<int32_t>(i);
        out_spans[2 * count + 1] = static_cast<int32_t>(j);
        ++count;
      }
      i = j;
    } else {
      // Standalone symbol "word": not whitespace, not reference punctuation.
      // ZWSP (WordBreak=Other, not word-like) and bare Extend chars produce
      // no token; a trailing Extend run attaches to the symbol (WB4) —
      // mirror of utils.text.word_spans.
      if ((cls[i] & kWs) == 0 && (cls[i] & kPunct) == 0 &&
          (cls[i] & kExtend) == 0 && static_cast<uint32_t>(cps[i]) != 0x200B) {
        int64_t j = i + 1;
        while (j < n && (cls[j] & kExtend) != 0 && !word[j]) ++j;
        if (count >= max_spans) return -1;
        out_spans[2 * count] = static_cast<int32_t>(i);
        out_spans[2 * count + 1] = static_cast<int32_t>(j);
        ++count;
        i = j;
      } else {
        ++i;
      }
    }
  }
  return count;
}

namespace {

// Concatenated-gram helpers shared by the duplicate scans.  A "gram" is the
// word sequence spans[idx..idx+n) either concatenated directly
// (find_all_duplicate, text.rs:250) or space-joined (get_n_grams,
// text.rs:184-194).  Grams are compared by flattened codepoint content —
// hashing is only a prefilter, equality is always verified, so results are
// exact (the Rust uses real HashMaps over Strings; same observable effect).

struct GramView {
  const int32_t* cps;
  const int32_t* spans;  // flat (start,end) pairs
  int64_t idx;           // first word
  int64_t n;             // word count
  bool joined;           // true: words separated by a virtual ' '
};

inline uint64_t gram_hash(const GramView& g) {
  uint64_t h = kFnvInit;
  for (int64_t w = 0; w < g.n; ++w) {
    if (g.joined && w > 0) h = fnv1a_step(h, ' ');
    int32_t s = g.spans[2 * (g.idx + w)];
    int32_t e = g.spans[2 * (g.idx + w) + 1];
    for (int32_t k = s; k < e; ++k) h = fnv1a_step(h, static_cast<uint32_t>(g.cps[k]));
  }
  return h;
}

inline int64_t gram_bytes(const GramView& g) {
  int64_t b = g.joined ? (g.n - 1) : 0;  // ' ' is 1 UTF-8 byte
  for (int64_t w = 0; w < g.n; ++w) {
    int32_t s = g.spans[2 * (g.idx + w)];
    int32_t e = g.spans[2 * (g.idx + w) + 1];
    for (int32_t k = s; k < e; ++k) b += utf8_width(static_cast<uint32_t>(g.cps[k]));
  }
  return b;
}

// Character-stream equality of two grams (concatenation equality, which is
// NOT word-wise equality when joined == false).
inline bool gram_eq(const GramView& a, const GramView& b) {
  int64_t wa = 0, wb = 0;
  int32_t ka = 0, kb = 0;
  bool space_a = false, space_b = false;
  // Position ka within word wa (or virtual space when space_a).
  auto advance = [](const GramView& g, int64_t& w, int32_t& k, bool& in_space,
                    int32_t& out_cp) -> bool {
    while (w < g.n) {
      if (in_space) {
        in_space = false;
        out_cp = ' ';
        return true;
      }
      int32_t s = g.spans[2 * (g.idx + w)];
      int32_t e = g.spans[2 * (g.idx + w) + 1];
      if (s + k < e) {
        out_cp = g.cps[s + k];
        ++k;
        return true;
      }
      ++w;
      k = 0;
      if (g.joined && w < g.n) in_space = true;
    }
    return false;
  };
  for (;;) {
    int32_t ca = 0, cb = 0;
    bool ha = advance(a, wa, ka, space_a, ca);
    bool hb = advance(b, wb, kb, space_b, cb);
    if (ha != hb) return false;
    if (!ha) return true;
    if (ca != cb) return false;
  }
}

}  // namespace

// find_all_duplicate (text.rs:241-259): total UTF-8 bytes of non-overlapping
// repeated n-grams (words concatenated without separator), advancing by n on
// a hit and by 1 otherwise.
int64_t tb_dup_ngram_bytes(const int32_t* cps, const int32_t* spans,
                           int64_t n_spans, int64_t n) {
  if (n <= 0 || n_spans < n) return 0;
  std::unordered_map<uint64_t, std::vector<int64_t>> seen;
  seen.reserve(static_cast<size_t>(n_spans));
  int64_t rep = 0;
  int64_t idx = 0;
  while (idx + n <= n_spans) {
    GramView g{cps, spans, idx, n, false};
    uint64_t h = gram_hash(g);
    auto it = seen.find(h);
    bool dup = false;
    if (it != seen.end()) {
      for (int64_t prev : it->second) {
        GramView p{cps, spans, prev, n, false};
        if (gram_eq(g, p)) {
          dup = true;
          break;
        }
      }
    }
    if (dup) {
      rep += gram_bytes(g);
      idx += n;
    } else {
      seen[h].push_back(idx);
      idx += 1;
    }
  }
  return rep;
}

// find_top_duplicate over space-joined n-grams (text.rs:211-238): byte length
// × count of the most frequent n-gram, ties broken by the larger byte
// contribution; 0 when nothing repeats.
int64_t tb_top_ngram_bytes(const int32_t* cps, const int32_t* spans,
                           int64_t n_spans, int64_t n) {
  if (n <= 0 || n_spans < n) return 0;
  struct Entry {
    int64_t first;
    int64_t count;
  };
  std::unordered_map<uint64_t, std::vector<Entry>> table;
  table.reserve(static_cast<size_t>(n_spans));
  int64_t max_count = 0;
  for (int64_t idx = 0; idx + n <= n_spans; ++idx) {
    GramView g{cps, spans, idx, n, true};
    uint64_t h = gram_hash(g);
    auto& bucket = table[h];
    bool found = false;
    for (auto& e : bucket) {
      GramView p{cps, spans, e.first, n, true};
      if (gram_eq(g, p)) {
        ++e.count;
        if (e.count > max_count) max_count = e.count;
        found = true;
        break;
      }
    }
    if (!found) {
      bucket.push_back({idx, 1});
      if (max_count < 1) max_count = 1;
    }
  }
  if (max_count <= 1) return 0;
  int64_t best = 0;
  for (auto& kv : table) {
    for (auto& e : kv.second) {
      if (e.count == max_count) {
        GramView g{cps, spans, e.first, n, true};
        int64_t v = gram_bytes(g) * max_count;
        if (v > best) best = v;
      }
    }
  }
  return best;
}

// find_duplicates (text.rs:197-208) over arbitrary item spans (lines or
// paragraphs): *out_elems = duplicate item count, returns total UTF-8 bytes
// of the duplicates.
int64_t tb_dup_items(const int32_t* cps, const int32_t* spans, int64_t n_items,
                     int64_t* out_elems) {
  std::unordered_map<uint64_t, std::vector<int64_t>> seen;
  seen.reserve(static_cast<size_t>(n_items));
  int64_t dup_elems = 0;
  int64_t dup_bytes = 0;
  for (int64_t i = 0; i < n_items; ++i) {
    GramView g{cps, spans, i, 1, false};
    uint64_t h = gram_hash(g);
    auto& bucket = seen[h];
    bool dup = false;
    for (int64_t prev : bucket) {
      GramView p{cps, spans, prev, 1, false};
      if (gram_eq(g, p)) {
        dup = true;
        break;
      }
    }
    if (dup) {
      ++dup_elems;
      dup_bytes += gram_bytes(g);
    } else {
      bucket.push_back(i);
    }
  }
  *out_elems = dup_elems;
  return dup_bytes;
}

// ---------------------------------------------------------------------------
// Byte-level BPE token counting (reference analogue: the HF tokenizers
// native core behind token_counter.rs:8-43).  GPT-2-family tokenizers:
// byte→unicode remap, GPT-2 pre-tokenization, greedy rank-ordered merges.

namespace {

struct Bpe {
  // Tokens live in the byte→unicode *mapped* space, stored as UTF-8 strings.
  std::unordered_map<std::string, int32_t> token_ids;
  std::vector<std::string> tokens;
  // (left_id << 32 | right_id) -> (rank << 32 | merged_id)
  std::unordered_map<uint64_t, uint64_t> merges;
  int32_t byte_token[256];       // token id of each raw byte's mapped char
  const uint8_t* cls_table = nullptr;  // chartables classification
  int64_t cls_len = 0;

  int32_t intern(const std::string& s) {
    auto it = token_ids.find(s);
    if (it != token_ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(tokens.size());
    token_ids.emplace(s, id);
    tokens.push_back(s);
    return id;
  }

  uint8_t cls(uint32_t cp) const {
    if (cls_table == nullptr) return 0;
    int64_t i = static_cast<int64_t>(cp);
    if (i >= cls_len) i = cls_len - 1;
    return cls_table[i];
  }
};

inline void append_utf8(std::string* s, uint32_t cp) {
  if (cp < 0x80) {
    s->push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    s->push_back(static_cast<char>(0xc0 | (cp >> 6)));
    s->push_back(static_cast<char>(0x80 | (cp & 0x3f)));
  } else if (cp < 0x10000) {
    s->push_back(static_cast<char>(0xe0 | (cp >> 12)));
    s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3f)));
    s->push_back(static_cast<char>(0x80 | (cp & 0x3f)));
  } else {
    s->push_back(static_cast<char>(0xf0 | (cp >> 18)));
    s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3f)));
    s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3f)));
    s->push_back(static_cast<char>(0x80 | (cp & 0x3f)));
  }
}

// GPT-2's byte→unicode bijection: printable latin-1 ranges map to themselves,
// everything else to 0x100, 0x101, ... in raw-byte order.
void build_byte_map(uint32_t out[256]) {
  bool direct[256] = {false};
  for (int b = 33; b <= 126; ++b) direct[b] = true;
  for (int b = 161; b <= 172; ++b) direct[b] = true;
  for (int b = 174; b <= 255; ++b) direct[b] = true;
  uint32_t next = 256;
  for (int b = 0; b < 256; ++b) {
    if (direct[b]) {
      out[b] = static_cast<uint32_t>(b);
    } else {
      out[b] = next++;
    }
  }
}

// Greedy BPE merge of a mapped-space symbol sequence; returns token count.
int64_t bpe_merge_count(const Bpe* bpe, std::vector<int32_t>* parts) {
  while (parts->size() >= 2) {
    int64_t best_pos = -1;
    uint64_t best_rank = ~0ULL;
    int32_t best_merged = -1;
    for (size_t i = 0; i + 1 < parts->size(); ++i) {
      uint64_t key = (static_cast<uint64_t>(static_cast<uint32_t>((*parts)[i])) << 32) |
                     static_cast<uint32_t>((*parts)[i + 1]);
      auto it = bpe->merges.find(key);
      if (it != bpe->merges.end()) {
        uint64_t rank = it->second >> 32;
        if (rank < best_rank) {
          best_rank = rank;
          best_pos = static_cast<int64_t>(i);
          best_merged = static_cast<int32_t>(it->second & 0xffffffffULL);
        }
      }
    }
    if (best_pos < 0) break;
    (*parts)[best_pos] = best_merged;
    parts->erase(parts->begin() + best_pos + 1);
  }
  return static_cast<int64_t>(parts->size());
}

}  // namespace

// Build a BPE from the contents of a merges.txt (GPT-2 format: optional
// "#version" header line, then "left right" per line, rank = line order).
void* tb_bpe_new(const uint8_t* merges_blob, int64_t merges_len) {
  Bpe* bpe = new Bpe();
  uint32_t byte_map[256];
  build_byte_map(byte_map);
  for (int b = 0; b < 256; ++b) {
    std::string s;
    append_utf8(&s, byte_map[b]);
    bpe->byte_token[b] = bpe->intern(s);
  }
  const char* p = reinterpret_cast<const char*>(merges_blob);
  const char* end = p + merges_len;
  uint64_t rank = 0;
  bool first_line = true;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    size_t len = static_cast<size_t>(line_end - p);
    while (len > 0 && (p[len - 1] == '\r' || p[len - 1] == ' ')) --len;
    std::string line(p, len);
    p = nl ? nl + 1 : end;
    if (first_line) {
      first_line = false;
      if (line.rfind("#version", 0) == 0) continue;
    }
    if (line.empty() || line[0] == '#') continue;
    size_t sp = line.find(' ');
    if (sp == std::string::npos) continue;
    std::string left = line.substr(0, sp);
    std::string right = line.substr(sp + 1);
    int32_t l = bpe->intern(left);
    int32_t r = bpe->intern(right);
    int32_t m = bpe->intern(left + right);
    uint64_t key = (static_cast<uint64_t>(static_cast<uint32_t>(l)) << 32) |
                   static_cast<uint32_t>(r);
    bpe->merges.emplace(key, (rank << 32) | static_cast<uint32_t>(m));
    ++rank;
  }
  return bpe;
}

void tb_bpe_set_table(void* handle, const uint8_t* cls_table, int64_t table_len) {
  Bpe* bpe = static_cast<Bpe*>(handle);
  bpe->cls_table = cls_table;
  bpe->cls_len = table_len;
}

void tb_bpe_free(void* handle) { delete static_cast<Bpe*>(handle); }

// Count BPE tokens of a UTF-8 text: GPT-2 pre-tokenization (contractions,
// " ?letters", " ?numbers", " ?other", whitespace runs with the
// keep-last-space-for-next-token rule), then greedy merges per pre-token.
// Letter/number/whitespace classes come from the chartables table
// (\p{L}≈isalpha, \p{N}≈isdigit, \s≈isspace — documented approximation).
int64_t tb_bpe_count(void* handle, const uint8_t* utf8, int64_t len) {
  Bpe* bpe = static_cast<Bpe*>(handle);
  // Decode once, remembering each codepoint's byte span.
  std::vector<uint32_t> cps;
  std::vector<int32_t> byte_off;  // start byte of each cp; +1 sentinel
  cps.reserve(static_cast<size_t>(len));
  const uint8_t* p = utf8;
  const uint8_t* e = utf8 + len;
  while (p < e) {
    uint32_t cp;
    byte_off.push_back(static_cast<int32_t>(p - utf8));
    p = utf8_next(p, e, &cp);
    cps.push_back(cp);
  }
  byte_off.push_back(static_cast<int32_t>(len));
  int64_t n = static_cast<int64_t>(cps.size());

  auto is_alpha = [&](int64_t i) { return (bpe->cls(cps[i]) & kAlpha) != 0; };
  auto is_digit = [&](int64_t i) { return (bpe->cls(cps[i]) & kDigit) != 0; };
  auto is_space = [&](int64_t i) { return (bpe->cls(cps[i]) & kWs) != 0; };

  int64_t total = 0;
  std::vector<int32_t> parts;
  auto flush = [&](int64_t cp_start, int64_t cp_end) {
    // Map raw bytes [byte_off[cp_start], byte_off[cp_end]) through the byte
    // tokens and merge.
    parts.clear();
    for (int32_t b = byte_off[cp_start]; b < byte_off[cp_end]; ++b) {
      parts.push_back(bpe->byte_token[utf8[b]]);
    }
    total += bpe_merge_count(bpe, &parts);
  };

  int64_t i = 0;
  while (i < n) {
    // Contractions: 's 't 're 've 'm 'll 'd (case-sensitive, ASCII).
    if (cps[i] == '\'' && i + 1 < n) {
      uint32_t c1 = cps[i + 1];
      uint32_t c2 = (i + 2 < n) ? cps[i + 2] : 0;
      int64_t clen = 0;
      if (c1 == 's' || c1 == 't' || c1 == 'm' || c1 == 'd') clen = 2;
      if ((c1 == 'r' && c2 == 'e') || (c1 == 'v' && c2 == 'e') ||
          (c1 == 'l' && c2 == 'l'))
        clen = 3;
      if (clen > 0) {
        flush(i, i + clen);
        i += clen;
        continue;
      }
    }
    // " ?\p{L}+" / " ?\p{N}+" / " ?[^\s\p{L}\p{N}]+"
    int64_t start = i;
    int64_t j = (cps[i] == ' ' && i + 1 < n) ? i + 1 : i;
    if (j < n && is_alpha(j)) {
      while (j < n && is_alpha(j)) ++j;
      flush(start, j);
      i = j;
      continue;
    }
    if (j < n && is_digit(j)) {
      while (j < n && is_digit(j)) ++j;
      flush(start, j);
      i = j;
      continue;
    }
    if (j < n && !is_space(j) && !is_alpha(j) && !is_digit(j)) {
      while (j < n && !is_space(j) && !is_alpha(j) && !is_digit(j)) ++j;
      flush(start, j);
      i = j;
      continue;
    }
    // Whitespace runs: "\s+(?!\S)" then "\s+".  A run followed by a
    // non-space token donates its final char to that token only when it is
    // a literal ' ' (handled by the " ?" above on the next iteration).
    if (is_space(i)) {
      int64_t k = i;
      while (k < n && is_space(k)) ++k;
      // "\s+(?!\S)" backtracks one char when the run abuts a non-space
      // token (that char is then taken by the next token's " ?" when it is
      // a literal space, or stands alone via "\s+").
      int64_t run_end = (k < n && k - i >= 2) ? k - 1 : k;
      flush(i, run_end);
      i = run_end;
      continue;
    }
    // Unreachable fallback: single char token.
    flush(i, i + 1);
    ++i;
  }
  return total;
}

}  // extern "C"
