"""Native host runtime: lazy-built C++ core with ctypes bindings.

The reference's whole runtime is native Rust; here the host-side hot paths
(UTF-8 batch packing, UAX#29-lite word segmentation, n-gram duplicate scans,
byte-level BPE counting — see ``src/textblaster_native.cpp``) are C++,
compiled on first use with the toolchain baked into the image.  Everything
has a pure-Python/numpy fallback (``textblaster_tpu/utils/text.py``), which
stays the semantic source of truth: parity tests assert the two produce
identical results.

Set ``TEXTBLASTER_NATIVE=0`` to force the Python paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

__all__ = [
    "available",
    "pack_utf8",
    "utf8_lengths",
    "word_spans_native",
    "dup_ngram_bytes",
    "top_ngram_bytes",
    "dup_items",
    "BpeCounter",
]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_DIR, "libtextblaster_native.so")
_SRC = os.path.join(_DIR, "src", "textblaster_native.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64 = ctypes.c_int64
_p_u8 = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_p_i32 = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_p_i64 = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3",
        "-march=native",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-o",
        _SO_PATH,
        _SRC,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning("native build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        logger.warning("native build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("TEXTBLASTER_NATIVE", "1") == "0":
            return None
        if not os.path.exists(_SO_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            logger.warning("native library failed to load: %s", e)
            return None

        lib.tb_pack_utf8.argtypes = [_p_u8, _p_i64, _i64, _p_i32, _p_i32, _i64, _i64]
        lib.tb_pack_utf8.restype = None
        lib.tb_utf8_lengths.argtypes = [_p_u8, _p_i64, _i64, _p_i32]
        lib.tb_utf8_lengths.restype = None
        lib.tb_word_spans.argtypes = [_p_i32, _i64, _p_u8, _p_i32, _i64]
        lib.tb_word_spans.restype = _i64
        lib.tb_dup_ngram_bytes.argtypes = [_p_i32, _p_i32, _i64, _i64]
        lib.tb_dup_ngram_bytes.restype = _i64
        lib.tb_top_ngram_bytes.argtypes = [_p_i32, _p_i32, _i64, _i64]
        lib.tb_top_ngram_bytes.restype = _i64
        lib.tb_dup_items.argtypes = [
            _p_i32,
            _p_i32,
            _i64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.tb_dup_items.restype = _i64
        lib.tb_bpe_new.argtypes = [_p_u8, _i64]
        lib.tb_bpe_new.restype = ctypes.c_void_p
        lib.tb_bpe_set_table.argtypes = [ctypes.c_void_p, _p_u8, _i64]
        lib.tb_bpe_set_table.restype = None
        lib.tb_bpe_free.argtypes = [ctypes.c_void_p]
        lib.tb_bpe_free.restype = None
        lib.tb_bpe_count.argtypes = [ctypes.c_void_p, _p_u8, _i64]
        lib.tb_bpe_count.restype = _i64
        _lib = lib
        return _lib


def available() -> bool:
    """True when the compiled library is (or can be) loaded."""
    return _load() is not None


# --- packing ----------------------------------------------------------------


def pack_utf8(
    data: np.ndarray, offsets: np.ndarray, max_len: int, batch_size: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Decode ``n_docs`` UTF-8 documents (Arrow layout: ``data`` bytes +
    ``offsets``) into a zero-padded ``[batch_size, max_len] int32`` codepoint
    tensor.  Returns ``(cps, lengths)``; ``lengths[i] < 0`` flags an
    over-length document (row zeroed, magnitude = its codepoint count)."""
    lib = _load()
    assert lib is not None
    n_docs = offsets.shape[0] - 1
    assert n_docs <= batch_size
    cps = np.zeros((batch_size, max_len), dtype=np.int32)
    lengths = np.zeros(batch_size, dtype=np.int32)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    if n_docs > 0:
        lib.tb_pack_utf8(data, offsets, n_docs, cps, lengths, max_len, max_len)
    return cps, lengths


def utf8_lengths(data: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """Codepoint count per document without decoding (for bucketing)."""
    lib = _load()
    assert lib is not None
    n_docs = offsets.shape[0] - 1
    out = np.zeros(n_docs, dtype=np.int32)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    if n_docs > 0:
        lib.tb_utf8_lengths(data, offsets, n_docs, out)
    return out


# --- segmentation + duplicate scans ----------------------------------------


def word_spans_native(cps: np.ndarray, cls: np.ndarray) -> Optional[np.ndarray]:
    """Word (start, end) spans as an ``[n, 2] int32`` array, or ``None`` when
    the native library is unavailable.  Semantics identical to
    ``utils.text.word_spans``."""
    lib = _load()
    if lib is None:
        return None
    n = cps.shape[0]
    cps = np.ascontiguousarray(cps, dtype=np.int32)
    cls = np.ascontiguousarray(cls, dtype=np.uint8)
    max_spans = n + 1
    out = np.empty(2 * max_spans, dtype=np.int32)
    count = lib.tb_word_spans(cps, n, cls, out, max_spans)
    if count < 0:  # cannot happen (spans <= n), but keep the fallback seam
        return None
    return out[: 2 * count].reshape(-1, 2)


def dup_ngram_bytes(cps: np.ndarray, spans: np.ndarray, n: int) -> int:
    """find_all_duplicate over word spans (utils.text semantics)."""
    lib = _load()
    assert lib is not None
    cps = np.ascontiguousarray(cps, dtype=np.int32)
    spans = np.ascontiguousarray(spans.reshape(-1), dtype=np.int32)
    return int(lib.tb_dup_ngram_bytes(cps, spans, spans.shape[0] // 2, n))


def top_ngram_bytes(cps: np.ndarray, spans: np.ndarray, n: int) -> int:
    """find_top_duplicate over space-joined n-grams of the word spans."""
    lib = _load()
    assert lib is not None
    cps = np.ascontiguousarray(cps, dtype=np.int32)
    spans = np.ascontiguousarray(spans.reshape(-1), dtype=np.int32)
    return int(lib.tb_top_ngram_bytes(cps, spans, spans.shape[0] // 2, n))


def dup_items(cps: np.ndarray, spans: np.ndarray) -> Tuple[int, int]:
    """find_duplicates over item spans: (dup_elems, dup_utf8_bytes)."""
    lib = _load()
    assert lib is not None
    cps = np.ascontiguousarray(cps, dtype=np.int32)
    spans = np.ascontiguousarray(spans.reshape(-1), dtype=np.int32)
    elems = ctypes.c_int64(0)
    bytes_ = lib.tb_dup_items(
        cps, spans, spans.shape[0] // 2, ctypes.byref(elems)
    )
    return int(elems.value), int(bytes_)


# --- BPE --------------------------------------------------------------------


class BpeCounter:
    """Byte-level BPE token counter (GPT-2 family) over local merges.txt.

    The native analogue of the HF-tokenizers core used by TokenCounter
    (token_counter.rs:8-43 parity for token *counting* — ids are not needed
    for ``metadata["token_count"]``).
    """

    def __init__(self, merges_text: str) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        blob = np.frombuffer(merges_text.encode("utf-8"), dtype=np.uint8).copy()
        self._lib = lib
        self._handle = lib.tb_bpe_new(blob, blob.shape[0])
        from ..utils.chartables import char_table

        self._table = np.ascontiguousarray(char_table())
        lib.tb_bpe_set_table(self._handle, self._table, self._table.shape[0])

    @classmethod
    def from_file(cls, merges_path: str) -> "BpeCounter":
        with open(merges_path, encoding="utf-8") as f:
            return cls(f.read())

    def count(self, text: str) -> int:
        data = np.frombuffer(text.encode("utf-8"), dtype=np.uint8)
        if data.shape[0] == 0:
            return 0
        data = np.ascontiguousarray(data)
        return int(self._lib.tb_bpe_count(self._handle, data, data.shape[0]))

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.tb_bpe_free(handle)
            self._handle = None
