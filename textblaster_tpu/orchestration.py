"""Orchestration: feed documents in, aggregate outcomes out.

TPU-native re-design of the reference's producer/worker split
(``/root/reference/src/producer_logic.rs``, ``worker_logic.rs``): there is no
broker hop — documents flow straight from the Parquet reader into either the
host executor (oracle/baseline path) or the compiled device pipeline, and
outcomes flow straight into the aggregation sink.  The aggregation semantics
are the reference's exactly:

* Success -> output file, Filtered -> excluded file, batched at
  ``PARQUET_WRITE_BATCH_SIZE`` = 500 (producer_logic.rs:21, 148-167);
* **Error outcomes land in neither file** (producer_logic.rs:168-170,
  SURVEY.md §7 quirk #2);
* remainders flushed and writers closed at stream end (rs:185-193).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Optional, Tuple, Union

from .data_model import ProcessingOutcome, TextDocument
from .errors import PipelineError, StepError
from .executor import PipelineExecutor
from .io import ParquetInputConfig, ParquetReader, ParquetWriter
from .utils.metrics import FILTER_DROP_PREFIX, METRICS

logger = logging.getLogger(__name__)

PARQUET_WRITE_BATCH_SIZE = 500  # producer_logic.rs:21
DEFAULT_READ_BATCH_SIZE = 1024  # producer_logic.rs:37

__all__ = [
    "PARQUET_WRITE_BATCH_SIZE",
    "AggregationResult",
    "read_documents",
    "execute_processing_pipeline",
    "process_documents_host",
    "aggregate_results_from_stream",
]


@dataclass
class AggregationResult:
    """(received, success, filtered) counts (producer_logic.rs:195)."""

    received: int = 0
    success: int = 0
    filtered: int = 0
    errors: int = 0
    read_errors: int = 0


def read_documents(
    input_file: str,
    text_column: str = "text",
    id_column: str = "id",
    batch_size: int = DEFAULT_READ_BATCH_SIZE,
    skip_rows: int = 0,
    retry_policy=None,
) -> Iterator[Union[TextDocument, PipelineError]]:
    """Stream documents off disk (publish_tasks' reading half,
    producer_logic.rs:30-44).  ``skip_rows`` seeks past committed work on
    resume without decoding it (row-group cursor).  ``retry_policy``
    overrides the reader's default guard on the row-group read seam."""
    reader = ParquetReader(
        ParquetInputConfig(
            path=input_file,
            text_column=text_column,
            id_column=id_column,
            batch_size=batch_size,
        ),
        retry_policy=retry_policy,
    )
    return reader.read_documents(skip_rows=skip_rows)


def execute_processing_pipeline(
    executor: PipelineExecutor, document: TextDocument, worker_id: str = "host-0"
) -> Optional[ProcessingOutcome]:
    """One document through the executor -> outcome
    (worker_logic.rs:140-193): ``Ok`` -> Success, ``DocumentFiltered`` ->
    Filtered, any other step error -> Error outcome.

    The reference swallows hard errors (returns ``None`` and publishes no
    outcome, surfacing only as a count mismatch — worker_logic.rs:169-179).
    This build keeps the document visible in an Error outcome; the
    aggregation sink still writes it to neither file, preserving observable
    output parity while fixing the silent-loss accounting gap.
    """
    start = time.perf_counter()
    METRICS.inc("worker_active_tasks")
    try:
        result = executor.run_single(document)
        METRICS.inc("worker_tasks_processed_total")
        return ProcessingOutcome.success(result)
    except StepError as e:
        filtered = e.filtered()
        if filtered is not None:
            METRICS.inc("worker_tasks_filtered_total")
            # Funnel attribution: this is one of exactly two seams that
            # create a FILTERED outcome (the other is _assemble_row on the
            # device path), so the per-filter counters sum to the
            # excluded-Parquet row count by construction.
            METRICS.inc(FILTER_DROP_PREFIX + e.step_name)
            return ProcessingOutcome.filtered(filtered.document, filtered.reason)
        METRICS.inc("worker_tasks_failed_total")
        logger.error("Hard error in step '%s': %s", e.step_name, e.source)
        return ProcessingOutcome.error(document, str(e), worker_id)
    finally:
        METRICS.dec("worker_active_tasks")
        METRICS.observe("worker_task_processing_duration_seconds",
                        time.perf_counter() - start)


def process_documents_host(
    executor: PipelineExecutor,
    documents: Iterable[Union[TextDocument, PipelineError]],
    worker_id: str = "host-0",
    on_read_error: Optional[Callable[[PipelineError], None]] = None,
) -> Iterator[ProcessingOutcome]:
    """The host (CPU oracle / baseline) processing loop: the broker-free
    equivalent of process_tasks_with_executor (worker_logic.rs:241-283)."""
    for item in documents:
        if isinstance(item, PipelineError):
            logger.warning("Error reading document for task. Skipping. %s", item)
            if on_read_error is not None:
                on_read_error(item)
            continue
        outcome = execute_processing_pipeline(executor, item, worker_id)
        if outcome is not None:
            yield outcome


def aggregate_results_from_stream(
    stream: Iterable[ProcessingOutcome],
    output_file: str,
    excluded_file: str,
    published_count: Optional[int] = None,
    progress: Optional[Callable[[AggregationResult], None]] = None,
    deadletter=None,
    write_queue: int = 0,
) -> AggregationResult:
    """Route outcomes to the kept/excluded Parquet pair
    (producer_logic.rs:109-196).  Broker-independent: accepts any iterable of
    outcomes — the seam the reference's fake-stream tests rely on
    (producer_tests.rs:324-573).

    ``deadletter`` (a :class:`~textblaster_tpu.resilience.DeadLetterSink`)
    additionally receives every Error outcome; the kept/excluded pair still
    gets neither-file behavior for them, so the default artifacts are
    byte-identical with or without the sink.

    ``write_queue`` > 0 moves the actual Parquet writes onto a writer
    thread behind a bounded FIFO queue that deep (the overlapped pipeline's
    writer stage).  Batch boundaries and order are unchanged, so the files
    are byte-identical either way; a write error surfaces at the next
    ``write_batch`` or at close instead of at the failing call.
    """
    import os

    for f in (output_file, excluded_file):
        parent = os.path.dirname(f)
        if parent:
            os.makedirs(parent, exist_ok=True)

    out_writer = ParquetWriter(output_file)
    excl_writer = ParquetWriter(excluded_file)
    if write_queue > 0:
        from .utils.overlap import ThreadedWriter

        out_writer = ThreadedWriter(out_writer, max_queue=write_queue)
        excl_writer = ThreadedWriter(excl_writer, max_queue=write_queue)

    result = AggregationResult()
    out_batch: list[TextDocument] = []
    excl_batch: list[TextDocument] = []

    # Teardown discipline: each flush/close runs in its own guard so a failed
    # kept-file flush can neither mask the exception that aborted the stream
    # nor leak the excluded writer's file handle.  On a clean exit the first
    # teardown failure (if any) is re-raised; while a primary exception is
    # propagating, teardown failures are logged and suppressed.
    primary: Optional[BaseException] = None
    try:
        for outcome in stream:
            result.received += 1
            if outcome.kind == ProcessingOutcome.SUCCESS:
                result.success += 1
                METRICS.inc("producer_results_success_total")
                out_batch.append(outcome.document)
                if len(out_batch) >= PARQUET_WRITE_BATCH_SIZE:
                    out_writer.write_batch(out_batch)
                    out_batch.clear()
            elif outcome.kind == ProcessingOutcome.FILTERED:
                result.filtered += 1
                METRICS.inc("producer_results_filtered_total")
                excl_batch.append(outcome.document)
                if len(excl_batch) >= PARQUET_WRITE_BATCH_SIZE:
                    excl_writer.write_batch(excl_batch)
                    excl_batch.clear()
            else:
                # Error outcomes are counted in neither file (rs:168-170);
                # the opt-in dead-letter sink is the only place they land.
                result.errors += 1
                METRICS.inc("producer_results_error_total")
                if deadletter is not None:
                    deadletter.record_outcome(outcome)
            METRICS.inc("producer_results_received_total")
            if progress is not None:
                progress(result)
            if published_count is not None and result.received >= published_count:
                break

        if published_count is not None and result.received < published_count:
            logger.warning("Outcome stream closed before all outcomes received.")
    except BaseException as e:
        primary = e
        raise
    finally:
        teardown_error: Optional[BaseException] = None

        def guarded(step: Callable[[], None]) -> None:
            nonlocal teardown_error
            try:
                step()
            except BaseException as e:  # noqa: BLE001 — collected, not lost
                if teardown_error is None:
                    teardown_error = e
                else:
                    logger.error("Additional writer-teardown failure: %s", e)

        if out_batch:
            guarded(lambda: out_writer.write_batch(out_batch))
        if excl_batch:
            guarded(lambda: excl_writer.write_batch(excl_batch))
        guarded(out_writer.close)
        guarded(excl_writer.close)
        if teardown_error is not None:
            if primary is None:
                raise teardown_error
            logger.error(
                "Writer teardown failed while handling %r: %s",
                primary,
                teardown_error,
            )

    return result
