"""Prometheus-compatible metrics registry + HTTP ``/metrics`` endpoint.

Re-implementation of ``/root/reference/src/utils/prometheus_metrics.rs``: the
same metric names (9 producer-side + 7 worker-side, rs:16-143) exposed in
Prometheus text format over HTTP (rs:148-201).  Implemented with a
dependency-free registry and ``http.server`` in a daemon thread; a bind
failure is logged, not fatal (rs:186-195 parity).
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "Metrics",
    "METRICS",
    "setup_prometheus_metrics",
    "STAGE_COUNTERS",
    "stage_snapshot",
    "stage_breakdown",
    "format_stage_summary",
    "OCCUPANCY_BUCKET_PREFIX",
    "occupancy_snapshot",
    "occupancy_report",
    "format_occupancy_summary",
    "FILTER_DROP_PREFIX",
    "DEVICE_TIME_PREFIX",
    "DEVICE_BPS_PREFIX",
    "EVENT_KIND_PREFIX",
    "SLO_EVENTS_PREFIX",
    "SLO_BAD_EVENTS_PREFIX",
    "SLO_GAUGE_PREFIXES",
    "is_merge_gauge",
    "snapshot_delta",
    "events_report",
    "funnel_snapshot",
    "funnel_report",
    "format_funnel_summary",
    "metrics_snapshot",
    "resilience_report",
    "latency_report",
    "histogram_report",
    "build_run_report",
    "write_run_report",
    "RUN_REPORT_SCHEMA",
    "metrics_catalog_markdown",
    "HDR_SUBBUCKET_BITS",
    "HDR_RELATIVE_ERROR",
    "HDR_SPECS",
    "DOC_LATENCY_STAGES",
    "hdr_bucket_index",
    "hdr_bucket_high_us",
    "hdr_quantile_us",
]

# Histogram buckets mirroring the reference's defaults (prometheus crate).
_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# --- log-linear (HDR-style) histograms --------------------------------------
#
# Integer-microsecond values land in log-linear buckets: each power-of-two
# octave is split into 2**HDR_SUBBUCKET_BITS linear sub-buckets, so every
# bucket's width is at most its lower bound / 2**bits — i.e. any recorded
# value is reproduced by its bucket's upper bound within a bounded RELATIVE
# error, across the full dynamic range (1 µs .. hours) with a few hundred
# buckets at most.  All index math is pure-int and deterministic, and two
# histograms over the same scheme merge by bucket-wise addition — the
# property the multi-host run-report aggregation relies on for exact
# gang-wide quantiles.

HDR_SUBBUCKET_BITS = 5
_HDR_M = 1 << HDR_SUBBUCKET_BITS  # sub-buckets per octave

#: Worst-case relative error of a bucket-high readback vs the true value.
HDR_RELATIVE_ERROR = 1.0 / _HDR_M


def hdr_bucket_index(us: int) -> int:
    """Bucket index for an integer-microsecond value (log-linear scheme)."""
    v = int(us)
    if v < 0:
        v = 0
    if v < _HDR_M:
        return v  # first buckets are exact (width 1)
    k = v.bit_length() - 1
    sub = v >> (k - HDR_SUBBUCKET_BITS)  # in [M, 2M)
    return ((k - HDR_SUBBUCKET_BITS + 1) << HDR_SUBBUCKET_BITS) + (sub - _HDR_M)


def hdr_bucket_high_us(index: int) -> int:
    """Inclusive upper bound (µs) of a bucket — the quantile readback value.

    Strictly increasing in ``index``, and ``hdr_bucket_high_us(
    hdr_bucket_index(v)) >= v`` with relative error <= HDR_RELATIVE_ERROR.
    """
    i = int(index)
    if i < _HDR_M:
        return i
    e = (i >> HDR_SUBBUCKET_BITS) - 1
    sub = (i & (_HDR_M - 1)) + _HDR_M
    return ((sub + 1) << e) - 1


def hdr_quantile_us(buckets: Dict[int, int], count: int, q: float) -> int:
    """The q-quantile (µs) of a sparse ``{bucket_index: count}`` histogram.

    Rank semantics: the value at position ``ceil(q * count)`` of the sorted
    sample (1-based) — the "inverted CDF" definition, which is exact under
    bucket-wise merge: the quantile of a merged histogram equals the
    quantile of the concatenated samples (within the bucket error bound).
    """
    if count <= 0:
        return 0
    target = max(1, int(math.ceil(q * count)))
    seen = 0
    last = 0
    for idx in sorted(buckets):
        c = buckets[idx]
        if c <= 0:
            continue
        last = idx
        seen += c
        if seen >= target:
            return hdr_bucket_high_us(idx)
    return hdr_bucket_high_us(last)


#: Doc-lineage stage keys, in pipeline order, plus the end-to-end rollup —
#: each backs a dynamic HDR family ``doc_latency_<stage>_seconds``.
DOC_LATENCY_STAGES = (
    "read", "pack", "dispatch", "device_wait", "assemble", "write", "e2e",
)

#: Dynamic HDR histogram families (populated via ``Metrics.observe_hdr``) —
#: help strings for the exposition + the generated catalog.  Like the
#: occupancy/filter families, members only exist once observed.
HDR_SPECS: Dict[str, str] = {
    **{
        f"doc_latency_{stage}_seconds": (
            "Sampled per-document latency through the "
            f"'{stage}' stage (log-linear buckets, "
            "relative error <= 1/32)"
            if stage != "e2e"
            else "Sampled per-document end-to-end latency, first stage "
            "stamp to Parquet write (log-linear buckets, relative "
            "error <= 1/32)"
        )
        for stage in DOC_LATENCY_STAGES
    },
    "exchange_post_latency_seconds": (
        "Per-collective host_allgather post latency (log-linear buckets, "
        "relative error <= 1/32)"
    ),
    "multihost_lease_renew_latency_seconds": (
        "Per-renewal liveness-lease post latency, KV and file backends "
        "(log-linear buckets, relative error <= 1/32) — a fattening tail "
        "means heartbeat starvation is approaching the TTL"
    ),
}

# Metric name -> (type, help) — prometheus_metrics.rs:16-143.
_SPECS: Dict[str, Tuple[str, str]] = {
    # Producer side
    "producer_tasks_published_total": ("counter", "Total number of tasks published"),
    "producer_task_publish_errors_total": ("counter", "Task publish errors"),
    "producer_results_received_total": ("counter", "Total outcomes received"),
    "producer_results_success_total": ("counter", "Successful outcomes received"),
    "producer_results_filtered_total": ("counter", "Filtered outcomes received"),
    "producer_results_error_total": ("counter", "Error outcomes received"),
    "producer_results_deserialization_errors_total": (
        "counter",
        "Outcome deserialization errors",
    ),
    "producer_active_tasks_in_flight": ("gauge", "Tasks in flight"),
    "producer_task_publishing_duration_seconds": (
        "histogram",
        "Task publishing latency",
    ),
    # Worker side
    "worker_tasks_processed_total": ("counter", "Documents fully processed"),
    "worker_tasks_filtered_total": ("counter", "Documents filtered"),
    "worker_tasks_failed_total": ("counter", "Documents that hard-errored"),
    "worker_task_deserialization_errors_total": (
        "counter",
        "Task deserialization errors",
    ),
    "worker_outcome_publish_errors_total": ("counter", "Outcome publish errors"),
    "worker_task_processing_duration_seconds": (
        "histogram",
        "Per-document processing duration",
    ),
    "worker_active_tasks": ("gauge", "Documents currently being processed"),
    "worker_host_fallback_total": (
        "counter",
        "Documents rerouted to the host oracle (kernel table overflow or "
        "over-length outliers)",
    ),
    "worker_host_tail_total": (
        "counter",
        "Documents deliberately routed to the host oracle as end-of-stream "
        "tail groups too small to justify a padded device batch",
    ),
    "worker_fold_hazard_rows_total": (
        "counter",
        "Bad-words rows containing an IGNORECASE fold-hazard codepoint, "
        "re-decided by the host regex during batch assembly (per-row regex "
        "work, not a full pipeline fallback)",
    ),
    "worker_tokenizer_standin_total": (
        "counter",
        "TokenCounter instances that fell back to the vendored stand-in "
        "tokenizer (counts differ from the hub tokenizer)",
    ),
    # Resilience layer (no reference equivalent — the reference leans on
    # RabbitMQ redelivery; see textblaster_tpu/resilience/).
    "resilience_retries_total": (
        "counter",
        "Transient-fault re-attempts across all guarded seams",
    ),
    "resilience_retries_read_total": (
        "counter",
        "Re-attempts of Parquet row-group reads",
    ),
    "resilience_retries_device_total": (
        "counter",
        "Re-attempts of device batch execution",
    ),
    "resilience_retries_checkpoint_total": (
        "counter",
        "Re-attempts of checkpoint cursor commits",
    ),
    "resilience_retry_exhausted_total": (
        "counter",
        "Guarded operations that spent their whole retry budget",
    ),
    "resilience_ladder_split_total": (
        "counter",
        "Device batches split in half by the degradation ladder "
        "(OOM recovery rung)",
    ),
    "resilience_ladder_host_total": (
        "counter",
        "Documents rerun on the host oracle by the degradation ladder "
        "after device execution kept failing",
    ),
    "resilience_breaker_trips_total": (
        "counter",
        "Circuit-breaker trips (device path abandoned for the run)",
    ),
    "resilience_breaker_open": (
        "gauge",
        "1 while the device circuit breaker is open (run degraded to host)",
    ),
    "resilience_quarantined_rows_total": (
        "counter",
        "Input rows quarantined because their row group could not be read",
    ),
    "resilience_breaker_probe_total": (
        "counter",
        "Half-open probes granted by the device circuit breaker after a "
        "cooldown",
    ),
    "resilience_breaker_recoveries_total": (
        "counter",
        "Circuit-breaker closures via a successful half-open probe "
        "(device dispatch resumed)",
    ),
    "deadletter_rows_total": (
        "counter",
        "Rows routed to the opt-in dead-letter (--errors-file) sink",
    ),
    # Negotiated multi-host resilience (resilience/negotiated.py): fault
    # verdicts are allgathered per lockstep round, so these counters move
    # identically on every host.
    "resilience_negotiated_rounds_total": (
        "counter",
        "Multi-host lockstep rounds resolved under the negotiated guard",
    ),
    "resilience_negotiated_retries_total": (
        "counter",
        "Lockstep rounds jointly re-dispatched on every host after a "
        "negotiated fault verdict",
    ),
    "resilience_negotiated_degraded_rounds_total": (
        "counter",
        "Lockstep rounds jointly degraded to the host oracle (retry budget "
        "exhausted or bucket breaker latched)",
    ),
    "resilience_negotiated_batched_verdicts_total": (
        "counter",
        "Round fault flags that traveled piggybacked in a batched verdict "
        "vector (one allgather post for the whole window drain) instead of "
        "posting one scalar exchange each",
    ),
    "multihost_merge_commits_total": (
        "counter",
        "Final output files committed atomically (tmp+fsync+rename) by the "
        "host-0 shard merge",
    ),
    "multihost_stale_shards_removed_total": (
        "counter",
        "Stale *.shard* leftovers from prior runs removed under --force",
    ),
    # Elastic gang membership (resilience/membership.py): leased liveness,
    # deadline-bounded exchanges, and stripe adoption for multi-host runs.
    "multihost_membership_epoch": (
        "gauge",
        "Current membership epoch (starts at 1, bumps whenever the observed "
        "live set shrinks or grows)",
    ),
    "multihost_evictions_total": (
        "counter",
        "Peers evicted from the gang after their liveness lease expired",
    ),
    "multihost_rejoins_total": (
        "counter",
        "Peers observed rejoining the gang with a fresh lease (restart-in-"
        "place)",
    ),
    "multihost_adopted_stripes_total": (
        "counter",
        "Orphaned input stripes adopted from an evicted peer (--elastic)",
    ),
    "multihost_peer_failures_total": (
        "counter",
        "Lockstep exchanges aborted with a typed PeerFailure (deadline "
        "expired with peers missing, or a peer posted malformed data)",
    ),
    "multihost_lease_renewals_total": (
        "counter",
        "Liveness lease renewals posted by this process's heartbeat",
    ),
    "multihost_lease_age_ratio": (
        "gauge",
        "Own-lease age over TTL at the last self-fence/liveness check "
        "(>= 1.0 means the lease went stale — heartbeat starvation, e.g. "
        "a GIL-holding XLA compile)",
    ),
    "multihost_join_requests_total": (
        "counter",
        "Join requests this process posted next to the liveness leases "
        "(live scale-out admission)",
    ),
    "multihost_rank_joins_total": (
        "counter",
        "New ranks admitted into the running gang (live scale-out joins; "
        "counted once per join by the lowest previously-live rank, so the "
        "sum-merged run report reads joins, not member-observations)",
    ),
    "multihost_autoscale_spawned_total": (
        "counter",
        "Joiner processes spawned by the --autoscale supervisor under "
        "sustained backlog",
    ),
    # Overlapped multi-host lockstep (parallel/multihost.py): the in-flight
    # round window is negotiated once at run start (min over every host's
    # pipeline_depth); these fold into the run report's resilience section
    # like every multihost_* series.
    "multihost_negotiated_depth": (
        "gauge",
        "Joint lockstep window depth: the min over every host's "
        "--pipeline-depth, allgathered once at run start",
    ),
    "multihost_window_stall_seconds_total": (
        "counter",
        "Wall seconds blocked resolving the oldest in-flight lockstep "
        "round (window full, or the end-of-phase drain)",
    ),
    "multihost_lockstep_seconds_total": (
        "counter",
        "Wall seconds inside the negotiated lockstep phase loop "
        "(pack + dispatch + resolve), per host",
    ),
    "multihost_window_replayed_rounds_total": (
        "counter",
        "Launched-ahead lockstep rounds discarded and re-dispatched after "
        "a negotiated fault verdict drained the window",
    ),
    "multihost_gang_reformations_total": (
        "counter",
        "Gang reformations completed on the coordinated path "
        "(--survive-peer-loss): dead rank fenced, survivor set elected, "
        "interrupted exchange replayed",
    ),
    "multihost_fenced_ranks_total": (
        "counter",
        "Rank incarnations fenced during gang reformation (a fenced "
        "incarnation's late exchange posts are ignored forever)",
    ),
    "multihost_reformation_epoch": (
        "gauge",
        "Membership epoch after the most recent gang reformation on the "
        "coordinated path (gang-agreed; max-merged in the run report)",
    ),
    "multihost_file_exchange_posts_total": (
        "counter",
        "Exchange slot files posted by the file-lease transport "
        "(--exchange-transport file), one per rank per collective",
    ),
    "multihost_exchange_posts_total": (
        "counter",
        "host_allgather collectives this process posted a row into, any "
        "transport and any vector width — the batched verdict exchange "
        "drives this down by piggybacking a window's fault flags into one "
        "vector post",
    ),
    "multihost_exchange_post_seconds_total": (
        "counter",
        "Wall seconds inside host_allgather posts (transport round trip "
        "included), across all collectives this process joined",
    ),
    # Speculative cross-phase dispatch (parallel/multihost.py
    # resolve_barrier): next-phase rounds launch at each phase barrier
    # before the tail verdicts resolve, and the barrier's three classic
    # exchanges collapse into one post.  TEXTBLAST_SPECULATE=off /
    # --speculate-depth 0 zeroes all four series.
    "multihost_speculate_depth": (
        "gauge",
        "Joint speculative dispatch depth: the min over every host's "
        "--speculate-depth (default: the window depth), allgathered with "
        "the window depth at run start; 0 means the classic barrier",
    ),
    "multihost_speculated_rounds_total": (
        "counter",
        "Next-phase lockstep rounds launched at a phase barrier before "
        "the tail verdicts resolved (includes re-launches after a void)",
    ),
    "multihost_voided_rounds_total": (
        "counter",
        "Speculative launches discarded by the joint rollback — a fault "
        "verdict, bucket latch, or gang reformation voided the result and "
        "the round re-dispatched fresh (outputs stay byte-identical)",
    ),
    "multihost_barrier_elisions_total": (
        "counter",
        "Exchange posts saved at phase barriers by piggybacking the tail "
        "verdict batch, join-admission lanes, and next-phase round counts "
        "into one combined post (largest win on the file transport, "
        "where each post is a filesystem round-trip)",
    ),
    # Stall watchdog (resilience/watchdog.py): per-stage deadlines over the
    # host-side blocking waits.  --stage-deadline-s 0 (the default) disarms
    # the watchdog and zeroes every series here.
    "watchdog_stalls_total": (
        "counter",
        "Host-side stage waits that exceeded their watchdog deadline and "
        "raised a typed StallError (stage named in the trace instant) "
        "instead of blocking forever",
    ),
    "watchdog_escalations_total": (
        "counter",
        "StallErrors handed to existing recovery machinery: the "
        "retry -> split -> host ladder on the single-host path, a local "
        "fault verdict (joint window drain/retry) on the lockstep path",
    ),
    "watchdog_deadline_seconds_device_fetch": (
        "gauge",
        "Active watchdog deadline for the device-fetch stage, seconds "
        "(0 / absent = unbounded)",
    ),
    "watchdog_deadline_seconds_pack_wait": (
        "gauge",
        "Active watchdog deadline for the pack-pool future wait, seconds "
        "(0 / absent = unbounded)",
    ),
    "watchdog_deadline_seconds_write_queue": (
        "gauge",
        "Active watchdog deadline for the write-behind queue (enqueue and "
        "teardown drain), seconds (0 / absent = unbounded)",
    ),
    "watchdog_deadline_seconds_read_prefetch": (
        "gauge",
        "Active watchdog deadline for the reader-prefetch queue wait, "
        "seconds (0 / absent = unbounded)",
    ),
    # Overlapped-pipeline stage accounting (no reference equivalent).  The
    # counters are wall seconds spent *inside* each stage, summed across
    # worker threads; with overlap on, stages run concurrently, so the sum
    # can exceed end-to-end wall time — compare stages to each other, not
    # to the clock.
    "stage_read_seconds": (
        "counter",
        "Wall seconds decoding Parquet row-groups into documents",
    ),
    "stage_pack_seconds": (
        "counter",
        "Wall seconds packing documents into device batches",
    ),
    "stage_dispatch_seconds": (
        "counter",
        "Wall seconds enqueueing device programs (host-side dispatch cost)",
    ),
    "stage_device_wait_seconds": (
        "counter",
        "Wall seconds blocked on device results (device compute not hidden "
        "by host work)",
    ),
    "stage_post_seconds": (
        "counter",
        "Wall seconds in host post-passes (assembly, TokenCounter, "
        "C4BadWords re-decides, host-oracle reruns)",
    ),
    "stage_write_seconds": (
        "counter",
        "Wall seconds writing outcome batches to Parquet",
    ),
    "queue_depth_read": (
        "gauge",
        "Prefetched row-group blocks buffered ahead of the consumer",
    ),
    "queue_depth_pack": (
        "gauge",
        "Packed batches waiting in the pack-stage queue",
    ),
    "queue_depth_write": (
        "gauge",
        "Outcome batches waiting in the writer-thread queue",
    ),
    "inflight_batches": (
        "gauge",
        "Device batches currently in flight (dispatched, not yet fetched)",
    ),
    # Per-document tail-latency telemetry (utils/telemetry.py): a
    # deterministic doc-id sampler stamps sampled documents at every stage
    # seam and feeds the dynamic doc_latency_* HDR histogram families.
    "doc_sampled_total": (
        "counter",
        "Documents selected by the deterministic lineage sampler "
        "(--doc-sample-rate)",
    ),
    "doc_lineage_evicted_total": (
        "counter",
        "Sampled document lineages evicted before reaching the write "
        "stage (lineage table at capacity)",
    ),
    "writer_chars_total": (
        "counter",
        "Document characters written to Parquet output (telemetry runs "
        "only; feeds the live chars/s rollup window)",
    ),
    "geometry_drift": (
        "gauge",
        "Relative deviation of the live padding-waste window from the "
        "calibration-time baseline (max-merged across hosts)",
    ),
    "trace_events_dropped_total": (
        "counter",
        "Trace events dropped: ring overflow with no spill file, or a "
        "spill write that failed (disk full / unwritable path)",
    ),
    # Operational event journal (utils/events.py): severity-leveled JSONL
    # record of every resilience/membership/watchdog/SLO transition.
    "events_emitted_total": (
        "counter",
        "Operational events recorded by the journal (per-kind counts in "
        "the dynamic events_total_<kind> families)",
    ),
    "events_dropped_total": (
        "counter",
        "Journal events dropped: ring overflow with no spill file, or a "
        "spill write that failed (disk full / unwritable path)",
    ),
    "events_invalid_total": (
        "counter",
        "Journal emit() calls rejected for schema violations (unknown "
        "kind or missing required data fields)",
    ),
    # SLO engine (utils/slo.py): burn-rate alerting over declared
    # objectives; per-objective state lives in the dynamic slo_* families.
    "slo_alerts_total": (
        "counter",
        "Edge-triggered SLO alerts: both the fast and slow burn-rate "
        "windows exceeded the threshold for an objective",
    ),
    "pipeline_warmup_done": (
        "gauge",
        "1 once the warmup decision has resolved for this process (warmed "
        "or deliberately skipped) — the /healthz readiness gate",
    ),
    # Device-occupancy accounting (ops/pipeline.py record_occupancy): a
    # compiled program computes every padded lane of its fixed shape, so
    # real/padded is the fraction of device work spent on actual text.
    "occupancy_device_batches_total": (
        "counter",
        "Device batches dispatched (every backend: CPU, TPU, mesh)",
    ),
    "occupancy_padded_lanes_total": (
        "counter",
        "Codepoint lanes computed by the device across all dispatches "
        "(rows x bucket length, padding included)",
    ),
    "occupancy_real_codepoints_total": (
        "counter",
        "Real document codepoints carried by those lanes",
    ),
}

#: Per-bucket dispatch counters are dynamic — one counter per bucket length
#: actually dispatched (``occupancy_dispatches_bucket_<L>``); ``render`` and
#: the occupancy report discover them by this prefix.
OCCUPANCY_BUCKET_PREFIX = "occupancy_dispatches_bucket_"

#: Per-filter drop counters are dynamic too — one counter per filter name
#: (``filter_dropped_total_<name>``), incremented at the exact two seams
#: that create a FILTERED outcome (orchestration.execute_processing_pipeline
#: and ops/pipeline._assemble_row), so their sum equals the excluded-Parquet
#: row count by construction.
FILTER_DROP_PREFIX = "filter_dropped_total_"

#: Per-(bucket, phase) device-time HDR histogram families are dynamic —
#: one family per (bucket length, phase) actually dispatched
#: (``device_time_bucket_<L>_phase_<P>_seconds``, fed by
#: ``utils.profiler.PROFILER.record_dispatch``); ``render`` and the
#: ``device_profile`` report section discover them by this prefix.
DEVICE_TIME_PREFIX = "device_time_bucket_"

#: Roofline-style achieved-bandwidth gauges are dynamic too — one gauge
#: per (bucket, phase) (``device_achieved_bytes_per_s_bucket_<L>_phase_
#: <P>``): the program's modeled bytes accessed divided by the latest
#: dispatch's blocked-on-device seconds.
DEVICE_BPS_PREFIX = "device_achieved_bytes_per_s_bucket_"

#: Per-kind journal counters are dynamic — one counter per event kind
#: actually emitted (``events_total_<kind>``, fed by
#: ``utils.events.EVENTS.emit``); counters, so the multihost sum-merge
#: aggregates gang-wide event counts and run-report v4 reads them from
#: any flat snapshot.
EVENT_KIND_PREFIX = "events_total_"

#: Per-objective SLO families are dynamic too (one member per declared
#: ``--slo`` key): monotone event/bad-event counters plus the target /
#: burn-rate / budget-remaining gauges published by ``utils.slo.SLO``.
SLO_EVENTS_PREFIX = "slo_events_total_"
SLO_BAD_EVENTS_PREFIX = "slo_bad_events_total_"
SLO_GAUGE_PREFIXES = (
    "slo_target_", "slo_burn_rate_", "slo_budget_remaining_",
)


def is_merge_gauge(name: str) -> bool:
    """True when a flat-snapshot key must merge by max (a gauge), not by
    sum.  The multihost merge used to consult ``_SPECS`` alone, which
    silently summed *dynamic* gauges; every dynamic gauge family prefix
    is enumerated here so new ones can't regress the merge."""
    spec = _SPECS.get(name)
    if spec is not None:
        return spec[0] == "gauge"
    return name.startswith(SLO_GAUGE_PREFIXES)


def _dynamic_hdr_help(name: str) -> str:
    """HELP text for a dynamic HDR family not listed in ``HDR_SPECS``."""
    if name.startswith(DEVICE_TIME_PREFIX):
        body = name[len(DEVICE_TIME_PREFIX):]
        return (
            f"Per-dispatch blocked-on-device wall time at bucket_phase "
            f"{body.replace('_seconds', '')} (log-linear buckets, "
            "relative error <= 1/32)"
        )
    return "Log-linear latency histogram (microsecond base)"


#: The per-stage wall-time counters, in pipeline order.
STAGE_COUNTERS = (
    "stage_read_seconds",
    "stage_pack_seconds",
    "stage_dispatch_seconds",
    "stage_device_wait_seconds",
    "stage_post_seconds",
    "stage_write_seconds",
)


def stage_snapshot() -> Dict[str, float]:
    """Current values of the stage wall-time counters."""
    return {name: METRICS.get(name) for name in STAGE_COUNTERS}


def _delta_fn(baseline, values):
    """Shared resolver for the report helpers: with ``values`` (an already
    materialized name->value dict, e.g. a summed cross-host snapshot) read
    from it and apply ``baseline``; otherwise read the live registry."""
    base = baseline or {}
    if values is not None:
        return lambda name: max(0.0, float(values.get(name, 0.0)) - base.get(name, 0.0))
    return lambda name: max(0.0, METRICS.get(name) - base.get(name, 0.0))


def _prefixed_from(values: Optional[Dict[str, float]], prefix: str) -> Dict[str, float]:
    if values is not None:
        return {k: float(v) for k, v in values.items() if k.startswith(prefix)}
    return METRICS.prefixed(prefix)


def stage_breakdown(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """Per-stage seconds (optionally relative to a snapshot) plus a
    host-bound vs device-bound verdict.

    Host seconds are read+pack+dispatch+write plus the post-pass time not
    already accounted as device wait (the serial path blocks inside the
    post/assembly phase, so ``post`` includes ``device_wait``; clamp at 0).
    Device seconds are the explicit device-wait counter.  ``verdict`` is
    "host-bound" when host work dominates, "device-bound" when the device
    wait does, "balanced" within 20%.
    """
    delta = _delta_fn(baseline, values)
    stages = {name: delta(name) for name in STAGE_COUNTERS}
    device_s = stages["stage_device_wait_seconds"]
    post_host = max(0.0, stages["stage_post_seconds"] - device_s)
    host_s = (
        stages["stage_read_seconds"]
        + stages["stage_pack_seconds"]
        + stages["stage_dispatch_seconds"]
        + post_host
        + stages["stage_write_seconds"]
    )
    if host_s > device_s * 1.2:
        verdict = "host-bound"
    elif device_s > host_s * 1.2:
        verdict = "device-bound"
    else:
        verdict = "balanced"
    return {
        "stages_s": {k: round(v, 3) for k, v in stages.items()},
        "host_s": round(host_s, 3),
        "device_s": round(device_s, 3),
        "verdict": verdict,
    }


def format_stage_summary(
    baseline: Optional[Dict[str, float]] = None,
) -> str:
    """End-of-run, human-readable stage summary (one line per stage)."""
    b = stage_breakdown(baseline)
    lines = ["Stage breakdown (wall seconds inside each stage):"]
    for name in STAGE_COUNTERS:
        label = name[len("stage_"):-len("_seconds")]
        lines.append(f"  {label:<12} {b['stages_s'][name]:>9.3f}s")
    lines.append(
        f"  host {b['host_s']:.3f}s vs device-wait {b['device_s']:.3f}s "
        f"-> {b['verdict']}"
    )
    return "\n".join(lines)


def occupancy_snapshot() -> Dict[str, float]:
    """Current values of every occupancy counter (per-bucket ones included)
    — the ``baseline`` argument for a scoped ``occupancy_report``."""
    snap = {
        name: METRICS.get(name)
        for name in (
            "occupancy_device_batches_total",
            "occupancy_padded_lanes_total",
            "occupancy_real_codepoints_total",
        )
    }
    snap.update(METRICS.prefixed(OCCUPANCY_BUCKET_PREFIX))
    return snap


def occupancy_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """Device-occupancy summary, optionally relative to a snapshot.

    ``waste_ratio`` is the fraction of computed codepoint lanes that carried
    padding rather than document text — the quantity the calibrated
    geometry minimizes."""
    base = baseline or {}
    delta = _delta_fn(baseline, values)

    lanes = delta("occupancy_padded_lanes_total")
    real = delta("occupancy_real_codepoints_total")
    per_bucket = {}
    for name, value in sorted(
        _prefixed_from(values, OCCUPANCY_BUCKET_PREFIX).items(),
        key=lambda kv: int(kv[0][len(OCCUPANCY_BUCKET_PREFIX):]),
    ):
        d = value - base.get(name, 0.0)
        if d > 0:
            per_bucket[int(name[len(OCCUPANCY_BUCKET_PREFIX):])] = int(d)
    return {
        "device_batches": int(delta("occupancy_device_batches_total")),
        "real_codepoints": int(real),
        "padded_lanes": int(lanes),
        "waste_ratio": round(1.0 - real / lanes, 4) if lanes > 0 else 0.0,
        "per_bucket_dispatches": per_bucket,
    }


def format_occupancy_summary(
    baseline: Optional[Dict[str, float]] = None,
) -> str:
    """One-line, human-readable occupancy report for the CLI summary."""
    occ = occupancy_report(baseline)
    buckets = ", ".join(
        f"{length}x{n}" for length, n in occ["per_bucket_dispatches"].items()
    )
    return (
        f"Device occupancy: {occ['real_codepoints']:,} real of "
        f"{occ['padded_lanes']:,} computed codepoint lanes "
        f"({occ['waste_ratio']:.1%} padding waste) across "
        f"{occ['device_batches']} dispatches"
        + (f" [bucket x dispatches: {buckets}]." if buckets else ".")
    )


def funnel_snapshot() -> Dict[str, float]:
    """Current values of every per-filter drop counter — the ``baseline``
    argument for a scoped ``funnel_report``."""
    return METRICS.prefixed(FILTER_DROP_PREFIX)


def funnel_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """Per-filter drop attribution.  ``dropped_total`` equals the number of
    FILTERED outcomes (= excluded-Parquet rows) because the counters are
    incremented at the exact seams that create those outcomes."""
    base = baseline or {}
    per_filter: Dict[str, int] = {}
    for name, value in _prefixed_from(values, FILTER_DROP_PREFIX).items():
        d = value - base.get(name, 0.0)
        if d > 0:
            per_filter[name[len(FILTER_DROP_PREFIX):]] = int(d)
    per_filter = dict(
        sorted(per_filter.items(), key=lambda kv: (-kv[1], kv[0]))
    )
    return {
        "per_filter_dropped": per_filter,
        "dropped_total": int(sum(per_filter.values())),
    }


def format_funnel_summary(
    baseline: Optional[Dict[str, float]] = None,
    order: Optional[List[str]] = None,
) -> str:
    """Human-readable per-filter drop funnel for the CLI tail.  ``order``
    (the pipeline's step sequence) pins the display order; filters that
    dropped nothing are listed with 0 so the funnel reads as the config."""
    rep = funnel_report(baseline)
    per = dict(rep["per_filter_dropped"])
    names = list(order) if order else []
    names += [n for n in per if n not in names]
    total = rep["dropped_total"]
    lines = [f"Filter funnel ({total:,} documents dropped):"]
    for name in names:
        n = per.get(name, 0)
        share = f" ({n / total:.1%})" if total else ""
        lines.append(f"  {name:<24} {n:>9,}{share if n else ''}")
    if not names:
        lines.append("  (no filter drops recorded)")
    return "\n".join(lines)


def metrics_snapshot() -> Dict[str, float]:
    """Full copy of every counter/gauge (dynamic families included) —
    the unit of cross-host exchange and the run-report baseline.
    Histogram state rides along as flat ``name::b<i>`` / ``name::h<i>`` /
    ``name::sum`` / ``name::count`` keys: every one is a monotone count, so
    the cross-host sum-merge aggregates histograms bucket-wise exactly like
    counters (the keys can't collide with real metric names — '::' never
    appears in one)."""
    # Flush the SLO engine first (when armed): its counters are published
    # on evaluation ticks, and a run shorter than one tick would otherwise
    # hand the report/exchange a snapshot with stale zeros.
    try:
        from .slo import SLO

        if SLO.enabled:
            SLO.evaluate()
    except Exception:  # noqa: BLE001 — snapshot must not fail on a tick
        pass
    return METRICS.all_values()


#: Counter families surfaced in the run report's resilience section.
_RESILIENCE_REPORT_PREFIXES = (
    "resilience_", "deadletter_", "multihost_", "watchdog_",
)


def resilience_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, int]:
    """Every resilience/dead-letter/multihost counter as an int delta.

    ``multihost_`` gauges (e.g. the negotiated window depth) ride along as
    plain ints: they hold gang-agreed values, identical on every host, so
    the merged report carries them without a delta interpretation."""
    delta = _delta_fn(baseline, values)
    out: Dict[str, int] = {}
    for name, (mtype, _help) in _SPECS.items():
        if name.startswith(_RESILIENCE_REPORT_PREFIXES) and (
            mtype == "counter"
            or (mtype == "gauge" and name.startswith("multihost_"))
        ):
            out[name] = int(delta(name))
    return out


def _hdr_delta(
    vals: Dict[str, float], base: Dict[str, float], name: str
) -> Tuple[Dict[int, int], int, int]:
    """Decode one HDR family from a flat snapshot, relative to a baseline.

    Returns ``(sparse buckets, sum_us, count)`` with every count clamped at
    zero — the inverse of the ``name::h<i>`` encoding ``all_values`` emits.
    """
    prefix = name + "::h"
    buckets: Dict[int, int] = {}
    for k, v in vals.items():
        if k.startswith(prefix):
            d = int(v) - int(base.get(k, 0))
            if d > 0:
                buckets[int(k[len(prefix):])] = d
    sum_us = max(0, int(vals.get(name + "::sum", 0)) - int(base.get(name + "::sum", 0)))
    count = max(0, int(vals.get(name + "::count", 0)) - int(base.get(name + "::count", 0)))
    return buckets, sum_us, count


def latency_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """Per-stage + end-to-end sampled-latency quantiles (the run report's
    ``latency`` section).

    Reads the encoded HDR families out of ``values`` (or the live registry)
    relative to ``baseline``.  All math is pure-int bucket walking, so the
    same merged snapshot always produces byte-identical quantile blocks —
    the determinism the multi-host merged report relies on.
    """
    vals = values if values is not None else METRICS.all_values()
    base = baseline or {}
    stages: Dict[str, object] = {}
    families = [(s, f"doc_latency_{s}_seconds") for s in DOC_LATENCY_STAGES]
    families.append(("exchange_post", "exchange_post_latency_seconds"))
    families.append(("lease_renew", "multihost_lease_renew_latency_seconds"))
    for stage, fam in families:
        buckets, sum_us, count = _hdr_delta(vals, base, fam)
        if count <= 0:
            continue
        stages[stage] = {
            "count": count,
            "mean_s": round(sum_us / count / 1e6, 6),
            "p50_s": round(hdr_quantile_us(buckets, count, 0.50) / 1e6, 6),
            "p95_s": round(hdr_quantile_us(buckets, count, 0.95) / 1e6, 6),
            "p99_s": round(hdr_quantile_us(buckets, count, 0.99) / 1e6, 6),
            "max_le_s": round(
                hdr_bucket_high_us(max(buckets)) / 1e6, 6
            ) if buckets else 0.0,
        }
    return {"relative_error": HDR_RELATIVE_ERROR, "stages": stages}


def histogram_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """Fixed-bucket histogram deltas (the run report's ``histograms``
    section) — the families ``observe`` feeds, which earlier report
    versions silently dropped because snapshots excluded histogram state.

    Buckets are per-bucket (non-cumulative) counts keyed by upper bound, so
    a merged multi-host report's buckets equal the bucket-wise sum of the
    per-host snapshots by construction."""
    vals = values if values is not None else METRICS.all_values()
    base = baseline or {}
    out: Dict[str, object] = {}
    for name, (mtype, _help) in _SPECS.items():
        if mtype != "histogram":
            continue
        count = max(
            0,
            int(vals.get(f"{name}::count", 0)) - int(base.get(f"{name}::count", 0)),
        )
        if count <= 0:
            continue
        bucket_counts: Dict[str, int] = {}
        for i in range(len(_DEFAULT_BUCKETS) + 1):
            key = f"{name}::b{i}"
            d = int(vals.get(key, 0)) - int(base.get(key, 0))
            if d > 0:
                le = "+Inf" if i == len(_DEFAULT_BUCKETS) else f"{_DEFAULT_BUCKETS[i]:g}"
                bucket_counts[le] = d
        total = max(
            0.0,
            float(vals.get(f"{name}::sum", 0.0)) - float(base.get(f"{name}::sum", 0.0)),
        )
        out[name] = {
            "count": count,
            "sum_s": round(total, 6),
            "buckets": bucket_counts,
        }
    return out


#: Schema identifier stamped into every run report (bump on breaking shape
#: changes; consumers should match on it, not on key presence).  v2 adds
#: the ``latency`` (per-stage HDR quantile blocks) and ``histograms``
#: (fixed-bucket histogram deltas) sections; v3 adds ``device_profile``
#: (static cost model, per-(bucket, phase) device-time quantiles, roofline
#: gauges, top-K dispatches, lockstep decomposition); v4 adds ``events``
#: (per-kind operational journal counts + drop/invalid accounting) and
#: ``slo`` (per-objective burn-rate / error-budget state).
RUN_REPORT_SCHEMA = "textblaster-run-report/v4"


def events_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """The run report's ``events`` section: per-kind journal counts as int
    deltas, plus the emitted/dropped/invalid totals.  Pure counter reads,
    so the section built from a gang-merged snapshot carries the summed
    gang-wide event counts by construction."""
    base = baseline or {}
    delta = _delta_fn(baseline, values)
    per_kind: Dict[str, int] = {}
    for name, value in _prefixed_from(values, EVENT_KIND_PREFIX).items():
        d = value - base.get(name, 0.0)
        if d > 0:
            per_kind[name[len(EVENT_KIND_PREFIX):]] = int(d)
    emitted = int(delta("events_emitted_total"))
    if not per_kind and emitted == 0:
        return {}
    return {
        "emitted_total": emitted,
        "dropped_total": int(delta("events_dropped_total")),
        "invalid_total": int(delta("events_invalid_total")),
        "by_kind": dict(
            sorted(per_kind.items(), key=lambda kv: (-kv[1], kv[0]))
        ),
    }


def _slo_section(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """The ``slo`` report section, built by utils/slo.py.  Imported lazily
    (slo.py imports this module at runtime; the reverse edge only exists
    inside a report build) and never allowed to fail the report."""
    try:
        from .slo import slo_report

        return slo_report(baseline, values)
    except Exception as e:  # noqa: BLE001 — observability must not kill a run
        logger.warning("slo section skipped: %s", e)
        return {}


def snapshot_delta(
    before: Dict[str, float], now: Dict[str, float]
) -> Dict[str, float]:
    """A run-scoped metrics snapshot for report shards: counters as
    ``now - before``, merge-gauges (:func:`is_merge_gauge`) at their
    *current* value.  A gauge armed before the run window opened — the
    ``slo_target_*`` triple, watchdog deadlines — deltas to zero and
    would silently vanish from the merged report otherwise; the max-merge
    the gang applies downstream wants the level, not the movement."""
    out: Dict[str, float] = {}
    for k in set(now) | set(before):
        if is_merge_gauge(k):
            v = round(now.get(k, 0.0), 6)
            if v != 0.0:
                out[k] = v
        else:
            d = round(now.get(k, 0.0) - before.get(k, 0.0), 6)
            if d != 0.0:
                out[k] = d
    return out


def _device_profile_section(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """The ``device_profile`` report section, built by utils/profiler.py.
    Imported lazily (profiler.py imports this module at load time; the
    reverse edge only exists inside a report build) and never allowed to
    fail the report."""
    try:
        from .profiler import device_profile_report

        return device_profile_report(baseline, values)
    except Exception as e:  # noqa: BLE001 — observability must not kill a run
        logger.warning("device_profile section skipped: %s", e)
        return {}


def build_run_report(
    *,
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
    wall_time_s: Optional[float] = None,
    counts: Optional[Dict[str, int]] = None,
    provenance: Optional[Dict[str, object]] = None,
    hosts: Optional[List[Dict[str, object]]] = None,
) -> Dict[str, object]:
    """Machine-readable end-of-run artifact (the ``--run-report`` payload).

    Reads the live registry relative to ``baseline`` by default; pass
    ``values`` (e.g. per-host deltas summed across an allgather) to build
    the same report from a materialized snapshot instead.  ``hosts``
    attaches the per-host snapshots on the multihost merged report."""
    report: Dict[str, object] = {
        "schema": RUN_REPORT_SCHEMA,
        "wall_time_s": round(wall_time_s, 3) if wall_time_s is not None else None,
        "counts": dict(counts or {}),
        "stages": stage_breakdown(baseline, values),
        "latency": latency_report(baseline, values),
        "histograms": histogram_report(baseline, values),
        "occupancy": occupancy_report(baseline, values),
        "resilience": resilience_report(baseline, values),
        "funnel": funnel_report(baseline, values),
        "device_profile": _device_profile_section(baseline, values),
        "events": events_report(baseline, values),
        "slo": _slo_section(baseline, values),
        "config": dict(provenance or {}),
    }
    if hosts is not None:
        report["hosts"] = hosts
        report["num_hosts"] = len(hosts)
    return report


def write_run_report(path: str, report: Dict[str, object]) -> None:
    """Write the report as pretty-printed JSON (parents created)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=False)
        f.write("\n")


def metrics_catalog_markdown() -> str:
    """Markdown table of every metric — the README catalog is generated
    from this (``python -m textblaster_tpu.utils.metrics``) so the docs
    cannot drift from ``_SPECS``."""
    lines = [
        "| Metric | Type | Description |",
        "| --- | --- | --- |",
    ]
    for name, (mtype, help_text) in _SPECS.items():
        lines.append(f"| `{name}` | {mtype} | {help_text} |")
    lines.append(
        f"| `{OCCUPANCY_BUCKET_PREFIX}<L>` | counter | Dynamic family: "
        "device dispatches at bucket length `<L>` |"
    )
    lines.append(
        f"| `{FILTER_DROP_PREFIX}<name>` | counter | Dynamic family: "
        "documents dropped by filter `<name>` |"
    )
    for name, help_text in HDR_SPECS.items():
        lines.append(f"| `{name}` | histogram | Dynamic family: {help_text} |")
    lines.append(
        f"| `{DEVICE_TIME_PREFIX}<L>_phase_<P>_seconds` | histogram | "
        "Dynamic family: per-dispatch blocked-on-device wall time at "
        "bucket length `<L>`, phase `<P>` (log-linear buckets, relative "
        "error <= 1/32; fed by the profiler) |"
    )
    lines.append(
        f"| `{DEVICE_BPS_PREFIX}<L>_phase_<P>` | gauge | Dynamic family: "
        "achieved device bytes/s (modeled bytes accessed / last dispatch "
        "wait) at bucket length `<L>`, phase `<P>` |"
    )
    lines.append(
        f"| `{EVENT_KIND_PREFIX}<kind>` | counter | Dynamic family: "
        "operational journal events of kind `<kind>` (enumerated in "
        "`utils.events.KINDS`) |"
    )
    lines.append(
        f"| `{SLO_EVENTS_PREFIX}<key>` | counter | Dynamic family: SLO "
        "events evaluated for objective `<key>` |"
    )
    lines.append(
        f"| `{SLO_BAD_EVENTS_PREFIX}<key>` | counter | Dynamic family: "
        "SLO budget-consuming (bad) events for objective `<key>` |"
    )
    lines.append(
        "| `slo_target_<key>` | gauge | Dynamic family: declared SLO "
        "target for objective `<key>` |"
    )
    lines.append(
        "| `slo_burn_rate_<key>` | gauge | Dynamic family: fast-window "
        "error-budget burn rate for objective `<key>` (1.0 = consuming "
        "exactly the budget) |"
    )
    lines.append(
        "| `slo_budget_remaining_<key>` | gauge | Dynamic family: "
        "fraction of the error budget left for objective `<key>` |"
    )
    return "\n".join(lines)


class Metrics:
    """Thread-safe counter/gauge/histogram registry."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, float] = defaultdict(float)
        self._hist_counts: Dict[str, List[int]] = {}
        self._hist_sum: Dict[str, float] = defaultdict(float)
        self._hist_total: Dict[str, int] = defaultdict(int)
        # Log-linear histograms: sparse {bucket_index: count} per family,
        # sums kept in integer microseconds so merges stay exact.
        self._hdr: Dict[str, Dict[int, int]] = {}
        self._hdr_sum_us: Dict[str, int] = defaultdict(int)
        self._hdr_count: Dict[str, int] = defaultdict(int)

    def inc(self, name: str, amount: float = 1.0) -> None:
        with self._lock:
            self._values[name] += amount

    def dec(self, name: str, amount: float = 1.0) -> None:
        with self._lock:
            self._values[name] -= amount

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._values.get(name, 0.0)

    def prefixed(self, prefix: str) -> Dict[str, float]:
        """All dynamic counters whose name starts with ``prefix``."""
        with self._lock:
            return {
                k: v for k, v in self._values.items() if k.startswith(prefix)
            }

    def all_values(self) -> Dict[str, float]:
        """Copy of every counter/gauge value, with histogram state encoded
        as flat mergeable keys.

        Fixed-bucket histograms contribute ``name::b<i>`` (per-bucket,
        non-cumulative count) for every populated bucket plus ``name::sum``
        / ``name::count``; HDR families contribute ``name::h<idx>`` plus
        ``name::sum`` (µs) / ``name::count``.  Every encoded key is a
        monotone count, so the multi-host snapshot merge (which sums
        anything not declared a gauge) aggregates histograms bucket-wise
        with no special casing — run reports no longer drop them."""
        with self._lock:
            out = dict(self._values)
            for name, counts in self._hist_counts.items():
                for i, c in enumerate(counts):
                    if c:
                        out[f"{name}::b{i}"] = float(c)
                out[f"{name}::sum"] = self._hist_sum.get(name, 0.0)
                out[f"{name}::count"] = float(self._hist_total.get(name, 0))
            for name, buckets in self._hdr.items():
                for idx, c in buckets.items():
                    if c:
                        out[f"{name}::h{idx}"] = float(c)
                out[f"{name}::sum"] = float(self._hdr_sum_us.get(name, 0))
                out[f"{name}::count"] = float(self._hdr_count.get(name, 0))
            return out

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            if name not in self._hist_counts:
                self._hist_counts[name] = [0] * (len(_DEFAULT_BUCKETS) + 1)
            counts = self._hist_counts[name]
            for i, b in enumerate(_DEFAULT_BUCKETS):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._hist_sum[name] += value
            self._hist_total[name] += 1

    def observe_hdr(self, name: str, us: int) -> None:
        """Record one integer-microsecond value into a log-linear family."""
        v = max(0, int(us))
        idx = hdr_bucket_index(v)
        with self._lock:
            fam = self._hdr.get(name)
            if fam is None:
                fam = self._hdr[name] = {}
            fam[idx] = fam.get(idx, 0) + 1
            self._hdr_sum_us[name] += v
            self._hdr_count[name] += 1

    def hdr_state(self, name: str) -> Tuple[Dict[int, int], int, int]:
        """``(sparse buckets, sum_us, count)`` snapshot of one HDR family."""
        with self._lock:
            return (
                dict(self._hdr.get(name, {})),
                self._hdr_sum_us.get(name, 0),
                self._hdr_count.get(name, 0),
            )

    def reset(self) -> None:
        with self._lock:
            self._values.clear()
            self._hist_counts.clear()
            self._hist_sum.clear()
            self._hist_total.clear()
            self._hdr.clear()
            self._hdr_sum_us.clear()
            self._hdr_count.clear()

    def render(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            lines: List[str] = []
            for name, (mtype, help_text) in _SPECS.items():
                if mtype in ("counter", "gauge"):
                    lines.append(f"# HELP {name} {help_text}")
                    lines.append(f"# TYPE {name} {mtype}")
                    lines.append(f"{name} {self._values.get(name, 0.0):g}")
                else:
                    lines.append(f"# HELP {name} {help_text}")
                    lines.append(f"# TYPE {name} histogram")
                    counts = self._hist_counts.get(
                        name, [0] * (len(_DEFAULT_BUCKETS) + 1)
                    )
                    cumulative = 0
                    for i, b in enumerate(_DEFAULT_BUCKETS):
                        cumulative += counts[i]
                        lines.append(f'{name}_bucket{{le="{b:g}"}} {cumulative}')
                    cumulative += counts[-1]
                    lines.append(f'{name}_bucket{{le="+Inf"}} {cumulative}')
                    lines.append(f"{name}_sum {self._hist_sum.get(name, 0.0):g}")
                    lines.append(f"{name}_count {self._hist_total.get(name, 0)}")
            # Dynamic HDR histogram families — exposed as ordinary
            # Prometheus histograms: populated buckets become cumulative
            # counts at their upper bound (seconds), closed by +Inf, with
            # _sum/_count alongside.  Only buckets that received a sample
            # are listed; bucket highs are strictly increasing in the
            # index, so the le series is ascending by construction.
            for name in sorted(self._hdr):
                help_text = HDR_SPECS.get(name) or _dynamic_hdr_help(name)
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} histogram")
                fam = self._hdr[name]
                cumulative = 0
                for idx in sorted(fam):
                    cumulative += fam[idx]
                    le = hdr_bucket_high_us(idx) / 1e6
                    lines.append(
                        f'{name}_bucket{{le="{le:.6f}"}} {cumulative}'
                    )
                lines.append(f'{name}_bucket{{le="+Inf"}} {cumulative}')
                lines.append(
                    f"{name}_sum {self._hdr_sum_us.get(name, 0) / 1e6:.6f}"
                )
                lines.append(f"{name}_count {self._hdr_count.get(name, 0)}")
            # Dynamic counter families — the member sets are only known at
            # runtime (buckets actually dispatched, filters that dropped).
            dyn = sorted(
                (k for k in self._values if k.startswith(OCCUPANCY_BUCKET_PREFIX)),
                key=lambda k: int(k[len(OCCUPANCY_BUCKET_PREFIX):]),
            )
            for name in dyn:
                lines.append(
                    f"# HELP {name} Device dispatches at bucket length "
                    f"{name[len(OCCUPANCY_BUCKET_PREFIX):]}"
                )
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {self._values[name]:g}")
            for name in sorted(
                k for k in self._values if k.startswith(FILTER_DROP_PREFIX)
            ):
                lines.append(
                    f"# HELP {name} Documents dropped by filter "
                    f"{name[len(FILTER_DROP_PREFIX):]}"
                )
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {self._values[name]:g}")
            for name in sorted(
                k for k in self._values if k.startswith(DEVICE_BPS_PREFIX)
            ):
                lines.append(
                    f"# HELP {name} Achieved device bytes/s (modeled bytes "
                    f"accessed / last dispatch wait) at bucket_phase "
                    f"{name[len(DEVICE_BPS_PREFIX):]}"
                )
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {self._values[name]:g}")
            for name in sorted(
                k for k in self._values if k.startswith(EVENT_KIND_PREFIX)
            ):
                lines.append(
                    f"# HELP {name} Operational journal events of kind "
                    f"{name[len(EVENT_KIND_PREFIX):]}"
                )
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {self._values[name]:g}")
            # SLO dynamic families: events/bad-events counters, then the
            # target / burn-rate / budget-remaining gauges.  slo_events_
            # is a prefix of slo_events_total_ members only, so the two
            # counter loops can't overlap the gauge loop.
            for prefix, help_fmt in (
                (SLO_EVENTS_PREFIX, "SLO events evaluated for objective "),
                (SLO_BAD_EVENTS_PREFIX, "SLO budget-consuming events for objective "),
            ):
                for name in sorted(
                    k for k in self._values if k.startswith(prefix)
                ):
                    lines.append(f"# HELP {name} {help_fmt}{name[len(prefix):]}")
                    lines.append(f"# TYPE {name} counter")
                    lines.append(f"{name} {self._values[name]:g}")
            for prefix, help_fmt in (
                ("slo_target_", "Declared SLO target for objective "),
                ("slo_burn_rate_", "Fast-window error-budget burn rate for objective "),
                (
                    "slo_budget_remaining_",
                    "Fraction of the error budget left for objective ",
                ),
            ):
                for name in sorted(
                    k for k in self._values if k.startswith(prefix)
                ):
                    lines.append(f"# HELP {name} {help_fmt}{name[len(prefix):]}")
                    lines.append(f"# TYPE {name} gauge")
                    lines.append(f"{name} {self._values[name]:g}")
            return "\n".join(lines) + "\n"


#: Process-wide registry (the reference's lazy statics, rs:16-143).
METRICS = Metrics()


class _Handler(BaseHTTPRequestHandler):
    def _is_metrics_path(self) -> bool:
        # Strict scrapers send query strings (GET /metrics?timeout=5) —
        # match on the path component only.
        return self.path.split("?", 1)[0] == "/metrics"

    def _respond(self, send_body: bool) -> None:
        path = self.path.split("?", 1)[0]
        status = 200
        if self._is_metrics_path():
            body = METRICS.render().encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        elif path == "/telemetry":
            # Live rollup snapshot (JSON) next to the exposition.  Imported
            # lazily: telemetry.py imports this module at load time, the
            # reverse edge only exists inside a request.
            from .telemetry import TELEMETRY

            body = (
                json.dumps(TELEMETRY.snapshot(), sort_keys=True) + "\n"
            ).encode("utf-8")
            ctype = "application/json"
        elif path == "/healthz":
            # Live/ready verdict (200 ready, 503 starting/degraded) with a
            # component breakdown.  Lazy import for the same reason.
            from .slo import health_snapshot

            status, health = health_snapshot()
            body = (json.dumps(health, sort_keys=True) + "\n").encode("utf-8")
            ctype = "application/json"
        elif path == "/slo":
            # Live SLO engine state (objectives, burn rates, alerts).
            from .slo import SLO

            body = (
                json.dumps(SLO.snapshot(), sort_keys=True) + "\n"
            ).encode("utf-8")
            ctype = "application/json"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if send_body:
            self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        self._respond(send_body=True)

    def do_HEAD(self):  # noqa: N802 — probes (curl -I, LB health checks)
        self._respond(send_body=False)

    def log_message(self, fmt, *args):  # silence request logging
        logger.debug("metrics: " + fmt, *args)


def setup_prometheus_metrics(port: Optional[int]) -> Optional[ThreadingHTTPServer]:
    """Serve ``/metrics`` on the given port in a daemon thread
    (prometheus_metrics.rs:148-201).  Returns the server, or None if no port
    was requested or the bind failed (bind failure only logged, rs:186-195).
    """
    if port is None:
        return None
    try:
        server = ThreadingHTTPServer(("0.0.0.0", port), _Handler)
    except OSError as e:
        logger.error("Failed to bind metrics server on port %s: %s", port, e)
        return None
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    logger.info("Metrics server listening on port %s", port)
    return server


if __name__ == "__main__":  # README catalog generator
    print(metrics_catalog_markdown())
