"""Dependency-free span tracer emitting Chrome trace-event JSON.

The overlapped pipeline (utils/overlap.py + ops/pipeline.py process_chunk)
runs read/pack/dispatch/device-wait/post/write across four thread lanes,
and the multihost path adds negotiated lockstep rounds on top — the flat
Prometheus counters in utils/metrics.py say *how much* time each stage
took, but not *where the bubbles are*.  This module records per-batch
spans and resilience instant events into the Chrome trace-event format
(the JSON array flavor), which loads directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing:

* ``"X"`` complete events — one per span, with microsecond ``ts``/``dur``;
* ``"i"`` instant events — resilience transitions (retry, breaker
  trip/probe/recovery, negotiated verdicts, joint degradation);
* ``"C"`` counter events — queue depths, so Perfetto draws them as tracks;
* ``"M"`` metadata events — process/thread names, so each overlap thread
  (textblast-prefetch / textblast-pack-N / textblast-writer / MainThread)
  gets its own labeled lane.

Design constraints, in order:

1. **Near-zero cost when off.**  Tracing is opt-in (``--trace out.json``).
   Disabled, ``TRACER.span()`` is one attribute check returning a shared
   no-op context manager — no allocation, no lock.  All span sites are
   per-batch or per-round (never per-document), so even enabled the event
   rate is tiny next to the work being traced.
2. **Bounded memory.**  Events accumulate in a ring buffer; with a file
   configured the buffer spills to disk whenever it fills, so a
   multi-hour run holds at most ``ring`` events in memory.  Without a
   file (in-memory mode, used by tests) the ring simply drops the oldest
   events once full.
3. **Thread safety.**  One lock guards the ring; spans capture their
   timestamps outside it, so the critical section is a list append.
4. **Crash tolerance.**  The file is spilled incrementally as a JSON
   array; Perfetto's JSON importer tolerates a truncated (unterminated)
   array, so a killed run still yields a loadable trace.  ``close()``
   writes the terminator for well-formed JSON.

An opt-in bridge to ``jax.profiler.trace`` (``device_profile``) captures
the XLA device-side profile alongside the host-side spans — the host
trace shows *that* the device wait dominated; the profiler shows *why*.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["Tracer", "TRACER", "device_profile"]


class _NullSpan:
    """Shared no-op context manager returned by every call while tracing
    is disabled — the entire off-cost of a span site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add_args(self, args) -> None:
        """No-op counterpart of :meth:`_Span.add_args`."""


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def add_args(self, args: Dict[str, Any]) -> None:
        """Merge args discovered mid-span (e.g. the profiler's achieved
        bytes/s, known only once the device wait resolves) into the event
        emitted at exit.  Copies — the entry dict may be caller-shared."""
        if self._args is None:
            self._args = dict(args)
        else:
            self._args = {**self._args, **args}

    def __exit__(self, *exc):
        self._tracer._complete(self._name, self._t0, time.perf_counter(), self._args)
        return False


class Tracer:
    """Thread-safe Chrome trace-event recorder (see module docstring)."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self._ring_cap = 65536
        self._dropped = 0
        self._warned_drop = False
        self._path: Optional[str] = None
        self._fh = None
        self._wrote_any = False
        self._t0 = 0.0
        self._offset_us = 0  # cross-host clock alignment (align())
        self._pid = 0
        self._process_name = "textblast"
        self._tids: Dict[int, int] = {}  # thread ident -> compact tid

    # --- lifecycle ----------------------------------------------------------

    def configure(
        self,
        path: Optional[str] = None,
        *,
        ring: int = 65536,
        process_name: str = "textblast",
        pid: int = 0,
    ) -> None:
        """Enable tracing.  ``path=None`` keeps events in the bounded ring
        (test mode); otherwise the ring spills to ``path`` incrementally.
        ``pid`` labels the Perfetto process lane — multihost runs pass the
        process index so per-host traces can be concatenated."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._ring = []
            self._ring_cap = max(16, int(ring))
            self._dropped = 0
            self._warned_drop = False
            self._tids = {}
            self._path = path
            self._fh = None
            self._wrote_any = False
            self._t0 = time.perf_counter()
            self._offset_us = 0
            self._pid = int(pid)
            self._process_name = process_name
            if path is not None:
                parent = os.path.dirname(os.path.abspath(path))
                os.makedirs(parent, exist_ok=True)
                self._fh = open(path, "w", encoding="utf-8")
                self._fh.write("[\n")
            self.enabled = True
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "args": {"name": process_name},
            }
        )

    def close(self) -> None:
        """Flush the ring, terminate the JSON array, and disable tracing."""
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
            if self._fh is not None:
                self._spill_locked()
                if self._fh is not None:  # spill failure closes the file
                    try:
                        self._fh.write("\n]\n")
                        self._fh.close()
                    except OSError as e:
                        logger.warning(
                            "Trace close on %s failed: %s", self._path, e
                        )
                    self._fh = None
            if self._dropped:
                logger.warning(
                    "Trace ring overflowed in-memory mode: %d events dropped",
                    self._dropped,
                )

    def drain(self) -> List[Dict[str, Any]]:
        """Return and clear the in-memory events (test hook)."""
        with self._lock:
            out, self._ring = self._ring, []
            return out

    # --- cross-host clock alignment -----------------------------------------

    def wall_at_origin_us(self) -> int:
        """This trace's time origin (``ts`` 0) as wall-clock microseconds.

        ``ts`` values are ``perf_counter`` deltas from ``configure()``; to
        put several hosts' traces on one Perfetto timeline, each host maps
        its origin onto the shared wall clock and shifts by the difference
        (:meth:`align`)."""
        return int((time.time() - (time.perf_counter() - self._t0)) * 1e6)

    def align(self, offset_us: int, args: Optional[Dict[str, Any]] = None) -> None:
        """Shift every *subsequent* event's ``ts`` by ``offset_us`` and
        record a ``trace_clock_offset`` metadata event documenting it.

        Multihost runs call this once after the startup clock handshake
        (``parallel/multihost.py _align_trace_clocks``): host ``i``'s offset
        is its origin's wall-clock distance from the earliest host's origin,
        so concatenated per-host traces share one timeline instead of each
        starting at ``ts`` 0.  Events emitted before the handshake (tracer
        setup, config loading) keep their unshifted, near-zero timestamps.
        """
        if not self.enabled:
            return
        self._offset_us = int(offset_us)
        self._emit(
            {
                "name": "trace_clock_offset",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "args": {"offset_us": int(offset_us), **(args or {})},
            }
        )

    # --- recording ----------------------------------------------------------

    def span(self, name: str, args: Optional[Dict[str, Any]] = None):
        """Context manager recording one ``"X"`` complete event on the
        current thread's lane."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, args: Optional[Dict[str, Any]] = None) -> None:
        """Record a zero-duration ``"i"`` event (resilience transitions)."""
        if not self.enabled:
            return
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "t",
                "ts": self._now_us(),
                "pid": self._pid,
                "tid": self._tid(),
                **({"args": args} if args else {}),
            }
        )

    def counter(self, name: str, value: float) -> None:
        """Record a ``"C"`` counter sample (Perfetto draws a track)."""
        if not self.enabled:
            return
        self._emit(
            {
                "name": name,
                "ph": "C",
                "ts": self._now_us(),
                "pid": self._pid,
                "tid": 0,
                "args": {"value": value},
            }
        )

    def now_us(self) -> int:
        """Current time in microseconds on this trace's clock.

        With tracing configured, the value is a ``perf_counter`` delta from
        ``configure()`` shifted by the multihost :meth:`align` offset — the
        same clock every span and instant is stamped with, so consumers
        (the event journal) interleave correctly with the trace.  With
        tracing off, ``_t0`` is 0 and the value degrades to raw
        ``perf_counter`` microseconds: still monotone within the process,
        just not cross-host aligned."""
        return self._now_us()

    # --- internals ----------------------------------------------------------

    def _now_us(self) -> int:
        return int((time.perf_counter() - self._t0) * 1e6) + self._offset_us

    def _tid(self) -> int:
        """Compact per-thread lane id; first sight emits the thread_name
        metadata event so Perfetto labels the lane."""
        t = threading.current_thread()
        tid = self._tids.get(t.ident)
        if tid is None:
            with self._lock:
                tid = self._tids.get(t.ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[t.ident] = tid
                    self._append_locked(
                        {
                            "name": "thread_name",
                            "ph": "M",
                            "pid": self._pid,
                            "tid": tid,
                            "args": {"name": t.name},
                        }
                    )
        return tid

    def _complete(
        self, name: str, t0: float, t1: float, args: Optional[Dict[str, Any]]
    ) -> None:
        if not self.enabled:  # closed while the span was open
            return
        self._emit(
            {
                "name": name,
                "ph": "X",
                "ts": int((t0 - self._t0) * 1e6) + self._offset_us,
                "dur": max(0, int((t1 - t0) * 1e6)),
                "pid": self._pid,
                "tid": self._tid(),
                **({"args": args} if args else {}),
            }
        )

    def _emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._append_locked(event)

    def _append_locked(self, event: Dict[str, Any]) -> None:
        self._ring.append(event)
        if len(self._ring) >= self._ring_cap:
            if self._fh is not None:
                self._spill_locked()
            else:
                # In-memory mode: drop the oldest half, keep counting.
                drop = len(self._ring) // 2
                self._count_dropped_locked(drop)
                del self._ring[:drop]

    def _count_dropped_locked(self, n: int) -> None:
        """Account ``n`` dropped events: local counter, the
        ``trace_events_dropped_total`` metric, and a one-line stderr
        warning on the first drop (drops used to be silent — an unwritable
        spill path lost the whole ring with no sign anywhere)."""
        self._dropped += n
        first = not self._warned_drop
        self._warned_drop = True
        # Lazy import: metrics.py and trace.py are both leaf modules; the
        # one edge lives inside this rarely-hit path to keep it that way.
        from .metrics import METRICS

        METRICS.inc("trace_events_dropped_total", n)
        if first:
            print(
                f"textblast: trace events dropped ({n} so far) — ring "
                "overflow or unwritable spill file; trace will be "
                "incomplete",
                file=sys.stderr,
            )

    def _spill_locked(self) -> None:
        if not self._ring:
            return
        chunks = []
        for ev in self._ring:
            if self._wrote_any:
                chunks.append(",\n")
            self._wrote_any = True
            chunks.append(json.dumps(ev, separators=(",", ":")))
        try:
            self._fh.write("".join(chunks))
            self._fh.flush()
        except OSError as e:
            # Disk full / revoked path: count every event we just lost,
            # warn once, and stop spilling (the ring keeps the newest
            # events in memory so close() still has something to report).
            self._count_dropped_locked(len(self._ring))
            logger.warning("Trace spill to %s failed: %s", self._path, e)
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        self._ring = []


#: Process-wide tracer.  Import this, never construct your own — span
#: sites across the codebase all talk to the same instance.
TRACER = Tracer()


@contextmanager
def device_profile(log_dir: Optional[str]):
    """Opt-in bridge to ``jax.profiler.trace``: captures the XLA device
    profile (TensorBoard/Perfetto-loadable) into ``log_dir`` for the
    duration of the block.  ``log_dir=None`` is a no-op, and a backend
    without profiler support degrades to a warning, not a failure."""
    if not log_dir:
        yield
        return
    ctx = None
    try:
        import jax

        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.warning("jax.profiler.trace unavailable (%s); continuing", e)
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception as e:  # pragma: no cover
                logger.warning("jax.profiler.trace teardown failed: %s", e)
