"""Per-document tail-latency telemetry: sampled lineage, HDR histograms,
windowed live rollups.

Three subsystems, all off (and allocation-free on the hot path) until
``TELEMETRY.configure(sample_rate=N)`` with N > 0:

Sampled lineage
    A deterministic doc-id sampler — ``crc32(id) % rate == 0`` — picks the
    SAME documents on every host regardless of stripe assignment, so a
    multi-host gang's samples concatenate into one coherent population.
    Sampled documents are stamped with a first-seen perf-counter timestamp
    at each stage seam (read → pack → dispatch → device_wait → assemble →
    write); the Parquet write seam closes the lineage, turning consecutive
    stamps into per-stage latencies fed to the ``doc_latency_*_seconds``
    HDR families (utils/metrics.py) and a ``doc_flow`` trace instant.

HDR histograms
    :class:`LogLinearHistogram` wraps the pure-int log-linear bucket scheme
    (metrics.hdr_*): bounded relative error, exact bucket-wise merge.  The
    registry's families travel inside metric snapshots as flat ``::h``
    keys, so the multi-host run-report sum-merge produces exact gang-wide
    quantiles with no histogram-specific exchange.

Live rollups
    A daemon ticker samples throughput counters and queue-depth gauges into
    a fixed-size ring of time windows (docs/s, chars/s, waste ratio, queue
    depths, in-flight depth, exchange-post latency).  A drift detector
    compares each window's padding-waste ratio against the calibration-time
    baseline (:func:`expected_waste`) and fires a ``geometry_drift`` trace
    instant + gauge when it deviates — the hook the adaptive-geometry
    roadmap item consumes.  ``snapshot()`` serves the ring as JSON on the
    ``/telemetry`` endpoint next to ``/metrics``.

Hot-path discipline mirrors the tracer's ``_NullSpan``: every seam guards
with ``if TELEMETRY.enabled:`` — one attribute read, no call, no
allocation — so sampling off costs nothing measurable.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence

from .metrics import (
    DOC_LATENCY_STAGES,
    HDR_RELATIVE_ERROR,
    METRICS,
    hdr_bucket_index,
    hdr_bucket_high_us,
    hdr_quantile_us,
    latency_report,
)
from .trace import TRACER

__all__ = [
    "TELEMETRY",
    "Telemetry",
    "LogLinearHistogram",
    "doc_sampled",
    "expected_waste",
    "format_latency_summary",
    "STAGES",
]

#: Lineage stage keys in pipeline order (DOC_LATENCY_STAGES minus the
#: derived ``e2e`` rollup).
STAGES = tuple(s for s in DOC_LATENCY_STAGES if s != "e2e")

_STAGE_ORDER = {s: i for i, s in enumerate(STAGES)}

#: Open-lineage table cap: a doc that never reaches the write seam (filtered
#: upstream of sampling visibility, crashed batch, abandoned run) must not
#: leak memory forever, so the oldest lineage is evicted FIFO past this.
_LINEAGE_CAP = 65536


def doc_sampled(doc_id: str, rate: int) -> bool:
    """Deterministic 1-in-``rate`` sampler on the document id.

    crc32, not ``hash()``: Python string hashing is salted per process, so
    only a stable digest gives every host (and every rerun) the same sample
    set — the property that makes merged multi-host quantile populations
    coherent and repeated runs byte-comparable.
    """
    if rate <= 0:
        return False
    if rate == 1:
        return True
    return zlib.crc32(doc_id.encode("utf-8")) % rate == 0


class LogLinearHistogram:
    """Standalone log-linear histogram over the shared bucket scheme.

    The registry (``METRICS.observe_hdr``) is the production store; this
    class exists for composition outside it — merge experiments, tests,
    bench aggregation — with the same guarantees: bounded relative error
    (``HDR_RELATIVE_ERROR``) and exact, commutative, associative merge.
    """

    __slots__ = ("buckets", "sum_us", "count")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.sum_us = 0
        self.count = 0

    def record(self, us: int) -> None:
        v = max(0, int(us))
        idx = hdr_bucket_index(v)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.sum_us += v
        self.count += 1

    def record_seconds(self, seconds: float) -> None:
        self.record(int(seconds * 1e6))

    def merge(self, other: "LogLinearHistogram") -> "LogLinearHistogram":
        """New histogram = self + other (bucket-wise; inputs untouched)."""
        out = LogLinearHistogram()
        out.buckets = dict(self.buckets)
        for idx, c in other.buckets.items():
            out.buckets[idx] = out.buckets.get(idx, 0) + c
        out.sum_us = self.sum_us + other.sum_us
        out.count = self.count + other.count
        return out

    def quantile_us(self, q: float) -> int:
        return hdr_quantile_us(self.buckets, self.count, q)

    def quantile_s(self, q: float) -> float:
        return round(self.quantile_us(q) / 1e6, 6)

    def to_dict(self) -> Dict[str, object]:
        return {
            "buckets": {str(k): v for k, v in sorted(self.buckets.items())},
            "sum_us": self.sum_us,
            "count": self.count,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "LogLinearHistogram":
        h = cls()
        h.buckets = {int(k): int(v) for k, v in dict(d.get("buckets", {})).items()}
        h.sum_us = int(d.get("sum_us", 0))
        h.count = int(d.get("count", 0))
        return h


def expected_waste(lengths: Sequence[int], geometry) -> float:
    """Padding-waste ratio the geometry implies for a length sample.

    Each document lands in the smallest bucket that holds it (overflow
    clamps to the largest — those rows reroute to the host oracle but are
    counted at the bucket cap here, matching ``record_occupancy``'s lane
    accounting).  This is the calibration-time baseline the drift detector
    compares live windows against: same lengths + same geometry -> same
    baseline, deterministically.
    """
    buckets = tuple(geometry.buckets)
    lanes = 0
    real = 0
    for n in lengths:
        n = int(n)
        for b in buckets:
            if n <= b:
                lanes += b
                real += n
                break
        else:
            lanes += buckets[-1]
            real += buckets[-1]
    if lanes <= 0:
        return 0.0
    return round(1.0 - real / lanes, 6)


#: Monotone counters sampled per rollup window (delta over the window).
_WINDOW_COUNTERS = (
    "producer_results_received_total",
    "writer_chars_total",
    "occupancy_padded_lanes_total",
    "occupancy_real_codepoints_total",
    "multihost_exchange_posts_total",
    "multihost_exchange_post_seconds_total",
)

#: Gauges read point-in-time per window.
_WINDOW_GAUGES = (
    "queue_depth_read",
    "queue_depth_pack",
    "queue_depth_write",
    "inflight_batches",
    "multihost_negotiated_depth",
)


class Telemetry:
    """Process-wide telemetry hub (``TELEMETRY``)."""

    def __init__(self) -> None:
        #: THE hot-path guard: call sites check this one attribute and do
        #: nothing else when it is False.
        self.enabled = False
        self._lock = threading.Lock()
        self._rate = 0
        self._lineage: Dict[str, Dict[str, int]] = {}
        self._windows: deque = deque(maxlen=24)
        self._window_s = 5.0
        self._drift_threshold = 0.25
        self._baseline_waste: Optional[float] = None
        self._drift_high = False
        self._last_counters: Dict[str, float] = {}
        self._t0 = 0.0
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def configure(
        self,
        sample_rate: int = 0,
        *,
        window_s: float = 5.0,
        window_count: int = 24,
        drift_threshold: float = 0.25,
        start_ticker: bool = True,
    ) -> None:
        """Enable telemetry with a 1-in-``sample_rate`` doc sampler.

        ``sample_rate <= 0`` keeps (or returns) everything off.  The rollup
        ticker is a daemon thread; ``start_ticker=False`` lets tests drive
        windows synchronously via :meth:`roll_window`.
        """
        self.close()
        if sample_rate <= 0:
            return
        with self._lock:
            self._rate = int(sample_rate)
            self._window_s = float(window_s)
            self._drift_threshold = float(drift_threshold)
            self._windows = deque(maxlen=max(1, int(window_count)))
            self._lineage = {}
            self._baseline_waste = None
            self._drift_high = False
            self._last_counters = {
                name: METRICS.get(name) for name in _WINDOW_COUNTERS
            }
            self._t0 = time.perf_counter()
            self._stop = threading.Event()
        self.enabled = True
        if start_ticker:
            self._ticker = threading.Thread(
                target=self._tick, name="textblast-telemetry", daemon=True
            )
            self._ticker.start()

    def close(self) -> None:
        """Disable telemetry and stop the rollup ticker (idempotent)."""
        self.enabled = False
        self._stop.set()
        t, self._ticker = self._ticker, None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        with self._lock:
            self._rate = 0
            self._lineage = {}

    # -- sampled lineage -----------------------------------------------------

    def mark(self, stage: str, doc_ids: Iterable[str]) -> None:
        """Stamp sampled docs with a first-seen timestamp at ``stage``.

        ``setdefault`` semantics: re-marking (retry re-dispatch, the ladder
        re-fetching a split batch) never moves an existing stamp, so stage
        latencies measure first entry to next stage's first entry.
        """
        if not self.enabled:
            return
        now_us = int(time.perf_counter() * 1e6)
        rate = self._rate
        with self._lock:
            lineage = self._lineage
            for did in doc_ids:
                if not doc_sampled(did, rate):
                    continue
                rec = lineage.get(did)
                if rec is None:
                    if len(lineage) >= _LINEAGE_CAP:
                        lineage.pop(next(iter(lineage)))
                        METRICS.inc("doc_lineage_evicted_total")
                    rec = lineage[did] = {}
                    METRICS.inc("doc_sampled_total")
                rec.setdefault(stage, now_us)

    def complete(self, documents: Iterable) -> None:
        """Close lineages at the Parquet write seam.

        For each sampled document: the delta between consecutive present
        stamps is that stage's latency (a stage the doc skipped — e.g. no
        device dispatch on the host-oracle path — contributes nothing),
        the final segment ends now, and e2e spans first stamp to now.
        """
        if not self.enabled:
            return
        now_us = int(time.perf_counter() * 1e6)
        flows: List = []
        with self._lock:
            for doc in documents:
                did = getattr(doc, "id", None) or getattr(
                    getattr(doc, "document", None), "id", ""
                )
                rec = self._lineage.pop(did, None)
                if rec is None:
                    continue
                stamps = sorted(
                    ((s, t) for s, t in rec.items() if s in _STAGE_ORDER),
                    key=lambda st: (_STAGE_ORDER[st[0]], st[1]),
                )
                if not stamps:
                    continue
                flows.append((did, stamps))
        for did, stamps in flows:
            deltas: Dict[str, int] = {}
            for i, (stage, t) in enumerate(stamps):
                end = stamps[i + 1][1] if i + 1 < len(stamps) else now_us
                d = max(0, end - t)
                deltas[stage] = d
                METRICS.observe_hdr(f"doc_latency_{stage}_seconds", d)
            e2e = max(0, now_us - stamps[0][1])
            deltas["e2e"] = e2e
            METRICS.observe_hdr("doc_latency_e2e_seconds", e2e)
            TRACER.instant("doc_flow", {"id": did, "us": deltas})

    # -- geometry drift ------------------------------------------------------

    def set_geometry_baseline(self, waste_ratio: float) -> None:
        """Pin the calibration-time waste baseline the detector compares
        live windows against (otherwise the first non-empty window is
        adopted)."""
        with self._lock:
            self._baseline_waste = float(waste_ratio)

    # -- windowed rollups ----------------------------------------------------

    def _tick(self) -> None:
        while not self._stop.wait(self._window_s):
            try:
                self.roll_window()
            except Exception:  # noqa: BLE001 — telemetry must never kill a run
                pass

    def roll_window(self) -> Dict[str, object]:
        """Close one rollup window: counter deltas -> rates, gauge reads,
        waste ratio, drift check.  Called by the ticker (or directly by
        tests / bench for deterministic windows)."""
        now = {name: METRICS.get(name) for name in _WINDOW_COUNTERS}
        with self._lock:
            last = self._last_counters
            self._last_counters = dict(now)
            dt = self._window_s
            d = {k: max(0.0, now[k] - last.get(k, 0.0)) for k in now}
            lanes = d["occupancy_padded_lanes_total"]
            real = d["occupancy_real_codepoints_total"]
            waste = round(1.0 - real / lanes, 6) if lanes > 0 else None
            posts = d["multihost_exchange_posts_total"]
            post_s = d["multihost_exchange_post_seconds_total"]
            window: Dict[str, object] = {
                "t_s": round(time.perf_counter() - self._t0, 3),
                "window_s": dt,
                "docs_per_s": round(d["producer_results_received_total"] / dt, 3),
                "chars_per_s": round(d["writer_chars_total"] / dt, 1),
                "waste_ratio": waste,
                "exchange_posts_per_s": round(posts / dt, 3),
                "exchange_post_mean_s": (
                    round(post_s / posts, 6) if posts > 0 else None
                ),
            }
            for name in _WINDOW_GAUGES:
                window[name] = int(METRICS.get(name))
            drift = None
            if waste is not None:
                if self._baseline_waste is None:
                    self._baseline_waste = waste
                deviation = round(abs(waste - self._baseline_waste), 6)
                drift = deviation
                METRICS.set("geometry_drift", deviation)
                if deviation > self._drift_threshold:
                    if not self._drift_high:  # edge-trigger the instant
                        self._drift_high = True
                        from .events import EVENTS

                        if EVENTS.enabled:
                            EVENTS.emit(
                                "geometry_drift", ratio=deviation,
                                live_waste=waste,
                                baseline_waste=self._baseline_waste,
                            )
                        TRACER.instant(
                            "geometry_drift",
                            {
                                "live_waste": waste,
                                "baseline_waste": self._baseline_waste,
                                "deviation": deviation,
                            },
                        )
                else:
                    self._drift_high = False
            window["geometry_drift"] = drift
            self._windows.append(window)
            return window

    # -- snapshot / summary --------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """JSON-serializable live view: the window ring, drift state, and
        the current latency quantiles — the ``/telemetry`` endpoint body."""
        with self._lock:
            windows = list(self._windows)
            baseline = self._baseline_waste
            rate = self._rate
            window_s = self._window_s
            threshold = self._drift_threshold
            open_lineages = len(self._lineage)
        return {
            "enabled": self.enabled,
            "sample_rate": rate,
            "window_s": window_s,
            "drift_threshold": threshold,
            "baseline_waste_ratio": baseline,
            "geometry_drift": METRICS.get("geometry_drift"),
            "open_lineages": open_lineages,
            "sampled_docs": int(METRICS.get("doc_sampled_total")),
            "windows": windows,
            "latency": latency_report(),
        }


def format_latency_summary(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> str:
    """Human-readable tail-latency block for the CLI end-of-run summary."""
    rep = latency_report(baseline, values)
    stages = rep["stages"]
    if not stages:
        return "Per-document tail latency: no sampled documents completed."
    lines = [
        "Per-document tail latency (sampled, relative error <= "
        f"{rep['relative_error']:.2%}):"
    ]
    order = list(DOC_LATENCY_STAGES) + ["exchange_post"]
    for stage in order:
        s = stages.get(stage)
        if not s:
            continue
        lines.append(
            f"  {stage:<12} n={s['count']:>7}  p50={s['p50_s']:>9.6f}s  "
            f"p95={s['p95_s']:>9.6f}s  p99={s['p99_s']:>9.6f}s"
        )
    return "\n".join(lines)


#: Process-wide hub, disabled until configured.
TELEMETRY = Telemetry()
