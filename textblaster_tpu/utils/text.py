"""Text primitives: segmentation + duplicate detection.

Re-implements the reference's ``src/utils/text.rs`` semantics:

* ``split_into_words`` (text.rs:103-181): ICU4X UAX#29 word segmentation with a
  punctuation-only-token rejection on top.  Here the segmentation is a
  UAX#29-lite rule set computed *vectorized over codepoint arrays* — the same
  formulation the TPU kernels use — rather than a port of ICU: a word is a
  maximal run of alphanumerics/underscore joined by UAX#29 mid-characters
  (``:``, ``·``, ``'``, ``’``, ``.`` between letters; ``,``, ``;``, ``.``,
  ``'``, ``’`` between digits), and any character that is neither part of such
  a run, whitespace, nor in the reference PUNCTUATION set counts as a
  standalone symbol word (because ICU yields it as its own segment and the
  reference's rejection loop keeps it — text.rs:139-157).
  Known divergence from ICU: CJK runs are kept whole instead of
  dictionary-segmented.

* ``split_into_sentences`` (text.rs:59-101): UAX#29-lite sentence rules:
  mandatory break after paragraph separators; break after STerm (``!?…。！？``)
  + closes + spaces; break after ATerm (``.``) + closes + spaces unless the
  next character is lowercase or the ``.`` directly abuts an alphanumeric.
  Slices are trimmed and empties dropped, exactly like the reference.

* ``get_n_grams`` / ``find_duplicates`` / ``find_top_duplicate`` /
  ``find_all_duplicate`` (text.rs:184-259): note these sum **UTF-8 byte**
  lengths, not char counts — a reference quirk that parity must reproduce
  (SURVEY.md §7 "bytes-vs-chars quirks").
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .chartables import (
    ALNUM,
    ALPHA,
    DIGIT,
    EXTEND,
    PUNCT,
    WS,
    classify,
    codepoints,
)
from .chartables import PUNCTUATION  # re-export for filters  # noqa: F401


def _attach_extend(word: np.ndarray, cls: np.ndarray) -> np.ndarray:
    """UAX#29 WB4 (lite): Extend/Format chars inherit the wordness of the
    nearest preceding non-Extend char, so decomposed accents stay inside
    their word instead of shattering it (``'cafe\\u0301'`` is one word).
    Leading Extend runs keep their own (non-word) class."""
    ext = (cls & EXTEND) != 0
    if not ext.any():
        return word
    n = word.shape[0]
    idx = np.arange(n)
    src = np.maximum.accumulate(np.where(~ext, idx, -1))
    ok = ext & (src >= 0)
    out = word.copy()
    out[ok] = word[src[ok]]
    return out

try:  # native C++ fast path (lazy-built; None => pure numpy)
    from ..native import word_spans_native as _native_spans
except Exception:  # pragma: no cover - import robustness
    _native_spans = None

__all__ = [
    "DANISH_STOP_WORDS",
    "PUNCTUATION",
    "split_into_words",
    "word_spans",
    "split_into_sentences",
    "get_n_grams",
    "find_duplicates",
    "find_top_duplicate",
    "find_all_duplicate",
]

# Danish stop words (text.rs:9-25).
DANISH_STOP_WORDS = (
    "ad", "af", "aldrig", "alle", "alt", "anden", "andet", "andre", "at", "bare", "begge",
    "blev", "blive", "bliver", "da", "de", "dem", "den", "denne", "der", "deres", "det",
    "dette", "dig", "din", "dine", "disse", "dit", "dog", "du", "efter", "ej", "eller", "en",
    "end", "ene", "eneste", "enhver", "er", "et", "far", "fem", "fik", "fire", "flere",
    "fleste", "for", "fordi", "forrige", "fra", "få", "får", "før", "god", "godt", "ham",
    "han", "hans", "har", "havde", "have", "hej", "helt", "hende", "hendes", "her", "hos",
    "hun", "hvad", "hvem", "hver", "hvilken", "hvis", "hvor", "hvordan", "hvorfor",
    "hvornår", "i", "ikke", "ind", "ingen", "intet", "ja", "jeg", "jer", "jeres", "jo",
    "kan", "kom", "komme", "kommer", "kun", "kunne", "lad", "lav", "lidt", "lige", "lille",
    "man", "mand", "mange", "med", "meget", "men", "mens", "mere", "mig", "min", "mine",
    "mit", "mod", "må", "ned", "nej", "ni", "nogen", "noget", "nogle", "nu", "ny", "nyt",
    "når", "nær", "næste", "næsten", "og", "også", "okay", "om", "op", "os", "otte", "over",
    "på", "se", "seks", "selv", "ser", "ses", "sig", "sige", "sin", "sine", "sit", "skal",
    "skulle", "som", "stor", "store", "syv", "så", "sådan", "tag", "tage", "thi", "ti",
    "til", "to", "tre", "ud", "under", "var", "ved", "vi", "vil", "ville", "vor", "vores",
    "være", "været",
)

# UAX#29 word-joining characters (lite): see module docstring.
_MID_LETTER = frozenset("\u003a\u00b7\u05f4\u2027\ufe13\ufe55\uff1a")
_MID_NUM = frozenset("\u002c\u003b\u037e\u0589\u066c\ufe10\ufe14\uff0c\uff1b")
_MID_NUM_LET = frozenset("\u002e\u0027\u2019\u2024\ufe52\uff07\uff0e")

_MID_ALL = _MID_LETTER | _MID_NUM | _MID_NUM_LET
_MID_CP = np.array(sorted(ord(c) for c in _MID_ALL), dtype=np.uint32)
_MID_LETTER_CP = np.array(sorted(ord(c) for c in (_MID_LETTER | _MID_NUM_LET)), dtype=np.uint32)
_MID_NUM_CP = np.array(sorted(ord(c) for c in (_MID_NUM | _MID_NUM_LET)), dtype=np.uint32)


def _word_mask(cps: np.ndarray, cls: np.ndarray) -> np.ndarray:
    """Boolean in-word mask over a codepoint array (vectorized UAX#29-lite)."""
    n = cps.shape[0]
    word = ((cls & ALNUM) != 0) | (cps == ord("_"))
    if n < 3:
        return _attach_extend(word, cls)
    # A mid character joins two word characters when flanked by the right class.
    mid = np.isin(cps, _MID_CP)
    if mid.any():
        prev_cls = cls[:-2]
        next_cls = cls[2:]
        inner = mid[1:-1]
        letter_ok = (
            np.isin(cps[1:-1], _MID_LETTER_CP)
            & ((prev_cls & ALPHA) != 0)
            & ((next_cls & ALPHA) != 0)
        )
        num_ok = (
            np.isin(cps[1:-1], _MID_NUM_CP)
            & ((prev_cls & DIGIT) != 0)
            & ((next_cls & DIGIT) != 0)
        )
        joined = inner & (letter_ok | num_ok)
        word[1:-1] |= joined
    return _attach_extend(word, cls)


def word_spans(text: str, cjk_dict: bool = True) -> List[Tuple[int, int]]:
    """(start, end) codepoint spans of the word segments of ``text``.

    The segments returned correspond 1:1 to ``split_into_words(text)``.
    Dispatches to the native C++ core when available (identical semantics,
    asserted by tests/test_native.py); this numpy path is the source of truth.

    ``cjk_dict`` (default on — the oracle semantics) re-segments runs in
    dictionary scripts: script-transition breaks plus greedy longest-match
    over a Han lexicon (:mod:`textblaster_tpu.utils.cjk`), approximating the
    reference's ICU dictionary segmentation (text.rs:107).  ``False`` keeps
    such runs whole — the device kernels' twin semantics (documents with
    dictionary scripts are routed to the host oracle by the device pipeline,
    so the kernels never see them).
    """
    if not text:
        return []
    spans = _word_spans_raw(text)
    if cjk_dict:
        from .cjk import DICT_SCRIPT_RE, segment_span

        if DICT_SCRIPT_RE.search(text) is not None:
            resplit: List[Tuple[int, int]] = []
            for s, e in spans:
                if DICT_SCRIPT_RE.search(text, s, e) is not None:
                    resplit.extend(segment_span(text, s, e))
                else:
                    resplit.append((s, e))
            spans = resplit
    return spans


def _word_spans_raw(text: str) -> List[Tuple[int, int]]:
    cps = codepoints(text)
    cls = classify(cps)
    if _native_spans is not None:
        spans = _native_spans(cps.astype(np.int32), cls)
        if spans is not None:
            return [(int(s), int(e)) for s, e in spans]
    in_word = _word_mask(cps, cls)
    n = cps.shape[0]

    padded = np.zeros(n + 2, dtype=bool)
    padded[1:-1] = in_word
    starts = np.flatnonzero(padded[1:-1] & ~padded[:-2])
    ends = np.flatnonzero(padded[1:-1] & ~padded[2:]) + 1

    # The reference rejects any segment whose every char is in PUNCTUATION
    # (text.rs:139-157) — e.g. a lone "_" or "１" run must not count as a word.
    non_punct = ((cls & PUNCT) == 0).astype(np.int64)
    cum = np.concatenate(([0], np.cumsum(non_punct)))
    keep = (cum[ends] - cum[starts]) > 0

    # Standalone symbol "words": not in a run, not whitespace, not reference
    # punctuation (ICU yields isolated symbols as their own segments and the
    # rejection loop keeps them).  ZWSP is WordBreak=Other AND not word-like
    # in ICU, so it produces no token at all; a trailing Extend/Format run
    # attaches to the symbol (WB4 — e.g. emoji tag sequences stay one token).
    ext = (cls & EXTEND) != 0
    sym = ~in_word & ((cls & WS) == 0) & ((cls & PUNCT) == 0)
    sym &= cps != 0x200B
    sym &= ~ext  # bare Extend after ws/punct: no token (its segment would be
    #              punctuation-only / rejected in ICU terms)
    sym_pos = np.flatnonzero(sym)

    # End of each symbol token: swallow the following Extend run.
    ext_pad = np.zeros(n + 1, dtype=bool)
    ext_pad[:-1] = ext
    nonext_idx = np.arange(n + 1)
    # next non-extend position at-or-after i (scan from the right)
    nxt = np.minimum.accumulate(
        np.where(~ext_pad, nonext_idx, n)[::-1]
    )[::-1]

    spans = [(int(s), int(e)) for s, e, k in zip(starts, ends, keep) if k]
    spans.extend((int(p), int(nxt[p + 1])) for p in sym_pos)
    spans.sort()
    return spans


def split_into_words(text: str, cjk_dict: bool = True) -> List[str]:
    """Word list with reference semantics (text.rs:103-181), including the
    dictionary-script approximation of ICU's CJK segmentation (see
    :func:`word_spans`)."""
    return [text[s:e] for s, e in word_spans(text, cjk_dict=cjk_dict)]


# Sentence segmentation -------------------------------------------------------

# Mandatory paragraph/line separators (UAX#29 SB4).
_PARA_SEP = "\n\r\x85\u2028\u2029"
# STerm-lite: unconditional sentence terminators.
_STERM = "!?\u2026\u3002\uff01\uff1f\uff61"
# Close-lite: characters that attach to the preceding sentence.
_CLOSE = ")]}\"'\u201d\u2019\u00bb\u300d\u300f\u3011\u3009\u300b\uff09"
# Sp-lite: spaces that may follow the terminator before the break.
_SP = " \t\u00a0\u2000\u2001\u2002\u2003\u2004\u2005\u2006\u2007\u2008\u2009\u200a\u202f\u205f\u3000"

_TERM = "." + _STERM


def _cc(chars: str) -> str:
    """Build a regex character class from a literal character set."""
    return "[" + "".join(re.escape(c) for c in chars) + "]"


_SENT_RE = re.compile(
    "(?:\r\n|" + _cc(_PARA_SEP) + ")"  # mandatory break, or:
    "|(?:" + _cc(_TERM) + "+"  # terminator run
    + _cc(_CLOSE) + "*"  # closers
    + _cc(_SP) + "*)"  # trailing spaces
)


def _sentence_boundaries(text: str) -> List[int]:
    """Byte-free (codepoint index) sentence boundaries, UAX#29-lite."""
    bounds: List[int] = []
    n = len(text)
    for m in _SENT_RE.finditer(text):
        end = m.end()
        if end >= n:
            break
        g = m.group(0)
        first = g[0]
        if first in _PARA_SEP:
            bounds.append(end)
            continue
        nxt = text[end]
        if "." in g and not any(c in _STERM for c in g):
            # ATerm-only runs: SB6/SB7 — no break when the period directly
            # abuts an alphanumeric ("3.5", "e.g.x"); SB8 — no break before
            # a lowercase continuation.
            if g[-1] == "." and (nxt.isalnum() or nxt == "_"):
                continue
            if nxt.islower():
                continue
        bounds.append(end)
    return bounds


def split_into_sentences(text: str) -> List[str]:
    """Sentence list with reference semantics (text.rs:59-101).

    Trims the input first, slices between boundaries, trims each slice and
    drops empties — mirroring text.rs:62-100.
    """
    trimmed = text.strip()
    if not trimmed:
        return []
    bounds = _sentence_boundaries(trimmed)
    out: List[str] = []
    prev = 0
    for b in bounds + [len(trimmed)]:
        if b > prev:
            s = trimmed[prev:b].strip()
            if s:
                out.append(s)
        prev = b
    if not out:
        return [trimmed]
    return out


# N-gram / duplicate helpers --------------------------------------------------


def get_n_grams(words: Sequence[str], n: int) -> List[str]:
    """All contiguous n-grams joined by spaces (text.rs:184-194)."""
    if n <= 0 or n > len(words):
        return []
    return [" ".join(words[i : i + n]) for i in range(len(words) - n + 1)]


def _byte_len(s: str) -> int:
    return len(s.encode("utf-8"))


def find_duplicates(items: Sequence[str]) -> Tuple[int, int]:
    """(duplicate element count, total UTF-8 byte length of duplicates)
    (text.rs:197-208 — ``elem.len()`` is a byte length in Rust)."""
    seen = set()
    dup_elems = 0
    dup_bytes = 0
    for elem in items:
        if elem in seen:
            dup_elems += 1
            dup_bytes += _byte_len(elem)
        else:
            seen.add(elem)
    return dup_elems, dup_bytes


def find_top_duplicate(items: Sequence[str]) -> int:
    """Byte length x count of the most frequent item; ties broken by the
    larger byte contribution (text.rs:211-238).  0 when nothing repeats."""
    if not items:
        return 0
    counter: Dict[str, int] = {}
    for elem in items:
        counter[elem] = counter.get(elem, 0) + 1
    max_count = max(counter.values())
    if max_count <= 1:
        return 0
    return max(
        _byte_len(gram) * max_count for gram, c in counter.items() if c == max_count
    )


def ngram_dup_stats(
    text: str, top_ns: Sequence[int], dup_ns: Sequence[int]
) -> Tuple[Dict[int, int], Dict[int, int]]:
    """Batch n-gram duplicate statistics for one text.

    Returns ``(top, dup)`` where ``top[n]`` = ``find_top_duplicate`` of the
    space-joined n-grams and ``dup[n]`` = ``find_all_duplicate`` byte sums —
    the quantities GopherRepetition thresholds (gopher_rep.rs:163-196).
    Computed by the native core over one shared segmentation when available,
    else via the Python primitives.
    """
    from .cjk import DICT_SCRIPT_RE

    # The native core segments run-whole; texts with dictionary scripts take
    # the Python path so their word lists include the CJK re-segmentation.
    if _native_spans is not None and DICT_SCRIPT_RE.search(text) is None:
        try:
            from ..native import available, dup_ngram_bytes, top_ngram_bytes
        except Exception:  # pragma: no cover
            available = lambda: False  # noqa: E731
        if available():
            cps = codepoints(text).astype(np.int32)
            cls = classify(cps.astype(np.uint32))
            spans = _native_spans(cps, cls)
            if spans is not None:
                top = {n: top_ngram_bytes(cps, spans, n) for n in top_ns}
                dup = {n: dup_ngram_bytes(cps, spans, n) for n in dup_ns}
                return top, dup
    words = split_into_words(text)
    top = {n: find_top_duplicate(get_n_grams(words, n)) for n in top_ns}
    dup = {n: find_all_duplicate(words, n) for n in dup_ns}
    return top, dup


def find_all_duplicate(words: Sequence[str], n: int) -> int:
    """Total byte length of non-overlapping repeated n-grams, advancing by n on
    a duplicate hit and by 1 otherwise (text.rs:241-259).  N-grams here are the
    words concatenated *without* separators (text.rs:250)."""
    if n <= 0 or len(words) < n:
        return 0
    seen = set()
    repeated_bytes = 0
    idx = 0
    n_words = len(words)
    while idx + n <= n_words:
        gram = "".join(words[idx : idx + n])
        if gram in seen:
            repeated_bytes += _byte_len(gram)
            idx += n
        else:
            seen.add(gram)
            idx += 1
    return repeated_bytes
