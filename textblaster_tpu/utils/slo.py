"""Declarative SLO engine: burn-rate alerting + error-budget accounting.

The metrics registry says what the pipeline *did*; this module says
whether that is *good enough against a target*.  Objectives are declared
as ``KEY=TARGET`` pairs (``--slo`` / the ``slo:`` config block):

* ``availability=0.999`` — fraction of documents that must not
  hard-error: bad = ``producer_results_error_total``, total = every
  document outcome reaching the aggregation sink
  (``producer_results_received_total`` — the one seam every backend path
  feeds).  Error budget = 1 − target.
* ``p99_latency_s=0.25`` — 99% of sampled documents must finish their
  end-to-end path within the target.  Evaluated from the PR 12
  ``doc_latency_e2e_seconds`` HDR histogram: bad = samples whose bucket
  upper bound exceeds the target, total = all samples.  Implied error
  budget = 1% (it's a p99).
* ``throughput_floor=500`` — docs/s the run must sustain: each
  evaluation tick compares the since-last-tick document rate against the
  floor; bad = ticks below it.  Error budget = 5% of ticks.

Evaluation follows the SRE multi-window multi-burn-rate recipe: the
instantaneous burn rate (bad fraction / budget) is computed over a fast
and a slow trailing window — both clamped to the elapsed run length so
short runs still alert — and an ``slo_alert`` journal event fires
(edge-triggered, with a matching ``slo_resolved``) only when *both*
windows burn above the threshold, which suppresses one-tick blips
without missing sustained burn.

Mergeability is inherited from the metrics registry: each objective
maintains monotone ``slo_events_total_<key>`` / ``slo_bad_events_total_
<key>`` counters and publishes ``slo_target_<key>`` / ``slo_burn_rate_
<key>`` / ``slo_budget_remaining_<key>`` gauges, so the existing
multihost ``all_values()`` sum/max merge yields gang-wide SLO state and
:func:`slo_report` rebuilds burn/budget numbers from any flat snapshot
(run-report v4's ``slo`` section) — per-rank or merged, byte-identically.

Like TRACER / TELEMETRY / WATCHDOG / EVENTS, the engine is inert until
armed: one ``SLO.enabled`` attribute check at every seam.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "SLO_KEYS",
    "SLO",
    "SLOEngine",
    "parse_slo_arg",
    "slo_report",
    "health_snapshot",
]

#: The closed objective vocabulary: ``key -> (error budget, help)``.
#: ``availability``'s budget is derived from the target (1 − target); the
#: listed value is only the fallback when the target is degenerate.
SLO_KEYS: Dict[str, Tuple[float, str]] = {
    "availability": (
        0.001,
        "fraction of documents that must not hard-error (bad = error "
        "outcomes, total = all outcomes); budget = 1 - target",
    ),
    "p99_latency_s": (
        0.01,
        "99th-percentile sampled end-to-end document latency ceiling, "
        "seconds (needs --doc-sample-rate > 0); budget = 1% of samples",
    ),
    "throughput_floor": (
        0.05,
        "minimum sustained docs/s; evaluated per tick against the "
        "since-last-tick rate; budget = 5% of ticks",
    ),
}


def parse_slo_arg(arg: str) -> Tuple[str, float]:
    """Parse one ``KEY=TARGET`` objective; raises ``ValueError`` with an
    operator-readable message on any malformation."""
    if "=" not in arg:
        raise ValueError(
            f"--slo expects KEY=TARGET, got {arg!r} "
            f"(keys: {', '.join(SLO_KEYS)})"
        )
    key, _, raw = arg.partition("=")
    key = key.strip()
    if key not in SLO_KEYS:
        raise ValueError(
            f"unknown SLO key {key!r} (keys: {', '.join(SLO_KEYS)})"
        )
    try:
        value = float(raw.strip())
    except ValueError:
        raise ValueError(f"--slo {key}: target {raw!r} is not a number")
    if key == "availability" and not 0.0 < value <= 1.0:
        raise ValueError("--slo availability: target must be in (0, 1]")
    if key != "availability" and value <= 0:
        raise ValueError(f"--slo {key}: target must be > 0")
    return key, value


def _budget_for(key: str, target: float) -> float:
    if key == "availability":
        return max(1e-9, 1.0 - target)
    return SLO_KEYS[key][0]


class SLOEngine:
    """Continuous SLO evaluator over the live metrics registry.

    ``evaluate()`` is the whole engine: read cumulative (bad, total) pairs
    per objective, append them to a time-stamped sample ring, derive
    fast/slow-window burn rates, publish gauges/counters, and
    edge-trigger alerts.  A daemon ticker calls it periodically in
    production; tests call it synchronously."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._objectives: Dict[str, float] = {}
        self._fast_s = 60.0
        self._slow_s = 300.0
        self._threshold = 1.0
        self._tick_s = 5.0
        self._t0 = 0.0
        self._baseline: Dict[str, Tuple[int, int]] = {}
        self._samples: List[Tuple[float, Dict[str, Tuple[int, int]]]] = []
        self._alerting: Dict[str, bool] = {}
        self._last: Dict[str, Dict[str, float]] = {}
        self._tp_prev: Optional[Tuple[float, int]] = None
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- lifecycle ----------------------------------------------------------

    def configure(
        self,
        objectives: Dict[str, float],
        *,
        fast_window_s: float = 60.0,
        slow_window_s: float = 300.0,
        burn_threshold: float = 1.0,
        tick_s: float = 5.0,
        start_ticker: bool = True,
    ) -> None:
        """Arm the engine with ``{key: target}`` objectives.  Publishes the
        ``slo_target_<key>`` gauges immediately (they are gang-agreed
        constants — max-merge safe) and takes the cumulative baseline so a
        re-armed engine never charges pre-run errors to the budget."""
        for key in objectives:
            if key not in SLO_KEYS:
                raise ValueError(f"unknown SLO key {key!r}")
        from .metrics import METRICS

        with self._lock:
            self._objectives = dict(objectives)
            self._fast_s = float(fast_window_s)
            self._slow_s = float(slow_window_s)
            self._threshold = float(burn_threshold)
            self._tick_s = max(0.05, float(tick_s))
            self._t0 = time.monotonic()
            self._samples = []
            self._alerting = {k: False for k in objectives}
            self._last = {}
            self._tp_prev = None
            self._baseline = {
                k: self._read_cumulative(k, self._objectives[k])
                for k in objectives
            }
            self.enabled = bool(objectives)
        for key, target in objectives.items():
            METRICS.set(f"slo_target_{key}", float(target))
        if self.enabled and start_ticker:
            self._stop.clear()
            self._ticker = threading.Thread(
                target=self._run_ticker, name="textblast-slo", daemon=True
            )
            self._ticker.start()

    def close(self) -> None:
        """Stop the ticker, run one final evaluation, and disarm."""
        if not self.enabled:
            return
        self._stop.set()
        t = self._ticker
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._ticker = None
        try:
            self.evaluate()
        except Exception:  # pragma: no cover - teardown must not raise
            pass
        self.enabled = False

    def reset(self) -> None:
        """Full disarm for tests (mirrors WATCHDOG.reset())."""
        self._stop.set()
        t = self._ticker
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._ticker = None
        with self._lock:
            self.enabled = False
            self._objectives = {}
            self._samples = []
            self._alerting = {}
            self._last = {}
            self._tp_prev = None
            self._baseline = {}

    # --- evaluation ---------------------------------------------------------

    def _read_cumulative(self, key: str, target: float) -> Tuple[int, int]:
        """Cumulative (bad, total) event counts for one objective, read
        from the live registry (absolute, not baseline-relative)."""
        from .metrics import METRICS

        if key == "availability":
            # The aggregation-sink seam (producer_results_*) counts every
            # document outcome on every backend path — host, device, and
            # multihost stripes — unlike worker_tasks_*, which only the
            # host executor feeds.
            bad = int(METRICS.get("producer_results_error_total"))
            total = int(METRICS.get("producer_results_received_total"))
            return bad, total
        if key == "p99_latency_s":
            from .metrics import hdr_bucket_high_us

            buckets, _sum_us, count = METRICS.hdr_state(
                "doc_latency_e2e_seconds"
            )
            threshold_us = int(target * 1e6)
            bad = sum(
                c for idx, c in buckets.items()
                if hdr_bucket_high_us(idx) > threshold_us
            )
            return bad, count
        # throughput_floor: tick-based — cumulative counts live in the
        # registry counters this engine itself maintains.
        return (
            int(METRICS.get("slo_bad_events_total_throughput_floor")),
            int(METRICS.get("slo_events_total_throughput_floor")),
        )

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        """One evaluation tick: returns the per-key state it published."""
        if not self.enabled:
            return {}
        from .metrics import METRICS

        t = time.monotonic() if now is None else now
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            if not self.enabled:
                return {}
            objectives = dict(self._objectives)
            # The throughput objective turns the document counter into
            # per-tick pass/fail events before cumulative reads happen.
            if "throughput_floor" in objectives:
                self._tick_throughput_locked(t, objectives["throughput_floor"])
            cum = {
                k: self._read_cumulative(k, objectives[k]) for k in objectives
            }
            self._samples.append((t, cum))
            horizon = t - max(self._slow_s, self._fast_s) * 1.5
            while len(self._samples) > 2 and self._samples[0][0] < horizon:
                self._samples.pop(0)
            elapsed = max(1e-9, t - self._t0)
            for key, target in objectives.items():
                budget = _budget_for(key, target)
                base = self._baseline.get(key, (0, 0))
                bad = max(0, cum[key][0] - base[0])
                total = max(0, cum[key][1] - base[1])
                bad_frac = bad / total if total else 0.0
                burn_fast = self._window_burn_locked(
                    key, t, min(self._fast_s, elapsed), budget, base
                )
                burn_slow = self._window_burn_locked(
                    key, t, min(self._slow_s, elapsed), budget, base
                )
                remaining = max(0.0, 1.0 - (bad_frac / budget)) if total else 1.0
                state = {
                    "target": target,
                    "budget": budget,
                    "bad": float(bad),
                    "total": float(total),
                    "bad_frac": bad_frac,
                    "burn_rate": bad_frac / budget,
                    "burn_fast": burn_fast,
                    "burn_slow": burn_slow,
                    "budget_remaining": remaining,
                }
                out[key] = state
                self._last[key] = state
                firing = (
                    total > 0
                    and burn_fast > self._threshold
                    and burn_slow > self._threshold
                )
                was = self._alerting.get(key, False)
                self._alerting[key] = firing
                if firing and not was:
                    self._alert_edge_locked(key, state, resolved=False)
                elif was and not firing:
                    self._alert_edge_locked(key, state, resolved=True)
        # Publish outside the lock: METRICS has its own.
        for key, s in out.items():
            if key != "throughput_floor":
                METRICS.set(f"slo_events_total_{key}", s["total"])
                METRICS.set(f"slo_bad_events_total_{key}", s["bad"])
            METRICS.set(f"slo_burn_rate_{key}", round(s["burn_fast"], 6))
            METRICS.set(
                f"slo_budget_remaining_{key}", round(s["budget_remaining"], 6)
            )
        return out

    def _tick_throughput_locked(self, t: float, floor: float) -> None:
        from .metrics import METRICS

        done = int(METRICS.get("producer_results_received_total"))
        prev = self._tp_prev
        self._tp_prev = (t, done)
        if prev is None:
            return
        dt = t - prev[0]
        if dt <= 0:
            return
        rate = (done - prev[1]) / dt
        METRICS.inc("slo_events_total_throughput_floor")
        if rate < floor:
            METRICS.inc("slo_bad_events_total_throughput_floor")

    def _window_burn_locked(
        self,
        key: str,
        t: float,
        window_s: float,
        budget: float,
        base: Tuple[int, int],
    ) -> float:
        """Burn rate over the trailing ``window_s``: the bad fraction of
        events inside the window, over the budget.  The window anchor is
        the newest sample at or before ``t - window_s`` (falling back to
        the arm-time baseline for young runs)."""
        cutoff = t - window_s
        anchor = base
        for ts, cum in self._samples:
            if ts > cutoff:
                break
            anchor = cum.get(key, base)
        head = self._samples[-1][1].get(key, base) if self._samples else base
        bad = max(0, head[0] - anchor[0])
        total = max(0, head[1] - anchor[1])
        if total == 0:
            return 0.0
        return (bad / total) / budget

    def _alert_edge_locked(
        self, key: str, state: Dict[str, float], *, resolved: bool
    ) -> None:
        from .events import EVENTS
        from .metrics import METRICS

        if resolved:
            if EVENTS.enabled:
                EVENTS.emit("slo_resolved", key=key)
            logger.warning("SLO %s recovered (burn back under threshold)", key)
            return
        METRICS.inc("slo_alerts_total")
        if EVENTS.enabled:
            EVENTS.emit(
                "slo_alert",
                key=key,
                burn_rate=round(state["burn_fast"], 4),
                window_s=self._fast_s,
                burn_slow=round(state["burn_slow"], 4),
                budget_remaining=round(state["budget_remaining"], 4),
            )
        logger.error(
            "SLO alert: %s burning at %.2fx budget (fast) / %.2fx (slow), "
            "%.1f%% of error budget left",
            key, state["burn_fast"], state["burn_slow"],
            state["budget_remaining"] * 100.0,
        )

    def _run_ticker(self) -> None:
        while not self._stop.wait(self._tick_s):
            try:
                self.evaluate()
            except Exception as e:  # pragma: no cover - must not die
                logger.warning("SLO evaluation tick failed: %s", e)

    # --- introspection ------------------------------------------------------

    def active_alerts(self) -> List[str]:
        with self._lock:
            return sorted(k for k, v in self._alerting.items() if v)

    def objectives(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._objectives)

    def snapshot(self) -> Dict[str, object]:
        """JSON-ready engine state (the ``/slo`` endpoint body and the
        flight recorder's ``slo`` section)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "objectives": dict(self._objectives),
                "windows": {
                    "fast_s": self._fast_s,
                    "slow_s": self._slow_s,
                    "burn_threshold": self._threshold,
                    "tick_s": self._tick_s,
                },
                "elapsed_s": round(time.monotonic() - self._t0, 3)
                if self.enabled
                else 0.0,
                "state": {k: dict(v) for k, v in self._last.items()},
                "alerting": sorted(
                    k for k, v in self._alerting.items() if v
                ),
            }


#: Process-wide engine.  Import this, never construct your own.
SLO = SLOEngine()


def slo_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """The run report's ``slo`` section, rebuilt from a flat snapshot.

    Objectives are discovered from the ``slo_target_<key>`` gauges inside
    the snapshot itself, and burn/budget numbers derive only from the
    monotone ``slo_events_total_*`` / ``slo_bad_events_total_*`` counters
    — so the section computed from a gang-merged snapshot equals the
    bucket-wise merge of the per-rank snapshots by construction."""
    from .metrics import METRICS

    vals = values if values is not None else METRICS.all_values()
    base = baseline or {}
    out: Dict[str, object] = {}
    for name, target in sorted(vals.items()):
        if not name.startswith("slo_target_"):
            continue
        key = name[len("slo_target_"):]
        if key not in SLO_KEYS:
            continue
        budget = _budget_for(key, float(target))
        bad = max(
            0.0,
            vals.get(f"slo_bad_events_total_{key}", 0.0)
            - base.get(f"slo_bad_events_total_{key}", 0.0),
        )
        total = max(
            0.0,
            vals.get(f"slo_events_total_{key}", 0.0)
            - base.get(f"slo_events_total_{key}", 0.0),
        )
        bad_frac = bad / total if total else 0.0
        out[key] = {
            "target": float(target),
            "budget": round(budget, 9),
            "bad_events": int(bad),
            "events": int(total),
            "bad_frac": round(bad_frac, 9),
            "burn_rate": round(bad_frac / budget, 6),
            "budget_remaining": round(
                max(0.0, 1.0 - bad_frac / budget), 6
            ) if total else 1.0,
        }
    alerts = max(
        0.0,
        vals.get("slo_alerts_total", 0.0) - base.get("slo_alerts_total", 0.0),
    )
    if not out and alerts == 0:
        return {}
    return {"objectives": out, "alerts_total": int(alerts)}


#: Most-recently-seen watchdog escalation count, so health degrades on a
#: *new* escalation and recovers on the next clean scrape instead of
#: latching degraded forever on a cumulative counter.
_health_state = {"escalations_seen": 0.0}


def health_snapshot() -> Tuple[int, Dict[str, object]]:
    """The ``/healthz`` verdict: ``(http_status, body)``.

    Live/ready semantics: the process is *live* by virtue of answering;
    it is *ready* once warmup has resolved (``pipeline_warmup_done``) and
    no degradation signal is active — circuit breaker open, liveness
    lease stale (membership-epoch freshness), a watchdog escalation since
    the previous scrape, or a firing SLO alert.  200 when ready, 503
    (starting or degraded) otherwise, always with a component breakdown
    in the JSON body."""
    from .metrics import METRICS

    warm = METRICS.get("pipeline_warmup_done") >= 1.0
    breaker_open = METRICS.get("resilience_breaker_open") >= 1.0
    lease_ratio = METRICS.get("multihost_lease_age_ratio")
    lease_stale = lease_ratio >= 1.0
    escalations = METRICS.get("watchdog_escalations_total")
    new_escalation = escalations > _health_state["escalations_seen"]
    _health_state["escalations_seen"] = escalations
    alerts = SLO.active_alerts() if SLO.enabled else []

    degraded = breaker_open or lease_stale or new_escalation or bool(alerts)
    if not warm:
        status = "starting"
    elif degraded:
        status = "degraded"
    else:
        status = "ok"
    body: Dict[str, object] = {
        "status": status,
        "live": True,
        "ready": warm and not degraded,
        "components": {
            "warmup_done": warm,
            "breaker_open": breaker_open,
            "lease_age_ratio": round(lease_ratio, 4),
            "lease_stale": lease_stale,
            "watchdog_escalations": int(escalations),
            "new_escalation": new_escalation,
            "slo_alerts": alerts,
            "membership_epoch": int(
                METRICS.get("multihost_membership_epoch")
            ),
        },
    }
    return (200 if body["ready"] else 503), body
