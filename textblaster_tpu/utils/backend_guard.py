"""Hang-proof CPU-only mode for processes that must never touch a remote chip.

This image injects a remote-TPU PJRT plugin via ``sitecustomize`` (registered
at interpreter start, before any project code runs).  JAX initializes every
*registered* platform on first backend use even when ``JAX_PLATFORMS=cpu``
selects only the CPU — and the remote plugin's init dials a relay that can
block indefinitely while the chip is claimed by another process or the tunnel
is down.  Observed effects: ``jax.devices()`` hanging >15 min in CPU-only
test runs, and the benchmark's CPU fallback path dying with the same hang it
was meant to survive.

:func:`force_cpu_backend` drops every non-CPU backend factory before first
initialization, so the process provably cannot dial out.  Call it before any
JAX computation in processes that are CPU-by-contract (the test suite, the
benchmark's fallback mode, the virtual-mesh dryrun).
"""

from __future__ import annotations

import os

__all__ = ["force_cpu_backend", "enable_cpu_x64"]


def enable_cpu_x64() -> None:
    """Enable 64-bit types for a CPU-by-contract process.

    The duplicate-table sorts then take ``sort2``'s packed path — one
    ``(key << 32) | payload`` int64 operand through the single-operand
    ``lax.sort``, which XLA:CPU runs ~4.4x faster than the two-operand
    comparator form (pallas_sort.py).  CPU-only by design: TPU processes
    keep the default x64-off config (Mosaic kernels and the f32 compute
    path are built for it), and the virtual-mesh dryrun mirrors the TPU
    configuration, so neither calls this."""
    import jax

    jax.config.update("jax_enable_x64", True)


def force_cpu_backend() -> None:
    """Restrict this process to the in-process CPU backend, irreversibly.

    Safe to call multiple times; a no-op once backends are initialized (at
    that point either the remote platform already came up or we are past the
    risk of a first-init hang).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        from jax._src import xla_bridge as xb

        if not xb.backends_are_initialized():
            for name, reg in list(xb._backend_factories.items()):
                if name == "cpu":
                    continue

                def _refuse(*args, _name=name, **kwargs):
                    raise RuntimeError(
                        f"backend {_name!r} disabled by force_cpu_backend()"
                    )

                # Keep the platform REGISTERED (popping it breaks MLIR's
                # known-platform validation for tpu lowering rules) but make
                # its init fail fast and quietly instead of dialing out.
                xb._backend_factories[name] = _registration_like(
                    reg, factory=_refuse
                )
    except Exception as e:  # noqa: BLE001 — private API may drift across jax versions
        import logging

        # Degraded to env-var-only protection, which does NOT prevent the
        # remote plugin's first-init hang — make the regression diagnosable.
        logging.getLogger(__name__).warning(
            "backend_guard could not patch jax backend factories (%s: %s); "
            "remote-plugin init hangs are possible again",
            type(e).__name__,
            e,
        )
    import jax

    jax.config.update("jax_platforms", "cpu")


def _registration_like(reg, factory):
    """A copy of a BackendRegistration with the factory swapped and failures
    made quiet, tolerant of NamedTuple vs dataclass across jax versions."""
    try:
        return reg._replace(factory=factory, fail_quietly=True)
    except AttributeError:
        import dataclasses

        return dataclasses.replace(reg, factory=factory, fail_quietly=True)
