"""Codepoint classification tables.

The reference classifies characters with ICU4X + a custom PUNCTUATION set
(``/root/reference/src/utils/text.rs:28-57``).  Here we precompute one dense
``uint8`` bitmask table over the full Unicode range so that both the CPU oracle
(numpy) and the TPU kernels (device gather over the same table) classify
characters identically.  The table is built once per process from Python's
unicodedata-backed ``str`` predicates.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "ALNUM",
    "ALPHA",
    "DIGIT",
    "WS",
    "PUNCT",
    "LOWER",
    "UPPER",
    "EXTEND",
    "char_table",
    "classify",
    "codepoints",
    "PUNCTUATION",
    "PUNCTUATION_LIT",
]

# Bit flags
ALNUM = 1 << 0  # str.isalnum()  (ICU ALetter|Numeric approximation)
ALPHA = 1 << 1  # str.isalpha()  (char::is_alphabetic parity, gopher_quality.rs:171)
DIGIT = 1 << 2  # str.isdigit()
WS = 1 << 3  # str.isspace()  (char::is_whitespace parity)
PUNCT = 1 << 4  # membership in the reference PUNCTUATION set (text.rs:40-57)
LOWER = 1 << 5  # str.islower() (sentence segmentation SB8)
UPPER = 1 << 6  # str.isupper()
EXTEND = 1 << 7  # UAX#29 WB4 attachers: combining marks (Mn/Mc/Me) + format
#                  (Cf) chars that are not already alphanumeric — they extend
#                  the preceding word instead of breaking it (NFD text parity)

# Exactly the literal punctuation characters of the reference (text.rs:28-29).
PUNCTUATION_LIT = (
    "!/—”:％１〈&(、━\\【#%「」，】；+^]~“《„';’{|∶´[=-`*．（–？！：$～«〉,><》)?）。…@_.\"}►»"
)

# Codepoint ranges included in PUNCTUATION (text.rs:32-37): half-open [start, end).
PUNCTUATION_RANGES = ((0, 9), (11, 13), (13, 32), (127, 160))

#: The reference's global punctuation set (text.rs:40-57), as a Python frozenset.
PUNCTUATION = frozenset(PUNCTUATION_LIT) | frozenset(
    chr(cp) for start, end in PUNCTUATION_RANGES for cp in range(start, end)
)

# Table covers planes 0-3 (0x0-0x3FFFF): everything assigned an alphanumeric /
# space / punctuation property lives below this bound, EXCEPT the plane-14
# tag/variation-selector block (U+E0000-E01EF, all Mn/Cf = EXTEND), which
# ``classify`` handles with a range check so emoji tag sequences attach
# instead of shattering into symbol tokens.  Planes 4+ are otherwise
# unassigned or private-use, classifying as 0 — same as Python's str
# predicates.  Lookups clip the index, so any codepoint is safe to classify.
_MAX_CP = 0x40000
_PLANE14_LO, _PLANE14_HI = 0xE0000, 0xE0200
_TABLE: np.ndarray | None = None


def _build_table() -> np.ndarray:
    import unicodedata

    table = np.zeros(_MAX_CP, dtype=np.uint8)
    for cp in range(_MAX_CP):
        c = chr(cp)
        v = 0
        if c.isalnum():
            v |= ALNUM
        if c.isalpha():
            v |= ALPHA
        if c.isdigit():
            v |= DIGIT
        if c.isspace():
            v |= WS
        if c.islower():
            v |= LOWER
        if c.isupper():
            v |= UPPER
        # UAX#29 Format excludes ZWSP (U+200B): it BREAKS words, it does not
        # join them (WordBreak=Other).  ZWNJ/ZWJ stay attachers.
        if (
            not (v & ALNUM)
            and cp != 0x200B
            and unicodedata.category(c) in ("Mn", "Mc", "Me", "Cf")
        ):
            v |= EXTEND
        if v:
            table[cp] = v
    for ch in PUNCTUATION:
        table[ord(ch)] |= PUNCT
    return table


def char_table() -> np.ndarray:
    """Return the dense ``[0x40000] uint8`` classification table (cached)."""
    global _TABLE
    if _TABLE is None:
        _TABLE = _build_table()
    return _TABLE


def classify(cps: np.ndarray) -> np.ndarray:
    """Classify a codepoint array; indices are clipped into the table.
    Plane-14 tag/variation-selector chars classify as EXTEND by range."""
    table = char_table()
    cls = table[np.minimum(cps, _MAX_CP - 1).astype(np.int64)]
    plane14 = (cps >= _PLANE14_LO) & (cps < _PLANE14_HI)
    if plane14.any():
        cls = np.where(plane14, np.uint8(EXTEND), cls)
    return cls


def codepoints(text: str) -> np.ndarray:
    """Decode a Python string to a ``uint32`` codepoint array (no copy loops)."""
    if not text:
        return np.empty(0, dtype=np.uint32)
    return np.frombuffer(text.encode("utf-32-le"), dtype=np.uint32)
