"""Bounded producer/consumer plumbing for the overlapped host pipeline.

The device path's host work is three independent stages — read Parquet,
pack batches, write outcomes — each of which spends most of its time in
GIL-releasing C code (pyarrow decode, ``str.encode``/numpy scatter, pyarrow
write).  Running them on their own threads behind small bounded queues
overlaps them with device compute without changing a single outcome: the
queues are strict FIFO, so ordering is identical to the serial path and
only wall time moves.

Three primitives live here:

``prefetch_iter``
    Wrap any iterator so a daemon thread runs it ahead of the consumer,
    buffering up to ``depth`` blocks of ``block`` items in a bounded queue.
    Exceptions raised by the source re-raise at the consumer's ``next()``
    in order, and abandoning the iterator (``close()`` / GC) stops the
    thread promptly.

``ThreadedWriter``
    Wrap a ParquetWriter-shaped object so ``write_batch`` enqueues and a
    single worker thread performs the actual writes in FIFO order.  The
    first write error is re-raised to the caller at the next call (or at
    ``close()``), preserving the serial path's error semantics; ``close()``
    drains the queue, joins the thread (progress-bounded — a wedged drain
    surfaces a typed ``StallError`` carrying the residual queue depth
    instead of hanging shutdown forever), and closes the inner writer.

Both queue seams (reader prefetch ``get``, write-behind ``put``) are
supervised by the stall watchdog when it is armed; disabled (the default)
each seam pays a single ``WATCHDOG.enabled`` attribute check.

``shared_pack_pool``
    The process-wide pack-worker ``ThreadPoolExecutor``.  Packing releases
    the GIL (str.encode + numpy scatter), so one pool serves every call
    site — ``CompiledPipeline``'s per-phase packer and the multi-host
    lockstep window both submit here instead of spinning up private
    executors per pipeline instance.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional

from ..resilience.watchdog import WATCHDOG
from .metrics import METRICS
from .trace import TRACER

__all__ = ["prefetch_iter", "ThreadedWriter", "shared_pack_pool"]

#: Queue sentinel: the producer finished cleanly.
_DONE = object()


class _PrefetchIterator:
    def __init__(self, source: Iterable, depth: int, block: int) -> None:
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._block: List[Any] = []
        self._pos = 0
        self._done = False
        self._thread = threading.Thread(
            target=self._produce,
            args=(iter(source), block),
            name="textblast-prefetch",
            daemon=True,
        )
        self._thread.start()

    def _produce(self, source: Iterator, block: int) -> None:
        try:
            buf: List[Any] = []
            for item in source:
                buf.append(item)
                if len(buf) >= block:
                    if not self._put(buf):
                        return
                    buf = []
            if buf:
                if not self._put(buf):
                    return
            self._put(_DONE)
        except BaseException as e:  # re-raised at the consumer's next()
            self._put(e)

    def _put(self, item: Any) -> bool:
        # Bounded put that gives up when the consumer abandoned us, so an
        # early break/close never leaves a thread blocked forever.
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "_PrefetchIterator":
        return self

    def __next__(self) -> Any:
        while True:
            if self._pos < len(self._block):
                item = self._block[self._pos]
                self._pos += 1
                return item
            if self._done:
                raise StopIteration
            if WATCHDOG.enabled:
                got = WATCHDOG.queue_get("read_prefetch", self._queue)
            else:
                got = self._queue.get()
            # Per-block (never per-item): the gauge feeds the live rollup's
            # read-queue track the same way ThreadedWriter feeds write's.
            METRICS.set("queue_depth_read", self._queue.qsize())
            if got is _DONE:
                self._done = True
                raise StopIteration
            if isinstance(got, BaseException):
                self._done = True
                raise got
            self._block = got
            self._pos = 0

    def qsize(self) -> int:
        """Blocks buffered ahead of the consumer (approximate, like
        ``queue.Queue.qsize``)."""
        return self._queue.qsize()

    def close(self) -> None:
        self._stop.set()
        # Drain so a blocked put wakes immediately.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=2.0)

    def __del__(self) -> None:  # best effort; close() is the real path
        self._stop.set()


def prefetch_iter(source: Iterable, depth: int = 4, block: int = 256):
    """Run ``source`` on a background thread, ``depth`` blocks ahead.

    Items are forwarded in order; source exceptions re-raise at the
    consumer's ``next()`` at the position they occurred.  ``block`` items
    are handed over per queue op to keep synchronization off the per-item
    hot path.
    """
    return _PrefetchIterator(source, depth=depth, block=block)


#: Process-wide pack pools, keyed by worker count (executors cannot grow,
#: so distinct ``pack_workers`` settings get distinct pools; in practice a
#: process uses one setting and therefore one pool).
_PACK_POOLS: Dict[int, Any] = {}
_PACK_POOLS_LOCK = threading.Lock()


def shared_pack_pool(workers: int = 2):
    """The process-wide pack-worker pool for ``workers`` threads.

    Reused across every call site (single-host phase packers, the
    multi-host lockstep window, tests) — pack work is short-lived and
    GIL-releasing, so sharing one executor avoids a thread-pool per
    ``CompiledPipeline`` while keeping submission order = completion
    consumption order for any caller that resolves its own futures FIFO.
    Never shut down explicitly: workers are idle between submissions and
    the interpreter joins them at exit.
    """
    from concurrent.futures import ThreadPoolExecutor

    w = max(1, int(workers))
    with _PACK_POOLS_LOCK:
        pool = _PACK_POOLS.get(w)
        if pool is None:
            pool = ThreadPoolExecutor(
                max_workers=w, thread_name_prefix="textblast-pack"
            )
            _PACK_POOLS[w] = pool
        return pool


class ThreadedWriter:
    """FIFO write-behind wrapper around a ParquetWriter-shaped object.

    Only ``write_batch(list)`` and ``close()`` are offloaded/ordered; any
    other attribute proxies to the inner writer.  The batch list is copied
    on enqueue, so callers may reuse/clear their buffer (orchestration.py
    does ``batch.clear()`` style reuse).
    """

    def __init__(self, inner: Any, max_queue: int = 8) -> None:
        self._inner = inner
        self._queue: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, max_queue))
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._drain, name="textblast-writer", daemon=True
        )
        self._thread.start()

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is _DONE:
                    return
                if self._error is None:
                    try:
                        self._inner.write_batch(item)
                    except BaseException as e:
                        self._error = e
            finally:
                self._queue.task_done()
                METRICS.set("queue_depth_write", self._queue.qsize())
                TRACER.counter("queue_depth_write", self._queue.qsize())

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            self._closed = True
            raise err

    def write_batch(self, outcomes: List[Any]) -> None:
        if self._closed:
            raise RuntimeError("ThreadedWriter is closed")
        self._raise_pending()
        if WATCHDOG.enabled:
            WATCHDOG.queue_put("write_queue", self._queue, list(outcomes))
        else:
            self._queue.put(list(outcomes))
        METRICS.set("queue_depth_write", self._queue.qsize())
        TRACER.counter("queue_depth_write", self._queue.qsize())

    def _put_done(self) -> None:
        # Teardown put, progress-bounded: the sentinel only fails to land
        # if the queue is full AND the drain thread stopped consuming —
        # surface that as a typed stall (with the residual depth) instead
        # of blocking close() forever.  The timer restarts whenever the
        # drain makes progress, so a slow-but-live flush is never killed.
        deadline_s = WATCHDOG.deadline_for("write_queue") or 60.0
        last = self._queue.qsize()
        start = time.monotonic()
        while True:
            try:
                self._queue.put(_DONE, timeout=0.1)
                return
            except queue.Full:
                depth = self._queue.qsize()
                if depth < last:
                    last = depth
                    start = time.monotonic()
                    continue
                elapsed = time.monotonic() - start
                if elapsed >= deadline_s:
                    WATCHDOG.stall(
                        "write_queue",
                        elapsed,
                        deadline_s,
                        f"teardown enqueue: queue depth {depth}",
                    )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._put_done()
        # Progress-bounded join (historically unbounded — a wedged writer
        # thread hung shutdown forever): no-progress past the write_queue
        # deadline (60 s when the watchdog is disarmed) raises StallError
        # naming the stage and the residual queue depth.
        WATCHDOG.join_thread("write_queue", self._thread, self._queue.qsize)
        try:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
        finally:
            self._inner.close()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)
