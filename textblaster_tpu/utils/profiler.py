"""Device-time attribution: static per-program cost model, dispatch-level
device-time histograms, lockstep stall decomposition, and a
machine-independent perf-regression sentinel.

Four subsystems, all off (one attribute check per seam) until
``PROFILER.configure()``:

Static cost model
    ``warmup_parallel`` captures ``compiled.cost_analysis()`` +
    ``memory_analysis()`` (flops, bytes accessed, peak buffer sizes) for
    every (bucket, phase, rows) program it installs — from the fresh
    compile, or from the ``.cost.json`` sidecar the AOT executable cache
    stores next to each ``.aotx`` entry (utils/compile_cache.py), so a
    warm start keeps the exact numbers its executables were compiled
    with.  :func:`cost_fingerprint` folds the sorted per-program table
    into one sha256 — bit-stable for a given config + geometry + fusion
    hatches, and therefore diffable across machines and runs.

Dispatch-level device timing
    ``CompiledPipeline._device_fetch`` (and the lockstep resolve fetch in
    parallel/multihost.py) feed each dispatch's blocked-on-device wall
    time into per-(bucket, phase) HDR families
    (``device_time_bucket_<L>_phase_<P>_seconds`` — the same mergeable
    log-linear scheme as the doc-latency families, so gang-wide quantiles
    come out of the unchanged snapshot sum-merge), update a roofline-style
    achieved-bytes/s gauge against the modeled bytes, and keep a top-K
    slowest-dispatch table.  All of it lands in the run report's
    ``device_profile`` section; the modeled cost and achieved rate also
    ride the ``device_wait`` Perfetto span args.

Lockstep decomposition
    :func:`lockstep_decomposition` splits the multihost lockstep loop's
    wall time into device / exchange-post / residual-stall / other from
    counters that already travel through the snapshot merge — a pure
    report-side computation, no new exchange.

Regression sentinel
    ``python -m textblaster_tpu.utils.profiler --baseline/--check`` diffs
    a run's cost fingerprint + per-(bucket, phase) scan dispatch counts
    against a checked-in baseline JSON with tolerance bands.  Dispatch
    counts come from ``jax.eval_shape`` tracing (no compile, no device),
    so they are machine-independent and exact; static costs get warn/fail
    relative-drift bands to absorb jax-version churn.  Runs
    deterministically on CPU under Pallas interpret mode — the
    generalization of the depfuse dispatch-count gate into a CI tool that
    catches *any* silent cost regression (a fusion hatch quietly
    disabled, a chain split back into staged passes).
"""

from __future__ import annotations

import hashlib
import heapq
import json
import logging
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from .metrics import (
    DEVICE_BPS_PREFIX,
    DEVICE_TIME_PREFIX,
    METRICS,
    _hdr_delta,
    hdr_bucket_high_us,
    hdr_quantile_us,
)

logger = logging.getLogger(__name__)

__all__ = [
    "PROFILER",
    "Profiler",
    "program_cost",
    "program_key",
    "cost_fingerprint",
    "device_profile_report",
    "lockstep_decomposition",
    "collect_sentinel_profile",
    "compare_profiles",
    "SENTINEL_SCHEMA",
    "main",
]

#: Sentinel baseline file schema tag (bump on breaking shape changes).
SENTINEL_SCHEMA = "textblaster-cost-baseline/v1"

#: Cost fields carried per program and compared by the sentinel's
#: tolerance bands, in display order.
COST_FIELDS = (
    "flops",
    "transcendentals",
    "bytes_accessed",
    "peak_bytes",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
)

_FAMILY_RE = re.compile(
    rf"^{DEVICE_TIME_PREFIX}(\d+)_phase_(\d+)_seconds$"
)


def program_key(length: int, phase: int, rows: int) -> str:
    """Canonical per-program key — ``b<bucket>/p<phase>/r<rows>`` — used by
    the cost table, the fingerprint, and the sentinel baseline."""
    return f"b{int(length)}/p{int(phase)}/r{int(rows)}"


def device_time_family(length: int, phase: int) -> str:
    """HDR family name for one (bucket, phase) dispatch population."""
    return f"{DEVICE_TIME_PREFIX}{int(length)}_phase_{int(phase)}_seconds"


def program_cost(compiled) -> Optional[Dict[str, int]]:
    """Extract the static cost model from a compiled executable.

    Sums ``cost_analysis()`` across modules (jax returns a list of
    per-module dicts on some versions, a single dict on others) and folds
    ``memory_analysis()`` buffer sizes in.  Every value is rounded to an
    int so the table is bit-stable under JSON round-trips.  Returns None
    when the backend exposes neither analysis (nothing to model beats a
    table of fabricated zeros)."""
    cost = {field: 0 for field in COST_FIELDS}
    got = False
    try:
        ca = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend-dependent
        ca = None
    if isinstance(ca, dict):
        ca = [ca]
    for mod in ca or []:
        if not isinstance(mod, dict):
            continue
        try:
            cost["flops"] += int(round(float(mod.get("flops", 0.0))))
            cost["bytes_accessed"] += int(
                round(float(mod.get("bytes accessed", 0.0)))
            )
            cost["transcendentals"] += int(
                round(float(mod.get("transcendentals", 0.0)))
            )
            got = True
        except (TypeError, ValueError):  # pragma: no cover
            continue
    try:
        ma = compiled.memory_analysis()
    except Exception:  # pragma: no cover - backend-dependent
        ma = None
    if ma is not None:
        try:
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            out = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            cost["argument_bytes"] = arg
            cost["output_bytes"] = out
            cost["temp_bytes"] = tmp
            # Peak live-buffer footprint: arguments + outputs + temporaries
            # (aliased pairs counted once by XLA's own accounting).
            cost["peak_bytes"] = arg + out + tmp
            got = True
        except (TypeError, ValueError):  # pragma: no cover
            pass
    return cost if got else None


def cost_fingerprint(table: Dict[str, Dict[str, int]]) -> Optional[str]:
    """sha256 over the canonical (sorted-key, separators-free) JSON of a
    ``{program_key: cost}`` table — THE config-level cost fingerprint."""
    if not table:
        return None
    blob = json.dumps(table, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class Profiler:
    """Process-wide performance observatory (``PROFILER``).

    Hot-path discipline mirrors ``TELEMETRY``/``TRACER``: every seam
    guards with ``if PROFILER.enabled:`` — one attribute read, nothing
    else, when profiling is off."""

    def __init__(self) -> None:
        #: THE hot-path guard.
        self.enabled = False
        self._lock = threading.Lock()
        # program_key -> {"cost": {...} | None, "source": str,
        #                 "length": int, "phase": int, "rows": int}
        self._programs: Dict[str, Dict[str, Any]] = {}
        # (length, phase) -> cost dict of the largest-rows program, the
        # denominator for per-dispatch roofline math (split-rung rows get
        # their own exact entry when present).
        self._by_bucket_phase: Dict[Tuple[int, int], Dict[str, int]] = {}
        self._top: List[Tuple[float, int, Dict[str, Any]]] = []
        self._top_k = 8
        self._seq = 0

    # -- lifecycle -----------------------------------------------------------

    def configure(self, top_k: int = 8) -> None:
        """Enable profiling with a fresh state (idempotent re-arms)."""
        with self._lock:
            self._programs = {}
            self._by_bucket_phase = {}
            self._top = []
            self._top_k = max(1, int(top_k))
            self._seq = 0
        self.enabled = True

    def close(self) -> None:
        """Disable the hot-path seams.  Captured state is kept so an
        end-of-run report built after teardown still has the cost model."""
        self.enabled = False

    # -- static cost model ---------------------------------------------------

    def record_program_cost(
        self,
        length: int,
        phase: int,
        rows: int,
        cost: Optional[Dict[str, int]],
        source: str = "compile",
    ) -> None:
        """Register one program's static cost (``source``: "compile",
        "aot-sidecar", or "aot-recompute")."""
        pk = program_key(length, phase, rows)
        with self._lock:
            self._programs[pk] = {
                "cost": dict(cost) if cost else None,
                "source": source,
                "length": int(length),
                "phase": int(phase),
                "rows": int(rows),
            }
            if cost:
                bp = (int(length), int(phase))
                cur = self._by_bucket_phase.get(bp)
                if cur is None or int(rows) >= cur.get("_rows", -1):
                    self._by_bucket_phase[bp] = {**cost, "_rows": int(rows)}

    def cost_table(self) -> Dict[str, Dict[str, int]]:
        """``{program_key: cost}`` for every program with a model — the
        fingerprint input (sources and row metadata excluded)."""
        with self._lock:
            return {
                pk: dict(rec["cost"])
                for pk, rec in self._programs.items()
                if rec["cost"]
            }

    def cost_entries(self) -> Dict[str, Dict[str, Any]]:
        """Cost table with provenance (``source``) for the report."""
        with self._lock:
            out = {}
            for pk, rec in sorted(self._programs.items()):
                out[pk] = {
                    **(rec["cost"] or {}),
                    "source": rec["source"],
                }
            return out

    def cost_fingerprint(self) -> Optional[str]:
        return cost_fingerprint(self.cost_table())

    def modeled_cost(
        self, length: int, phase: int, rows: Optional[int] = None
    ) -> Optional[Dict[str, int]]:
        """The cost model for one dispatch shape: exact (bucket, phase,
        rows) entry when present, else the bucket/phase's full-rows one."""
        with self._lock:
            if rows is not None:
                rec = self._programs.get(program_key(length, phase, rows))
                if rec is not None and rec["cost"]:
                    return rec["cost"]
            return self._by_bucket_phase.get((int(length), int(phase)))

    # -- dispatch timing -----------------------------------------------------

    def record_dispatch(
        self, length: int, phase: int, rows: int, seconds: float
    ) -> Dict[str, Any]:
        """Record one dispatch's blocked-on-device wall time.

        Feeds the per-(bucket, phase) HDR family, updates the achieved
        bytes/s roofline gauge against the modeled bytes, and keeps the
        top-K slowest-dispatch table.  Returns the attribution dict the
        caller may attach to its Perfetto span."""
        seconds = max(0.0, float(seconds))
        METRICS.observe_hdr(
            device_time_family(length, phase), int(seconds * 1e6)
        )
        info: Dict[str, Any] = {
            "bucket": int(length),
            "phase": int(phase),
            "rows": int(rows),
            "seconds": round(seconds, 6),
        }
        cost = self.modeled_cost(length, phase, rows)
        if cost:
            info["modeled_flops"] = int(cost.get("flops", 0))
            info["modeled_bytes"] = int(cost.get("bytes_accessed", 0))
            if seconds > 0:
                bps = cost.get("bytes_accessed", 0) / seconds
                info["achieved_bytes_per_s"] = int(bps)
                METRICS.set(
                    f"{DEVICE_BPS_PREFIX}{int(length)}_phase_{int(phase)}",
                    bps,
                )
        with self._lock:
            self._seq += 1
            heapq.heappush(self._top, (seconds, self._seq, info))
            if len(self._top) > self._top_k:
                heapq.heappop(self._top)
        return info

    def top_dispatches(self) -> List[Dict[str, Any]]:
        """The K slowest dispatches seen, slowest first (per-process — the
        table does not travel through snapshot merges; the HDR families
        carry the mergeable population)."""
        with self._lock:
            return [
                info
                for _, _, info in sorted(self._top, key=lambda t: -t[0])
            ]


#: Process-wide observatory, disabled until configured.
PROFILER = Profiler()


# --- report builders ---------------------------------------------------------


def _discover_families(vals: Dict[str, float]) -> List[Tuple[str, int, int]]:
    """(family, bucket, phase) for every device-time HDR family present in
    a flat snapshot (discovered via the ``::count`` key)."""
    out = []
    for key in vals:
        if not key.endswith("::count"):
            continue
        m = _FAMILY_RE.match(key[: -len("::count")])
        if m:
            out.append((m.group(0), int(m.group(1)), int(m.group(2))))
    return sorted(out, key=lambda t: (t[1], t[2]))


def lockstep_decomposition(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Optional[Dict[str, object]]:
    """Attribute the multihost lockstep loop's wall time, from counters
    that already ride the snapshot sum-merge (no new exchange):

    * ``device_s`` — blocked fetching device results (the timed lockstep
      resolve fetch feeds ``stage_device_wait_seconds``);
    * ``exchange_post_s`` — inside ``host_allgather`` posts;
    * ``stall_s`` — resolve-blocked time not explained by the device
      fetch or the posts (verdict negotiation waits, assembly);
    * ``other_s`` — the loop's remainder (pack, launch, scheduling).

    Device fetch and most posts happen inside the resolve stall, so the
    shares partition the loop total.  Returns None when no lockstep loop
    ran in the window."""
    from .metrics import _delta_fn

    delta = _delta_fn(baseline, values)
    total = delta("multihost_lockstep_seconds_total")
    if total <= 0:
        return None
    stall = min(total, delta("multihost_window_stall_seconds_total"))
    device = min(total, delta("stage_device_wait_seconds"))
    exchange = min(total, delta("multihost_exchange_post_seconds_total"))
    residual_stall = max(0.0, stall - device - exchange)
    other = max(0.0, total - device - exchange - residual_stall)
    shares = {
        "device": device,
        "exchange_post": exchange,
        "stall": residual_stall,
        "other": other,
    }
    return {
        "lockstep_s": round(total, 3),
        "window_stall_s": round(stall, 3),
        "device_s": round(device, 3),
        "exchange_post_s": round(exchange, 3),
        "stall_residual_s": round(residual_stall, 3),
        "other_s": round(other, 3),
        "shares": {k: round(v / total, 4) for k, v in shares.items()},
    }


def device_profile_report(
    baseline: Optional[Dict[str, float]] = None,
    values: Optional[Dict[str, float]] = None,
) -> Dict[str, object]:
    """The run report's ``device_profile`` section.

    Dual-mode like the other report helpers: reads the live registry
    relative to ``baseline``, or a materialized ``values`` snapshot (e.g.
    the multi-host sum-merge — the HDR families merge bucket-wise, so the
    gang-wide quantiles are exact).  The cost model and top-K table are
    process-local: every host compiles the same programs, so the builder
    host's model speaks for the gang."""
    vals = values if values is not None else METRICS.all_values()
    base = baseline or {}
    dispatch: Dict[str, object] = {}
    for fam, length, phase in _discover_families(vals):
        buckets, sum_us, count = _hdr_delta(vals, base, fam)
        if count <= 0:
            continue
        mean_s = sum_us / count / 1e6
        entry: Dict[str, object] = {
            "count": count,
            "mean_s": round(mean_s, 6),
            "p50_s": round(hdr_quantile_us(buckets, count, 0.50) / 1e6, 6),
            "p95_s": round(hdr_quantile_us(buckets, count, 0.95) / 1e6, 6),
            "p99_s": round(hdr_quantile_us(buckets, count, 0.99) / 1e6, 6),
            "max_le_s": round(
                hdr_bucket_high_us(max(buckets)) / 1e6, 6
            ) if buckets else 0.0,
        }
        cost = PROFILER.modeled_cost(length, phase)
        if cost and mean_s > 0:
            entry["modeled_flops"] = int(cost.get("flops", 0))
            entry["modeled_bytes"] = int(cost.get("bytes_accessed", 0))
            entry["achieved_bytes_per_s"] = int(
                cost.get("bytes_accessed", 0) / mean_s
            )
            entry["achieved_flops_per_s"] = int(
                cost.get("flops", 0) / mean_s
            )
        dispatch[f"b{length}/p{phase}"] = entry
    # Roofline-style self-normalization: each (bucket, phase)'s achieved
    # bytes/s against the best achieved anywhere in the run — a program
    # far below 1.0 is stalling on something other than memory bandwidth.
    best = max(
        (
            e["achieved_bytes_per_s"]
            for e in dispatch.values()
            if "achieved_bytes_per_s" in e
        ),
        default=0,
    )
    if best > 0:
        for e in dispatch.values():
            if "achieved_bytes_per_s" in e:
                e["utilization_vs_best"] = round(
                    e["achieved_bytes_per_s"] / best, 4
                )
    report: Dict[str, object] = {
        "cost_fingerprint": PROFILER.cost_fingerprint(),
        "cost_model": PROFILER.cost_entries(),
        "dispatch": dispatch,
        "top_dispatches": PROFILER.top_dispatches(),
    }
    lockstep = lockstep_decomposition(baseline, values)
    if lockstep is not None:
        report["lockstep"] = lockstep
    return report


# --- regression sentinel -----------------------------------------------------

#: Default sentinel workload — the depfuse gate's filter mix (one program
#: family per device-stat kind), small enough to compile in CI yet broad
#: enough that a disabled fusion hatch moves its dispatch counts.
_SENTINEL_YAML = """
pipeline:
  - type: GopherRepetitionFilter
    dup_line_frac: 0.3
    top_n_grams: [[2, 0.25], [3, 0.28]]
    dup_n_grams: [[5, 0.15], [6, 0.16]]
  - type: GopherQualityFilter
    min_doc_words: 4
    min_stop_words: 1
    stop_words: [ "og", "the", "er", "i" ]
  - type: C4QualityFilter
    split_paragraph: false
    remove_citations: true
    filter_no_terminal_punct: true
    min_num_sentences: 1
    min_words_per_line: 2
    max_word_length: 1000
    filter_lorem_ipsum: true
    filter_javascript: true
    filter_curly_bracket: true
    filter_policy: true
"""


def collect_sentinel_profile(
    config=None,
    buckets: Tuple[int, ...] = (256, 512),
    batch_size: int = 16,
    costs: bool = True,
    aot_cache=None,
) -> Dict[str, object]:
    """Build the sentinel profile for one config + geometry.

    Per (bucket, phase) program: the ``jax.eval_shape`` scan dispatch
    counts (no compile — machine-independent and exact) and, with
    ``costs=True``, the static cost model from a real warmup (compile or
    AOT-sidecar).  ``costs=False`` skips every compile — enough for the
    fast dispatch-count half of ``--check``."""
    import jax

    from ..config.pipeline import parse_pipeline_config
    from ..ops.pipeline import CompiledPipeline
    from .compile_cache import _TRACE_ENV_KNOBS

    if config is None:
        config = parse_pipeline_config(_SENTINEL_YAML)
    pipeline = CompiledPipeline(
        config, buckets=tuple(buckets), batch_size=batch_size
    )
    fp = None
    table: Dict[str, Dict[str, int]] = {}
    if costs:
        was = PROFILER.enabled
        PROFILER.configure()
        try:
            pipeline.warmup_parallel(
                aot_cache=aot_cache, include_split_rows=False
            )
            table = PROFILER.cost_table()
            fp = PROFILER.cost_fingerprint()
        finally:
            PROFILER.enabled = was
    programs: Dict[str, object] = {}
    for _key, length, phase, rows in pipeline._warmup_jobs(
        include_split_rows=False
    ):
        pk = program_key(length, phase, rows)
        entry: Dict[str, object] = {
            "dispatch_counts": dict(
                sorted(pipeline.scan_dispatch_counts(length, phase, rows).items())
            )
        }
        if pk in table:
            entry["cost"] = table[pk]
        programs[pk] = entry
    return {
        "schema": SENTINEL_SCHEMA,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "buckets": list(buckets),
        "batch_size": int(batch_size),
        "env": {
            k: os.environ.get(k, "")
            for k in (*_TRACE_ENV_KNOBS, *_SCHEDULING_ENV_KNOBS)
        },
        "cost_fingerprint": fp,
        "programs": programs,
    }


def compare_profiles(
    base: Dict[str, object],
    current: Dict[str, object],
    warn_tol: float = 0.01,
    fail_tol: float = 0.05,
) -> Tuple[str, List[str]]:
    """Diff two sentinel profiles.  Returns ``(status, findings)`` with
    status "pass" / "warn" / "fail".

    Dispatch counts are exact: any difference fails, naming the drifted
    (bucket, phase) entries.  Cost fields get relative tolerance bands:
    within ``warn_tol`` passes silently, within ``fail_tol`` warns,
    beyond fails.  A program present on only one side fails."""
    findings: List[str] = []
    status = "pass"

    def worse(new: str) -> None:
        nonlocal status
        order = {"pass": 0, "warn": 1, "fail": 2}
        if order[new] > order[status]:
            status = new

    base_programs = dict(base.get("programs", {}))
    cur_programs = dict(current.get("programs", {}))
    for pk in sorted(set(base_programs) | set(cur_programs)):
        b, c = base_programs.get(pk), cur_programs.get(pk)
        if b is None or c is None:
            worse("fail")
            findings.append(
                f"FAIL {pk}: program {'appeared' if b is None else 'vanished'}"
            )
            continue
        bc = dict(b.get("dispatch_counts", {}))
        cc = dict(c.get("dispatch_counts", {}))
        if bc != cc:
            worse("fail")
            findings.append(
                f"FAIL {pk}: dispatch counts drifted {bc} -> {cc}"
            )
        b_cost = b.get("cost")
        c_cost = c.get("cost")
        if not b_cost or not c_cost:
            continue  # counts-only side: cost bands don't apply
        for field in COST_FIELDS:
            bv = int(b_cost.get(field, 0))
            cv = int(c_cost.get(field, 0))
            if bv == cv:
                continue
            rel = abs(cv - bv) / max(1, abs(bv))
            if rel > fail_tol:
                worse("fail")
                findings.append(
                    f"FAIL {pk}: {field} {bv} -> {cv} "
                    f"({rel:+.2%} > fail tolerance {fail_tol:.2%})"
                )
            elif rel > warn_tol:
                worse("warn")
                findings.append(
                    f"WARN {pk}: {field} {bv} -> {cv} "
                    f"({rel:+.2%} > warn tolerance {warn_tol:.2%})"
                )
    b_fp = base.get("cost_fingerprint")
    c_fp = current.get("cost_fingerprint")
    if b_fp and c_fp and b_fp != c_fp and status == "pass":
        # Every field inside tolerance but the table is not bit-identical:
        # surface it without failing (jax-version flop-model churn).
        findings.append(
            f"NOTE cost fingerprint drifted within tolerance: "
            f"{b_fp[:12]} -> {c_fp[:12]}"
        )
    return status, findings


#: Scheduling-only knobs the drift note also names: they must NEVER change
#: per-(bucket, phase) dispatch counts (TEXTBLAST_SPECULATE moves multi-host
#: launches across phase barriers, not programs, and
#: TEXTBLAST_STAGE_DEADLINE_S only bounds host-side waits), so they are
#: deliberately NOT in compile_cache._TRACE_ENV_KNOBS — but if counts ever
#: drift with one set, the note points straight at it instead of leaving a
#: silent diff.
_SCHEDULING_ENV_KNOBS = (
    "TEXTBLAST_SPECULATE",
    "TEXTBLAST_NO_OVERLAP",
    "TEXTBLAST_STAGE_DEADLINE_S",
    "TEXTBLAST_EVENTS",
    "TEXTBLAST_SLO",
)


def _env_drift_note(base: Dict[str, object]) -> List[str]:
    """Informational lines when the check environment's trace-shaping
    knobs differ from the baseline's record — the usual root cause when
    dispatch counts drift (e.g. TEXTBLAST_DEPFUSE=off).  Scheduling knobs
    absent from older baselines compare against "" (their recorded-empty
    default), so no baseline regeneration is needed to get them named."""
    notes = []
    env = dict(base.get("env", {}))
    for k in _SCHEDULING_ENV_KNOBS:
        env.setdefault(k, "")
    for k, bv in sorted(env.items()):
        cv = os.environ.get(k, "")
        if cv != bv:
            notes.append(f"NOTE env {k}={cv!r} (baseline recorded {bv!r})")
    return notes


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m textblaster_tpu.utils.profiler",
        description=(
            "Machine-independent perf-regression sentinel: record or check "
            "the per-program cost fingerprint + scan dispatch counts."
        ),
    )
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument(
        "--baseline", metavar="OUT.JSON",
        help="Compile the sentinel workload and write the baseline profile",
    )
    mode.add_argument(
        "--check", metavar="BASELINE.JSON",
        help="Re-profile and diff against a recorded baseline",
    )
    ap.add_argument(
        "--config", default=None,
        help="Pipeline YAML (default: the embedded sentinel workload)",
    )
    ap.add_argument("--buckets", default="256,512")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--warn-tol", type=float, default=0.01)
    ap.add_argument("--fail-tol", type=float, default=0.05)
    ap.add_argument(
        "--no-interpret", action="store_true",
        help="Do not force TEXTBLAST_PALLAS_INTERPRET=1 (default forces it "
             "so the profile is deterministic on CPU)",
    )
    ap.add_argument(
        "--counts-only", action="store_true",
        help="With --check: diff only the eval_shape dispatch counts (no "
             "compiles) — the machine-independent exact half, fast enough "
             "for a tier-1 CI gate",
    )
    args = ap.parse_args(argv)

    if args.check and not os.path.exists(args.check):
        print(
            f"SKIP: no baseline at {args.check} — generate one with "
            f"--baseline {args.check}"
        )
        return 0

    if not args.no_interpret:
        # Deterministic CPU path; setdefault so a deliberate hatch flip
        # (e.g. TEXTBLAST_DEPFUSE=off) stays visible to the check.
        os.environ.setdefault("TEXTBLAST_PALLAS_INTERPRET", "1")

    # Honor the watchdog env knob so the guard test "sentinel stays PASS
    # with the watchdog armed" exercises the sentinel workload under the
    # same runtime configuration a supervised run would use (the knob is
    # scheduling-only: dispatch counts must not move).
    from ..resilience.watchdog import WATCHDOG

    WATCHDOG.configure_from_env()

    config = None
    if args.config:
        from ..config.pipeline import load_pipeline_config

        config = load_pipeline_config(args.config)
    buckets = tuple(
        sorted(int(x) for x in args.buckets.split(",") if x.strip())
    )
    batch = int(args.batch_size)

    if args.baseline:
        profile = collect_sentinel_profile(
            config, buckets=buckets, batch_size=batch, costs=True
        )
        parent = os.path.dirname(os.path.abspath(args.baseline))
        os.makedirs(parent, exist_ok=True)
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(profile, f, indent=2, sort_keys=True)
            f.write("\n")
        print(
            f"BASELINE {args.baseline}: {len(profile['programs'])} programs, "
            f"cost fingerprint {str(profile['cost_fingerprint'])[:12]}"
        )
        return 0

    with open(args.check, "r", encoding="utf-8") as f:
        base = json.load(f)
    if base.get("schema") != SENTINEL_SCHEMA:
        print(
            f"FAIL: baseline schema {base.get('schema')!r} != "
            f"{SENTINEL_SCHEMA!r} — regenerate with --baseline"
        )
        return 1
    buckets = tuple(base.get("buckets", buckets))
    batch = int(base.get("batch_size", batch))
    # Two-stage check: the eval_shape dispatch counts are free — if they
    # already drifted, fail before paying a single compile.
    counts_only = collect_sentinel_profile(
        config, buckets=buckets, batch_size=batch, costs=False
    )
    status, findings = compare_profiles(
        base, counts_only, args.warn_tol, args.fail_tol
    )
    if status == "fail":
        findings.append(
            "NOTE cost comparison skipped: dispatch counts already failed"
        )
    elif args.counts_only:
        findings.append("NOTE cost comparison skipped: --counts-only")
    else:
        full = collect_sentinel_profile(
            config, buckets=buckets, batch_size=batch, costs=True
        )
        status, findings = compare_profiles(
            base, full, args.warn_tol, args.fail_tol
        )
    if status != "pass":
        findings.extend(_env_drift_note(base))
    for line in findings:
        print(line)
    n = len(base.get("programs", {}))
    print(
        f"{status.upper()}: {n} programs checked against {args.check} "
        f"(warn tol {args.warn_tol:.2%}, fail tol {args.fail_tol:.2%})"
    )
    return 1 if status == "fail" else 0


if __name__ == "__main__":  # pragma: no cover - CLI entry
    import sys

    # Under ``python -m`` this file runs as ``__main__`` — a SECOND module
    # instance with its own PROFILER singleton, distinct from the one the
    # pipeline seams import.  Delegate to the canonical module so
    # configure() arms the instance the warmup actually checks.
    from textblaster_tpu.utils.profiler import main as _main

    sys.exit(_main())
