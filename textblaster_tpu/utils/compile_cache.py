"""Persistent XLA compilation cache setup.

The filter-pipeline programs are large graphs (every filter traced into one
``jit`` per shape bucket), and remote TPU compiles through the axon tunnel
take minutes; a persistent on-disk cache makes repeat runs (tests, the
driver's bench, CLI re-invocations) near-instant.  Shared by ``bench.py``,
``tests/conftest.py``, and the CLI.
"""

from __future__ import annotations

import os

__all__ = ["enable_compilation_cache", "DEFAULT_CACHE_DIR"]

#: Repo-local cache directory (gitignored).
DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".cache",
    "jax",
)


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created if
    missing).  Returns the directory used.

    ``TEXTBLAST_NO_COMPILE_CACHE=1`` turns this into a no-op (measurement
    escape hatch: cache-loaded XLA:CPU executables can differ in performance
    from the in-memory JIT result of a fresh compile)."""
    import jax

    if os.environ.get("TEXTBLAST_NO_COMPILE_CACHE") == "1":
        return ""
    cache_dir = cache_dir or DEFAULT_CACHE_DIR
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir
