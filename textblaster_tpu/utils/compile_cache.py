"""Persistent compilation caches.

Two layers, both rooted in the repo-local (gitignored) ``.cache/``:

1. **XLA's built-in compilation cache** (:func:`enable_compilation_cache`)
   — skips the XLA *compile*, but every process still pays trace + lower
   per program (~seconds each for the fused filter graphs).
2. **Serialized AOT executable store** (:class:`AOTExecutableCache`) —
   pickles ``jax.experimental.serialize_executable.serialize()`` payloads
   per program, keyed by everything that shapes the traced computation
   (geometry + filter-config fingerprints, jax/jaxlib versions, backend,
   device topology, program shape, trace-shaping env knobs, and a
   content hash of this package's sources).  A warm start loads finished
   executables and skips trace, lower, *and* compile —
   ``CompiledPipeline.warmup_parallel`` consults it first.

``TEXTBLAST_NO_COMPILE_CACHE=1`` bypasses both layers (measurement escape
hatch: cache-loaded XLA:CPU executables can differ in performance from the
in-memory JIT result of a fresh compile).

Entries that fail to unpickle or to deserialize (corrupt, truncated, or
written by an incompatible runtime that slipped past the key) are evicted
and silently recompiled — a cache problem must never take down a run.
The store is size-capped (``TEXTBLAST_AOT_CACHE_MB``, default 512) with
least-recently-*used* eviction: loads touch the entry's mtime.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import logging
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "enable_compilation_cache",
    "DEFAULT_CACHE_DIR",
    "DEFAULT_AOT_DIR",
    "AOTExecutableCache",
    "aot_cache_enabled",
    "aot_cache_supported",
    "config_fingerprint",
    "program_cache_key",
]

_CACHE_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".cache",
)

#: XLA compilation-cache directory (gitignored).
DEFAULT_CACHE_DIR = os.path.join(_CACHE_ROOT, "jax")

#: Serialized-executable store directory (gitignored).
DEFAULT_AOT_DIR = os.path.join(_CACHE_ROOT, "aot")

_SUFFIX = ".aotx"

#: Static cost-model sidecar written next to each executable entry
#: (``<key>.cost.json``): the ``cost_analysis``/``memory_analysis``
#: numbers captured at compile time, so an AOT cache hit keeps the exact
#: cost model of the compile that produced it (re-running the analyses on
#: a deserialized executable is backend-dependent).  Sidecars ride their
#: entry's lifecycle — evicted together, never counted against the size
#: cap (a few hundred bytes each).
_COST_SUFFIX = ".cost.json"


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (created if
    missing).  Returns the directory used.

    ``TEXTBLAST_NO_COMPILE_CACHE=1`` turns this into a no-op (measurement
    escape hatch: cache-loaded XLA:CPU executables can differ in performance
    from the in-memory JIT result of a fresh compile)."""
    import jax

    if os.environ.get("TEXTBLAST_NO_COMPILE_CACHE") == "1":
        return ""
    cache_dir = cache_dir or DEFAULT_CACHE_DIR
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir


def aot_cache_enabled() -> bool:
    """The executable store honors the same bypass as the XLA cache."""
    return os.environ.get("TEXTBLAST_NO_COMPILE_CACHE") != "1"


@functools.lru_cache(maxsize=1)
def aot_cache_supported() -> bool:
    """Whether the installed jax has the AOT serialization API.

    ``jax.export`` only round-trips StableHLO — the importer still pays a
    full XLA compile, which is the cost this cache exists to skip —
    so the *executable*-level ``serialize_executable`` API is required."""
    try:
        from jax.experimental.serialize_executable import (  # noqa: F401
            deserialize_and_load,
            serialize,
        )

        return True
    except Exception:  # pragma: no cover - older/partial jax builds
        return False


# --- cache keys -------------------------------------------------------------


def config_fingerprint(config: Any) -> str:
    """Filter-config fingerprint: step types + params as stable JSON (the
    same recipe the checkpoint manifest uses, re-implemented here so the
    cache layer stays import-light)."""
    steps = getattr(config, "pipeline", config)
    blob = json.dumps(
        [{"type": s.type, "params": dataclasses.asdict(s.params)} for s in steps],
        sort_keys=True,
        default=str,
    )
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


#: Env knobs that change the *traced program* (scan schedule, table impl,
#: wire dtype, phase layout, Pallas kernel selection).  Two processes whose
#: knobs differ must never share an executable.
_TRACE_ENV_KNOBS = (
    "TEXTBLAST_SCAN_IMPL",
    "TEXTBLAST_TABLE_IMPL",
    "TEXTBLAST_WIRE",
    "TEXTBLAST_PHASES",
    "TEXTBLAST_PALLAS",
    "TEXTBLAST_NO_PALLAS",
    "TEXTBLAST_PALLAS_INTERPRET",
    "TEXTBLAST_FUSED",
    "TEXTBLAST_DEPFUSE",
)


@functools.lru_cache(maxsize=1)
def _code_fingerprint() -> str:
    """Content hash of this package's sources.  The traced program changes
    whenever the kernels change; jax/config versioning alone would happily
    serve an executable compiled from last week's code."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for root, dirs, files in sorted(os.walk(pkg_dir)):
        dirs.sort()
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            h.update(os.path.relpath(path, pkg_dir).encode("utf-8"))
            try:
                with open(path, "rb") as f:
                    h.update(f.read())
            except OSError:  # pragma: no cover - racing an editor
                continue
    return h.hexdigest()[:16]


def program_cache_key(
    *,
    config_fp: str,
    geometry_fp: str,
    backend: str,
    length: int,
    phase: int,
    rows: int,
    wire: str,
    n_devices: int = 1,
    mesh: bool = False,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Stable key for one compiled program.  Everything that shapes the
    trace or the executable's validity participates; any mismatch is a
    cache miss, never a wrong program."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover
        jaxlib_version = "?"
    parts = {
        "code": _code_fingerprint(),
        "config": config_fp,
        "geometry": geometry_fp,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": backend,
        "n_devices": n_devices,
        "mesh": bool(mesh),
        "processes": jax.process_count(),
        "length": length,
        "phase": phase,
        "rows": rows,
        "wire": wire,
        "x64": bool(jax.config.jax_enable_x64),
        "env": {k: os.environ.get(k, "") for k in _TRACE_ENV_KNOBS},
    }
    if extra:
        parts["extra"] = extra
    blob = json.dumps(parts, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:32]


# --- the store --------------------------------------------------------------


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class AOTExecutableCache:
    """On-disk store of serialized compiled executables.

    ``load``/``store`` never raise for cache-side problems: a missing,
    corrupt, or incompatible entry is a miss (and is evicted), a failed
    write is a warning.  Writes are atomic (tmp + rename) so concurrent
    warmup threads and sibling processes can share the directory."""

    def __init__(
        self, cache_dir: Optional[str] = None, max_bytes: Optional[int] = None
    ) -> None:
        self.cache_dir = (
            cache_dir
            or os.environ.get("TEXTBLAST_AOT_CACHE_DIR")
            or DEFAULT_AOT_DIR
        )
        if max_bytes is None:
            max_bytes = int(
                float(os.environ.get("TEXTBLAST_AOT_CACHE_MB", "512")) * 1_000_000
            )
        self.max_bytes = max_bytes

    def _path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + _SUFFIX)

    def _cost_path(self, key: str) -> str:
        return os.path.join(self.cache_dir, key + _COST_SUFFIX)

    def load_cost(self, key: str):
        """The cost-model sidecar for ``key`` as a dict, or None (absent,
        bypassed, corrupt — the latter evicted, like executables)."""
        if not aot_cache_enabled():
            return None
        path = self._cost_path(key)
        try:
            with open(path, "r", encoding="utf-8") as f:
                cost = json.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt / truncated
            logger.warning("evicting corrupt cost sidecar %s: %s", key, e)
            _unlink_quiet(path)
            return None
        if not isinstance(cost, dict):
            _unlink_quiet(path)
            return None
        return cost

    def store_cost(self, key: str, cost) -> bool:
        """Write the cost-model sidecar for ``key`` (atomic tmp + rename);
        returns True on success.  Failures are warnings, never fatal."""
        if not aot_cache_enabled() or not isinstance(cost, dict):
            return False
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump(cost, f, sort_keys=True)
                os.replace(tmp, self._cost_path(key))
            finally:
                if os.path.exists(tmp):  # replace failed
                    _unlink_quiet(tmp)
        except OSError as e:  # pragma: no cover - disk full etc.
            logger.warning("cost sidecar write failed for %s: %s", key, e)
            return False
        return True

    def load(self, key: str):
        """Return the deserialized executable for ``key``, or None on any
        miss (absent, bypassed, unsupported, corrupt — the latter evicted)."""
        if not (aot_cache_enabled() and aot_cache_supported()):
            return None
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt / truncated / wrong pickle
            logger.warning("evicting corrupt AOT cache entry %s: %s", key, e)
            _unlink_quiet(path)
            _unlink_quiet(self._cost_path(key))
            return None
        try:
            from jax.experimental.serialize_executable import deserialize_and_load

            compiled = deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # runtime/topology mismatch that beat the key
            logger.warning("evicting unloadable AOT cache entry %s: %s", key, e)
            _unlink_quiet(path)
            _unlink_quiet(self._cost_path(key))
            return None
        try:
            os.utime(path, None)  # LRU recency
        except OSError:  # pragma: no cover
            pass
        return compiled

    def store(self, key: str, compiled) -> bool:
        """Serialize ``compiled`` under ``key``; returns True on success.
        Backends whose executables do not serialize simply decline."""
        if not (aot_cache_enabled() and aot_cache_supported()):
            return False
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
                serialize,
            )

            payload, in_tree, out_tree = serialize(compiled)
            # Validate before writing: executables XLA served from its own
            # persistent compilation cache serialize without their kernel
            # object code ("Symbols not found" on load, XLA:CPU) — a store
            # that every future process would evict is worse than no store.
            deserialize_and_load(payload, in_tree, out_tree)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception as e:
            logger.debug("AOT serialize declined for %s: %s", key, e)
            return False
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._path(key))
            finally:
                if os.path.exists(tmp):  # replace failed
                    _unlink_quiet(tmp)
        except OSError as e:  # pragma: no cover - disk full etc.
            logger.warning("AOT cache write failed for %s: %s", key, e)
            return False
        self._evict_lru()
        return True

    def _entries(self):
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                st = os.stat(path)
            except OSError:  # racing another evictor
                continue
            out.append((st.st_mtime, st.st_size, path))
        return out

    def _evict_lru(self) -> int:
        """Drop least-recently-used entries until under the size cap.
        Returns the number evicted."""
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        evicted = 0
        for _, size, path in sorted(entries):
            if total <= self.max_bytes:
                break
            _unlink_quiet(path)
            _unlink_quiet(path[: -len(_SUFFIX)] + _COST_SUFFIX)
            total -= size
            evicted += 1
        if evicted:
            logger.info("AOT cache evicted %d entr%s (size cap %d bytes)",
                        evicted, "y" if evicted == 1 else "ies", self.max_bytes)
        return evicted

    def size_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())
