"""Structured operational event journal (JSONL, severity-leveled).

The trace (utils/trace.py) answers *where did the time go*; the metrics
registry answers *how much*; neither answers the operator's question
*what happened, in what order* when a run degrades or dies.  Every
operational transition — retries, breaker trips, negotiated verdicts,
peer failures, gang reformations, joins/evictions, watchdog stalls,
speculation voids, geometry drift, checkpoint commits, warmup outcomes —
today exists only as a stderr one-liner or a Perfetto instant.  This
module gives them one durable, machine-readable record:

* **One journal per rank**, JSONL (one JSON object per line), spilled
  incrementally from a bounded ring so a killed run still leaves a
  readable prefix on disk (each line is self-contained — no terminator
  needed, unlike the trace's JSON array).
* **Monotone sequence numbers** per rank, so the order of events is
  recoverable even if timestamps collide.
* **Aligned timestamps**: ``ts_us`` comes from ``TRACER.now_us()`` — the
  PR 6 cross-host aligned trace clock — so journals from every rank of a
  gang interleave on one timeline.  With tracing off the clock degrades
  to raw ``perf_counter`` microseconds (monotone per process).
* **Rank/incarnation/epoch stamping**: each record carries the emitting
  rank, its incarnation (bumped on gang reformation), and the membership
  epoch read live from the metrics registry, so postmortems can attribute
  every line to a precise gang configuration.
* **Near-zero cost when off.**  Journaling is opt-in; disarmed, every
  seam is a single ``EVENTS.enabled`` attribute check — same contract as
  TRACER / TELEMETRY / WATCHDOG.

The record schema is closed: every ``kind`` is enumerated in :data:`KINDS`
with its default severity and required data fields, and ``emit()``
validates against it — an unknown kind or missing field is counted
(``events_invalid_total``) and dropped rather than poisoning consumers.
Per-kind counts are mirrored into the metrics registry as dynamic
``events_total_<kind>`` counters, so the existing multihost ``all_values``
sum-merge aggregates gang-wide event counts for free (run-report v4).

The **flight recorder** (:func:`flight_record`) is the crash-path
consumer: on any fatal exit it snapshots the last-N journal events, the
full metrics registry, live telemetry rollups, and SLO state into
``<output>.flightrec/rank<r>.json`` (atomic tmp+rename), so postmortems
never depend on a scrollback buffer.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "KINDS",
    "SEVERITIES",
    "EventJournal",
    "EVENTS",
    "JournalLogHandler",
    "flight_record",
    "validate_record",
]

#: Severity ladder, least to most severe.  ``emit(severity=...)`` may
#: upgrade a kind's default (e.g. a retry that exhausted its budget) but
#: every value must come from this set.
SEVERITIES = ("info", "warning", "error", "critical")

#: The closed event vocabulary: ``kind -> (default severity, required
#: data fields)``.  Adding a kind here is a schema change — consumers
#: (run-report v4, the flight recorder, downstream log shippers) key on
#: these names, and the schema lint test enumerates every ``emit()`` call
#: site against this table.
KINDS: Dict[str, tuple] = {
    # -- resilience: retry ladder / circuit breaker ---------------------------
    "retry": ("warning", ("seam", "attempt", "error")),
    "retry_exhausted": ("error", ("seam", "attempts", "error")),
    "breaker_trip": ("error", ("seam", "failures")),
    "breaker_probe": ("info", ("seam",)),
    "breaker_recovery": ("info", ("seam",)),
    "breaker_reopen": ("warning", ("seam",)),
    "ladder_split": ("warning", ("batch", "depth")),
    "ladder_host": ("warning", ("batch",)),
    # -- negotiated lockstep rounds -------------------------------------------
    "negotiated_verdict": ("warning", ("bucket", "attempt")),
    "negotiated_retry": ("warning", ("bucket", "attempt")),
    "negotiated_degraded": ("warning", ("bucket",)),
    "negotiated_reformed": ("warning", ("bucket",)),
    # -- gang membership / reformation / elastic join -------------------------
    "peer_failure": ("critical", ("missing_ranks",)),
    "gang_reform_start": ("warning", ("epoch",)),
    "gang_reformation": ("warning", ("epoch", "world_size")),
    "gang_admission_start": ("info", ("epoch",)),
    "gang_admission": ("info", ("epoch", "world_size")),
    "membership_join": ("info", ("rank", "epoch")),
    "membership_rejoin": ("info", ("rank", "epoch")),
    "membership_evict": ("warning", ("rank", "epoch")),
    "rank_fenced": ("warning", ("rank",)),
    "join_request": ("info", ("rank",)),
    "stripe_adopted": ("warning", ("stripe", "adopter")),
    "autoscale_spawn": ("info", ("rank",)),
    # -- watchdog / speculation / drift ---------------------------------------
    "watchdog_stall": ("error", ("stage", "elapsed_s", "deadline_s")),
    "watchdog_escalation": ("critical", ("reason",)),
    "speculation_void": ("warning", ("voided", "cause")),
    "geometry_drift": ("warning", ("ratio",)),
    "window_depth_mismatch": ("warning", ("joint",)),
    # -- durability / startup -------------------------------------------------
    "checkpoint_commit": ("info", ("chunk",)),
    "checkpoint_adopted": ("warning", ("owner",)),
    "warmup_complete": ("info", ("programs", "total_s", "cache_hits")),
    # -- SLO engine / logging bridge / run lifecycle --------------------------
    "slo_alert": ("error", ("key", "burn_rate", "window_s")),
    "slo_resolved": ("info", ("key",)),
    "log": ("warning", ("logger", "message")),
    "run_start": ("info", ()),
    "run_end": ("info", ("exit_code",)),
    "fatal": ("critical", ("reason",)),
}


def validate_record(record: Dict[str, Any]) -> None:
    """Raise ``ValueError`` unless ``record`` is a schema-valid journal
    record: enumerated kind, legal severity, required envelope fields,
    and every kind-mandated data field present."""
    for field in ("seq", "ts_us", "kind", "severity", "rank", "incarnation",
                  "epoch", "data"):
        if field not in record:
            raise ValueError(f"journal record missing field {field!r}")
    kind = record["kind"]
    spec = KINDS.get(kind)
    if spec is None:
        raise ValueError(f"unknown event kind {kind!r}")
    if record["severity"] not in SEVERITIES:
        raise ValueError(f"illegal severity {record['severity']!r}")
    data = record["data"]
    if not isinstance(data, dict):
        raise ValueError("data must be a mapping")
    missing = [f for f in spec[1] if f not in data]
    if missing:
        raise ValueError(f"kind {kind!r} missing data fields {missing}")


class EventJournal:
    """Thread-safe, monotonically-sequenced operational event journal.

    Mirrors the Tracer's bounded-ring + incremental-spill + drop-accounting
    design (utils/trace.py) but writes JSONL and additionally keeps a
    small ``recent`` deque that survives spills — the flight recorder's
    last-N view must not go empty just because the ring flushed."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self._ring_cap = 4096
        self._recent: Deque[Dict[str, Any]] = deque(maxlen=256)
        self._counts: Dict[str, int] = {}
        self._seq = 0
        self._dropped = 0
        self._invalid = 0
        self._warned_drop = False
        self._path: Optional[str] = None
        self._fh = None
        self._rank = 0
        self._incarnation = 0

    # --- lifecycle ----------------------------------------------------------

    def configure(
        self,
        path: Optional[str] = None,
        *,
        rank: int = 0,
        incarnation: int = 0,
        ring: int = 4096,
        recent: int = 256,
    ) -> None:
        """Arm the journal.  ``path=None`` keeps events in the bounded ring
        only (test / SLO-only mode); otherwise the ring spills to ``path``
        as JSONL whenever it fills and on ``close()``."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            self._ring = []
            self._ring_cap = max(16, int(ring))
            self._recent = deque(maxlen=max(16, int(recent)))
            self._counts = {}
            self._seq = 0
            self._dropped = 0
            self._invalid = 0
            self._warned_drop = False
            self._path = path
            self._fh = None
            self._rank = int(rank)
            self._incarnation = int(incarnation)
            if path is not None:
                parent = os.path.dirname(os.path.abspath(path))
                os.makedirs(parent, exist_ok=True)
                self._fh = open(path, "w", encoding="utf-8")
            self.enabled = True

    def set_incarnation(self, incarnation: int) -> None:
        """Bump the incarnation stamp (gang reformation elected a new
        configuration); subsequent records carry the new value."""
        self._incarnation = int(incarnation)

    def close(self) -> None:
        """Flush the ring to the spill file and disarm."""
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
            if self._fh is not None:
                self._spill_locked()
                if self._fh is not None:  # spill failure closes the file
                    try:
                        self._fh.close()
                    except OSError as e:
                        logger.warning(
                            "Event journal close on %s failed: %s",
                            self._path, e,
                        )
                    self._fh = None
            if self._dropped:
                logger.warning(
                    "Event journal dropped %d events (ring overflow or "
                    "unwritable spill file)", self._dropped,
                )

    def drain(self) -> List[Dict[str, Any]]:
        """Return and clear the in-memory ring (test hook)."""
        with self._lock:
            out, self._ring = self._ring, []
            return out

    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The last-N emitted records (flight-recorder view); survives
        ring spills, newest last."""
        with self._lock:
            out = list(self._recent)
        return out if n is None else out[-int(n):]

    def counts(self) -> Dict[str, int]:
        """Per-kind emit counts since ``configure()``."""
        with self._lock:
            return dict(self._counts)

    # --- recording ----------------------------------------------------------

    def emit(self, kind: str, severity: Optional[str] = None, **data: Any) -> None:
        """Record one event.  ``severity`` defaults from :data:`KINDS`;
        schema violations are counted and dropped, never raised — the
        journal must not take down the pipeline it is documenting."""
        if not self.enabled:
            return
        spec = KINDS.get(kind)
        if spec is None or severity is not None and severity not in SEVERITIES:
            self._count_invalid(kind)
            return
        missing = [f for f in spec[1] if f not in data]
        if missing:
            self._count_invalid(kind)
            return
        # Epoch is read live so records emitted across a reformation carry
        # the membership generation they happened under.
        from .metrics import EVENT_KIND_PREFIX, METRICS
        from .trace import TRACER

        record = {
            "seq": 0,  # assigned under the lock below
            "ts_us": TRACER.now_us(),
            "kind": kind,
            "severity": severity or spec[0],
            "rank": self._rank,
            "incarnation": self._incarnation,
            "epoch": int(METRICS.get("multihost_membership_epoch")),
            "data": data,
        }
        with self._lock:
            if not self.enabled:  # closed concurrently
                return
            self._seq += 1
            record["seq"] = self._seq
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._recent.append(record)
            self._append_locked(record)
        METRICS.inc("events_emitted_total")
        METRICS.inc(EVENT_KIND_PREFIX + kind)

    # --- internals ----------------------------------------------------------

    def _count_invalid(self, kind: str) -> None:
        from .metrics import METRICS

        with self._lock:
            self._invalid += 1
            first = self._invalid == 1
        METRICS.inc("events_invalid_total")
        if first:
            logger.warning(
                "Event journal dropped a schema-invalid record (kind=%r); "
                "further violations counted in events_invalid_total", kind,
            )

    def _append_locked(self, record: Dict[str, Any]) -> None:
        self._ring.append(record)
        if len(self._ring) >= self._ring_cap:
            if self._fh is not None:
                self._spill_locked()
            else:
                # Ring-only mode: drop the oldest half, keep counting.
                drop = len(self._ring) // 2
                self._count_dropped_locked(drop)
                del self._ring[:drop]

    def _count_dropped_locked(self, n: int) -> None:
        """Account ``n`` dropped events: local counter, the
        ``events_dropped_total`` metric, and a one-line stderr warning on
        the first drop — same contract as the trace ring."""
        self._dropped += n
        first = not self._warned_drop
        self._warned_drop = True
        from .metrics import METRICS

        METRICS.inc("events_dropped_total", n)
        if first:
            print(
                f"textblast: journal events dropped ({n} so far) — ring "
                "overflow or unwritable spill file; the event journal "
                "will be incomplete",
                file=sys.stderr,
            )

    def _spill_locked(self) -> None:
        if not self._ring:
            return
        chunks = []
        for record in self._ring:
            chunks.append(json.dumps(record, separators=(",", ":")))
            chunks.append("\n")
        try:
            self._fh.write("".join(chunks))
            self._fh.flush()
        except OSError as e:
            self._count_dropped_locked(len(self._ring))
            logger.warning(
                "Event journal spill to %s failed: %s", self._path, e
            )
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        self._ring = []


#: Process-wide journal.  Import this, never construct your own — emit
#: sites across the codebase all talk to the same instance.
EVENTS = EventJournal()


class JournalLogHandler(logging.Handler):
    """Routes WARNING+ log records into the event journal when armed.

    Installed once by ``utils/logging_setup.init_logging`` on the root
    logger; per-record cost while the journal is disarmed is the
    ``EVENTS.enabled`` check.  Records from the journal's own logger are
    skipped outright (drop / invalid-record diagnostics are accounted in
    ``events_*_total``, not re-journaled), and a thread-local reentrancy
    guard prevents recursion when capture itself logs."""

    _reentrant = threading.local()

    def __init__(self) -> None:
        super().__init__(level=logging.WARNING)

    def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
        if not EVENTS.enabled:
            return
        if record.name == __name__:
            # The journal's own diagnostics (drop / invalid-record
            # accounting) are already counted in events_*_total;
            # re-journaling them would feed the journal its own exhaust.
            return
        if getattr(self._reentrant, "active", False):
            return
        self._reentrant.active = True
        try:
            severity = "error" if record.levelno >= logging.ERROR else "warning"
            EVENTS.emit(
                "log",
                severity=severity,
                logger=record.name,
                level=record.levelname,
                message=record.getMessage(),
            )
        except Exception:  # pragma: no cover - never break logging
            pass
        finally:
            self._reentrant.active = False


#: Flight-recorder dump schema tag.
FLIGHTREC_SCHEMA = "textblaster-flightrec/v1"


def flight_record(
    base_path: str,
    *,
    rank: int = 0,
    reason: str = "fatal",
    exc: Optional[BaseException] = None,
) -> Optional[str]:
    """Write a crash flight-recorder dump for this rank.

    ``base_path`` is the run's output path (or any stable per-run path);
    the dump lands at ``<base_path>.flightrec/rank<r>.json`` via atomic
    tmp+fsync+rename so a concurrent scraper never sees a torn file.
    The payload bundles everything a postmortem needs without scrollback:
    the last-N journal events, per-kind counts, the full metrics registry,
    live telemetry rollups, and SLO state.  Best-effort by construction —
    returns the written path, or None if anything failed (the fatal path
    that called us must still exit cleanly)."""
    try:
        from .metrics import METRICS

        payload: Dict[str, Any] = {
            "schema": FLIGHTREC_SCHEMA,
            "reason": reason,
            "rank": int(rank),
            "incarnation": EVENTS._incarnation,
            "ts_us": None,
            "exception": None,
            "events_recent": EVENTS.recent(),
            "events_counts": EVENTS.counts(),
            "events_dropped": EVENTS._dropped,
            "metrics": METRICS.all_values(),
        }
        from .trace import TRACER

        payload["ts_us"] = TRACER.now_us()
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
            }
        try:
            from .telemetry import TELEMETRY

            if TELEMETRY.enabled:
                payload["telemetry"] = TELEMETRY.snapshot()
        except Exception:  # pragma: no cover - rollup must not kill the dump
            pass
        try:
            from .slo import SLO

            if SLO.enabled:
                payload["slo"] = SLO.snapshot()
        except Exception:  # pragma: no cover
            pass

        out_dir = base_path + ".flightrec"
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"rank{int(rank)}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception as e:  # pragma: no cover - best-effort by contract
        logger.warning("Flight-recorder dump failed: %s", e)
        return None
