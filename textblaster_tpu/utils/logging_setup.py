"""Structured logging: console WARN + daily-rolling JSON file.

Equivalent of the reference's two-layer tracing subscriber
(``/root/reference/src/bin/producer.rs:58-83``, ``bin/worker.rs:53-80``):
console at WARN, JSON lines to ``./log/<name>.log`` with daily rotation, and
the global level taken from an env var (``TEXTBLAST_LOG``, standing in for
``RUST_LOG``).
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import os
from datetime import datetime, timezone

__all__ = ["init_logging"]


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            # record.created, not now(): format time lags emit time whenever
            # the handler queue backs up, and post-mortem ordering depends
            # on the emit-time stamp.
            "timestamp": datetime.fromtimestamp(
                record.created, timezone.utc
            ).isoformat(),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            payload["exception"] = self.formatException(record.exc_info)
        for key in ("doc_id", "step", "worker_id"):
            if hasattr(record, key):
                payload[key] = getattr(record, key)
        return json.dumps(payload, ensure_ascii=False)


def init_logging(name: str, log_dir: str = "./log") -> None:
    level_name = os.environ.get("TEXTBLAST_LOG", "INFO").upper()
    level = getattr(logging, level_name, logging.INFO)

    root = logging.getLogger()
    root.setLevel(level)
    # Drop handlers from any previous init (idempotent for tests).
    for h in list(root.handlers):
        root.removeHandler(h)

    console = logging.StreamHandler()
    console.setLevel(logging.WARNING)
    console.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    root.addHandler(console)

    # WARNING+ records mirror into the operational event journal (kind
    # "log") once EVENTS is armed; the handler self-gates on
    # EVENTS.enabled, so an unarmed run pays one attribute check per
    # warning — not per log call.
    from .events import JournalLogHandler

    root.addHandler(JournalLogHandler())

    try:
        os.makedirs(log_dir, exist_ok=True)
        file_handler = logging.handlers.TimedRotatingFileHandler(
            os.path.join(log_dir, f"{name}.log"), when="midnight", utc=True
        )
        file_handler.setLevel(level)
        file_handler.setFormatter(_JsonFormatter())
        root.addHandler(file_handler)
    except OSError:
        logging.getLogger(__name__).warning(
            "Could not open log file in %s; console only.", log_dir
        )
