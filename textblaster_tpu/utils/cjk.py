"""Dictionary-lite CJK word segmentation (VERDICT r4 item 8).

The reference's ``WordSegmenter::new_auto()``
(``/root/reference/src/utils/text.rs:107``) dictionary-segments Han/kana and
Thai runs; the UAX#29-lite splitter here previously kept such runs whole, so
every word-count-driven decision (Gopher/C4/FineWeb) diverged on CJK text.
This module closes the zh side with a real frequency lexicon and bounds the
rest:

* **Script boundaries** (Han↔Hiragana↔Katakana↔Latin…) are always breaks —
  ICU's CJ dictionary never emits a token spanning scripts.
* **Han runs** are greedy-longest-match segmented against a lexicon derived
  from the ``jieba`` package's ``dict.txt`` (≈350k entries with corpus
  frequencies; jieba ships in this image — no network).  Out-of-lexicon
  characters become single-char tokens, like ICU's fallback.  Greedy
  longest-match is chosen over jieba's own max-probability DP because it is
  deterministic, lexicon-only, and exactly reproducible by the device's
  window-hash machinery later; its boundary agreement with the DP is
  measured in ``tests/test_cjk_segmentation.py``.
* **Kana and Thai runs** stay whole within their script (no ja/th lexicon
  exists offline) — the remaining, now-isolated divergence vs ICU.

Documents containing these scripts are routed to the host oracle by the
device pipeline (``ops/pipeline.py``): word-table kernels never see
dictionary-segmented text, so host/device decision parity stays exact while
the host oracle moves closer to the reference's ICU semantics.
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, List, Sequence, Set, Tuple

__all__ = [
    "DICT_SCRIPT_RE",
    "has_astral",
    "has_dict_script",
    "segment_span",
    "zh_lexicon",
]

# Supplementary-plane codepoints (emoji, rare CJK extensions, historic
# scripts).  Not a CJK concern per se, but the same routing machinery uses
# it: the device wire format is uint16 on accelerators
# (ops/pipeline.py), so astral rows take the host oracle.
_ASTRAL_RE = re.compile("[\U00010000-\U0010FFFF]")


def has_astral(text: str) -> bool:
    """True if any char of ``text`` is outside the BMP."""
    return _ASTRAL_RE.search(text) is not None

# Scripts ICU segments by dictionary: Han (+ext A, compat), Hiragana,
# Katakana (+phonetic ext), Thai.  (Lao/Khmer/Myanmar are also dictionary
# scripts in ICU; they are included in the routing class so their documents
# reach the host oracle, which keeps their runs whole — divergence for them
# is documented, not silent.)
_DICT_RANGES = (
    (0x0E00, 0x0E7F),   # Thai
    (0x0E80, 0x0EFF),   # Lao
    (0x1000, 0x109F),   # Myanmar
    (0x1780, 0x17FF),   # Khmer
    (0x3040, 0x309F),   # Hiragana
    (0x30A0, 0x30FF),   # Katakana
    (0x31F0, 0x31FF),   # Katakana phonetic extensions
    (0x3400, 0x4DBF),   # CJK ext A
    (0x4E00, 0x9FFF),   # CJK unified
    (0xF900, 0xFAFF),   # CJK compatibility
)

DICT_SCRIPT_RE = re.compile(
    "[" + "".join(f"{chr(lo)}-{chr(hi)}" for lo, hi in _DICT_RANGES) + "]"
)

_HAN = ((0x3400, 0x4DBF), (0x4E00, 0x9FFF), (0xF900, 0xFAFF))

#: Longest lexicon entry used for matching (chars).  99.9% of jieba's Han
#: entries are <=4 chars; capping keeps the device window-table design
#: (one hash table per length) small.
MAX_WORD = 4


def has_dict_script(text: str) -> bool:
    """True if any char of ``text`` is in a dictionary-segmented script."""
    return DICT_SCRIPT_RE.search(text) is not None


def _is_han(cp: int) -> bool:
    return any(lo <= cp <= hi for lo, hi in _HAN)


def _script_key(cp: int) -> int:
    """Coarse script id used for mandatory boundaries inside an alnum run."""
    if _is_han(cp):
        return 1
    if 0x3040 <= cp <= 0x309F:
        return 2  # hiragana
    if 0x30A0 <= cp <= 0x30FF or 0x31F0 <= cp <= 0x31FF:
        return 3  # katakana
    if 0x0E00 <= cp <= 0x0E7F:
        return 4  # thai
    if 0x0E80 <= cp <= 0x0EFF:
        return 5  # lao
    if 0x1000 <= cp <= 0x109F:
        return 6  # myanmar
    if 0x1780 <= cp <= 0x17FF:
        return 7  # khmer
    return 0  # everything else (latin, digits, ...) — one class


@lru_cache(maxsize=1)
def zh_lexicon() -> Tuple[Set[str], ...]:
    """Han lexicon by length: ``lex[n]`` is the set of n-char entries
    (2 <= n <= MAX_WORD), pure-Han only, from jieba's dict.txt.

    Returns empty sets when jieba is unavailable (segmenting then falls back
    to single-char tokens for Han — still closer to ICU than run-whole,
    and the divergence test skips)."""
    by_len: Tuple[Set[str], ...] = tuple(set() for _ in range(MAX_WORD + 1))
    try:
        import jieba

        with jieba.get_dict_file() as f:
            for raw in f:
                word = raw.decode("utf-8").split(" ", 1)[0]
                n = len(word)
                if 2 <= n <= MAX_WORD and all(_is_han(ord(c)) for c in word):
                    by_len[n].add(word)
    except Exception:  # noqa: BLE001 — no jieba: empty lexicon, see docstring
        pass
    return by_len


def _segment_han(s: str, offset: int, out: List[Tuple[int, int]]) -> None:
    """Greedy longest-match over the Han lexicon; OOV chars single."""
    lex = zh_lexicon()
    i, n = 0, len(s)
    while i < n:
        for ln in range(min(MAX_WORD, n - i), 1, -1):
            if s[i : i + ln] in lex[ln]:
                out.append((offset + i, offset + i + ln))
                i += ln
                break
        else:
            out.append((offset + i, offset + i + 1))
            i += 1


def segment_span(text: str, start: int, end: int) -> List[Tuple[int, int]]:
    """Re-segment one UAX#29 alnum-run span that contains dictionary-script
    chars: break at script transitions, dictionary-split the Han stretches,
    keep other stretches whole.  Returns (start, end) spans covering
    [start, end) in order."""
    out: List[Tuple[int, int]] = []
    i = start
    while i < end:
        key = _script_key(ord(text[i]))
        j = i + 1
        while j < end and _script_key(ord(text[j])) == key:
            j += 1
        if key == 1:
            _segment_han(text[i:j], i, out)
        else:
            out.append((i, j))
        i = j
    return out
