"""Deterministic synthetic blocklist generator for full-scale badwords tests.

The reference downloads real LDNOOBW lists at first use
(``/root/reference/src/pipeline/filters/c4_filters.rs:318-454``; the upstream
``en`` list has ~400 entries spanning ~20 distinct lengths, including
multi-word phrases).  This environment has no egress, so scale testing uses
*generated* lists with the same shape statistics: entry count, length spread
(one window-hash pass per distinct length is the device cost driver,
:mod:`textblaster_tpu.ops.badwords`), and a multi-word-phrase fraction.
Vocabulary is irrelevant to the machinery being tested — only shape is.

Deterministic by seed so tests, bench configs, and device-table builds all
see the identical list without shipping fake "bad words" as package data.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["synth_badwords"]

_CONS = "bcdfghjklmnpqrstvwz"
_VOW = "aeiouy"


def _syllable(rng: np.random.Generator) -> str:
    s = _CONS[int(rng.integers(len(_CONS)))] + _VOW[int(rng.integers(len(_VOW)))]
    if rng.random() < 0.4:
        s += _CONS[int(rng.integers(len(_CONS)))]
    return s


def _latin_word(rng: np.random.Generator, syllables: int) -> str:
    return "".join(_syllable(rng) for _ in range(syllables))


def synth_badwords(seed: int, n: int = 400, cjk: bool = False) -> List[str]:
    """``n`` unique entries with LDNOOBW-like shape statistics.

    Latin mode: pronounceable 1-5 syllable words (2-15 chars) plus ~15%
    two/three-word phrases (real lists contain phrases; phrases exercise the
    space-in-pattern window path).  CJK mode: 2-8 ideograph strings from the
    CJK Unified block (real zh/ja lists are short unanchored substrings).
    """
    rng = np.random.default_rng(seed)
    words = set()
    while len(words) < n:
        if cjk:
            ln = int(rng.integers(2, 9))
            cps = rng.integers(0x4E00, 0x9FA5, size=ln)
            words.add("".join(chr(int(c)) for c in cps))
        else:
            w = _latin_word(rng, int(rng.integers(1, 6)))
            r = rng.random()
            if r < 0.10:
                w = f"{w} {_latin_word(rng, int(rng.integers(1, 4)))}"
            elif r < 0.15:
                w = (
                    f"{w} {_latin_word(rng, int(rng.integers(1, 3)))}"
                    f" {_latin_word(rng, int(rng.integers(1, 3)))}"
                )
            words.add(w)
    return sorted(words)
