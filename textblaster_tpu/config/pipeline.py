"""Typed, validated YAML pipeline configuration.

Re-implementation of ``/root/reference/src/config/pipeline.rs``: the same 7
step types discriminated by a ``type`` field, the same per-params validation
rules (pipeline.rs:82-367) with matching error messages, and the same loader
behavior (read file -> YAML parse -> validate, pipeline.rs:372-393).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

from ..errors import ConfigError, ConfigValidationError

__all__ = [
    "PipelineConfig",
    "StepConfig",
    "C4QualityParams",
    "GopherRepetitionParams",
    "GopherQualityParams",
    "C4BadWordsParams",
    "LanguageDetectionParams",
    "FineWebQualityFilterParams",
    "TokenCounterParams",
    "ResilienceConfig",
    "OverlapConfig",
    "SLOConfig",
    "load_pipeline_config",
    "parse_pipeline_config",
]


def _require(d: Dict[str, Any], key: str, step: str) -> Any:
    if key not in d:
        raise ConfigError(f"missing field `{key}` for step {step}")
    return d[key]


@dataclass
class C4QualityParams:
    """pipeline.rs:67-100"""

    split_paragraph: bool
    remove_citations: bool
    filter_no_terminal_punct: bool
    min_num_sentences: int
    min_words_per_line: int
    max_word_length: int
    filter_lorem_ipsum: bool
    filter_javascript: bool
    filter_curly_bracket: bool
    filter_policy: bool

    def validate(self) -> None:
        if self.min_num_sentences == 0:
            raise ConfigValidationError(
                "C4QualityParams: min_num_sentences must be greater than 0"
            )
        if self.min_words_per_line == 0:
            raise ConfigValidationError(
                "C4QualityParams: min_words_per_line must be greater than 0"
            )
        if self.max_word_length == 0:
            raise ConfigValidationError(
                "C4QualityParams: max_word_length must be greater than 0"
            )


@dataclass
class GopherRepetitionParams:
    """pipeline.rs:102-159"""

    dup_line_frac: Optional[float] = None
    dup_para_frac: Optional[float] = None
    dup_line_char_frac: Optional[float] = None
    dup_para_char_frac: Optional[float] = None
    top_n_grams: List[Tuple[int, float]] = field(default_factory=list)
    dup_n_grams: List[Tuple[int, float]] = field(default_factory=list)

    def validate(self) -> None:
        fractions = (
            ("dup_line_frac", self.dup_line_frac),
            ("dup_para_frac", self.dup_para_frac),
            ("dup_line_char_frac", self.dup_line_char_frac),
            ("dup_para_char_frac", self.dup_para_char_frac),
        )
        for name, val in fractions:
            if val is not None and not (0.0 <= val <= 1.0):
                raise ConfigValidationError(
                    f"GopherRepetitionParams: {name} must be between 0.0 and 1.0, "
                    f"got {val}"
                )
        for name, n_grams in (
            ("top_n_grams", self.top_n_grams),
            ("dup_n_grams", self.dup_n_grams),
        ):
            for idx, (size, fraction) in enumerate(n_grams):
                if size == 0:
                    raise ConfigValidationError(
                        f"GopherRepetitionParams: n-gram size in {name} at index "
                        f"{idx} must be greater than 0"
                    )
                if not (0.0 <= fraction <= 1.0):
                    raise ConfigValidationError(
                        f"GopherRepetitionParams: n-gram fraction in {name} at "
                        f"index {idx} must be between 0.0 and 1.0, got {fraction}"
                    )


@dataclass
class GopherQualityParams:
    """pipeline.rs:161-258"""

    min_doc_words: Optional[int] = None
    max_doc_words: Optional[int] = None
    min_avg_word_length: Optional[float] = None
    max_avg_word_length: Optional[float] = None
    max_symbol_word_ratio: Optional[float] = None
    max_bullet_lines_ratio: Optional[float] = None
    max_ellipsis_lines_ratio: Optional[float] = None
    max_non_alpha_words_ratio: Optional[float] = None
    min_stop_words: Optional[int] = None
    stop_words: Optional[List[str]] = None

    def validate(self) -> None:
        if self.min_doc_words is not None and self.min_doc_words == 0:
            raise ConfigValidationError(
                "GopherQualityParams: min_doc_words must be greater than 0"
            )
        if self.max_doc_words is not None and self.max_doc_words == 0:
            raise ConfigValidationError(
                "GopherQualityParams: max_doc_words must be greater than 0"
            )
        if (
            self.min_doc_words is not None
            and self.max_doc_words is not None
            and self.min_doc_words > self.max_doc_words
        ):
            raise ConfigValidationError(
                f"GopherQualityParams: min_doc_words ({self.min_doc_words}) cannot "
                f"be greater than max_doc_words ({self.max_doc_words})"
            )
        if self.min_avg_word_length is not None and self.min_avg_word_length <= 0.0:
            raise ConfigValidationError(
                "GopherQualityParams: min_avg_word_length must be greater than 0.0"
            )
        if self.max_avg_word_length is not None and self.max_avg_word_length <= 0.0:
            raise ConfigValidationError(
                "GopherQualityParams: max_avg_word_length must be greater than 0.0"
            )
        if (
            self.min_avg_word_length is not None
            and self.max_avg_word_length is not None
            and self.min_avg_word_length > self.max_avg_word_length
        ):
            raise ConfigValidationError(
                f"GopherQualityParams: min_avg_word_length "
                f"({self.min_avg_word_length}) cannot be greater than "
                f"max_avg_word_length ({self.max_avg_word_length})"
            )
        ratio_params = (
            ("max_symbol_word_ratio", self.max_symbol_word_ratio),
            ("max_bullet_lines_ratio", self.max_bullet_lines_ratio),
            ("max_ellipsis_lines_ratio", self.max_ellipsis_lines_ratio),
            ("max_non_alpha_words_ratio", self.max_non_alpha_words_ratio),
        )
        for name, val in ratio_params:
            if val is not None and val < 0.0:
                raise ConfigValidationError(
                    f"GopherQualityParams: {name} must be non-negative, got {val}"
                )


@dataclass
class C4BadWordsParams:
    """pipeline.rs:260-285"""

    keep_fraction: float
    fail_on_missing_language: bool
    default_language: str
    seed: Optional[int] = None
    cache_base_path: Optional[Path] = None  # not deserialized from YAML (serde skip)

    def validate(self) -> None:
        if not (0.0 <= self.keep_fraction <= 1.0):
            raise ConfigValidationError(
                f"C4BadWordsParams: keep_fraction must be between 0.0 and 1.0, "
                f"got {self.keep_fraction}"
            )
        if not self.default_language:
            raise ConfigValidationError(
                "C4BadWordsParams: default_language cannot be empty"
            )


@dataclass
class LanguageDetectionParams:
    """pipeline.rs:287-309"""

    min_confidence: float
    allowed_languages: List[str]

    def validate(self) -> None:
        if not (0.0 <= self.min_confidence <= 1.0):
            raise ConfigValidationError(
                f"LanguageDetectionParams: min_confidence must be between 0.0 and "
                f"1.0, got {self.min_confidence}"
            )
        if not self.allowed_languages:
            raise ConfigValidationError(
                "LanguageDetectionParams: allowed_languages cannot be empty"
            )


@dataclass
class FineWebQualityFilterParams:
    """pipeline.rs:311-349"""

    line_punct_thr: float = 0.0
    line_punct_exclude_zero: bool = False
    short_line_thr: float = 0.0
    short_line_length: int = 0
    char_duplicates_ratio: float = 0.0
    new_line_ratio: float = 0.0
    stop_chars: Optional[List[str]] = None

    def validate(self) -> None:
        params = (
            ("line_punct_thr", self.line_punct_thr),
            ("short_line_thr", self.short_line_thr),
            ("char_duplicates_ratio", self.char_duplicates_ratio),
            ("new_line_ratio", self.new_line_ratio),
        )
        for name, value in params:
            if not (0.0 <= value <= 1.0):
                raise ConfigValidationError(
                    f"FineWebQualityFilterParams: {name} must be between 0.0 and "
                    f"1.0, got {value}"
                )
        if self.short_line_length == 0:
            raise ConfigValidationError(
                "FineWebQualityFilterParams: short_line_length must be greater than 0"
            )


@dataclass
class TokenCounterParams:
    """pipeline.rs:351-368"""

    tokenizer_name: str

    def validate(self) -> None:
        if not self.tokenizer_name:
            raise ConfigValidationError(
                "TokenCounterParams: tokenizer_name cannot be empty"
            )


_PARAM_TYPES = {
    "C4QualityFilter": C4QualityParams,
    "GopherRepetitionFilter": GopherRepetitionParams,
    "GopherQualityFilter": GopherQualityParams,
    "C4BadWordsFilter": C4BadWordsParams,
    "LanguageDetectionFilter": LanguageDetectionParams,
    "FineWebQualityFilter": FineWebQualityFilterParams,
    "TokenCounter": TokenCounterParams,
}

_REQUIRED_FIELDS = {
    "C4QualityFilter": (
        "split_paragraph",
        "remove_citations",
        "filter_no_terminal_punct",
        "min_num_sentences",
        "min_words_per_line",
        "max_word_length",
        "filter_lorem_ipsum",
        "filter_javascript",
        "filter_curly_bracket",
        "filter_policy",
    ),
    "GopherRepetitionFilter": (),
    "GopherQualityFilter": (),
    "C4BadWordsFilter": ("keep_fraction", "fail_on_missing_language", "default_language"),
    "LanguageDetectionFilter": ("min_confidence", "allowed_languages"),
    "FineWebQualityFilter": (
        "line_punct_thr",
        "line_punct_exclude_zero",
        "short_line_thr",
        "short_line_length",
        "char_duplicates_ratio",
        "new_line_ratio",
    ),
    "TokenCounter": ("tokenizer_name",),
}

# Fields serde skips during deserialization (pipeline.rs:266-267).
_SKIPPED_FIELDS = {"C4BadWordsFilter": ("cache_base_path",)}


@dataclass
class ResilienceConfig:
    """Fault-tolerance knobs for the execution layer (no reference
    equivalent — the reference leans on RabbitMQ redelivery).

    Parsed from an optional top-level ``resilience:`` mapping in the pipeline
    YAML.  Deliberately excluded from the checkpoint config fingerprint
    (checkpoint.py hashes ``config.pipeline`` only): retry budgets do not
    change outcomes, so tuning them must not invalidate a resumable run.
    """

    max_retries: int = 3          # re-attempts after the first try, per seam
    backoff_base_s: float = 0.05  # first backoff delay
    backoff_max_s: float = 2.0    # backoff cap
    backoff_multiplier: float = 2.0
    jitter: float = 0.5           # each delay widened by up to this fraction
    breaker_threshold: int = 3    # consecutive device failures before the trip
    breaker_cooldown_s: float = 30.0  # open time before a half-open probe;
    #                                   0 latches open for the run's life
    split_retry: bool = True      # enable the split-in-half OOM rung
    # Stall-watchdog deadline per host-side stage (device fetch, pack wait,
    # write queue, reader prefetch), in seconds.  0 (the default) disarms
    # the watchdog entirely — every seam keeps its historical unbounded
    # wait and pays one attribute check.  Scheduling-only like the rest of
    # this mapping: a stall degrades *where* work runs, never what it
    # decides, so it stays out of the checkpoint fingerprint and AOT keys.
    stage_deadline_s: float = 0.0

    def validate(self) -> None:
        if self.max_retries < 0:
            raise ConfigValidationError(
                "ResilienceConfig: max_retries must be non-negative"
            )
        for name, val in (
            ("backoff_base_s", self.backoff_base_s),
            ("backoff_max_s", self.backoff_max_s),
            ("jitter", self.jitter),
        ):
            if val < 0.0:
                raise ConfigValidationError(
                    f"ResilienceConfig: {name} must be non-negative, got {val}"
                )
        if self.backoff_multiplier < 1.0:
            raise ConfigValidationError(
                "ResilienceConfig: backoff_multiplier must be >= 1.0, "
                f"got {self.backoff_multiplier}"
            )
        if self.breaker_threshold < 1:
            raise ConfigValidationError(
                "ResilienceConfig: breaker_threshold must be >= 1, "
                f"got {self.breaker_threshold}"
            )
        if self.breaker_cooldown_s < 0.0:
            raise ConfigValidationError(
                "ResilienceConfig: breaker_cooldown_s must be non-negative, "
                f"got {self.breaker_cooldown_s}"
            )
        if self.stage_deadline_s < 0.0:
            raise ConfigValidationError(
                "ResilienceConfig: stage_deadline_s must be non-negative, "
                f"got {self.stage_deadline_s}"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResilienceConfig":
        if not isinstance(d, dict):
            raise ConfigError("`resilience` must be a mapping")
        known = set(cls.__dataclass_fields__)
        # serde-without-deny_unknown_fields parity: extra keys are ignored.
        fields_d = {k: v for k, v in d.items() if k in known}
        try:
            return cls(**fields_d)
        except TypeError as e:
            raise ConfigError(f"invalid resilience config: {e}") from e


@dataclass
class OverlapConfig:
    """Host-pipeline overlap knobs for the device backend (no reference
    equivalent — the reference's workers are synchronous per message).

    Parsed from an optional top-level ``overlap:`` mapping in the pipeline
    YAML.  Like ``resilience``, excluded from the checkpoint config
    fingerprint (checkpoint.py hashes ``config.pipeline`` only): overlap
    changes wall time, never outcomes, so tuning it must not invalidate a
    resumable run.
    """

    enabled: bool = True       # --no-overlap forces False
    pipeline_depth: int = 2    # device batches kept in flight (1 = serial)
    pack_workers: int = 2      # threads over the GIL-releasing pack work
    read_ahead: int = 4        # Parquet read-ahead queue, in read batches
    write_queue: int = 8       # writer-thread queue, in outcome batches
    overflow_flush: int = 64   # host-fallback docs buffered before a flush
    # Multi-host speculative cross-phase dispatch: next-phase rounds this
    # host will launch at a phase barrier before the tail verdicts resolve
    # (--speculate-depth).  None follows pipeline_depth; 0 opts out, which
    # min-negotiates the WHOLE gang onto the classic barrier — same as
    # TEXTBLAST_SPECULATE=off.  Single-host runs ignore it.
    speculate_depth: Optional[int] = None

    def validate(self) -> None:
        for name, val, lo in (
            ("pipeline_depth", self.pipeline_depth, 1),
            ("pack_workers", self.pack_workers, 1),
            ("read_ahead", self.read_ahead, 1),
            ("write_queue", self.write_queue, 1),
            ("overflow_flush", self.overflow_flush, 1),
        ):
            if val < lo:
                raise ConfigValidationError(
                    f"OverlapConfig: {name} must be >= {lo}, got {val}"
                )
        if self.speculate_depth is not None and self.speculate_depth < 0:
            raise ConfigValidationError(
                "OverlapConfig: speculate_depth must be >= 0 (0 disables "
                f"speculation), got {self.speculate_depth}"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OverlapConfig":
        if not isinstance(d, dict):
            raise ConfigError("`overlap` must be a mapping")
        known = set(cls.__dataclass_fields__)
        # serde-without-deny_unknown_fields parity: extra keys are ignored.
        fields_d = {k: v for k, v in d.items() if k in known}
        try:
            return cls(**fields_d)
        except TypeError as e:
            raise ConfigError(f"invalid overlap config: {e}") from e


@dataclass
class SLOConfig:
    """Service-level objectives for the run (no reference equivalent).

    Parsed from an optional top-level ``slo:`` mapping in the pipeline
    YAML: objective keys map directly to targets, engine knobs ride in
    the same mapping::

        slo:
          availability: 0.999
          p99_latency_s: 0.25
          fast_window_s: 30

    ``--slo KEY=TARGET`` on the command line overrides per key.  Like
    ``resilience`` and ``overlap``, excluded from the checkpoint config
    fingerprint (checkpoint.py hashes ``config.pipeline`` only):
    objectives judge a run, they never change its outputs.
    """

    objectives: Dict[str, float] = field(default_factory=dict)
    fast_window_s: float = 60.0     # fast burn-rate window
    slow_window_s: float = 300.0    # slow burn-rate window
    burn_threshold: float = 1.0     # alert iff BOTH windows burn above this
    tick_s: float = 5.0             # evaluation cadence

    #: Engine knobs that live beside the objectives in the ``slo:`` block.
    _KNOBS = ("fast_window_s", "slow_window_s", "burn_threshold", "tick_s")

    def validate(self) -> None:
        # The objective vocabulary is owned by utils.slo (single source of
        # truth with --slo parsing); imported lazily to keep config loading
        # free of the observability stack.
        from ..utils.slo import SLO_KEYS

        for key, target in self.objectives.items():
            if key not in SLO_KEYS:
                raise ConfigValidationError(
                    f"SLOConfig: unknown objective {key!r} "
                    f"(keys: {', '.join(SLO_KEYS)})"
                )
            try:
                target = float(target)
            except (TypeError, ValueError):
                raise ConfigValidationError(
                    f"SLOConfig: target for {key} must be a number, "
                    f"got {target!r}"
                )
            if key == "availability" and not 0.0 < target <= 1.0:
                raise ConfigValidationError(
                    "SLOConfig: availability target must be in (0, 1], "
                    f"got {target}"
                )
            if key != "availability" and target <= 0:
                raise ConfigValidationError(
                    f"SLOConfig: {key} target must be > 0, got {target}"
                )
        for name in ("fast_window_s", "slow_window_s", "tick_s"):
            if getattr(self, name) <= 0:
                raise ConfigValidationError(
                    f"SLOConfig: {name} must be positive, "
                    f"got {getattr(self, name)}"
                )
        if self.burn_threshold <= 0:
            raise ConfigValidationError(
                "SLOConfig: burn_threshold must be positive, "
                f"got {self.burn_threshold}"
            )
        if self.fast_window_s > self.slow_window_s:
            raise ConfigValidationError(
                "SLOConfig: fast_window_s must not exceed slow_window_s "
                f"({self.fast_window_s} > {self.slow_window_s})"
            )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOConfig":
        if not isinstance(d, dict):
            raise ConfigError("`slo` must be a mapping")
        knobs = {k: v for k, v in d.items() if k in cls._KNOBS}
        objectives = {
            k: v for k, v in d.items() if k not in cls._KNOBS
        }
        try:
            return cls(
                objectives={k: float(v) for k, v in objectives.items()},
                **{k: float(v) for k, v in knobs.items()},
            )
        except (TypeError, ValueError) as e:
            raise ConfigError(f"invalid slo config: {e}") from e


@dataclass
class StepConfig:
    """One pipeline step: a type tag + typed params (pipeline.rs:26-64)."""

    type: str
    params: Any

    @property
    def name(self) -> str:
        return self.type

    def validate(self) -> None:
        self.params.validate()

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StepConfig":
        if not isinstance(d, dict) or "type" not in d:
            raise ConfigError("pipeline step is missing the `type` tag")
        step_type = d["type"]
        if step_type not in _PARAM_TYPES:
            raise ConfigError(
                f"unknown variant `{step_type}`, expected one of "
                + ", ".join(f"`{t}`" for t in _PARAM_TYPES)
            )
        fields_d = {k: v for k, v in d.items() if k != "type"}
        for skipped in _SKIPPED_FIELDS.get(step_type, ()):
            fields_d.pop(skipped, None)
        for req in _REQUIRED_FIELDS[step_type]:
            _require(fields_d, req, step_type)
        param_cls = _PARAM_TYPES[step_type]
        # serde without deny_unknown_fields silently ignores extra keys
        # (pipeline.rs:26-37) — e.g. legacy `language:` keys in FineWeb steps.
        known = set(param_cls.__dataclass_fields__)
        fields_d = {k: v for k, v in fields_d.items() if k in known}
        # Normalize [ [n, frac], ... ] lists into tuples.
        for key in ("top_n_grams", "dup_n_grams"):
            if key in fields_d and fields_d[key] is not None:
                try:
                    fields_d[key] = [(int(n), float(f)) for n, f in fields_d[key]]
                except (TypeError, ValueError) as e:
                    raise ConfigError(
                        f"invalid {key} for step {step_type}: {e}"
                    ) from e
        try:
            params = param_cls(**fields_d)
        except TypeError as e:
            raise ConfigError(f"invalid params for step {step_type}: {e}") from e
        return cls(type=step_type, params=params)


@dataclass
class PipelineConfig:
    """pipeline.rs:10-22 (+ the optional resilience section, ours only)."""

    pipeline: List[StepConfig]
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    overlap: OverlapConfig = field(default_factory=OverlapConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)

    def validate(self) -> None:
        for step in self.pipeline:
            step.validate()
        self.resilience.validate()
        self.overlap.validate()
        self.slo.validate()

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PipelineConfig":
        if not isinstance(d, dict) or "pipeline" not in d:
            raise ConfigError("missing field `pipeline`")
        steps_raw = d["pipeline"]
        if steps_raw is None or not isinstance(steps_raw, list):
            raise ConfigError("`pipeline` must be a list of steps")
        resilience_raw = d.get("resilience")
        overlap_raw = d.get("overlap")
        slo_raw = d.get("slo")
        return cls(
            pipeline=[StepConfig.from_dict(s) for s in steps_raw],
            resilience=(
                ResilienceConfig.from_dict(resilience_raw)
                if resilience_raw is not None
                else ResilienceConfig()
            ),
            overlap=(
                OverlapConfig.from_dict(overlap_raw)
                if overlap_raw is not None
                else OverlapConfig()
            ),
            slo=(
                SLOConfig.from_dict(slo_raw)
                if slo_raw is not None
                else SLOConfig()
            ),
        )


def parse_pipeline_config(content: str, origin: str = "<string>") -> PipelineConfig:
    """Parse + validate YAML content (split out for broker-free tests)."""
    try:
        raw = yaml.safe_load(content)
    except yaml.YAMLError as e:
        raise ConfigError(
            f"Failed to parse pipeline config YAML from '{origin}': {e}"
        ) from e
    try:
        config = PipelineConfig.from_dict(raw if raw is not None else {})
    except ConfigError as e:
        raise ConfigError(
            f"Failed to parse pipeline config YAML from '{origin}': {e.args[0]}"
        ) from e
    config.validate()
    return config


def load_pipeline_config(config_path: str | Path) -> PipelineConfig:
    """Load and parse a pipeline YAML (pipeline.rs:372-393)."""
    path = Path(config_path)
    try:
        content = path.read_text(encoding="utf-8")
    except OSError as e:
        raise ConfigError(
            f"Failed to read pipeline config file '{path}': {e}"
        ) from e
    return parse_pipeline_config(content, origin=str(path))
