"""textblaster_tpu — a TPU-native framework for large-scale text-dataset
cleaning with the capabilities of kris927b/TextBlaster.

Where the reference fans documents out to Rust workers over RabbitMQ, this
framework is a single SPMD JAX/XLA program: Parquet row-groups are sharded
across TPU chips, documents live in HBM as packed ragged UTF-8 byte tensors,
filters run as vectorized XLA/Pallas scans producing keep/drop masks and
reason codes, and masks are gathered over ICI/DCN so the host streams one
kept/excluded Parquet pair — no broker hop.

Layer map (TPU-native re-design of SURVEY.md §1):

* :mod:`~textblaster_tpu.data_model` / :mod:`~textblaster_tpu.errors` — L1
  foundations (document record, outcome, error taxonomy).
* :mod:`~textblaster_tpu.utils.text` — L1 text primitives (UAX#29-lite
  segmentation shared by host oracle and device kernels).
* :mod:`~textblaster_tpu.config` — YAML pipeline spec + validation + CLI.
* :mod:`~textblaster_tpu.io` — Parquet reader/writer (reference schema).
* :mod:`~textblaster_tpu.filters` — L3 host-path steps (parity oracle).
* :mod:`~textblaster_tpu.executor` — L4 host executor.
* :mod:`~textblaster_tpu.ops` — L3/L4 device path: packed batches + fused
  filter kernels compiled with jit.
* :mod:`~textblaster_tpu.parallel` — L5/L6 sharding runtime (mesh, pjit,
  collective aggregation) replacing the reference's AMQP layer.
* :mod:`~textblaster_tpu.models` — statistical language-ID model.
"""

__version__ = "0.1.0"

from .data_model import ProcessingOutcome, TextDocument  # noqa: F401
from .errors import (  # noqa: F401
    ConfigError,
    ConfigValidationError,
    DocumentFiltered,
    PipelineError,
    StepError,
)
from .executor import PipelineExecutor, ProcessingStep  # noqa: F401
