"""Config -> step instances (host path).

Equivalent of ``build_pipeline_from_config``
(``/root/reference/src/worker_logic.rs:39-134``): a 7-arm dispatch from
:class:`~textblaster_tpu.config.pipeline.StepConfig` to constructed steps.
The device path compiles the same config into one fused XLA program instead
(:mod:`textblaster_tpu.ops.pipeline`).
"""

from __future__ import annotations

from typing import List, Optional

from .config.pipeline import PipelineConfig, StepConfig
from .errors import ConfigError
from .executor import PipelineExecutor, ProcessingStep
from .filters import (
    C4BadWordsFilter,
    C4QualityFilter,
    FineWebQualityFilter,
    GopherQualityFilter,
    GopherRepetitionFilter,
    LanguageDetectionFilter,
    TokenCounter,
)
from .filters.c4_badwords import C4BadWordsParams as _RuntimeBadWordsParams

__all__ = ["build_step", "build_pipeline_from_config"]


def build_step(step: StepConfig) -> ProcessingStep:
    p = step.params
    if step.type == "C4QualityFilter":
        return C4QualityFilter(
            split_paragraph=p.split_paragraph,
            remove_citations=p.remove_citations,
            filter_no_terminal_punct=p.filter_no_terminal_punct,
            min_num_sentences=p.min_num_sentences,
            min_words_per_line=p.min_words_per_line,
            max_word_length=p.max_word_length,
            filter_lorem_ipsum=p.filter_lorem_ipsum,
            filter_javascript=p.filter_javascript,
            filter_curly_bracket=p.filter_curly_bracket,
            filter_policy=p.filter_policy,
        )
    if step.type == "GopherRepetitionFilter":
        return GopherRepetitionFilter(
            dup_line_frac=p.dup_line_frac,
            dup_para_frac=p.dup_para_frac,
            dup_line_char_frac=p.dup_line_char_frac,
            dup_para_char_frac=p.dup_para_char_frac,
            top_n_grams=p.top_n_grams,
            dup_n_grams=p.dup_n_grams,
        )
    if step.type == "GopherQualityFilter":
        return GopherQualityFilter(
            min_doc_words=p.min_doc_words,
            max_doc_words=p.max_doc_words,
            min_avg_word_length=p.min_avg_word_length,
            max_avg_word_length=p.max_avg_word_length,
            max_symbol_word_ratio=p.max_symbol_word_ratio,
            max_bullet_lines_ratio=p.max_bullet_lines_ratio,
            max_ellipsis_lines_ratio=p.max_ellipsis_lines_ratio,
            max_non_alpha_words_ratio=p.max_non_alpha_words_ratio,
            min_stop_words=p.min_stop_words,
            stop_words=p.stop_words,
        )
    if step.type == "C4BadWordsFilter":
        return C4BadWordsFilter(
            _RuntimeBadWordsParams(
                keep_fraction=p.keep_fraction,
                fail_on_missing_language=p.fail_on_missing_language,
                seed=p.seed,
                default_language=p.default_language,
                cache_base_path=p.cache_base_path,
            )
        )
    if step.type == "LanguageDetectionFilter":
        return LanguageDetectionFilter(
            min_confidence=p.min_confidence,
            allowed_languages=p.allowed_languages,
        )
    if step.type == "FineWebQualityFilter":
        return FineWebQualityFilter(
            line_punct_thr=p.line_punct_thr,
            line_punct_exclude_zero=p.line_punct_exclude_zero,
            short_line_thr=p.short_line_thr,
            short_line_length=p.short_line_length,
            char_duplicates_ratio=p.char_duplicates_ratio,
            new_line_ratio=p.new_line_ratio,
            stop_chars=set(p.stop_chars) if p.stop_chars is not None else None,
        )
    if step.type == "TokenCounter":
        # Reference panics on tokenizer load failure (worker_logic.rs:115-122);
        # here the UnexpectedError propagates out of construction.
        return TokenCounter(p.tokenizer_name)
    raise ConfigError(f"unknown step type '{step.type}'")


def build_pipeline_from_config(
    config: PipelineConfig, steps_filter: Optional[List[str]] = None
) -> PipelineExecutor:
    """Construct the host-path executor for a validated config."""
    steps = [
        build_step(s)
        for s in config.pipeline
        if steps_filter is None or s.type in steps_filter
    ]
    return PipelineExecutor(steps)
