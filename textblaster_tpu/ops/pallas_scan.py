"""VMEM-resident blocked associative scans (DFA composition, rolling hashes).

The per-row hot scans — DFA matching over nibble-packed transition maps
(:mod:`.dfa`) and the segmented polynomial-hash streams feeding the
repetition/duplicate statistics (:mod:`.stats`) — run as log-depth
``lax.associative_scan`` under XLA, which materializes every doubling
level's ``[B, L]`` intermediate in HBM.  This module runs the *same
associative ops* as a blocked sequential scan instead: the grid tiles rows
(8-row sublane tiles), each tile stays resident in VMEM while an in-kernel
``fori_loop`` walks fixed-width lane blocks, scanning each block with
Hillis–Steele doubling (circular lane rolls masked to the op identity) and
folding a per-row carry across blocks — intermediate state never
round-trips HBM.

Every op here is int32 ALU with exact wraparound semantics, so the kernel
is **bit-identical** to the lax schedules by integer associativity; the
decision parity vs the host oracle is preserved exactly (the parity fuzz
suite in ``tests/test_pallas_scan.py`` stamps this, not approximates it).

Escape hatches / fallback:

* ``TEXTBLAST_PALLAS=off`` (or the older ``TEXTBLAST_NO_PALLAS=1``)
  disables every Pallas kernel — callers fall back to the lax scans.
* Non-TPU backends fall back automatically.  ``TEXTBLAST_PALLAS_INTERPRET=1``
  forces the interpret-mode kernel anywhere — how the fuzz suite runs the
  exact kernel program under tier-1 on CPU.
* Mosaic ``pallas_call`` custom calls carry no GSPMD partitioning rule, so
  a program jitted with multi-device shardings cannot contain a bare one.
  ``CompiledPipeline`` traces mesh programs under :func:`mesh_tracing`,
  which turns these kernels off for that trace — the lax scans partition
  fine under GSPMD (the sort kernel shard_maps instead; the scans keep
  scope and simply fall back).
"""

from __future__ import annotations

import contextlib
import functools
import logging
import threading
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_sort import ROWS, interpret_forced, pallas_enabled, pltpu, roll_lanes

logger = logging.getLogger(__name__)

__all__ = [
    "affine_hash_scan",
    "dfa_compose_scan",
    "mesh_tracing",
    "pallas_scan_ok",
    "pallas_scan_supported",
]

#: Lanes per in-kernel scan block.  Blocked doubling costs
#: ``L/BLK * (log2(BLK)+1)`` roll+compose levels vs ``L * log2(L)`` for a
#: whole-row scan — 512 keeps the working set one register-friendly tile
#: while shaving the upper doubling levels of long buckets.
_BLK = 512

_MAX_LANES = 65536  # beyond this the [8, L] tile no longer fits VMEM comfortably

_tls = threading.local()


@contextlib.contextmanager
def mesh_tracing(active: bool = True):
    """Mark the current (thread-local) trace as targeting a multi-device
    sharded program, where a bare ``pallas_call`` is illegal (no GSPMD
    rule).  ``pallas_scan_supported`` returns False inside this context."""
    prev = getattr(_tls, "mesh_tracing", False)
    _tls.mesh_tracing = bool(active)
    try:
        yield
    finally:
        _tls.mesh_tracing = prev


def _mesh_trace_active() -> bool:
    return bool(getattr(_tls, "mesh_tracing", False))


def _blk_for(length: int) -> int:
    for blk in (_BLK, 256, 128):
        if length % blk == 0:
            return blk
    raise ValueError(f"row length {length} is not a multiple of 128")


def _scan_body(op: Callable, identities: Sequence[int], refs) -> None:
    """Kernel body: blocked inclusive scan of an n-stream int32 tuple state
    along the lane axis, one VMEM-resident row tile per grid step."""
    n = len(refs) // 2
    in_refs, out_refs = refs[:n], refs[n:]
    rows, length = in_refs[0].shape
    blk = _blk_for(length)
    # In-kernel lane index (Pallas kernels cannot capture host constants).
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
    idents = tuple(jnp.int32(i) for i in identities)

    def body(i, carry):
        start = i * blk
        xs = tuple(r[:, pl.ds(start, blk)] for r in in_refs)
        d = 1
        while d < blk:
            # Hillis–Steele level: acc[j] = op(acc[j-d], acc[j]).  The roll
            # is circular; wrapped lanes are masked to the op identity.
            shifted = tuple(
                jnp.where(lane >= d, roll_lanes(x, d), ident)
                for x, ident in zip(xs, idents)
            )
            xs = op(shifted, xs)
            d *= 2
        # Fold the running prefix of all earlier blocks ([rows, 1],
        # broadcast) in front of this block's inclusive scan.
        xs = op(carry, xs)
        for r, x in zip(out_refs, xs):
            r[:, pl.ds(start, blk)] = x
        return tuple(x[:, blk - 1 : blk] for x in xs)

    init = tuple(jnp.full((rows, 1), i, jnp.int32) for i in identities)
    jax.lax.fori_loop(0, length // blk, body, init)


def _pallas_scan_tuple(
    op: Callable,
    identities: Sequence[int],
    xs: Tuple[jax.Array, ...],
    interpret: bool,
) -> Tuple[jax.Array, ...]:
    """Row-wise inclusive associative scan of int32 ``[B, L]`` streams.
    ``op`` maps (earlier-tuple, later-tuple) -> tuple with elementwise jnp
    ops only (operands may broadcast ``[B, 1]`` against ``[B, blk]``)."""
    n = len(xs)
    b, length = xs[0].shape

    def kernel(*refs):
        _scan_body(op, identities, refs)

    spec = pl.BlockSpec((ROWS, length), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct((b, length), jnp.int32)
    return pl.pallas_call(
        kernel,
        grid=(b // ROWS,),
        in_specs=[spec] * n,
        out_specs=[spec] * n,
        out_shape=[shape] * n,
        interpret=interpret,
    )(*(x.astype(jnp.int32) for x in xs))


# --- associative ops (must match the lax twins bit-for-bit) -----------------


def _affine_op(xs, ys):
    # Segmented polynomial hash: affine maps h -> m*h + a, composed
    # earlier-then-later; identical to stats._poly_hash_many's compose.
    mx, axs = xs[0], xs[1:]
    my, ays = ys[0], ys[1:]
    return (mx * my,) + tuple(ay + my * ax for ax, ay in zip(axs, ays))


def _dfa_op(n_states: int) -> Callable:
    def op(xs, ys):
        # (b . a)(s) = b[a[s]]: route each of a's nibbles through b —
        # identical to dfa.dfa_states's compose.
        a, b = xs[0], ys[0]
        out = None
        for s in range(n_states):
            nib = (a >> (4 * s)) & 15
            term = ((b >> (nib << 2)) & 15) << (4 * s)
            out = term if out is None else out | term
        return (out,)

    return op


def _dfa_ident(n_states: int) -> int:
    ident = 0
    for s in range(n_states):
        ident |= s << (4 * s)
    return ident


# --- support gates ----------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _probe_backend() -> bool:
    """Compile and run one tiny kernel on the live backend, checking it
    against the lax result — Mosaic availability differs per
    backend/runtime version and a failed probe must mean fallback, not a
    crashed pipeline."""
    if pltpu is None or jax.default_backend() == "cpu":
        return False
    try:
        m = jnp.full((ROWS, 128), 31, jnp.int32)
        a = (jax.lax.broadcasted_iota(jnp.int32, (ROWS, 128), 1) * 7) % 97
        got = _pallas_scan_tuple(_affine_op, (1, 0), (m, a), interpret=False)
        want = jax.lax.associative_scan(_affine_op, (m, a), axis=1)
        ok = all(bool(jnp.array_equal(g, w)) for g, w in zip(got, want))
        if not ok:  # pragma: no cover - would be a Mosaic miscompile
            logger.warning("Pallas scan probe mismatch; using lax scans")
        return ok
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("Pallas scan unavailable on %s: %s", jax.default_backend(), e)
        return False


def pallas_scan_supported() -> bool:
    """Whether the scan kernels can run here.  Env decisions are re-read per
    call (only the backend probe is cached); always False while tracing a
    mesh-sharded program (see :func:`mesh_tracing`)."""
    if not pallas_enabled():
        return False
    if _mesh_trace_active():
        return False
    if interpret_forced():
        return True
    return _probe_backend()


def pallas_scan_ok(b: int, length: int) -> bool:
    """Shape + support gate callers use before dispatching to a kernel."""
    return (
        pallas_scan_supported()
        and b > 0
        and b % ROWS == 0
        and 128 <= length <= _MAX_LANES
        and length % 128 == 0
    )


# --- public kernels ---------------------------------------------------------


def dfa_compose_scan(fns: jax.Array, n_states: int) -> jax.Array:
    """Inclusive scan of nibble-packed DFA transition maps along axis 1 —
    the kernel twin of ``dfa.dfa_states``'s <=8-state composition.  Callers
    gate on :func:`pallas_scan_ok` first."""
    (out,) = _pallas_scan_tuple(
        _dfa_op(n_states),
        (_dfa_ident(n_states),),
        (fns,),
        interpret=interpret_forced(),
    )
    return out


def affine_hash_scan(
    m: jax.Array, accs: Tuple[jax.Array, ...]
) -> Tuple[jax.Array, ...]:
    """Inclusive scan of the shared-multiplier affine hash op — the kernel
    twin of ``stats._poly_hash_many``.  Returns the scanned accumulator
    streams (the scanned multiplier is internal).  Callers gate on
    :func:`pallas_scan_ok` first."""
    identities = (1,) + (0,) * len(accs)
    out = _pallas_scan_tuple(
        _affine_op, identities, (m,) + tuple(accs), interpret=interpret_forced()
    )
    return out[1:]
