"""VMEM-resident blocked associative scans (DFA composition, rolling hashes)
and the fused per-bucket filter megakernel.

The per-row hot scans — DFA matching over nibble-packed transition maps
(:mod:`.dfa`) and the segmented polynomial-hash streams feeding the
repetition/duplicate statistics (:mod:`.stats`) — run as log-depth
``lax.associative_scan`` under XLA, which materializes every doubling
level's ``[B, L]`` intermediate in HBM.  This module runs the *same
associative ops* as a blocked sequential scan instead: the grid tiles rows
(8-row sublane tiles), each tile stays resident in VMEM while an in-kernel
``fori_loop`` walks fixed-width lane blocks, scanning each block with
Hillis–Steele doubling (circular lane rolls masked to the op identity) and
folding a per-row carry across blocks — intermediate state never
round-trips HBM.

:func:`fused_scan` goes one step further: it lowers *several* independent
scan groups (affine hash streams, segmented adds, DFA compositions, and
whole-row reductions) into ONE ``pallas_call`` that walks the packed
codepoint tile once — each lane block is loaded once and every group's
doubling runs on it in-register, so a phase's worth of filter statistics
costs one kernel dispatch per (bucket, phase) instead of one per scan, and
no intermediate mask or stat stream touches HBM between filters.  Groups
marked ``emit="last"`` write only their final ``[B, 1]`` carry (a per-row
total), never the full scanned stream.

Every op here is int32 ALU with exact wraparound semantics, so the kernels
are **bit-identical** to the lax schedules by integer associativity; the
decision parity vs the host oracle is preserved exactly (the parity fuzz
suites in ``tests/test_pallas_scan.py`` and ``tests/test_fused_scan.py``
stamp this, not approximate it).

Escape hatches / fallback:

* ``TEXTBLAST_PALLAS=off`` (or the older ``TEXTBLAST_NO_PALLAS=1``)
  disables every Pallas kernel — callers fall back to the lax scans.
* ``TEXTBLAST_FUSED=off`` disables only the fused megakernel — the
  per-scan kernels (and their lax fallbacks) still run.
* ``TEXTBLAST_DEPFUSE=off`` disables only the *dependency-chained*
  multi-pass megakernel (:func:`chain_scan`) — callers fall back to the
  staged schedule (which may still use :func:`fused_scan` for its
  independent groups).
* Non-TPU backends fall back automatically.  ``TEXTBLAST_PALLAS_INTERPRET=1``
  forces the interpret-mode kernel anywhere — how the fuzz suite runs the
  exact kernel program under tier-1 on CPU.
* Mosaic ``pallas_call`` custom calls carry no GSPMD partitioning rule, so
  a program jitted with multi-device shardings cannot contain a bare one.
  ``CompiledPipeline`` traces mesh programs under ``mesh_tracing(mesh)``,
  which makes every scan here dispatch through ``shard_map`` over the data
  axis instead (mirroring ``pallas_sort.sort2``) — rows are independent, so
  each device scans its own row shard in VMEM and mesh-sharded programs no
  longer fall back to the lax scans.  The legacy ``mesh_tracing()`` form
  (no mesh object) still declines the kernels outright.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

from .pallas_sort import (
    ROWS,
    interpret_forced,
    pallas_enabled,
    pltpu,
    roll_lanes,
    shard_map,
)

logger = logging.getLogger(__name__)

__all__ = [
    "Tap",
    "add_group",
    "affine_group",
    "affine_hash_scan",
    "chain_group",
    "chain_pass",
    "chain_scan",
    "chain_scan_ok",
    "copy_group",
    "count_scan_dispatches",
    "depfuse_enabled",
    "dfa_compose_scan",
    "dfa_group",
    "fused_enabled",
    "fused_scan",
    "fused_scan_ok",
    "mesh_tracing",
    "pallas_scan_ok",
    "pallas_scan_supported",
    "record_scan_dispatch",
    "segmax_group",
]

#: Lanes per in-kernel scan block.  Blocked doubling costs
#: ``L/BLK * (log2(BLK)+1)`` roll+compose levels vs ``L * log2(L)`` for a
#: whole-row scan — 512 keeps the working set one register-friendly tile
#: while shaving the upper doubling levels of long buckets.
_BLK = 512

_MAX_LANES = 65536  # beyond this the [8, L] tile no longer fits VMEM comfortably

#: The fused kernel holds every group's input *and* output tiles resident at
#: once, so its lane ceiling is tighter than the 2–4-stream per-scan kernels.
_FUSED_MAX_LANES = 16384

#: Mesh axis the batch dimension is sharded over (parallel.mesh.DATA_AXIS;
#: duplicated here to keep this module importable standalone).
_DATA_AXIS = "data"

_tls = threading.local()


@contextlib.contextmanager
def mesh_tracing(mesh=True):
    """Mark the current (thread-local) trace as targeting a multi-device
    sharded program, where a bare ``pallas_call`` is illegal (no GSPMD
    rule).

    Pass the program's :class:`~jax.sharding.Mesh` and every scan kernel in
    this module dispatches through ``shard_map`` over the data axis — each
    device scans its own row shard in VMEM (the ``pallas_sort.sort2``
    pattern).  The legacy forms keep working: ``mesh_tracing()`` / ``True``
    declines the kernels for the scope (no mesh to shard_map over), and
    ``mesh_tracing(False)`` re-enables bare kernels inside an active scope.
    """
    prev = getattr(_tls, "mesh", False)
    _tls.mesh = mesh
    try:
        yield
    finally:
        _tls.mesh = prev


def _mesh_shards() -> Optional[int]:
    """How many data-axis shards the current trace's rows split into.

    1 outside ``mesh_tracing`` (bare kernels are fine); the data-axis size
    under ``mesh_tracing(mesh)``; None when kernels must decline — the
    legacy ``mesh_tracing()`` marker, or a mesh without a usable data axis
    (callers then take the lax scans, which partition fine under GSPMD)."""
    state = getattr(_tls, "mesh", False)
    if state is False or state is None:
        return 1
    if state is True:
        return None
    size = dict(state.shape).get(_DATA_AXIS)
    if size == 1 and state.devices.size > 1:
        return None
    return size


def _current_mesh() -> Optional[Mesh]:
    """The mesh to shard_map kernels over, or None for a bare kernel."""
    state = getattr(_tls, "mesh", False)
    if isinstance(state, Mesh):
        shards = _mesh_shards()
        if shards is not None and shards > 1:
            return state
    return None


# --- dispatch accounting ----------------------------------------------------
#
# bench.py's BENCH_FUSED A/B counts how many scan dispatches one traced
# (bucket, phase) program issues — the figure the fused kernel exists to
# shrink.  Recording is thread-local and a no-op unless a
# count_scan_dispatches() scope is active, so the hot path pays one getattr.


def record_scan_dispatch(kind: str) -> None:
    """Count one scan dispatch of ``kind`` ("fused", "pallas_scan",
    "lax_scan") if a :func:`count_scan_dispatches` scope is active."""
    counts = getattr(_tls, "dispatch_counts", None)
    if counts is not None:
        counts[kind] = counts.get(kind, 0) + 1


@contextlib.contextmanager
def count_scan_dispatches():
    """Collect per-kind scan dispatch counts issued while tracing under this
    scope (trace-time accounting: each recorded dispatch is one device
    kernel/scan in the lowered program)."""
    prev = getattr(_tls, "dispatch_counts", None)
    counts: Dict[str, int] = {}
    _tls.dispatch_counts = counts
    try:
        yield counts
    finally:
        _tls.dispatch_counts = prev


def _blk_for(length: int) -> int:
    for blk in (_BLK, 256, 128):
        if length % blk == 0:
            return blk
    raise ValueError(f"row length {length} is not a multiple of 128")


def _scan_body(op: Callable, identities: Sequence[int], refs) -> None:
    """Kernel body: blocked inclusive scan of an n-stream int32 tuple state
    along the lane axis, one VMEM-resident row tile per grid step."""
    n = len(refs) // 2
    in_refs, out_refs = refs[:n], refs[n:]
    rows, length = in_refs[0].shape
    blk = _blk_for(length)
    # In-kernel lane index (Pallas kernels cannot capture host constants).
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
    idents = tuple(jnp.int32(i) for i in identities)

    def body(i, carry):
        start = i * blk
        xs = tuple(r[:, pl.ds(start, blk)] for r in in_refs)
        d = 1
        while d < blk:
            # Hillis–Steele level: acc[j] = op(acc[j-d], acc[j]).  The roll
            # is circular; wrapped lanes are masked to the op identity.
            shifted = tuple(
                jnp.where(lane >= d, roll_lanes(x, d), ident)
                for x, ident in zip(xs, idents)
            )
            xs = op(shifted, xs)
            d *= 2
        # Fold the running prefix of all earlier blocks ([rows, 1],
        # broadcast) in front of this block's inclusive scan.
        xs = op(carry, xs)
        for r, x in zip(out_refs, xs):
            r[:, pl.ds(start, blk)] = x
        return tuple(x[:, blk - 1 : blk] for x in xs)

    init = tuple(jnp.full((rows, 1), i, jnp.int32) for i in identities)
    jax.lax.fori_loop(0, length // blk, body, init)


def _pallas_scan_tuple(
    op: Callable,
    identities: Sequence[int],
    xs: Tuple[jax.Array, ...],
    interpret: bool,
) -> Tuple[jax.Array, ...]:
    """Row-wise inclusive associative scan of int32 ``[B, L]`` streams.
    ``op`` maps (earlier-tuple, later-tuple) -> tuple with elementwise jnp
    ops only (operands may broadcast ``[B, 1]`` against ``[B, blk]``)."""
    n = len(xs)
    b, length = xs[0].shape

    def kernel(*refs):
        _scan_body(op, identities, refs)

    spec = pl.BlockSpec((ROWS, length), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct((b, length), jnp.int32)
    return tuple(
        pl.pallas_call(
            kernel,
            grid=(b // ROWS,),
            in_specs=[spec] * n,
            out_specs=[spec] * n,
            out_shape=[shape] * n,
            interpret=interpret,
        )(*(x.astype(jnp.int32) for x in xs))
    )


# --- associative ops (must match the lax twins bit-for-bit) -----------------


def _affine_op(xs, ys):
    # Segmented polynomial hash: affine maps h -> m*h + a, composed
    # earlier-then-later; identical to stats._poly_hash_many's compose.
    mx, axs = xs[0], xs[1:]
    my, ays = ys[0], ys[1:]
    return (mx * my,) + tuple(ay + my * ax for ax, ay in zip(axs, ays))


def _add_op(xs, ys):
    # Plain elementwise sum streams — exact by integer associativity, used
    # both for cumulative counts and (emit="last") whole-row totals.
    return tuple(x + y for x, y in zip(xs, ys))


def _dfa_op(n_states: int) -> Callable:
    def op(xs, ys):
        # (b . a)(s) = b[a[s]]: route each of a's nibbles through b —
        # identical to dfa.dfa_states's compose.
        a, b = xs[0], ys[0]
        out = None
        for s in range(n_states):
            nib = (a >> (4 * s)) & 15
            term = ((b >> (nib << 2)) & 15) << (4 * s)
            out = term if out is None else out | term
        return (out,)

    return op


def _dfa_ident(n_states: int) -> int:
    ident = 0
    for s in range(n_states):
        ident |= s << (4 * s)
    return ident


#: Identity for the segmented-max value stream: max(_I32_MIN, x) == x.
_I32_MIN = -(2**31)


def _segmax_op(xs, ys):
    # Segmented running max over (value, reset) pairs — the kernel twin of
    # device._seg_max_op (reset-as-int32, same select/or formulation).
    av, ar = xs
    bv, br = ys
    return (jnp.where(br != 0, bv, jnp.maximum(av, bv)), ar | br)


# --- fused multi-group megakernel -------------------------------------------
#
# A "group" is one independent associative scan over one or more int32
# [B, L] streams.  fused_scan() lowers a list of groups into a single
# pallas_call whose body walks each lane block once and runs every group's
# Hillis–Steele doubling on the in-register tile — so a phase's statistics
# cost one dispatch, and streams a caller only needs reduced (emit="last")
# never touch HBM at full width.


def affine_group(
    m: jax.Array, accs: Sequence[jax.Array], emit: str = "scan"
) -> dict:
    """Shared-multiplier segmented affine-hash group (the fused twin of
    :func:`affine_hash_scan`).  Emits only the accumulator streams — the
    scanned multiplier stays in-register."""
    return {"kind": "affine", "xs": (m,) + tuple(accs), "emit": emit}


def add_group(vals: Sequence[jax.Array], emit: str = "scan") -> dict:
    """Elementwise running-sum group.  ``emit="last"`` yields ``[B, 1]``
    whole-row totals (the fused twin of ``jnp.sum(..., axis=1)``)."""
    return {"kind": "add", "xs": tuple(vals), "emit": emit}


def dfa_group(fns: jax.Array, n_states: int, emit: str = "scan") -> dict:
    """Nibble-packed DFA transition-map composition group (the fused twin of
    :func:`dfa_compose_scan`)."""
    return {"kind": "dfa", "xs": (fns,), "emit": emit, "n_states": n_states}


def _group_spec(g: dict) -> Tuple[Optional[Callable], Tuple[int, ...], int, Tuple[int, ...], bool]:
    """(op, identities, n_operands, emitted stream indices, emit_last).

    ``n_operands`` counts the streams the associative op runs over — for
    chain groups with a ``prep`` this is ``g["n_ops"]`` (what prep returns),
    not the dep count.  ``emit="none"`` behaves like "scan" in-kernel but
    the chain layer stages the stream through scratch instead of HBM."""
    kind = g["kind"]
    n_in = g.get("n_ops", len(g["xs"]))
    emit = g.get("emit", "scan")
    if emit not in ("scan", "last", "none"):
        raise ValueError(f"unknown emit mode {emit!r}")
    emit_last = emit == "last"
    if kind == "affine":
        return _affine_op, (1,) + (0,) * (n_in - 1), n_in, tuple(range(1, n_in)), emit_last
    if kind == "add":
        return _add_op, (0,) * n_in, n_in, tuple(range(n_in)), emit_last
    if kind == "dfa":
        n_states = g["n_states"]
        return _dfa_op(n_states), (_dfa_ident(n_states),), 1, (0,), emit_last
    if kind == "segmax":
        return _segmax_op, (_I32_MIN, 0), 2, (0,), emit_last
    if kind == "copy":
        # Elementwise pass-through (no doubling, no carry): materializes a
        # prep-derived stream so later passes can tap it.
        if emit_last:
            raise ValueError("copy groups cannot emit='last'")
        return None, (0,) * n_in, n_in, tuple(range(n_in)), False
    raise ValueError(f"unknown fused group kind {kind!r}")


def _fused_body(specs, refs) -> None:
    """Kernel body: one pass over the row tile's lane blocks, every group's
    blocked doubling + carry fold run on each in-register block."""
    n_in_total = sum(s[2] for s in specs)
    in_refs, out_refs = refs[:n_in_total], refs[n_in_total:]
    rows, length = in_refs[0].shape
    blk = _blk_for(length)
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)

    # Static partition of the flat ref lists back into per-group views.
    group_in, group_out = [], []
    i = j = 0
    for _, _, n_in, emit_idx, _ in specs:
        group_in.append(in_refs[i : i + n_in])
        i += n_in
        group_out.append(out_refs[j : j + len(emit_idx)])
        j += len(emit_idx)

    def body(b_i, carry):
        start = b_i * blk
        new_carry = []
        for g, (op, identities, _, emit_idx, emit_last) in enumerate(specs):
            xs = tuple(r[:, pl.ds(start, blk)] for r in group_in[g])
            idents = tuple(jnp.int32(v) for v in identities)
            d = 1
            while d < blk:
                shifted = tuple(
                    jnp.where(lane >= d, roll_lanes(x, d), ident)
                    for x, ident in zip(xs, idents)
                )
                xs = op(shifted, xs)
                d *= 2
            xs = op(carry[g], xs)
            if not emit_last:
                for r, x_idx in zip(group_out[g], emit_idx):
                    r[:, pl.ds(start, blk)] = xs[x_idx]
            new_carry.append(tuple(x[:, blk - 1 : blk] for x in xs))
        return tuple(new_carry)

    init = tuple(
        tuple(jnp.full((rows, 1), v, jnp.int32) for v in s[1]) for s in specs
    )
    final = jax.lax.fori_loop(0, length // blk, body, init)
    for g, (_, _, _, emit_idx, emit_last) in enumerate(specs):
        if emit_last:
            for r, x_idx in zip(group_out[g], emit_idx):
                r[:, :] = final[g][x_idx]


def _fused_call(groups: Sequence[dict], interpret: bool) -> Tuple[jax.Array, ...]:
    """One pallas_call evaluating every group; returns the flat tuple of
    emitted streams in group order."""
    specs = tuple(_group_spec(g) for g in groups)
    xs = tuple(x for g in groups for x in g["xs"])
    b, length = xs[0].shape

    def kernel(*refs):
        _fused_body(specs, refs)

    row_spec = pl.BlockSpec((ROWS, length), lambda i: (i, 0))
    last_spec = pl.BlockSpec((ROWS, 1), lambda i: (i, 0))
    out_specs: List[pl.BlockSpec] = []
    out_shapes: List[jax.ShapeDtypeStruct] = []
    for _, _, _, emit_idx, emit_last in specs:
        for _ in emit_idx:
            out_specs.append(last_spec if emit_last else row_spec)
            out_shapes.append(
                jax.ShapeDtypeStruct((b, 1) if emit_last else (b, length), jnp.int32)
            )
    return tuple(
        pl.pallas_call(
            kernel,
            grid=(b // ROWS,),
            in_specs=[row_spec] * len(xs),
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
        )(*(x.astype(jnp.int32) for x in xs))
    )


def _regroup(groups: Sequence[dict], flat: Sequence[jax.Array]):
    """Split a flat emitted-stream tuple back into per-group tuples."""
    out, i = [], 0
    for g in groups:
        k = len(_group_spec(g)[3])
        out.append(tuple(flat[i : i + k]))
        i += k
    return out


# --- shard_map dispatch -----------------------------------------------------


def _shard_mapped(fn: Callable, mesh: Mesh, xs: Tuple[jax.Array, ...], n_out: int):
    """Run ``fn`` (a bare pallas scan over the local row shard) under
    shard_map, rows sharded along the data axis — the pallas_sort._sharded_sort
    pattern.  Rows are independent, so no collective is inserted."""
    spec = P(_DATA_AXIS, None)
    kwargs = dict(mesh=mesh, in_specs=(spec,) * len(xs), out_specs=(spec,) * n_out)
    try:
        # Replication checking needs vma annotations pallas outputs don't
        # carry; rows are fully sharded, nothing is replicated — disable it.
        mapped = shard_map(fn, check_vma=False, **kwargs)
    except TypeError:  # pre-vma JAX spells it check_rep
        mapped = shard_map(fn, check_rep=False, **kwargs)
    return mapped(*xs)


def _dispatch_scan_tuple(
    op: Callable, identities: Sequence[int], xs: Tuple[jax.Array, ...]
) -> Tuple[jax.Array, ...]:
    """Mesh-aware dispatch for the per-scan kernels: bare pallas_call on a
    single device, shard_map'd over the data axis under ``mesh_tracing(mesh)``.
    Callers gate on :func:`pallas_scan_ok` first."""
    record_scan_dispatch("pallas_scan")
    interpret = interpret_forced()
    mesh = _current_mesh()
    if mesh is not None:
        def fn(*ks):
            return _pallas_scan_tuple(op, identities, ks, interpret)

        return tuple(_shard_mapped(fn, mesh, tuple(xs), len(xs)))
    return _pallas_scan_tuple(op, identities, tuple(xs), interpret)


# --- support gates ----------------------------------------------------------


def _env_hatches() -> Tuple[str, ...]:
    """The env hatches that shape a probe verdict.  Probe caches key on
    these so flipping a hatch mid-process (as tests do) re-probes instead of
    serving the verdict cached under the old env."""
    return (
        os.environ.get("TEXTBLAST_PALLAS", ""),
        os.environ.get("TEXTBLAST_NO_PALLAS", ""),
        os.environ.get("TEXTBLAST_PALLAS_INTERPRET", ""),
        os.environ.get("TEXTBLAST_FUSED", ""),
        os.environ.get("TEXTBLAST_DEPFUSE", ""),
    )


@functools.lru_cache(maxsize=32)
def _probe_cached(env: Tuple[str, ...], backend: str) -> bool:
    """Compile and run one tiny kernel on the live backend, checking it
    against the lax result — Mosaic availability differs per
    backend/runtime version and a failed probe must mean fallback, not a
    crashed pipeline."""
    del env  # participates only in the cache key
    if pltpu is None or backend == "cpu":
        return False
    try:
        with jax.ensure_compile_time_eval():
            m = jnp.full((ROWS, 128), 31, jnp.int32)
            a = (jax.lax.broadcasted_iota(jnp.int32, (ROWS, 128), 1) * 7) % 97
            got = _pallas_scan_tuple(_affine_op, (1, 0), (m, a), interpret=False)
            want = jax.lax.associative_scan(_affine_op, (m, a), axis=1)
            ok = all(bool(jnp.array_equal(g, w)) for g, w in zip(got, want))
        if not ok:  # pragma: no cover - would be a Mosaic miscompile
            logger.warning("Pallas scan probe mismatch; using lax scans")
        return ok
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("Pallas scan unavailable on %s: %s", backend, e)
        return False


def _probe_backend() -> bool:
    return _probe_cached(_env_hatches(), jax.default_backend())


@functools.lru_cache(maxsize=32)
def _probe_fused_cached(env: Tuple[str, ...], backend: str) -> bool:
    """Probe the fused megakernel specifically: its emit="last" outputs use
    a narrower BlockSpec the per-scan probe never exercises."""
    del env
    if pltpu is None or backend == "cpu":
        return False
    try:
        with jax.ensure_compile_time_eval():
            m = jnp.full((ROWS, 128), 31, jnp.int32)
            a = (jax.lax.broadcasted_iota(jnp.int32, (ROWS, 128), 1) * 7) % 97
            ones = jnp.ones((ROWS, 128), jnp.int32)
            got = _fused_call(
                [affine_group(m, (a,)), add_group((ones,), emit="last")],
                interpret=False,
            )
            want_h = jax.lax.associative_scan(_affine_op, (m, a), axis=1)[1]
            ok = bool(jnp.array_equal(got[0], want_h)) and bool(
                jnp.array_equal(got[1], jnp.full((ROWS, 1), 128, jnp.int32))
            )
        if not ok:  # pragma: no cover - would be a Mosaic miscompile
            logger.warning("fused scan probe mismatch; using staged scans")
        return ok
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("fused scan unavailable on %s: %s", backend, e)
        return False


def _probe_fused() -> bool:
    return _probe_fused_cached(_env_hatches(), jax.default_backend())


def fused_enabled() -> bool:
    """``TEXTBLAST_FUSED=off`` (or ``0``/``false``) disables the fused
    megakernel only; re-read per call so tests/benches can toggle it."""
    return os.environ.get("TEXTBLAST_FUSED", "").lower() not in ("off", "0", "false")


def pallas_scan_supported() -> bool:
    """Whether the scan kernels can run here.  Env decisions are re-read per
    call (the backend probe is cached keyed on env hatches + backend);
    False under the legacy mesh-marker trace or a mesh with no usable data
    axis (see :func:`mesh_tracing` — a real mesh shard_maps instead)."""
    if not pallas_enabled():
        return False
    if _mesh_shards() is None:
        return False
    if interpret_forced():
        return True
    return _probe_backend()


def pallas_scan_ok(b: int, length: int) -> bool:
    """Shape + support gate callers use before dispatching to a kernel.
    Under ``mesh_tracing(mesh)`` the row count must split evenly into
    ROWS-aligned per-device shards (the shard_map'd kernel sees ``b/shards``
    rows)."""
    if not pallas_scan_supported():
        return False
    shards = _mesh_shards()
    if shards is None or b <= 0 or b % shards:
        return False
    return (
        (b // shards) % ROWS == 0
        and 128 <= length <= _MAX_LANES
        and length % 128 == 0
    )


def fused_scan_ok(b: int, length: int) -> bool:
    """Gate for :func:`fused_scan` — the per-scan gate plus the fused
    kernel's own hatch, probe, and tighter VMEM lane ceiling."""
    if not fused_enabled():
        return False
    if not pallas_scan_ok(b, length):
        return False
    if length > _FUSED_MAX_LANES:
        return False
    return interpret_forced() or _probe_fused()


# --- public kernels ---------------------------------------------------------


def dfa_compose_scan(fns: jax.Array, n_states: int) -> jax.Array:
    """Inclusive scan of nibble-packed DFA transition maps along axis 1 —
    the kernel twin of ``dfa.dfa_states``'s <=8-state composition.  Callers
    gate on :func:`pallas_scan_ok` first."""
    (out,) = _dispatch_scan_tuple(
        _dfa_op(n_states), (_dfa_ident(n_states),), (fns,)
    )
    return out


def affine_hash_scan(
    m: jax.Array, accs: Tuple[jax.Array, ...]
) -> Tuple[jax.Array, ...]:
    """Inclusive scan of the shared-multiplier affine hash op — the kernel
    twin of ``stats._poly_hash_many``.  Returns the scanned accumulator
    streams (the scanned multiplier is internal).  Callers gate on
    :func:`pallas_scan_ok` first."""
    identities = (1,) + (0,) * len(accs)
    out = _dispatch_scan_tuple(_affine_op, identities, (m,) + tuple(accs))
    return out[1:]


def fused_scan(groups: Sequence[dict]) -> List[Tuple[jax.Array, ...]]:
    """Evaluate several independent scan groups in ONE kernel pass over the
    row tile — see the module docstring.  Returns one tuple of emitted
    int32 streams per group, in order: ``[B, L]`` scans for ``emit="scan"``
    groups, ``[B, 1]`` per-row totals for ``emit="last"`` groups.  Callers
    gate on :func:`fused_scan_ok` first."""
    record_scan_dispatch("fused")
    interpret = interpret_forced()
    mesh = _current_mesh()
    if mesh is not None:
        xs = tuple(x for g in groups for x in g["xs"])
        sizes = [len(g["xs"]) for g in groups]
        n_out = sum(len(_group_spec(g)[3]) for g in groups)

        def fn(*flat_xs):
            local, i = [], 0
            for g, n in zip(groups, sizes):
                local.append(dict(g, xs=tuple(flat_xs[i : i + n])))
                i += n
            return _fused_call(local, interpret)

        flat = tuple(_shard_mapped(fn, mesh, xs, n_out))
    else:
        flat = _fused_call(groups, interpret)
    return _regroup(groups, flat)


# --- dependency-chained multi-pass megakernel --------------------------------
#
# fused_scan only fuses *independent* groups: a scan whose operands derive
# from another scan's output still pays a separate dispatch with an HBM
# round-trip between the two.  chain_scan lifts that restriction: a chain is
# an ordered list of passes, and a pass's groups may consume earlier passes'
# emitted streams through Tap references — resolved in-kernel against the
# output (or VMEM scratch) row tile, which the earlier pass has fully
# written by the time the later pass's fori_loop starts.  The whole chain is
# ONE pallas_call: the GopherRepetition hash -> n-gram dedup feeders, the
# word-cumsum -> n_words consumers, and the sentence-DFA -> compaction
# handoff each walk the packed tile once instead of 2-4 staged dispatches.
#
# Orientation: every stream (external or emitted) is stored in natural lane
# order.  A pass with reverse=True *walks* the row tile back-to-front (its
# lane blocks are loaded mirrored + flipped into "walk order", scanned, and
# written back flipped), which computes the staged ``rev(scan(rev(x)))``
# idiom bit-exactly while emitting the result already in natural
# orientation.  Prep callables always see walk-ordered blocks; since they
# are elementwise, flip commutes and parity is preserved.
#
# Tap(pass_idx, out_idx, shift, fill) addresses the ``out_idx``-th emitted
# stream (flattened over that pass's groups, all emit modes counted) of an
# earlier pass.  shift=1 reads the stream at the *previous walk position*
# (the staged ``_shift_r`` in a forward pass, ``_shift_l`` in a reverse
# pass), with ``fill`` injected at walk position 0.  Shifted *external*
# operands never need kernel support — callers pre-shift them on the host
# (elementwise, exact).  emit="none" streams are tap-only: they live in VMEM
# scratch (``pltpu.VMEM``) and never touch HBM; when pltpu is unavailable
# (interpret-only platforms) they degrade to discarded outputs.


class Tap(NamedTuple):
    """Reference to an earlier chain pass's emitted stream (see above)."""

    pass_idx: int
    out_idx: int
    shift: int = 0
    fill: int = 0


def chain_group(
    kind: str,
    deps: Sequence,
    prep: Optional[Callable] = None,
    n_ops: Optional[int] = None,
    emit: str = "scan",
    n_states: Optional[int] = None,
) -> dict:
    """A chain-pass scan group.  ``deps`` mixes ``[B, L]`` arrays (external
    operands) and :class:`Tap` references; ``prep`` (elementwise, walk-frame)
    maps the loaded dep blocks to the op's ``n_ops`` operand streams —
    omitted, the deps are the operands directly."""
    g = {"kind": kind, "xs": tuple(deps), "emit": emit}
    if n_states is not None:
        g["n_states"] = n_states
    if prep is not None:
        if n_ops is None:
            raise ValueError("chain_group with prep= requires n_ops=")
        g["prep"] = prep
        g["n_ops"] = n_ops
    return g


def segmax_group(v, r, emit: str = "scan") -> dict:
    """Segmented running-max group over (value, reset) — the fused twin of
    ``device.seg_scan_max``."""
    return {"kind": "segmax", "xs": (v, r), "emit": emit}


def copy_group(vals: Sequence, emit: str = "none") -> dict:
    """Elementwise materialization group (no scan): stages prep-derived
    streams so later passes can tap them."""
    return {"kind": "copy", "xs": tuple(vals), "emit": emit}


def chain_pass(groups: Sequence[dict], reverse: bool = False) -> dict:
    """One pass of a :func:`chain_scan` program."""
    return {"groups": list(groups), "reverse": bool(reverse)}


def _chain_plan(passes: Sequence[dict]):
    """Resolve a chain program statically: dedup external arrays (by object
    identity), assign every emitted stream to an output or scratch slot, and
    produce the kernel plan plus the caller-facing result layout."""
    ext_arrays: List[jax.Array] = []
    ext_index: Dict[int, int] = {}
    stream_table: List[List[Tuple[Tuple[str, int], str]]] = []
    out_modes: List[str] = []  # per out slot: "scan" | "last" | "drop"
    n_scratch = 0
    plan = []
    layout: List[List[List[int]]] = []
    use_scratch = pltpu is not None
    for p_idx, pss in enumerate(passes):
        groups_plan = []
        pass_streams: List[Tuple[Tuple[str, int], str]] = []
        pass_layout: List[List[int]] = []
        for g in pss["groups"]:
            spec = _group_spec(g)
            emit = g.get("emit", "scan")
            deps = []
            for d in g["xs"]:
                if isinstance(d, Tap):
                    if not 0 <= d.pass_idx < p_idx:
                        raise ValueError(
                            f"Tap(pass_idx={d.pass_idx}) must reference an "
                            f"earlier pass (current pass {p_idx})"
                        )
                    if d.shift not in (0, 1):
                        raise ValueError("Tap.shift must be 0 or 1")
                    storage, s_emit = stream_table[d.pass_idx][d.out_idx]
                    if s_emit == "last":
                        raise ValueError("cannot tap an emit='last' stream")
                    deps.append(("s", storage, d.shift, int(d.fill)))
                else:
                    key = id(d)
                    if key not in ext_index:
                        ext_index[key] = len(ext_arrays)
                        ext_arrays.append(d)
                    deps.append(("e", ext_index[key]))
            streams: List[Tuple[str, int]] = []
            g_layout: List[int] = []
            for _ in spec[3]:
                if emit == "none" and use_scratch:
                    storage = ("scratch", n_scratch)
                    n_scratch += 1
                else:
                    storage = ("out", len(out_modes))
                    out_modes.append("drop" if emit == "none" else emit)
                    if emit != "none":
                        g_layout.append(storage[1])
                streams.append(storage)
                pass_streams.append((storage, emit))
            groups_plan.append(
                {"spec": spec, "prep": g.get("prep"), "deps": deps, "streams": streams}
            )
            pass_layout.append(g_layout)
        stream_table.append(pass_streams)
        plan.append({"reverse": bool(pss.get("reverse", False)), "groups": groups_plan})
        layout.append(pass_layout)
    if not ext_arrays:
        raise ValueError("chain_scan needs at least one external operand")
    return plan, ext_arrays, out_modes, n_scratch, layout


def _chain_body(plan, refs, n_ext: int, n_out: int) -> None:
    """Kernel body: sequential per-pass fori_loops over one VMEM-resident
    row tile.  Pass p fully writes its emitted streams (output or scratch
    refs) before pass p+1's loop starts, so taps — including block-crossing
    shift taps — read settled data without leaving the kernel."""
    in_refs = refs[:n_ext]
    out_refs = refs[n_ext : n_ext + n_out]
    scratch_refs = refs[n_ext + n_out :]
    rows, length = in_refs[0].shape
    blk = _blk_for(length)
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)

    def ref_for(storage):
        return out_refs[storage[1]] if storage[0] == "out" else scratch_refs[storage[1]]

    for pss in plan:
        reverse = pss["reverse"]
        groups = pss["groups"]

        def load(ref, b_i, shift, fill):
            start = b_i * blk
            if reverse:
                # Mirrored block, flipped into walk order: walk lane w of
                # block b_i is natural lane length-1-(b_i*blk+w).
                x = jnp.flip(ref[:, pl.ds(length - start - blk, blk)], axis=1)
            else:
                x = ref[:, pl.ds(start, blk)]
            if shift:
                # Previous-walk-position value: the natural lane just past
                # this block's walk start (clamped; unused when b_i == 0,
                # where ``fill`` is injected instead).
                if reverse:
                    prev_idx = jnp.minimum(length - start, length - 1)
                else:
                    prev_idx = jnp.maximum(start - 1, 0)
                prev = jnp.where(
                    b_i == 0,
                    jnp.full((rows, 1), fill, jnp.int32),
                    ref[:, pl.ds(prev_idx, 1)],
                )
                x = jnp.where(lane < 1, prev, roll_lanes(x, 1))
            return x

        def body(b_i, carry):
            start = b_i * blk
            new_carry = []
            for gi, g in enumerate(groups):
                op, identities, _, emit_idx, emit_last = g["spec"]
                blocks = []
                for d in g["deps"]:
                    if d[0] == "e":
                        blocks.append(load(in_refs[d[1]], b_i, 0, 0))
                    else:
                        blocks.append(load(ref_for(d[1]), b_i, d[2], d[3]))
                prep = g["prep"]
                xs = tuple(prep(*blocks)) if prep is not None else tuple(blocks)
                xs = tuple(jnp.asarray(x).astype(jnp.int32) for x in xs)
                if op is not None:
                    idents = tuple(jnp.int32(v) for v in identities)
                    d2 = 1
                    while d2 < blk:
                        shifted = tuple(
                            jnp.where(lane >= d2, roll_lanes(x, d2), ident)
                            for x, ident in zip(xs, idents)
                        )
                        xs = op(shifted, xs)
                        d2 *= 2
                    xs = op(carry[gi], xs)
                if not emit_last:
                    for storage, x_idx in zip(g["streams"], emit_idx):
                        r = ref_for(storage)
                        if reverse:
                            r[:, pl.ds(length - start - blk, blk)] = jnp.flip(
                                xs[x_idx], axis=1
                            )
                        else:
                            r[:, pl.ds(start, blk)] = xs[x_idx]
                new_carry.append(
                    tuple(x[:, blk - 1 : blk] for x in xs) if op is not None else ()
                )
            return tuple(new_carry)

        init = tuple(
            tuple(jnp.full((rows, 1), v, jnp.int32) for v in g["spec"][1])
            if g["spec"][0] is not None
            else ()
            for g in groups
        )
        final = jax.lax.fori_loop(0, length // blk, body, init)
        for gi, g in enumerate(groups):
            _, _, _, emit_idx, emit_last = g["spec"]
            if emit_last:
                for storage, x_idx in zip(g["streams"], emit_idx):
                    ref_for(storage)[:, :] = final[gi][x_idx]


def _chain_call(plan, ext_arrays, out_modes, n_scratch: int, interpret: bool):
    b, length = ext_arrays[0].shape
    n_ext = len(ext_arrays)
    n_out = len(out_modes)

    def kernel(*refs):
        _chain_body(plan, refs, n_ext, n_out)

    row_spec = pl.BlockSpec((ROWS, length), lambda i: (i, 0))
    last_spec = pl.BlockSpec((ROWS, 1), lambda i: (i, 0))
    out_specs = [last_spec if m == "last" else row_spec for m in out_modes]
    out_shapes = [
        jax.ShapeDtypeStruct((b, 1) if m == "last" else (b, length), jnp.int32)
        for m in out_modes
    ]
    kwargs = {}
    if n_scratch:
        kwargs["scratch_shapes"] = [pltpu.VMEM((ROWS, length), jnp.int32)] * n_scratch
    return tuple(
        pl.pallas_call(
            kernel,
            grid=(b // ROWS,),
            in_specs=[row_spec] * n_ext,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
            **kwargs,
        )(*(x.astype(jnp.int32) for x in ext_arrays))
    )


def chain_scan(passes: Sequence[dict]) -> List[List[Tuple[jax.Array, ...]]]:
    """Evaluate a dependency-chained multi-pass program in ONE kernel
    dispatch — see the section comment above.  Returns, per pass, one tuple
    of emitted int32 arrays per group (``[B, L]`` for emit="scan", ``[B, 1]``
    for emit="last"; emit="none" streams are tap-only and omitted).  Every
    external operand must be ``[B, L]``.  Callers gate on
    :func:`chain_scan_ok` first."""
    record_scan_dispatch("fused")
    plan, ext_arrays, out_modes, n_scratch, layout = _chain_plan(passes)
    interpret = interpret_forced()
    mesh = _current_mesh()
    if mesh is not None:
        def fn(*xs):
            return _chain_call(plan, tuple(xs), out_modes, n_scratch, interpret)

        flat = tuple(_shard_mapped(fn, mesh, tuple(ext_arrays), len(out_modes)))
    else:
        flat = _chain_call(plan, tuple(ext_arrays), out_modes, n_scratch, interpret)
    return [
        [tuple(flat[s] for s in g_slots) for g_slots in p_layout]
        for p_layout in layout
    ]


def depfuse_enabled() -> bool:
    """``TEXTBLAST_DEPFUSE=off`` (or ``0``/``false``) disables the
    dependency-chained multi-pass megakernel only; re-read per call so
    tests/benches can toggle it."""
    return os.environ.get("TEXTBLAST_DEPFUSE", "").lower() not in ("off", "0", "false")


@functools.lru_cache(maxsize=32)
def _probe_depfuse_cached(env: Tuple[str, ...], backend: str) -> bool:
    """Probe the chain kernel specifically: reverse-walk passes (lane
    flips), cross-pass taps, shift taps, VMEM scratch staging, and the
    segmented-max op exercise Mosaic surface the fused probe never
    touches."""
    del env
    if pltpu is None or backend == "cpu":
        return False
    try:
        with jax.ensure_compile_time_eval():
            L = 256
            iota = jax.lax.broadcasted_iota(jnp.int32, (ROWS, L), 1)
            vals = (iota * 7 + 3) % 97
            reset = ((iota % 64) == 0).astype(jnp.int32)
            m = jnp.where(reset != 0, 0, 1)
            probe_passes = [
                    chain_pass([{"kind": "affine", "xs": (m, vals), "emit": "none"}]),
                    chain_pass(
                        [
                            chain_group(
                                "segmax",
                                (Tap(0, 0), reset),
                                prep=lambda seg, r: (jnp.where(r != 0, seg, 0), r),
                                n_ops=2,
                            )
                        ],
                        reverse=True,
                    ),
                    chain_pass(
                        [
                            chain_group(
                                "copy",
                                (Tap(1, 0), Tap(0, 0, shift=1, fill=0)),
                                prep=lambda rt, prev: (rt + prev,),
                                n_ops=1,
                                emit="scan",
                            ),
                            chain_group(
                                "add",
                                (Tap(1, 0),),
                                prep=lambda rt: (jnp.where(rt > 50, 1, 0),),
                                n_ops=1,
                                emit="last",
                            ),
                        ]
                    ),
                ]
            plan, ext, modes, n_scr, layout = _chain_plan(probe_passes)
            flat = _chain_call(plan, tuple(ext), modes, n_scr, interpret=False)
            got = [
                [tuple(flat[s] for s in g_slots) for g_slots in p_layout]
                for p_layout in layout
            ]
            seg = jax.lax.associative_scan(_affine_op, (m, vals), axis=1)[1]
            rt = jnp.flip(
                jax.lax.associative_scan(
                    _segmax_op,
                    (
                        jnp.flip(jnp.where(reset != 0, seg, 0), 1),
                        jnp.flip(reset, 1),
                    ),
                    axis=1,
                )[0],
                1,
            )
            prev = jnp.concatenate([jnp.zeros((ROWS, 1), jnp.int32), seg[:, :-1]], 1)
            ok = (
                bool(jnp.array_equal(got[2][0][0], rt + prev))
                and bool(
                    jnp.array_equal(
                        got[2][1][0],
                        jnp.sum(jnp.where(rt > 50, 1, 0), axis=1, keepdims=True),
                    )
                )
                and bool(jnp.array_equal(got[1][0][0], rt))
            )
        if not ok:  # pragma: no cover - would be a Mosaic miscompile
            logger.warning("chain scan probe mismatch; using staged scans")
        return ok
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("chain scan unavailable on %s: %s", backend, e)
        return False


def _probe_depfuse() -> bool:
    return _probe_depfuse_cached(_env_hatches(), jax.default_backend())


def chain_scan_ok(b: int, length: int) -> bool:
    """Gate for :func:`chain_scan` — the fused gate (so ``TEXTBLAST_FUSED``
    and the mesh/shape rules compose) plus the dependency-fusion hatch and
    its own backend probe."""
    if not depfuse_enabled():
        return False
    if not fused_scan_ok(b, length):
        return False
    return interpret_forced() or _probe_depfuse()
