"""Per-filter statistic kernels over packed codepoint batches.

Each kernel maps ``(cps [B, L], lengths [B])`` to per-document **integer**
statistics.  Ratios, thresholds, and reason strings are computed host-side in
float64 from these integers — identical to the oracle's arithmetic — so
device/host parity cannot be broken by accumulation order (SURVEY.md §7
stage 2: "segmented reductions ... then scalar threshold logic" — the scalar
logic stays on the host).

Structure recovery is scan-based: word/line/paragraph segmentation via
segmented associative scans (:mod:`.device`), citation matching and sentence
boundaries via DFA composition (:mod:`.dfa`), duplicate detection via in-row
sorts of (hash, length) keys.  All per-segment scatters write exactly once
per slot (at segment-end positions) — duplicate-index scatter order is
undefined in XLA.

Known device/oracle divergences (each measured by the parity suite,
tests/test_device_parity.py):
* duplicate detection compares 32-bit content hashes, not strings —
  cross-content collisions are ~2^-32 per pair.

(``find_all_duplicate``'s visited-set dynamics — the oracle's ``seen`` only
holds windows the greedy scan actually *visited*, so a window whose only
earlier twins were skipped over is NOT a duplicate — is reproduced exactly
by ``_find_all_dup_bytes_batched``'s lockstep walk; an earlier static
"any earlier twin" approximation diverged on dense repetitions and was
caught by tests/test_fuzz_parity.py.)
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.text import _CLOSE, _PARA_SEP, _SP, _STERM
from .compact import compact
from .device import (
    ALNUM,
    ALPHA,
    DIGIT,
    EXTEND,
    LOWER,
    PUNCT,
    WS,
    assoc_scan1,
    classify,
    isin_sorted,
    latch_scan,
    lower_table,
    rev,
    seg_scan_add,
    seg_scan_max,
    seg_scan_or,
    use_sort_tables,
    utf8_width,
    word_mask,
)
from .dfa import citation_spans, dfa_states
from .pallas_sort import sort2

__all__ = [
    "TextStructure",
    "structure",
    "gopher_quality_stats",
    "fineweb_stats",
    "gopher_rep_stats",
    "c4_stage",
    "C4Params",
    "sentence_counts",
    "hash_string",
]

NL = ord("\n")
CR = ord("\r")


def _shift_r(x: jax.Array, fill=0) -> jax.Array:
    """x[i-1] along axis 1 (``fill`` at position 0)."""
    return jnp.concatenate([jnp.full_like(x[:, :1], fill), x[:, :-1]], axis=1)


def _shift_l(x: jax.Array, fill=0) -> jax.Array:
    """x[i+1] along axis 1 (``fill`` at last position)."""
    return jnp.concatenate([x[:, 1:], jnp.full_like(x[:, :1], fill)], axis=1)


def _first_col(mask: jax.Array) -> jax.Array:
    out = jnp.zeros_like(mask, dtype=bool)
    return out.at[:, 0].set(True)


def hash_string(s: str) -> int:
    """Host twin of the device polynomial hash (int32 wraparound, mul 31)."""
    h = 0
    for ch in s:
        h = (h * 31 + ord(ch)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def _poly_hash_many(
    values: Tuple[jax.Array, ...],
    in_seg: jax.Array,
    seg_start: jax.Array,
    mul: int = 31,
) -> Tuple[jax.Array, ...]:
    """Segmented polynomial hashes h = h*mul + v via ONE affine scan shared
    by all ``values`` streams (they share the multiplier pattern, so fusing
    them shares the carry-multiply work and the scan's memory passes).

    Positions outside segments are pass-through; ``seg_start`` restarts.
    The value at each position is the hash of its segment's prefix.
    """
    m = jnp.where(seg_start, 0, jnp.where(in_seg, mul, 1)).astype(jnp.int32)
    accs = tuple(jnp.where(in_seg, v, 0).astype(jnp.int32) for v in values)

    def compose(x, y):
        mx, axs = x[0], x[1:]
        my, ays = y[0], y[1:]
        return (mx * my,) + tuple(ay + my * ax for ax, ay in zip(axs, ays))

    from .device import _scan_impl, chunk_scan_tuple, shift_scan_tuple
    from .pallas_scan import affine_hash_scan, pallas_scan_ok

    if pallas_scan_ok(*m.shape):
        # Blocked VMEM kernel — same int32 affine composition, bit-identical
        # to every lax schedule below (parity fuzzed in tests).
        return affine_hash_scan(m, accs)

    impl = _scan_impl()
    if impl != "assoc":
        # Affine identity is (m=1, a=0, ...) — one shared scan schedule
        # (device.shift_scan_tuple / chunk_scan_tuple).
        identities = (1,) + tuple(0 for _ in accs)
        fn = chunk_scan_tuple if impl == "chunk" else shift_scan_tuple
        return fn(compose, identities, (m,) + accs, axis=1)[1:]

    out = jax.lax.associative_scan(compose, (m,) + accs, axis=1)
    return out[1:]


def _poly_hash(
    cps: jax.Array, in_seg: jax.Array, seg_start: jax.Array, mul: int = 31
) -> jax.Array:
    return _poly_hash_many((cps,), in_seg, seg_start, mul=mul)[0]


# --- fused megakernel group builders -----------------------------------------
# Twins of the staged scans above, expressed as pallas_scan.fused_scan groups
# so several independent scans lower into ONE kernel pass over the row tile.
# Each builder re-states the staged path's recurrence exactly:
#
# * segmented sum (device.seg_scan_add, monoid _seg_add_op) is the affine
#   recurrence h = m*h_prev + v with m = 0 at segment resets, 1 elsewhere;
# * the segmented polynomial hash is the same recurrence with m = mul inside
#   segments (identical to _poly_hash_many's operand construction above).
#
# Both are int32 recurrences whose every schedule (lax shift/chunk/assoc,
# per-scan kernel, fused kernel) computes the same function exactly, so the
# fused path is bit-identical by integer associativity.  Callers gate on
# pallas_scan.fused_scan_ok first.


def _seg_add_group(values: Tuple[jax.Array, ...], reset: jax.Array) -> dict:
    """Fused-group twin of ``seg_scan_add`` over shared ``reset`` streams."""
    from .pallas_scan import affine_group

    m = jnp.where(reset, 0, 1).astype(jnp.int32)
    return affine_group(m, tuple(v.astype(jnp.int32) for v in values))


def _poly_hash_group(
    values: Tuple[jax.Array, ...],
    in_seg: jax.Array,
    seg_start: jax.Array,
    mul: int = 31,
) -> dict:
    """Fused-group twin of ``_poly_hash_many`` (same m/acc construction)."""
    from .pallas_scan import affine_group

    m = jnp.where(seg_start, 0, jnp.where(in_seg, mul, 1)).astype(jnp.int32)
    accs = tuple(jnp.where(in_seg, v, 0).astype(jnp.int32) for v in values)
    return affine_group(m, accs)


def _sum_group(values: Tuple[jax.Array, ...]) -> dict:
    """Fused-group twin of ``jnp.sum(v, axis=1)`` per stream: an add scan
    emitting only the final carry, so the totals never widen to [B, L]."""
    from .pallas_scan import add_group

    return add_group(tuple(v.astype(jnp.int32) for v in values), emit="last")


def _pattern_hash_group(src: jax.Array, mask: jax.Array) -> dict:
    """Chain-group twin of ``_pattern_union_starts``' candidate prefix hash
    (same m/acc construction as its ``_poly_hash`` call), so the candidate
    pass can ride another kernel's dispatch via the ``h_inc`` parameter."""
    first = jnp.zeros_like(mask).at[:, 0].set(True)
    return {
        "kind": "affine",
        "xs": (
            jnp.where(first, 0, 31).astype(jnp.int32),
            jnp.where(mask, src, 0).astype(jnp.int32),
        ),
    }


def _scatter(values, idx, active, m, fill=0, op="set"):
    """Scatter per-char ``values`` at ``active`` positions into ``[B, m]``
    slots keyed by ``idx``.  With op="set", callers must guarantee one active
    position per slot."""
    b = values.shape[0]
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    ok = active & (idx >= 0) & (idx < m)
    flat_idx = jnp.where(ok, rows * m + idx, b * m)
    out = jnp.full(b * m + 1, fill, dtype=values.dtype)
    src = jnp.where(ok, values, fill).reshape(-1)
    ref = out.at[flat_idx.reshape(-1)]
    if op == "set":
        out = ref.set(values.reshape(-1), mode="drop")
    elif op == "add":
        out = ref.add(src, mode="drop")
    elif op == "max":
        out = ref.max(src, mode="drop")
    else:
        raise ValueError(op)
    return out[:-1].reshape(b, m)


# --- Scatter-free table construction (the TPU path) --------------------------
# XLA:TPU lowers the per-segment scatters above to serialized per-element
# loops (round-3 on-chip profile: ~13s/batch, TPU_EVIDENCE_r03).  When
# ``use_sort_tables()`` is on, tables are built instead by ONE sorted
# compaction of the active positions (the already-tuned VMEM bitonic network)
# plus a small ``take_along_axis`` gather per value stream.  This requires
# the active positions' slot keys to enumerate 0..n-1 in row order (gapless)
# — every gated call site satisfies it by construction and says how.


def _rank_positions_many(actives, m, mesh=None):
    """For each ``[B, L]`` bool mask in ``actives``: positions of its 1st,
    2nd, ... active element per row, as ``(pos [B, m] int32, real [B, m]
    bool)``.  All masks share one stacked device sort (rows are independent,
    exactly like :func:`_sort_runs_many`)."""
    b, length = actives[0].shape
    pos = jnp.broadcast_to(jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
    keys = [jnp.where(a, pos, _I32_MAX) for a in actives]
    key = keys[0] if len(keys) == 1 else jnp.concatenate(keys, axis=0)
    # Pad the row length to a power of two for the Pallas network; padding
    # carries the invalid key and a safe gather index.
    padded = 1 << (length - 1).bit_length()
    if padded != length:
        key = jnp.pad(key, ((0, 0), (0, padded - length)), constant_values=_I32_MAX)
    s_key, s_pos = sort2(key, jnp.where(key == _I32_MAX, 0, key), mesh=mesh)
    if m > s_key.shape[1]:  # more slots than row positions: right-pad invalid
        extra = m - s_key.shape[1]
        s_key = jnp.pad(s_key, ((0, 0), (0, extra)), constant_values=_I32_MAX)
        s_pos = jnp.pad(s_pos, ((0, 0), (0, extra)))
    outs = []
    for i in range(len(actives)):
        blk_key = s_key[i * b : (i + 1) * b, :m]
        blk_pos = s_pos[i * b : (i + 1) * b, :m]
        outs.append((blk_pos, blk_key != _I32_MAX))
    return outs


def _gather_table(values, pos, real, fill=0):
    v = jnp.take_along_axis(values, pos, axis=1)
    return jnp.where(real, v, jnp.asarray(fill, dtype=values.dtype))


class TextStructure(NamedTuple):
    """Shared word-unit structure extracted once per packed batch."""

    cps: jax.Array  # [B, L] int32
    lengths: jax.Array  # [B]
    cls: jax.Array  # [B, L] uint8 class bits
    mask: jax.Array  # [B, L] bool — char belongs to the doc
    unit_end: jax.Array  # [B, L] bool — last char of each unit
    unit_valid: jax.Array  # [B, L] bool at unit_end — unit counts as a word
    unit_len: jax.Array  # [B, L] int32 at unit_end — chars in unit
    unit_bytes: jax.Array  # [B, L] int32 at unit_end — UTF-8 bytes of unit
    unit_hash: jax.Array  # [B, L] int32 at unit_end — content hash
    unit_lhash: jax.Array  # [B, L] int32 at unit_end — lowercased hash
    unit_alpha: jax.Array  # [B, L] bool at unit_end — has alphabetic char
    n_words: jax.Array  # [B] int32 — valid unit count
    word_idx: jax.Array  # [B, L] int32 at valid unit_end — word ordinal


def structure(
    cps: jax.Array, lengths: jax.Array, with_hashes: bool = True
) -> TextStructure:
    """``with_hashes=False`` skips the two polynomial-hash scans (the unit
    hash fields come back ``None``) — only GopherQuality (stop-word lhash)
    and GopherRepetition (dup-table hash/bytes) consume them, and the hash
    scans are a large share of this kernel's memory passes."""
    _, length = cps.shape
    mask = jnp.arange(length, dtype=jnp.int32)[None, :] < lengths[:, None]
    cls = classify(cps)
    cls = jnp.where(mask, cls, 0).astype(cls.dtype)

    from .pallas_scan import (
        Tap,
        chain_group,
        chain_pass,
        chain_scan,
        chain_scan_ok,
        fused_scan,
        fused_scan_ok,
    )

    if with_hashes:
        lt = lower_table()
        low = lt[jnp.minimum(cps, lt.shape[0] - 1)]

    ws = (cls & WS) != 0
    punct = (cls & PUNCT) != 0
    ext = ((cls & EXTEND) != 0) & mask

    if chain_scan_ok(*cps.shape) and length <= 8192:
        # Dependency-fused path: the whole unit-segmentation chain — the WB4
        # word hold scan, the symbol hold scan it feeds, the per-unit
        # aggregate/hash scans those masks gate, the unit_end/valid_end
        # derivation (a reverse pass: "next" lane values are walk-previous
        # taps), and the word-cumsum -> n_words consumers — runs as ONE
        # multi-pass kernel dispatch.  Every recurrence below restates the
        # staged branch's op exactly (segmented OR of {0,1} streams is a
        # segmented SUM compared > 0), so the streams are bit-identical.
        from .device import word_base

        word_raw, _ = word_base(cps, cls)
        ext_i = ext.astype(jnp.int32)
        wm = (word_raw & mask).astype(jnp.int32)
        base_raw = (~ws & ~punct & mask & (cps != 0x200B) & ~ext).astype(jnp.int32)
        sh_ext = _shift_r(ext_i)
        sh_wm = _shift_r(wm)
        widths_raw = utf8_width(cps)
        np_raw = (~punct).astype(jnp.int32)
        alpha_raw = ((cls & ALPHA) != 0).astype(jnp.int32)

        def _derive(held, hs, shh, e, w, br, she, shw):
            # in_word / in_unit / unit_start from the held scans (staged
            # twin formulas; XLA CSE dedups across the preps sharing them).
            iw = jnp.where(e != 0, held > 0, w != 0)
            bs = ~iw & (br != 0)
            sym = bs | ((e != 0) & ~iw & (hs > 0))
            iu = iw | sym
            piw = jnp.where(she != 0, shh > 0, shw != 0)
            us = (iw & ~piw) | bs
            return iu, us

        core = (
            Tap(0, 0),  # held (WB4 word hold)
            Tap(1, 0),  # held_sym
            Tap(0, 0, shift=1, fill=0),  # held at the previous lane
            ext_i,
            wm,
            base_raw,
            sh_ext,
            sh_wm,
        )

        def prep_sym(held, e, w, br):
            iw = jnp.where(e != 0, held > 0, w != 0)
            return e, (~iw & (br != 0)).astype(jnp.int32)

        def prep_agg(held, hs, shh, e, w, br, she, shw, wd, np_, al):
            iu, us = _derive(held, hs, shh, e, w, br, she, shw)
            m = jnp.where(us, 0, 1)
            acc1 = iu.astype(jnp.int32) * jnp.int32(1 << 17) + jnp.where(iu, wd, 0)
            acc2 = jnp.where(iu, np_, 0) * jnp.int32(1 << 16) + jnp.where(iu, al, 0)
            return m, acc1, acc2

        def prep_hash(held, hs, shh, e, w, br, she, shw, c, lo):
            iu, us = _derive(held, hs, shh, e, w, br, she, shw)
            m = jnp.where(us, 0, jnp.where(iu, 31, 1))
            return m, jnp.where(iu, c, 0), jnp.where(iu, lo, 0)

        def prep_copy(held, hs, shh, e, w, br, she, shw):
            iu, us = _derive(held, hs, shh, e, w, br, she, shw)
            return iu.astype(jnp.int32), us.astype(jnp.int32)

        p2_groups = [
            chain_group("affine", core + (widths_raw, np_raw, alpha_raw),
                        prep=prep_agg, n_ops=3),
        ]
        if with_hashes:
            p2_groups.append(
                chain_group("affine", core + (cps, low), prep=prep_hash, n_ops=3)
            )
        p2_groups.append(chain_group("copy", core, prep=prep_copy, n_ops=2))
        s_iu = 4 if with_hashes else 2  # flat stream index of in_unit in pass 2
        s_us = s_iu + 1

        def prep_vend(iu, iu_next, us_next, pb):
            ue = (iu != 0) & ((iu_next == 0) | (us_next != 0))
            return (jnp.where(ue & ((pb >> 16) > 0), 1, 0),)

        res = chain_scan(
            [
                chain_pass(
                    [{"kind": "affine", "xs": (ext_i, wm), "emit": "none"}]
                ),
                chain_pass(
                    [chain_group("affine", (Tap(0, 0), ext_i, wm, base_raw),
                                 prep=prep_sym, n_ops=2, emit="none")]
                ),
                chain_pass(p2_groups),
                chain_pass(
                    [chain_group(
                        "copy",
                        (Tap(2, s_iu), Tap(2, s_iu, shift=1, fill=0),
                         Tap(2, s_us, shift=1, fill=0), Tap(2, 1)),
                        prep=prep_vend, n_ops=1, emit="none",
                    )],
                    reverse=True,
                ),
                chain_pass(
                    [chain_group("add", (Tap(3, 0),), emit="scan")]
                ),
            ]
        )
        packed_a, packed_b = res[2][0]
        unit_len = packed_a >> 17
        unit_bytes = packed_a & jnp.int32((1 << 17) - 1)
        unit_valid = (packed_b >> 16) > 0
        unit_alpha = (packed_b & jnp.int32((1 << 16) - 1)) > 0
        unit_hash, unit_lhash = res[2][1] if with_hashes else (None, None)
        iu_s, us_s = res[2][-1]
        in_unit = iu_s != 0
        unit_start = us_s != 0
        unit_end = in_unit & (~_shift_l(in_unit, False) | _shift_l(unit_start, False))
        cs = res[4][0][0]
        word_idx = cs - 1
        n_words = cs[:, -1]

        return TextStructure(
            cps=cps,
            lengths=lengths,
            cls=cls,
            mask=mask,
            unit_end=unit_end,
            unit_valid=unit_valid,
            unit_len=unit_len,
            unit_bytes=unit_bytes,
            unit_hash=unit_hash,
            unit_lhash=unit_lhash,
            unit_alpha=unit_alpha,
            n_words=n_words,
            word_idx=word_idx,
        )

    in_word = word_mask(cps, cls) & mask
    # Symbols: not word/ws/punct; ZWSP yields no token (WordBreak=Other and
    # not word-like in ICU), bare Extend chars yield no token, and an Extend
    # run after a symbol CONTINUES that symbol's unit (WB4) — mirror of
    # utils.text.word_spans.
    base_symbol = ~in_word & ~ws & ~punct & mask & (cps != 0x200B) & ~ext
    held_sym = seg_scan_or(base_symbol.astype(jnp.int32), ~ext) > 0
    symbol = base_symbol | (ext & ~in_word & held_sym)

    in_unit = in_word | symbol
    prev_in_word = _shift_r(in_word, False)
    unit_start = (in_word & ~prev_in_word) | base_symbol
    next_start = _shift_l(unit_start, False)
    next_in_unit = _shift_l(in_unit, False)
    unit_end = in_unit & (~next_in_unit | next_start)

    ones = jnp.where(in_unit, 1, 0).astype(jnp.int32)
    widths = jnp.where(in_unit, utf8_width(cps), 0)
    nonpunct = jnp.where(in_unit, (~punct).astype(jnp.int32), 0)
    alpha = jnp.where(in_unit, ((cls & ALPHA) != 0).astype(jnp.int32), 0)

    if fused_scan_ok(*cps.shape):
        # One kernel pass for every per-unit scan of this kernel: the packed
        # aggregates and (when requested) both polynomial hash streams share
        # the tile walk, so this replaces 2-3 scan dispatches with one and no
        # intermediate stream round-trips HBM.  Same packed-field reasoning
        # as the staged branch below; fused lengths are <= 16384, within the
        # <= 8192-style field bounds only when length <= 8192, so the longer
        # buckets take the unpacked 4-stream group (still one dispatch).
        if length <= 8192:
            groups = [
                _seg_add_group(
                    (
                        ones * jnp.int32(1 << 17) + widths,
                        nonpunct * jnp.int32(1 << 16) + alpha,
                    ),
                    unit_start,
                )
            ]
        else:
            groups = [_seg_add_group((ones, widths, nonpunct, alpha), unit_start)]
        if with_hashes:
            groups.append(_poly_hash_group((cps, low), in_unit, unit_start))
        res = fused_scan(groups)
        if length <= 8192:
            packed_a, packed_b = res[0]
            unit_len = packed_a >> 17
            unit_bytes = packed_a & jnp.int32((1 << 17) - 1)
            unit_valid = (packed_b >> 16) > 0
            unit_alpha = (packed_b & jnp.int32((1 << 16) - 1)) > 0
        else:
            # Counts of {0,1} streams: "> 0" on a segmented SUM equals the
            # staged branch's segmented OR bit-for-bit.
            u_len, u_bytes, u_np, u_al = res[0]
            unit_len, unit_bytes = u_len, u_bytes
            unit_valid = u_np > 0
            unit_alpha = u_al > 0
        unit_hash, unit_lhash = res[1] if with_hashes else (None, None)
    else:
        if length <= 8192:
            # Fuse the four per-unit aggregates into two packed add-scans:
            # within a unit, chars <= 8192 (14 bits used: counts <= 2^13) and
            # UTF-8 bytes <= 4*8192 (field below bit 17), so len<<17|bytes
            # and nonpunct<<16|alpha add without cross-field carries.
            packed_a = seg_scan_add(ones * jnp.int32(1 << 17) + widths, unit_start)
            packed_b = seg_scan_add(nonpunct * jnp.int32(1 << 16) + alpha, unit_start)
            unit_len = packed_a >> 17
            unit_bytes = packed_a & jnp.int32((1 << 17) - 1)
            unit_valid = (packed_b >> 16) > 0
            unit_alpha = (packed_b & jnp.int32((1 << 16) - 1)) > 0
        else:
            unit_len = seg_scan_add(ones, unit_start)
            unit_bytes = seg_scan_add(widths, unit_start)
            unit_valid = seg_scan_or(nonpunct, unit_start) > 0
            unit_alpha = seg_scan_or(alpha, unit_start) > 0

        if with_hashes:
            unit_hash, unit_lhash = _poly_hash_many((cps, low), in_unit, unit_start)
        else:
            unit_hash = unit_lhash = None

    valid_end = unit_end & unit_valid
    word_idx = jnp.cumsum(valid_end.astype(jnp.int32), axis=1) - 1
    n_words = jnp.sum(valid_end, axis=1).astype(jnp.int32)

    return TextStructure(
        cps=cps,
        lengths=lengths,
        cls=cls,
        mask=mask,
        unit_end=unit_end,
        unit_valid=unit_valid,
        unit_len=unit_len,
        unit_bytes=unit_bytes,
        unit_hash=unit_hash,
        unit_lhash=unit_lhash,
        unit_alpha=unit_alpha,
        n_words=n_words,
        word_idx=word_idx,
    )


def _lowered(cps: jax.Array, mask: jax.Array) -> jax.Array:
    lt = lower_table()
    return jnp.where(mask, lt[jnp.minimum(cps, lt.shape[0] - 1)], 0)


def _match_pattern(src: jax.Array, mask: jax.Array, pattern: str) -> jax.Array:
    """[B, L] bool: fixed string ``pattern`` starts at each position."""
    hit = mask
    for k, ch in enumerate(pattern):
        shifted = jnp.pad(src[:, k:], ((0, 0), (0, k)), constant_values=-1)
        mk = jnp.pad(mask[:, k:], ((0, 0), (0, k)), constant_values=False)
        hit = hit & (shifted == ord(ch)) & mk
    return hit


def _pattern_union_starts(
    src: jax.Array, mask: jax.Array, patterns: Tuple[str, ...], h_inc=None
) -> jax.Array:
    """[B, L] bool: some pattern in ``patterns`` starts at each position.

    Two-phase: rolling-hash window candidates (one affine scan + one
    gather/multiply/compare per pattern), then the exact shifted-compare
    match under a batch-global ``lax.cond`` taken only when a candidate
    exists.  Clean batches — the common case for lorem-ipsum / javascript /
    policy text — pay only the hash pass; decisions always come from the
    exact compare, so hash collisions cannot alter semantics.

    ``h_inc`` optionally supplies the inclusive prefix hash precomputed by a
    caller's chain kernel (operands per :func:`_pattern_hash_group`) so the
    candidate pass rides an existing dispatch.
    """
    vals = jnp.where(mask, src, 0)
    first = jnp.zeros_like(mask).at[:, 0].set(True)
    if h_inc is None:
        h_inc = _poly_hash(vals, jnp.ones_like(mask), first)  # inclusive prefix hash
    h_exc = _shift_r(h_inc, 0)  # hash of chars [0, i)

    def to_i32(u: int) -> np.int32:
        u &= 0xFFFFFFFF
        return np.int32(u - (1 << 32)) if u >= (1 << 31) else np.int32(u)

    cand = jnp.zeros_like(mask)
    for pat in patterns:
        n = len(pat)
        target = np.int32(hash_string(pat))
        pw = to_i32(pow(31, n, 1 << 32))
        # Window [i, i+n): hash = h_inc[i+n-1] - h_exc[i] * 31^n (int32 wrap).
        h_end = jnp.pad(h_inc[:, n - 1 :], ((0, 0), (0, n - 1)))
        cand = cand | ((h_end - h_exc * pw == target) & mask)

    def verify():
        hit = jnp.zeros_like(mask)
        for pat in patterns:
            hit = hit | _match_pattern(src, mask, pat)
        return hit

    return jax.lax.cond(jnp.any(cand), verify, lambda: jnp.zeros_like(mask))


# --- Line structure ----------------------------------------------------------


class LineInfo(NamedTuple):
    line_id: jax.Array  # [B, L] int32 — rust_lines index per char
    line_start: jax.Array  # [B, L] bool — first char of each line (or its \n)
    content: jax.Array  # [B, L] bool — not \n, not \r-before-\n
    is_nl: jax.Array  # [B, L] bool
    n_lines: jax.Array  # [B] int32 — rust_lines count
    last_content: jax.Array  # [B, L] bool — last content char of its line


def line_info(cps: jax.Array, mask: jax.Array) -> LineInfo:
    is_nl = (cps == NL) & mask
    next_is_nl = _shift_l(is_nl, False)
    stripped_cr = (cps == CR) & next_is_nl & mask
    content = mask & ~is_nl & ~stripped_cr

    line_id = jnp.cumsum(is_nl.astype(jnp.int32), axis=1) - is_nl.astype(jnp.int32)

    prev_nl = _shift_r(is_nl, False)
    line_start = mask & (prev_nl | _first_col(mask))

    # last content char of its line: next non-content or row end.
    last_content = content & ~_shift_l(content, False)

    n_newlines = jnp.sum(is_nl, axis=1).astype(jnp.int32)
    lengths = jnp.sum(mask, axis=1).astype(jnp.int32)
    pos = jnp.arange(cps.shape[1], dtype=jnp.int32)[None, :]
    last_char_nl = jnp.any((pos == lengths[:, None] - 1) & is_nl, axis=1)
    n_lines = jnp.where(
        lengths == 0, 0, n_newlines + jnp.where(last_char_nl, 0, 1)
    ).astype(jnp.int32)
    return LineInfo(line_id, line_start, content, is_nl, n_lines, last_content)


def _line_reset(li: LineInfo, mask: jax.Array) -> jax.Array:
    """Scan-reset mask starting a fresh segment at each line's first char
    (resets placed on the char after each \\n, and at column 0)."""
    return _first_col(mask) | _shift_r(li.is_nl, False)


def _first_nonws_in_line(nonws: jax.Array, li: LineInfo, mask: jax.Array) -> jax.Array:
    cnt = seg_scan_add(nonws.astype(jnp.int32), _line_reset(li, mask))
    return nonws & (cnt == 1)


def _last_nonws_in_line(nonws: jax.Array, li: LineInfo, mask: jax.Array) -> jax.Array:
    r_reset = _first_col(mask) | _shift_r(rev(li.is_nl), False)
    cnt_r = seg_scan_add(rev(nonws).astype(jnp.int32), r_reset)
    return rev(rev(nonws) & (cnt_r == 1))


# --- Duplicate counting over (hash, bytes) tables ----------------------------
# Lexicographic (validity, hash, payload) sort: the VMEM-resident Pallas
# bitonic network on TPU, lax.sort elsewhere (:mod:`.pallas_sort`).  Every key
# stays int32 (JAX x64 mode is off, and int32 sorts are faster on TPU anyway).
# Invalid slots carry a leading 1 key, sorting them past all real segments.


_I32_MAX = np.int32(2**31 - 1)


def _sort_runs_many(jobs, mesh=None):
    """Sort many same-shaped ``(hash, payload, valid)`` jobs in ONE device
    sort, returning ``(is_real, s_hash, s_payload)`` per job.

    Two structural tricks keep this cheap (it was the pipeline's dominant
    cost when emitted as one 3-key sort per n-gram size):

    * jobs stack along the batch axis — rows are independent, so k jobs of
      shape ``[B, m]`` cost one ``[kB, m]`` sort network / lax.sort call;
    * the sort uses a SINGLE int32 key: invalid slots are biased to
      ``INT32_MAX`` and valid hashes clamped to ``INT32_MAX - 1`` (one more
      2^-32-per-pair collision class on top of hashing itself, see module
      docstring), so validity needs no second key and ``is_real`` is just a
      position-vs-count compare after the sort.  Runs are keyed by hash
      alone; the payload rides as a sort value (stable off-TPU, full-pair
      bitonic on TPU — within-run payload order differs, which no consumer
      depends on for iota/byte payloads under the no-collision assumption).
    """
    b, m = jobs[0][0].shape
    keys, n_valid = [], []
    for h, _, v in jobs:
        keys.append(jnp.where(v, jnp.minimum(h, _I32_MAX - 1), _I32_MAX))
        n_valid.append(jnp.sum(v, axis=1).astype(jnp.int32))
    if len(jobs) == 1:
        s_key, s_payload = sort2(keys[0], jobs[0][1], mesh=mesh)
    else:
        s_key, s_payload = sort2(
            jnp.concatenate(keys, axis=0),
            jnp.concatenate([j[1] for j in jobs], axis=0),
            mesh=mesh,
        )
    iota = jnp.arange(m, dtype=jnp.int32)[None, :]
    outs = []
    for i, nv in enumerate(n_valid):
        outs.append(
            (
                iota < nv[:, None],
                s_key[i * b : (i + 1) * b],
                s_payload[i * b : (i + 1) * b],
            )
        )
    return outs


def _dup_counts_sorted(sorted_triple) -> Tuple[jax.Array, jax.Array]:
    """find_duplicates semantics over hashed segments: every occurrence after
    the first counts (text.rs:197-208)."""
    is_real, s_hash, s_bytes = sorted_triple
    same_prev = (
        jnp.concatenate(
            [
                jnp.zeros_like(is_real[:, :1]),
                s_hash[:, 1:] == s_hash[:, :-1],
            ],
            axis=1,
        )
        & is_real
    )
    dup_elems = jnp.sum(same_prev, axis=1).astype(jnp.int32)
    dup_bytes = jnp.sum(jnp.where(same_prev, s_bytes, 0), axis=1).astype(jnp.int32)
    return dup_elems, dup_bytes


def _dup_counts(seg_hash, seg_bytes, seg_valid, mesh=None) -> Tuple[jax.Array, jax.Array]:
    return _dup_counts_sorted(
        _sort_runs_many([(seg_hash, seg_bytes, seg_valid)], mesh=mesh)[0]
    )


def _run_starts(s_hash: jax.Array) -> jax.Array:
    """Run-start mask over a hash-sorted table (hash change or slot 0)."""
    return jnp.concatenate(
        [
            jnp.ones_like(s_hash[:, :1], dtype=bool),
            s_hash[:, 1:] != s_hash[:, :-1],
        ],
        axis=1,
    )


def _sorted_table_streams(tagged_triples, mesh=None):
    """ONE chain dispatch for every per-run scan over the sorted tables:
    run lengths for "top" jobs, first-window-index-in-run for "dup" jobs
    (the staged scans inside _top_duplicate_sorted / _dup_run_info_sorted).

    Returns a per-job list of precomputed streams, or ``None`` when the
    table shape fails the chain gate — callers fall back to the staged
    per-scan path, which computes the identical int32 recurrences.
    """
    from .pallas_scan import chain_pass, chain_scan, chain_scan_ok

    if not tagged_triples:
        return None
    b, m = tagged_triples[0][1][1].shape
    if not chain_scan_ok(b, m):
        return None
    groups = []
    for kind, (is_real, s_hash, sidx) in tagged_triples:
        rs = _run_starts(s_hash)
        if kind == "top":
            groups.append(
                {
                    "kind": "affine",
                    "xs": (jnp.where(rs, 0, 1), jnp.ones_like(s_hash)),
                }
            )
        else:
            groups.append(
                {
                    "kind": "segmax",
                    "xs": (jnp.where(rs, sidx, -(2**30)), rs.astype(jnp.int32)),
                }
            )
    res = chain_scan([chain_pass(groups)])
    return [g[0] for g in res[0]]


def _top_duplicate_sorted(sorted_triple, run_len=None) -> jax.Array:
    """find_top_duplicate semantics: bytes*count of the most frequent item,
    ties by larger contribution, 0 when nothing repeats (text.rs:211-238)."""
    is_real, s_hash, s_bytes = sorted_triple
    run_start = _run_starts(s_hash)
    if run_len is None:
        run_len = seg_scan_add(jnp.ones_like(s_hash), run_start)
    run_end = _shift_l(run_start, True)
    counts = jnp.where(run_end & is_real, run_len, 0)
    max_count = jnp.max(counts, axis=1, keepdims=True)
    contrib = jnp.where(
        run_end & is_real & (run_len == max_count), s_bytes * run_len, 0
    )
    top = jnp.max(contrib, axis=1)
    return jnp.where(max_count[:, 0] > 1, top, 0).astype(jnp.int32)


# --- GopherQuality -----------------------------------------------------------


def gopher_quality_stats(
    st: TextStructure, stop_word_hashes: Sequence[int]
) -> Dict[str, jax.Array]:
    """Integer stats for GopherQualityFilter (gopher_quality.rs:69-295)."""
    from .pallas_scan import fused_scan, fused_scan_ok

    cps, cls, mask = st.cps, st.cls, st.mask
    valid_end = st.unit_end & st.unit_valid

    n_words = st.n_words

    # Non-overlapping "..." count: dot-run lengths // 3 (str::matches parity).
    is_dot = (cps == ord(".")) & mask
    dot_start = is_dot & ~_shift_r(is_dot, False)

    li = line_info(cps, mask)
    ws = (cls & WS) != 0
    nonws = li.content & ~ws

    if stop_word_hashes:
        sw = jnp.asarray(np.sort(np.array(stop_word_hashes, dtype=np.int32)))
        is_stop = isin_sorted(st.unit_lhash, sw)
    else:
        is_stop = None

    from .pallas_scan import (
        Tap,
        chain_group,
        chain_pass,
        chain_scan,
        chain_scan_ok,
    )

    if chain_scan_ok(*cps.shape):
        # Dependency-chain kernel: the staged path runs the three line scans,
        # then derives bullet/ellipsis line flags from their outputs on the
        # host and sums them — two more full-width [B, L] round-trips.  Here
        # a third pass consumes the counter streams in-register and emits
        # only the [B, 1] totals; the dot-run stream is the single full-width
        # output (its //3 consumer stays host-side: int32 division).
        totals = [
            ((cps == ord("#")) & mask).astype(jnp.int32),
            ((cps == 0x2026) & mask).astype(jnp.int32),
            jnp.where(valid_end, st.unit_len, 0).astype(jnp.int32),
            (valid_end & st.unit_alpha).astype(jnp.int32),
        ]
        if is_stop is not None:
            totals.append((valid_end & is_stop).astype(jnp.int32))
        r_reset = _first_col(mask) | _shift_r(rev(li.is_nl), False)
        nonws_i = nonws.astype(jnp.int32)
        is_bullet_i = ((cps == 0x2022) | (cps == ord("-"))).astype(jnp.int32)
        ell_cp_i = ((cps == 0x2026)).astype(jnp.int32)
        is_dot_i = is_dot.astype(jnp.int32)

        def _prep_line_flags(lead_cnt, cnt_r, dot_run_t, nw, bul, ell, dt):
            leader_ = (nw != 0) & (lead_cnt == 1)
            last_ = (nw != 0) & (cnt_r == 1)
            return (
                (leader_ & (bul != 0)).astype(jnp.int32),
                (last_ & ((ell != 0) | ((dt != 0) & (dot_run_t >= 3)))).astype(
                    jnp.int32
                ),
            )

        res = chain_scan(
            [
                chain_pass(
                    [
                        _seg_add_group((is_dot_i,), dot_start),
                        {
                            "kind": "affine",
                            "xs": (
                                jnp.where(_line_reset(li, mask), 0, 1),
                                nonws_i,
                            ),
                            "emit": "none",
                        },
                        _sum_group(tuple(totals)),
                    ]
                ),
                chain_pass(
                    [
                        # Reversed per-line counter: operands in natural
                        # orientation (the reverse pass walks them flipped),
                        # so rev() of the staged reversed-frame operands.
                        {
                            "kind": "affine",
                            "xs": (rev(jnp.where(r_reset, 0, 1)), nonws_i),
                            "emit": "none",
                        }
                    ],
                    reverse=True,
                ),
                chain_pass(
                    [
                        chain_group(
                            "add",
                            (
                                Tap(0, 1),
                                Tap(1, 0),
                                Tap(0, 0),
                                nonws_i,
                                is_bullet_i,
                                ell_cp_i,
                                is_dot_i,
                            ),
                            prep=_prep_line_flags,
                            n_ops=2,
                            emit="last",
                        )
                    ]
                ),
            ]
        )
        (dot_run,) = res[0][0]
        t = res[0][2]
        hash_count = t[0][:, 0]
        ellipsis_uni = t[1][:, 0]
        sum_len = t[2][:, 0]
        alpha_words = t[3][:, 0]
        stop_words = t[4][:, 0] if is_stop is not None else jnp.zeros_like(n_words)
        bullet_lines = res[2][0][0][:, 0]
        ellipsis_lines = res[2][0][1][:, 0]
        dot_end = is_dot & ~_shift_l(is_dot, False)
        ellipsis_ascii = jnp.sum(jnp.where(dot_end, dot_run // 3, 0), axis=1)
        ellipsis_units = (ellipsis_ascii + ellipsis_uni).astype(jnp.int32)
        return {
            "n_words": n_words,
            "n_non_symbol": n_words,
            "sum_word_len": sum_len,
            "hash_count": hash_count,
            "ellipsis_units": ellipsis_units,
            "n_lines": li.n_lines,
            "bullet_lines": bullet_lines,
            "ellipsis_lines": ellipsis_lines,
            "alpha_words": alpha_words,
            "stop_words": stop_words,
        }

    if fused_scan_ok(*cps.shape):
        # One kernel for the phase's three independent scans (dot runs,
        # first-/last-non-ws-in-line counters) plus every whole-row total
        # that does not depend on a scan output — the totals emit as [B, 1]
        # final carries, so no mask or count stream touches HBM.
        totals = [
            ((cps == ord("#")) & mask).astype(jnp.int32),
            ((cps == 0x2026) & mask).astype(jnp.int32),
            jnp.where(valid_end, st.unit_len, 0).astype(jnp.int32),
            (valid_end & st.unit_alpha).astype(jnp.int32),
        ]
        if is_stop is not None:
            totals.append((valid_end & is_stop).astype(jnp.int32))
        r_reset = _first_col(mask) | _shift_r(rev(li.is_nl), False)
        res = fused_scan(
            [
                _seg_add_group((is_dot.astype(jnp.int32),), dot_start),
                _seg_add_group(
                    (nonws.astype(jnp.int32),), _line_reset(li, mask)
                ),
                _seg_add_group((rev(nonws).astype(jnp.int32),), r_reset),
                _sum_group(tuple(totals)),
            ]
        )
        (dot_run,) = res[0]
        leader = nonws & (res[1][0] == 1)
        last_nonws = rev(rev(nonws) & (res[2][0] == 1))
        t = res[3]
        hash_count = t[0][:, 0]
        ellipsis_uni = t[1][:, 0]
        sum_len = t[2][:, 0]
        alpha_words = t[3][:, 0]
        stop_words = t[4][:, 0] if is_stop is not None else jnp.zeros_like(n_words)
    else:
        dot_run = seg_scan_add(is_dot.astype(jnp.int32), dot_start)
        leader = _first_nonws_in_line(nonws, li, mask)
        last_nonws = _last_nonws_in_line(nonws, li, mask)
        hash_count = jnp.sum((cps == ord("#")) & mask, axis=1).astype(jnp.int32)
        ellipsis_uni = jnp.sum((cps == 0x2026) & mask, axis=1).astype(jnp.int32)
        sum_len = jnp.sum(
            jnp.where(valid_end, st.unit_len, 0), axis=1
        ).astype(jnp.int32)
        alpha_words = jnp.sum(valid_end & st.unit_alpha, axis=1).astype(jnp.int32)
        stop_words = (
            jnp.sum(valid_end & is_stop, axis=1).astype(jnp.int32)
            if is_stop is not None
            else jnp.zeros_like(n_words)
        )

    dot_end = is_dot & ~_shift_l(is_dot, False)
    ellipsis_ascii = jnp.sum(jnp.where(dot_end, dot_run // 3, 0), axis=1)
    ellipsis_units = (ellipsis_ascii + ellipsis_uni).astype(jnp.int32)

    # Bullet lines: first non-ws char is '•' or '-' (trim_start semantics).
    is_bullet_char = (cps == 0x2022) | (cps == ord("-"))
    bullet_lines = jnp.sum(leader & is_bullet_char, axis=1).astype(jnp.int32)

    # Ellipsis-ended lines: last non-ws char is '…' or closes a >=3 dot run.
    ell_line = last_nonws & ((cps == 0x2026) | (is_dot & (dot_run >= 3)))
    ellipsis_lines = jnp.sum(ell_line, axis=1).astype(jnp.int32)

    return {
        "n_words": n_words,
        # All valid units contain a non-PUNCT char, so non_symbol == words.
        "n_non_symbol": n_words,
        "sum_word_len": sum_len,
        "hash_count": hash_count,
        "ellipsis_units": ellipsis_units,
        "n_lines": li.n_lines,
        "bullet_lines": bullet_lines,
        "ellipsis_lines": ellipsis_lines,
        "alpha_words": alpha_words,
        "stop_words": stop_words,
    }


# --- FineWeb -----------------------------------------------------------------


def fineweb_stats(
    st: TextStructure,
    stop_chars: Sequence[str],
    max_lines: int,
    short_line_length: int,
    mesh=None,
) -> Dict[str, jax.Array]:
    """Integer stats for FineWebQualityFilter (fineweb_quality.rs:71-225)."""
    from .pallas_scan import fused_scan, fused_scan_ok

    cps, cls, mask = st.cps, st.cls, st.mask
    li = line_info(cps, mask)
    ws = (cls & WS) != 0
    nonws = li.content & ~ws
    reset = _line_reset(li, mask)

    if fused_scan_ok(*cps.shape):
        # One kernel for this filter's four line scans, the reversed
        # last-non-ws counter, and the two whole-row totals.  has_nonws
        # becomes a segmented SUM of the {0,1} stream — every consumer tests
        # "> 0", where sum and or agree bit-for-bit.
        r_reset = _first_col(mask) | _shift_r(rev(li.is_nl), False)
        res = fused_scan(
            [
                _seg_add_group(
                    (
                        li.content.astype(jnp.int32),
                        jnp.where(li.content, utf8_width(cps), 0),
                        nonws.astype(jnp.int32),
                    ),
                    reset,
                ),
                _poly_hash_group((cps,), li.content, reset),
                _seg_add_group((rev(nonws).astype(jnp.int32),), r_reset),
                _sum_group(
                    (
                        (mask & ~li.is_nl).astype(jnp.int32),
                        li.is_nl.astype(jnp.int32),
                    )
                ),
            ]
        )
        char_cnt, byte_cnt, has_nonws = res[0]
        (line_hash,) = res[1]
        last_nonws = rev(rev(nonws) & (res[2][0] == 1))
        total_chars_no_nl = res[3][0][:, 0]
        newline_count = res[3][1][:, 0]
    else:
        # Per-line cumulative values, scattered once at the line's last
        # content char (single write per slot).
        char_cnt = seg_scan_add(li.content.astype(jnp.int32), reset)
        byte_cnt = seg_scan_add(jnp.where(li.content, utf8_width(cps), 0), reset)
        has_nonws = seg_scan_or(nonws.astype(jnp.int32), reset)
        line_hash = _poly_hash(cps, li.content, reset)
        last_nonws = _last_nonws_in_line(nonws, li, mask)
        total_chars_no_nl = jnp.sum(mask & ~li.is_nl, axis=1).astype(jnp.int32)
        newline_count = jnp.sum(li.is_nl, axis=1).astype(jnp.int32)

    lc = li.last_content
    if use_sort_tables():
        # Slot j = the j-th line WITH content (blank lines hold no values on
        # the scatter path either — their slots are pure fills there, and no
        # consumer below reads slots positionally: validity masks, sums, and
        # the dup sort are all permutation/gap insensitive).
        [(tpos, treal)] = _rank_positions_many([lc], max_lines, mesh)
        line_chars = _gather_table(char_cnt, tpos, treal)
        line_bytes = _gather_table(byte_cnt, tpos, treal)
        line_has_content = _gather_table(has_nonws, tpos, treal) > 0
        line_hash_t = _gather_table(line_hash, tpos, treal)
    else:
        line_chars = _scatter(char_cnt, li.line_id, lc, max_lines)
        line_bytes = _scatter(byte_cnt, li.line_id, lc, max_lines)
        line_has_content = _scatter(has_nonws, li.line_id, lc, max_lines) > 0
        line_hash_t = _scatter(line_hash, li.line_id, lc, max_lines)
    # Byte-length mixing, as in gopher_rep's tables (collision discrimination).
    line_hash_t = line_hash_t * jnp.int32(31) + line_bytes

    n_nonblank = jnp.sum(line_has_content, axis=1).astype(jnp.int32)

    sc = jnp.asarray(np.sort(np.array([ord(c) for c in stop_chars], dtype=np.int32)))
    ends_stop_char = last_nonws & isin_sorted(cps, sc)
    ends_stop = jnp.sum(ends_stop_char, axis=1).astype(jnp.int32)

    dup_elems, dup_bytes = _dup_counts(line_hash_t, line_bytes, line_has_content, mesh)

    # Short-line count on device (the threshold is config-static), so the
    # [B, ML] line tables never leave the chip (fineweb_quality.rs:126-146).
    short_lines = jnp.sum(
        line_has_content & (line_chars <= short_line_length), axis=1
    ).astype(jnp.int32)

    return {
        "n_nonblank_lines": n_nonblank,
        "lines_ending_stop": ends_stop,
        "short_lines": short_lines,
        "dup_line_bytes": dup_bytes,
        "total_chars_no_newline": total_chars_no_nl,
        "n_words": st.n_words,
        "newline_count": newline_count,
        "line_overflow": li.n_lines > max_lines,
    }


# --- GopherRepetition --------------------------------------------------------


def gopher_rep_stats(
    st: TextStructure,
    top_ns: Sequence[int],
    dup_ns: Sequence[int],
    max_segs: int,
    max_words: int,
    mesh=None,
) -> Dict[str, jax.Array]:
    """Integer stats for GopherRepetitionFilter (gopher_rep.rs:52-219)."""
    cps, cls, mask = st.cps, st.cls, st.mask
    ws = (cls & WS) != 0
    _, length = cps.shape
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]

    # Trim bounds (gopher_rep.rs:57).
    nonws = mask & ~ws
    any_nonws = jnp.any(nonws, axis=1)
    t0 = jnp.min(jnp.where(nonws, pos, length), axis=1)
    t1 = jnp.max(jnp.where(nonws, pos, -1), axis=1)
    in_trim = (pos >= t0[:, None]) & (pos <= t1[:, None]) & mask
    trimmed_len = jnp.where(any_nonws, t1 - t0 + 1, 0).astype(jnp.int32)

    is_nl = (cps == NL) & in_trim
    prev_nl = _shift_r(is_nl, False)
    at_t0 = pos == t0[:, None]

    # Line segments: split on \n+.
    l_content = in_trim & ~is_nl
    l_start = l_content & (prev_nl | at_t0)

    # Paragraph separators: \n chars inside runs of >= 2.
    nl_start = is_nl & ~prev_nl
    nl_run_end = is_nl & ~_shift_l(is_nl, False)
    widths = utf8_width(cps)

    from .pallas_scan import Tap, chain_group, chain_pass, chain_scan, chain_scan_ok

    if chain_scan_ok(*cps.shape):
        # Dependency-chain megakernel: the nl-run counter, the reversed
        # run-total broadcast, and the four line/paragraph segment scans (the
        # paragraph pair depends on run_total through is_sep/p_start) walk
        # the row tile in ONE dispatch instead of six.  Pass 0 counts
        # newline runs; pass 1 (reverse) broadcasts each run's total back
        # over its run; pass 2 derives the paragraph frame from run_total
        # taps in-register and runs all four segment hash/byte scans.  Every
        # operand restates the staged recurrence exactly (_seg_add_group
        # note) — bit-identical by int32 associativity.
        is_nl_i = is_nl.astype(jnp.int32)

        def _prep_run_total(nl_run_t, ne):
            return jnp.where(ne != 0, nl_run_t, 0), ne

        def _para_frame(rt, sh_rt, nl, sh_nl, it, t0f):
            sep = (nl != 0) & (rt >= 2)
            sh_sep = (sh_nl != 0) & (sh_rt >= 2)
            p_c = (it != 0) & ~sep
            p_s = p_c & (sh_sep | (t0f != 0))
            return p_c, p_s

        def _prep_p_hash(rt, sh_rt, nl, sh_nl, it, t0f, c):
            p_c, p_s = _para_frame(rt, sh_rt, nl, sh_nl, it, t0f)
            return (
                jnp.where(p_s, 0, jnp.where(p_c, 31, 1)),
                jnp.where(p_c, c, 0),
            )

        def _prep_p_bytes(rt, sh_rt, nl, sh_nl, it, t0f, w):
            p_c, p_s = _para_frame(rt, sh_rt, nl, sh_nl, it, t0f)
            return jnp.where(p_s, 0, 1), jnp.where(p_c, w, 0)

        para_deps = (
            Tap(1, 0),
            Tap(1, 0, shift=1, fill=0),
            is_nl_i,
            prev_nl.astype(jnp.int32),
            in_trim.astype(jnp.int32),
            at_t0.astype(jnp.int32),
        )
        res = chain_scan(
            [
                chain_pass(
                    [
                        {
                            "kind": "affine",
                            "xs": (jnp.where(nl_start, 0, 1), is_nl_i),
                            "emit": "none",
                        }
                    ]
                ),
                chain_pass(
                    [
                        chain_group(
                            "segmax",
                            (Tap(0, 0), nl_run_end.astype(jnp.int32)),
                            prep=_prep_run_total,
                            n_ops=2,
                        )
                    ],
                    reverse=True,
                ),
                chain_pass(
                    [
                        {
                            "kind": "affine",
                            "xs": (
                                jnp.where(l_start, 0, jnp.where(l_content, 31, 1)),
                                jnp.where(l_content, cps, 0),
                            ),
                        },
                        {
                            "kind": "affine",
                            "xs": (
                                jnp.where(l_start, 0, 1),
                                jnp.where(l_content, widths, 0),
                            ),
                        },
                        chain_group(
                            "affine", para_deps + (cps,), prep=_prep_p_hash, n_ops=2
                        ),
                        chain_group(
                            "affine", para_deps + (widths,), prep=_prep_p_bytes, n_ops=2
                        ),
                    ]
                ),
            ]
        )
        run_total = res[1][0][0]
        l_pre = (res[2][0][0], res[2][1][0])
        p_pre = (res[2][2][0], res[2][3][0])
    else:
        nl_run = seg_scan_add(is_nl.astype(jnp.int32), nl_start)
        run_total = rev(
            seg_scan_max(rev(jnp.where(nl_run_end, nl_run, 0)), rev(nl_run_end))
        )
        l_pre = p_pre = None

    is_sep = is_nl & (run_total >= 2)
    p_content = in_trim & ~is_sep
    p_start = p_content & (_shift_r(is_sep, False) | at_t0)

    def seg_values(content, start, pre=None):
        end = content & ~_shift_l(content, False)
        if pre is not None:
            h, by = pre
        else:
            h = _poly_hash(cps, content, start)
            by = seg_scan_add(jnp.where(content, widths, 0), start)
        n = jnp.sum(start, axis=1).astype(jnp.int32)
        return end, h, by, n

    def seg_finish(tbl_h, tbl_b, n):
        # Mix the byte length into the run key: equal strings keep equal
        # keys, while hash-colliding unequal strings of different lengths
        # no longer count as duplicates (ADVICE r2 discrimination note).
        tbl_h = tbl_h * jnp.int32(31) + tbl_b
        tbl_valid = jnp.arange(max_segs, dtype=jnp.int32)[None, :] < n[:, None]
        return tbl_h, tbl_b, tbl_valid, n

    l_end, l_h, l_by, n_l = seg_values(l_content, l_start, l_pre)
    p_end, p_h, p_by, n_p = seg_values(p_content, p_start, p_pre)
    if use_sort_tables():
        # Segments are non-empty char runs, so seg ids are gapless 0..n-1 and
        # slot j == the j-th segment end — identical to the scatter layout.
        (lr, pr) = _rank_positions_many([l_end, p_end], max_segs, mesh)
        lh, lb, lv, n_l = seg_finish(
            _gather_table(l_h, *lr), _gather_table(l_by, *lr), n_l
        )
        ph, pb, pv, n_p = seg_finish(
            _gather_table(p_h, *pr), _gather_table(p_by, *pr), n_p
        )
    else:
        l_sid = jnp.cumsum(l_start.astype(jnp.int32), axis=1) - 1
        p_sid = jnp.cumsum(p_start.astype(jnp.int32), axis=1) - 1
        lh, lb, lv, n_l = seg_finish(
            _scatter(l_h, l_sid, l_end, max_segs),
            _scatter(l_by, l_sid, l_end, max_segs),
            n_l,
        )
        ph, pb, pv, n_p = seg_finish(
            _scatter(p_h, p_sid, p_end, max_segs),
            _scatter(p_by, p_sid, p_end, max_segs),
            n_p,
        )
    l_sorted, p_sorted = _sort_runs_many([(lh, lb, lv), (ph, pb, pv)], mesh=mesh)
    l_dup_elems, l_dup_bytes = _dup_counts_sorted(l_sorted)
    p_dup_elems, p_dup_bytes = _dup_counts_sorted(p_sorted)

    # Word tables for n-grams (word_idx enumerates valid ends gaplessly, so
    # the sorted compaction lands each word at its scatter slot).
    valid_end = st.unit_end & st.unit_valid
    if use_sort_tables():
        [(wpos, wreal)] = _rank_positions_many([valid_end], max_words, mesh)
        whash = _gather_table(st.unit_hash, wpos, wreal)
        wbytes = _gather_table(st.unit_bytes, wpos, wreal)
    else:
        whash = _scatter(st.unit_hash, st.word_idx, valid_end, max_words)
        wbytes = _scatter(st.unit_bytes, st.word_idx, valid_end, max_words)
    n_words = st.n_words
    widx = jnp.arange(max_words, dtype=jnp.int32)[None, :]

    out: Dict[str, jax.Array] = {
        "trimmed_len": trimmed_len,
        "n_paragraphs": n_p,
        "para_dup_elems": p_dup_elems,
        "para_dup_bytes": p_dup_bytes,
        "n_lines": n_l,
        "line_dup_elems": l_dup_elems,
        "line_dup_bytes": l_dup_bytes,
        "seg_overflow": (n_l > max_segs) | (n_p > max_segs),
        "word_overflow": n_words > max_words,
    }

    # Build all n-gram tables, then run every dup-detection sort as ONE
    # batched device sort and every greedy-selection DFA as ONE batched scan
    # (per-n emission dominated compile time and HLO size).
    ns = sorted(set(list(top_ns) + list(dup_ns)))
    grams = {}
    for n in ns:
        gh = jnp.zeros_like(whash)
        gb = jnp.zeros_like(wbytes)
        for k in range(n):
            gh = gh * jnp.int32(1000003) + jnp.pad(whash[:, k:], ((0, 0), (0, k)))
            gb = gb + jnp.pad(wbytes[:, k:], ((0, 0), (0, k)))
        # Byte-length mixing, as for the line/para tables above.
        gh = gh * jnp.int32(31) + gb
        win_valid = (widx + n) <= n_words[:, None]
        grams[n] = (gh, gb, win_valid)

    b, m = whash.shape
    idx = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None, :], (b, m))
    dup_sizes = sorted(set(dup_ns))
    min_dup = dup_sizes[0] if dup_sizes else None

    # Ungated jobs: every top-n job plus the SMALLEST dup-n job.  A truly
    # duplicated n-gram contains a duplicated (n-1)-gram at the same offset,
    # so "no dup min_dup-grams" implies no dup larger-n-grams either — the
    # expensive larger-n sorts and the greedy-selection machinery run under a
    # lax.cond taken only when the cheap gate fires.  (Hash-collision-only
    # "dups" at larger n without a min_dup dup are suppressed by the gate —
    # a strict reduction of the documented collision divergence.)
    #
    # The gate is batch-global (one dirty row runs the branch for the whole
    # batch); it pays off for clean or small batches — parity suites, shards
    # of already-deduped text — while dirty web-scale batches cost one extra
    # sort dispatch over the ungated form.
    jobs, tags = [], []
    for n in ns:
        gh, gb, win_valid = grams[n]
        if n in top_ns:
            # " "-joined n-grams: byte length includes n-1 single-byte spaces.
            jobs.append((gh, gb + (n - 1), win_valid))
            tags.append(("top", n))
        if n == min_dup:
            jobs.append((gh, idx, win_valid))
            tags.append(("dup", n))

    dup_min_flags = dup_min_rid = None
    srts = _sort_runs_many(jobs, mesh=mesh) if jobs else []
    # All post-sort per-run scans (top-n run lengths + min-dup run ids) fuse
    # into one chain dispatch over the stacked tables when the table shape
    # passes the gate; None falls back to the identical staged scans.
    pre = _sorted_table_streams(
        [(kind, srt) for (kind, _), srt in zip(tags, srts)], mesh=mesh
    )
    for i, ((kind, n), srt) in enumerate(zip(tags, srts)):
        if kind == "top":
            out[f"top_{n}"] = _top_duplicate_sorted(
                srt, run_len=pre[i] if pre else None
            )
        else:
            dup_min_flags, dup_min_rid = _dup_run_info_sorted(
                srt, grams[n][2], idx, mesh=mesh,
                first_in_run=pre[i] if pre else None,
            )

    if dup_sizes:
        rest = dup_sizes[1:]

        def _dup_work(operand):
            _, min_rid = operand
            walk = [(min_dup, min_rid, grams[min_dup][2], grams[min_dup][1])]
            if rest:
                rjobs = [(grams[n][0], idx, grams[n][2]) for n in rest]
                rsrts = _sort_runs_many(rjobs, mesh=mesh)
                rpre = _sorted_table_streams(
                    [("dup", srt) for srt in rsrts], mesh=mesh
                )
                for i, (n, srt) in enumerate(zip(rest, rsrts)):
                    _, rid_n = _dup_run_info_sorted(
                        srt, grams[n][2], idx, mesh=mesh,
                        first_in_run=rpre[i] if rpre else None,
                    )
                    walk.append((n, rid_n, grams[n][2], grams[n][1]))
            res = _find_all_dup_bytes_batched(walk)
            return tuple(res[f"dup_{n}"] for n in dup_sizes)

        def _dup_zero(operand):
            zero = jnp.zeros_like(n_words)
            return tuple(zero for _ in dup_sizes)

        dup_outs = jax.lax.cond(
            jnp.any(dup_min_flags), _dup_work, _dup_zero, (dup_min_flags, dup_min_rid)
        )
        for n, v in zip(dup_sizes, dup_outs):
            out[f"dup_{n}"] = v
    return out


def _dup_run_info_sorted(
    sorted_triple, win_valid, idx, mesh=None, first_in_run=None
) -> Tuple[jax.Array, jax.Array]:
    """``(flags, run_first)`` from a ``(hash, idx)``-sorted window table:
    ``flags`` — "an earlier identical window exists" (a superset of
    find_all_duplicate's dynamic dup test, used as the rarity gate);
    ``run_first`` — each window's run id (the minimum window index sharing
    its hash), the canonical slot for the walk's visited table."""
    is_real, s_hash, sidx = sorted_triple
    b, m = s_hash.shape
    run_start = _run_starts(s_hash)
    if first_in_run is None:
        # Sorted by (hash, idx): the run's first slot holds the minimum index.
        first_in_run = seg_scan_max(jnp.where(run_start, sidx, -(2**30)), run_start)
    if use_sort_tables():
        # Un-sort by window index instead of scattering: the real entries'
        # sidx values are exactly 0..n_valid-1 (win_valid is a prefix mask),
        # so sorting (sidx, first_in_run) restores window order with slot j
        # holding window j's run id — the scatter layout, fills included.
        # Pad m to a power of two first (ADVICE r4): sort2's Pallas bitonic
        # network requires it, and a non-pow2 width here silently fell back
        # to lax.sort — correct but off the tuned VMEM path.  Pad keys are
        # _I32_MAX, sorting to the end; the real entries occupy slots
        # 0..n_valid-1 either way, so slicing back is exact.
        k0 = jnp.where(is_real, sidx, _I32_MAX)
        k1 = jnp.where(is_real, first_in_run, 0)
        m_pow2 = 1 << (max(m - 1, 1)).bit_length()
        if m_pow2 != m:
            pad = ((0, 0), (0, m_pow2 - m))
            k0 = jnp.pad(k0, pad, constant_values=_I32_MAX)
            k1 = jnp.pad(k1, pad)
        first_occ = sort2(k0, k1, mesh=mesh)[1][:, :m]
    else:
        first_occ = _scatter(first_in_run, sidx, is_real, m)
    return win_valid & (first_occ < idx), first_occ


def _find_all_dup_bytes_batched(jobs) -> Dict[str, jax.Array]:
    """find_all_duplicate, EXACT: the oracle's greedy scan with its
    visited-set dynamics (text.rs:241-259) — ``seen`` holds only windows the
    scan actually visited, a hit counts the window's bytes and jumps ``n``
    (the jumped-over windows are never inserted), a miss inserts and steps 1.

    Every job ``(n, run_first, win_valid, gb)`` stacks along the batch axis
    and one ``lax.scan`` over the ``m`` window positions walks all rows in
    lockstep: the carry is a per-row visited table indexed by ``run_first``
    (each window's canonical run id — equal hash == equal gram under the
    module's no-collision assumption), a skip counter, and the byte
    accumulator.  Position dynamics can't be pointer-jumped ahead of time —
    whether a window is a duplicate depends on which of its twins were
    themselves skipped — which is why this is a sequential scan and not the
    earlier (approximate) binary-lifting chain.  It only runs under the
    min-dup rarity gate, so clean batches never pay for it.
    """
    out: Dict[str, jax.Array] = {}
    if not jobs:
        return out
    b, m = jobs[0][1].shape
    n_vec = jnp.concatenate(
        [jnp.full((b,), n, jnp.int32) for n, _, _, _ in jobs]
    )  # [kB]
    rid = jnp.concatenate([j[1] for j in jobs], axis=0)  # [kB, m]
    val = jnp.concatenate([j[2] for j in jobs], axis=0)
    gbs = jnp.concatenate([j[3] for j in jobs], axis=0)
    rows = jnp.arange(rid.shape[0], dtype=jnp.int32)
    onehot_visited = use_sort_tables()
    lane = jnp.arange(m, dtype=jnp.int32)[None, :]

    def step(carry, xs):
        visited, skip, acc = carry
        rid_c, gb_c, val_c = xs  # [kB] each
        can = (skip == 0) & val_c
        if onehot_visited:
            # One-hot compare instead of row gather/scatter: O(kB*m) VPU work
            # per step, but no serialized dynamic addressing on TPU.
            oh = lane == rid_c[:, None]
            seen = jnp.sum(jnp.where(oh, visited, 0), axis=1) > 0
            hit = can & seen
            visited = jnp.maximum(
                visited, (oh & (can & ~seen)[:, None]).astype(jnp.int32)
            )
        else:
            seen = visited[rows, rid_c] > 0
            hit = can & seen
            visited = visited.at[rows, rid_c].max((can & ~seen).astype(jnp.int32))
        acc = acc + jnp.where(hit, gb_c, 0)
        skip = jnp.where(hit, n_vec - 1, jnp.maximum(skip - 1, 0))
        return (visited, skip, acc), None

    init = (
        jnp.zeros(rid.shape, jnp.int32),
        jnp.zeros(rid.shape[0], jnp.int32),
        jnp.zeros(rid.shape[0], jnp.int32),
    )
    (_, _, acc), _ = jax.lax.scan(step, init, (rid.T, gbs.T, val.T))
    for i, (n, _, _, _) in enumerate(jobs):
        out[f"dup_{n}"] = acc[i * b : (i + 1) * b]
    return out


# --- Sentence counting (device twin of split_into_sentences) -----------------

_TERM_SET = np.sort(np.array([ord(c) for c in ("." + _STERM)], dtype=np.int32))
_STERM_SET = np.sort(np.array([ord(c) for c in _STERM], dtype=np.int32))
_CLOSE_SET = np.sort(np.array([ord(c) for c in _CLOSE], dtype=np.int32))
_SP_SET = np.sort(np.array([ord(c) for c in _SP], dtype=np.int32))
_PSEP_SET = np.sort(np.array([ord(c) for c in _PARA_SEP], dtype=np.int32))

# Match DFA over symbols 0=other, 1=TERM, 2=CLOSE, 3=SP.
# States: 0 outside, 1 in terms, 2 in closes, 3 in spaces.
_SENT_T = np.zeros((4, 4), dtype=np.int32)
_SENT_T[0, :] = 0
_SENT_T[1, :] = 1
_SENT_T[2, :] = [0, 2, 2, 0]
_SENT_T[3, :] = [0, 3, 3, 3]


def sentence_boundaries(cps: jax.Array, mask: jax.Array, cls: jax.Array) -> jax.Array:
    """[B, L] bool — a sentence boundary falls immediately BEFORE each True
    position (the device twin of utils.text._sentence_boundaries, applied to
    the chars selected by ``mask``)."""
    term = isin_sorted(cps, jnp.asarray(_TERM_SET)) & mask
    sterm = isin_sorted(cps, jnp.asarray(_STERM_SET)) & mask
    close = isin_sorted(cps, jnp.asarray(_CLOSE_SET)) & mask
    sp = isin_sorted(cps, jnp.asarray(_SP_SET)) & mask
    psep = isin_sorted(cps, jnp.asarray(_PSEP_SET)) & mask

    sym = jnp.zeros_like(cps)
    sym = jnp.where(term, 1, sym)
    sym = jnp.where(close & ~term, 2, sym)
    sym = jnp.where(sp & ~close & ~term, 3, sym)
    state = dfa_states(sym, _SENT_T)
    prev_state = _shift_r(state, 0)

    # Match-start: a terminator not already inside a terminator run.
    match_start = term & (prev_state != 1)
    has_sterm = (
        seg_scan_or(jnp.where(state > 0, sterm.astype(jnp.int32), 0), match_start) > 0
    )
    prev_has_sterm = _shift_r(has_sterm.astype(jnp.int32), 0) > 0
    dot_last = (
        _shift_r((cps == ord(".")) & mask, False) & (prev_state == 1)
    )

    lower = (cls & LOWER) != 0
    alnum_ = ((cls & ALNUM) != 0) | (cps == ord("_"))

    # Boundary candidate: previous char inside a match; current char either
    # exits the match or starts a fresh terminator run after closes/spaces.
    fresh_term = term & ((prev_state == 2) | (prev_state == 3))
    candidate = mask & (prev_state > 0) & ((state == 0) | fresh_term)

    no_break = ~prev_has_sterm & ((dot_last & alnum_) | lower)
    return (candidate & ~no_break) | (_shift_r(psep, False) & mask)


def _sentence_frame(cps: jax.Array, mask: jax.Array, cls: jax.Array) -> dict:
    """Elementwise operands of :func:`sentence_boundaries`, shared between
    the staged path and the chain kernel (all int32, kernel-ready)."""
    from .dfa import dfa_packed_fns

    term = isin_sorted(cps, jnp.asarray(_TERM_SET)) & mask
    sterm = isin_sorted(cps, jnp.asarray(_STERM_SET)) & mask
    close = isin_sorted(cps, jnp.asarray(_CLOSE_SET)) & mask
    sp = isin_sorted(cps, jnp.asarray(_SP_SET)) & mask
    psep = isin_sorted(cps, jnp.asarray(_PSEP_SET)) & mask

    sym = jnp.zeros_like(cps)
    sym = jnp.where(term, 1, sym)
    sym = jnp.where(close & ~term, 2, sym)
    sym = jnp.where(sp & ~close & ~term, 3, sym)
    return {
        "fns": dfa_packed_fns(sym, _SENT_T),
        "term": term.astype(jnp.int32),
        "sterm": sterm.astype(jnp.int32),
        "lower": ((cls & LOWER) != 0).astype(jnp.int32),
        "alnum": (((cls & ALNUM) != 0) | (cps == ord("_"))).astype(jnp.int32),
        "sh_dot": _shift_r((cps == ord(".")) & mask, False).astype(jnp.int32),
        "sh_psep": (_shift_r(psep, False) & mask).astype(jnp.int32),
        "mask": mask.astype(jnp.int32),
    }


def _sentence_passes(fr: dict, begin_extra: jax.Array, nonws: jax.Array, emit: str):
    """Passes 0-2 of the sentence chain: DFA map composition → sterm run
    counter → per-segment non-ws counter.  The boundary rule is derived from
    packed-state taps in-register — the same int32 formulas as
    :func:`sentence_boundaries` (prev_* via shift taps with fill 0, matching
    the staged ``_shift_r(..., 0)``; the sterm OR becomes a segmented SUM
    tested ``> 0``, which agrees bit-for-bit on {0,1} streams)."""
    from .pallas_scan import Tap, chain_group, chain_pass

    def _prep_hst(pk, pk_prev, t, s):
        st = pk & 15
        return (
            jnp.where((t != 0) & ((pk_prev & 15) != 1), 0, 1),
            jnp.where(st > 0, s, 0),
        )

    def _prep_cnt(pk, pk_prev, hst_prev, t, shd, lo, al, shp, mk, ex, nw):
        st = pk & 15
        pst = pk_prev & 15
        fresh = (t != 0) & ((pst == 2) | (pst == 3))
        cand = (mk != 0) & (pst > 0) & ((st == 0) | fresh)
        dot_last = (shd != 0) & (pst == 1)
        nb = ~(hst_prev > 0) & ((dot_last & (al != 0)) | (lo != 0))
        boundary = (cand & ~nb) | ((shp != 0) & (mk != 0))
        return jnp.where(boundary | (ex != 0), 0, 1), nw

    return [
        chain_pass(
            [{"kind": "dfa", "xs": (fr["fns"],), "emit": emit, "n_states": 4}]
        ),
        chain_pass(
            [
                chain_group(
                    "affine",
                    (Tap(0, 0), Tap(0, 0, shift=1, fill=0), fr["term"], fr["sterm"]),
                    prep=_prep_hst,
                    n_ops=2,
                    emit=emit,
                )
            ]
        ),
        chain_pass(
            [
                chain_group(
                    "affine",
                    (
                        Tap(0, 0),
                        Tap(0, 0, shift=1, fill=0),
                        Tap(1, 0, shift=1, fill=0),
                        fr["term"],
                        fr["sh_dot"],
                        fr["lower"],
                        fr["alnum"],
                        fr["sh_psep"],
                        fr["mask"],
                        begin_extra.astype(jnp.int32),
                        nonws.astype(jnp.int32),
                    ),
                    prep=_prep_cnt,
                    n_ops=2,
                    emit="scan" if emit == "scan" else emit,
                )
            ]
        ),
    ]


def sentence_counts(cps: jax.Array, lengths: jax.Array) -> jax.Array:
    """Sentences per row — ``len(split_into_sentences(text))`` for rows whose
    content is already globally trimmed (C4's rewritten batches are)."""
    from .pallas_scan import Tap, chain_group, chain_pass, chain_scan, chain_scan_ok

    _, length = cps.shape
    mask = jnp.arange(length, dtype=jnp.int32)[None, :] < lengths[:, None]
    cls = classify(cps)
    cls = jnp.where(mask, cls, 0).astype(cls.dtype)
    ws = (cls & WS) != 0
    nonws = mask & ~ws

    if chain_scan_ok(*cps.shape):
        # DFA → sterm counter → segment counter → total, ONE dispatch: every
        # intermediate (three staged dispatches' worth) stays in scratch and
        # only the [B, 1] sentence count reaches HBM.
        fr = _sentence_frame(cps, mask, cls)
        passes = _sentence_passes(fr, _first_col(mask), nonws, emit="none")
        passes.append(
            chain_pass(
                [
                    chain_group(
                        "add",
                        (Tap(2, 0), nonws.astype(jnp.int32)),
                        prep=lambda c, nw: ((nw != 0) & (c == 1),),
                        n_ops=1,
                        emit="last",
                    )
                ]
            )
        )
        res = chain_scan(passes)
        return res[3][0][0][:, 0].astype(jnp.int32)

    boundary = sentence_boundaries(cps, mask, cls)

    # Count segments containing >= 1 non-ws char.
    seg_begin = boundary | _first_col(mask)
    cnt = seg_scan_add(nonws.astype(jnp.int32), seg_begin)
    first_nonws = nonws & (cnt == 1)
    return jnp.sum(first_nonws, axis=1).astype(jnp.int32)


# --- C4 stage ----------------------------------------------------------------


class C4Params(NamedTuple):
    split_paragraph: bool
    remove_citations: bool
    filter_no_terminal_punct: bool
    min_num_sentences: int
    min_words_per_line: int
    max_word_length: int
    filter_lorem_ipsum: bool
    filter_javascript: bool
    filter_curly_bracket: bool
    filter_policy: bool


_END_PUNCT_SET = np.sort(
    np.array([ord(c) for c in (".", "!", "?", '"', "'", "”")], dtype=np.int32)
)

_POLICY = (
    "terms of use",
    "privacy policy",
    "cookie policy",
    "uses cookies",
    "use of cookies",
    "use cookies",
)


def c4_stage(
    cps: jax.Array,
    lengths: jax.Array,
    params: C4Params,
    max_lines: int,
    mesh=None,
) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array]:
    """The C4 quality filter as a device stage (c4_filters.rs:147-295).

    Returns ``(stats, new_cps, new_lengths)``: the new batch is the rewritten
    content (kept lines joined by ``\\n``) for every row.

    ``split_paragraph=True`` segments on newlines (``content.lines()``,
    c4_filters.rs:150-156); ``False`` segments on sentence boundaries via the
    shared sentence DFA (:func:`sentence_boundaries`), synthesizing one
    ``\\n`` separator per kept-sentence join from the inter-sentence
    whitespace.  A sentence boundary with NO whitespace after it (rare:
    terminator directly followed by the next sentence's first char) cannot
    host a separator — those rows set ``line_overflow`` and take the counted
    bit-exact host fallback.
    """
    _, length = cps.shape
    mask = jnp.arange(length, dtype=jnp.int32)[None, :] < lengths[:, None]
    cls = classify(cps)
    cls = jnp.where(mask, cls, 0).astype(cls.dtype)
    ws = (cls & WS) != 0
    low = _lowered(cps, mask)
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]

    # Doc-level early rejects (c4_filters.rs:166-187).  The lorem-ipsum
    # candidate prefix hash rides the segmentation chain kernel below when
    # the chain gate holds (lorem_h), so has_lorem finalizes after the split.
    lorem_h = None
    has_curly = jnp.any(((cps == ord("{")) | (cps == ord("}"))) & mask, axis=1)

    def _citation_deleted(unit_content):
        if not params.remove_citations:
            return jnp.zeros_like(mask)
        # Citation machinery only runs on batches that contain a '[' at all
        # (rare in clean text — the same skip the oracle's regex scan gets
        # from its first-byte check).
        return jax.lax.cond(
            jnp.any((cps == ord("[")) & mask),
            lambda: citation_spans(
                jnp.where(unit_content, cps, 0),
                ((cls & DIGIT) != 0) & unit_content,
                ws & unit_content,
            ),
            lambda: jnp.zeros_like(mask),
        )

    gap_overflow = jnp.zeros(cps.shape[0], dtype=bool)
    if params.split_paragraph:
        li = line_info(cps, mask)
        nonws = li.content & ~ws
        reset = _line_reset(li, mask)

        # Per-line trim: chars at/after the first non-ws, at/before the last.
        from .pallas_scan import (
            chain_pass,
            chain_scan,
            chain_scan_ok,
            fused_scan,
            fused_scan_ok,
        )

        r_reset = _first_col(mask) | _shift_r(rev(li.is_nl), False)
        if chain_scan_ok(*cps.shape):
            # Forward line counter (+ the doc-level lorem-ipsum candidate
            # hash riding along) and the reversed counter as a second pass —
            # reverse-pass operands are given in natural orientation (the
            # kernel walks them flipped), i.e. rev() of the staged
            # reversed-frame operands.
            g0 = [_seg_add_group((nonws.astype(jnp.int32),), reset)]
            if params.filter_lorem_ipsum:
                g0.append(_pattern_hash_group(low, mask))
            res = chain_scan(
                [
                    chain_pass(g0),
                    chain_pass(
                        [
                            {
                                "kind": "affine",
                                "xs": (
                                    rev(jnp.where(r_reset, 0, 1)),
                                    nonws.astype(jnp.int32),
                                ),
                            }
                        ],
                        reverse=True,
                    ),
                ]
            )
            after_first = res[0][0][0] >= 1
            if params.filter_lorem_ipsum:
                lorem_h = res[0][1][0]
            before_last = res[1][0][0] >= 1
        elif fused_scan_ok(*cps.shape):
            # The forward and reversed line counters are independent — one
            # fused kernel pass instead of two staged scans.
            res = fused_scan(
                [
                    _seg_add_group((nonws.astype(jnp.int32),), reset),
                    _seg_add_group((rev(nonws).astype(jnp.int32),), r_reset),
                ]
            )
            after_first = res[0][0] >= 1
            before_last = rev(res[1][0] >= 1)
        else:
            after_first = seg_scan_add(nonws.astype(jnp.int32), reset) >= 1
            before_last = rev(
                seg_scan_add(rev(nonws).astype(jnp.int32), r_reset) >= 1
            )
        in_line_trim = li.content & after_first & before_last

        deleted = _citation_deleted(li.content)
        keep1 = (in_line_trim & ~deleted) | li.is_nl
        c1_src = cps
        n_units = li.n_lines
    else:
        # Sentence mode: global trim (split_into_sentences trims the input,
        # utils/text.py), boundaries from the shared DFA, segments between
        # boundaries, each trimmed; blank segments are not sentences.
        nonws_all = mask & ~ws
        any_nonws = jnp.any(nonws_all, axis=1)
        t0 = jnp.min(jnp.where(nonws_all, pos, length), axis=1)
        t1 = jnp.max(jnp.where(nonws_all, pos, -1), axis=1)
        in_trim = (pos >= t0[:, None]) & (pos <= t1[:, None]) & mask

        nonws = in_trim & ~ws
        from .pallas_scan import chain_scan, chain_scan_ok

        if chain_scan_ok(*cps.shape):
            # Sentence DFA → sterm counter → segment counter in one kernel
            # (+ the lorem candidate hash riding pass 0); the boundary mask
            # the compaction handoff needs is recomputed elementwise from
            # the emitted packed-state/sterm streams — the exact staged
            # formulas from sentence_boundaries, so bit-identical.
            fr = _sentence_frame(cps, in_trim, cls)
            at_t0x = (pos == t0[:, None]) & in_trim
            passes = _sentence_passes(fr, at_t0x, nonws, emit="scan")
            if params.filter_lorem_ipsum:
                passes[0]["groups"].append(_pattern_hash_group(low, mask))
            res = chain_scan(passes)
            state = res[0][0][0] & 15
            if params.filter_lorem_ipsum:
                lorem_h = res[0][1][0]
            hst = res[1][0][0]
            cnt = res[2][0][0]
            prev_state = _shift_r(state, 0)
            prev_has_sterm = _shift_r(hst, 0) > 0
            term = fr["term"] != 0
            fresh_term = term & ((prev_state == 2) | (prev_state == 3))
            candidate = in_trim & (prev_state > 0) & ((state == 0) | fresh_term)
            dot_last = (fr["sh_dot"] != 0) & (prev_state == 1)
            no_break = ~prev_has_sterm & (
                (dot_last & (fr["alnum"] != 0)) | (fr["lower"] != 0)
            )
            boundary = (candidate & ~no_break) | ((fr["sh_psep"] != 0) & in_trim)
            seg_begin = (boundary | (pos == t0[:, None])) & in_trim
        else:
            boundary = sentence_boundaries(cps, in_trim, cls)
            seg_begin = (boundary | (pos == t0[:, None])) & in_trim
            cnt = seg_scan_add(nonws.astype(jnp.int32), seg_begin)
        first_nonws_seg = nonws & (cnt == 1)
        n_units = jnp.sum(first_nonws_seg, axis=1).astype(jnp.int32)

        # Segment ends: last char of each segment (next char starts a new
        # one or leaves the trim).
        seg_end = in_trim & (_shift_l(seg_begin, False) | ~_shift_l(in_trim, False))
        r_reset = _first_col(mask) | rev(seg_end)
        cnt_r = seg_scan_add(rev(nonws).astype(jnp.int32), r_reset)
        before_last = rev(cnt_r >= 1)
        in_sent_trim = in_trim & (cnt >= 1) & before_last
        sent_last_nonws = rev(rev(nonws) & (cnt_r == 1))

        # One synthesized '\n' per kept-sentence join: the first char after
        # each sentence's trimmed end (inter-sentence gaps are pure ws), if
        # any sentence follows.
        suffix_nonws = _shift_l(
            rev(jnp.cumsum(rev(nonws).astype(jnp.int32), axis=1)) > 0, False
        )
        sep_keep = _shift_r(sent_last_nonws, False) & ~nonws & in_trim & suffix_nonws
        gap_overflow = jnp.any(sent_last_nonws & _shift_l(nonws, False), axis=1)

        deleted = _citation_deleted(in_trim)
        keep1 = (in_sent_trim & ~deleted) | sep_keep
        c1_src = jnp.where(sep_keep, jnp.int32(NL), cps)
        del any_nonws  # rows without content have empty keep1 already

    if params.filter_lorem_ipsum:
        has_lorem = jnp.any(
            _pattern_union_starts(low, mask, ("lorem ipsum",), h_inc=lorem_h), axis=1
        )
    else:
        has_lorem = jnp.zeros(cps.shape[0], dtype=bool)

    c1_cps, c1_len = compact(c1_src, keep1, mesh=mesh)

    # --- per-line checks on the compacted batch ---
    m1 = jnp.arange(length, dtype=jnp.int32)[None, :] < c1_len[:, None]
    st1 = structure(c1_cps, c1_len, with_hashes=False)
    li1 = line_info(c1_cps, m1)
    low1 = _lowered(c1_cps, m1)

    valid_end1 = st1.unit_end & st1.unit_valid
    is_dot1 = (c1_cps == ord(".")) & m1
    dot_start1 = is_dot1 & ~_shift_r(is_dot1, False)

    # Only the UNION of javascript/policy line flags affects line_keep (no
    # per-cause stats are reported), so all patterns share one candidate
    # pass (_pattern_union_starts).
    line_patterns: Tuple[str, ...] = ()
    if params.filter_javascript:
        line_patterns += ("javascript",)
    if params.filter_policy:
        line_patterns += _POLICY

    from .pallas_scan import chain_pass as _cpass, chain_scan as _cscan
    from .pallas_scan import chain_scan_ok as _cok

    starts_h = None
    if _cok(*cps.shape):
        # Post-compaction pass: the dot-run counter and the line-pattern
        # candidate hash share one dispatch over the rewritten batch.
        g1 = [_seg_add_group((is_dot1.astype(jnp.int32),), dot_start1)]
        if line_patterns:
            g1.append(_pattern_hash_group(low1, m1))
        res1 = _cscan([_cpass(g1)])
        dot_run1 = res1[0][0][0]
        if line_patterns:
            starts_h = res1[0][1][0]
    else:
        dot_run1 = seg_scan_add(is_dot1.astype(jnp.int32), dot_start1)
    starts = (
        _pattern_union_starts(low1, m1, line_patterns, h_inc=starts_h)
        if line_patterns
        else None
    )

    if use_sort_tables():
        # Slot j = line id j: every line present in the compacted batch has
        # exactly one representative char — its '\n', or the row's final
        # char — in line order, so the sorted compaction reproduces the
        # scatter slot layout (a final line whose chars all trimmed away has
        # no slot on either path; its verdict comes from the fills via
        # ``line_exists`` below).  Per-line values become segmented scans
        # read at the representative.
        reset1 = _line_reset(li1, m1)
        row_last1 = m1 & ~_shift_l(m1, False)
        rep1 = (li1.is_nl | row_last1) & m1
        [(lpos1, lreal1)] = _rank_positions_many([rep1], max_lines, mesh)
        content_set1 = li1.content | reset1

        line_words = _gather_table(
            seg_scan_add(valid_end1.astype(jnp.int32), reset1), lpos1, lreal1
        )
        line_max_word = _gather_table(
            seg_scan_max(jnp.where(valid_end1, st1.unit_len, 0), reset1),
            lpos1,
            lreal1,
        )
        # "Value at the line's last content char" via a latch over content
        # positions (a blank line's representative reads the latch cleared
        # at its line start — the scatter fill).
        line_last_char = _gather_table(
            latch_scan(jnp.where(li1.content, c1_cps, 0), content_set1),
            lpos1,
            lreal1,
        )
        line_end_dots = _gather_table(
            latch_scan(jnp.where(li1.content & is_dot1, dot_run1, 0), content_set1),
            lpos1,
            lreal1,
        )
        if starts is not None:
            bad_pattern_line = (
                _gather_table(
                    seg_scan_or(starts.astype(jnp.int32), reset1), lpos1, lreal1
                )
                > 0
            )
        else:
            bad_pattern_line = jnp.zeros_like(line_words, dtype=bool)
    else:
        line_words = _scatter(
            jnp.ones_like(c1_cps), li1.line_id, valid_end1, max_lines, op="add"
        )
        line_max_word = _scatter(
            st1.unit_len, li1.line_id, valid_end1, max_lines, op="max"
        )
        # Terminal punctuation: last char of each (already trimmed) line.
        line_last_char = _scatter(c1_cps, li1.line_id, li1.last_content, max_lines)
        line_end_dots = _scatter(
            jnp.where(is_dot1, dot_run1, 0), li1.line_id, li1.last_content, max_lines
        )
        if starts is not None:
            bad_pattern_line = (
                _scatter(
                    starts.astype(jnp.int32), li1.line_id, starts, max_lines, op="add"
                )
                > 0
            )
        else:
            bad_pattern_line = jnp.zeros_like(line_words, dtype=bool)

    ends_terminal = isin_sorted(line_last_char, jnp.asarray(_END_PUNCT_SET)) & (
        line_last_char > 0
    )
    ends_ellipsis = line_end_dots >= 3

    # Unit count comes from the ORIGINAL batch: a final line whose content
    # trimmed away entirely has no chars and no trailing \n in the compacted
    # batch, so li1 under-counts it — but it still exists as a (droppable)
    # line in the oracle's rust_lines view.  (Sentence mode has no such
    # invisible units: every sentence contains a non-ws char.)
    n_lines1 = n_units
    line_exists = jnp.arange(max_lines, dtype=jnp.int32)[None, :] < n_lines1[:, None]

    if params.max_word_length > 0:
        drop_too_long = line_exists & (line_max_word > params.max_word_length)
    else:
        drop_too_long = jnp.zeros_like(line_exists)
    remaining = line_exists & ~drop_too_long
    if params.filter_no_terminal_punct:
        drop_no_term = remaining & ~(ends_terminal & ~ends_ellipsis)
    else:
        drop_no_term = jnp.zeros_like(remaining)
    remaining = remaining & ~drop_no_term
    if params.min_words_per_line > 0:
        drop_few_words = remaining & (line_words < params.min_words_per_line)
    else:
        drop_few_words = jnp.zeros_like(remaining)
    remaining = remaining & ~drop_few_words
    line_keep = remaining & ~bad_pattern_line

    # --- compact kept lines into the rewritten batch ---
    later = rev(jnp.cumsum(rev(line_keep.astype(jnp.int32)), axis=1), axis=1)
    keep_later = _shift_l(later, 0) > 0  # a kept line exists after slot l

    lid1 = jnp.minimum(li1.line_id, max_lines - 1)
    char_line_keep = jnp.take_along_axis(line_keep, lid1, axis=1)
    char_keep_later = jnp.take_along_axis(keep_later, lid1, axis=1)
    keep2 = (li1.content & char_line_keep & m1) | (
        li1.is_nl & char_line_keep & char_keep_later
    )
    c2_cps, c2_len = compact(c1_cps, keep2, mesh=mesh)

    n_sent = sentence_counts(c2_cps, c2_len)

    # Rewrite-identity flag: the rewritten batch equals this stage's input
    # (both zero-padded), so the host can skip its per-document Python
    # string rebuild — the common case on clean text, where every line is
    # kept and already trimmed.
    rewrite_identity = (c2_len == lengths) & jnp.all(c2_cps == cps, axis=1)

    false_b = jnp.zeros_like(has_lorem)
    stats = {
        "has_lorem": has_lorem if params.filter_lorem_ipsum else false_b,
        "has_curly": has_curly if params.filter_curly_bracket else false_b,
        "n_sentences": n_sent,
        "rewrite_identity": rewrite_identity,  # [B]
        "line_keep": line_keep,  # [B, ML]
        "n_lines": jnp.minimum(n_lines1, jnp.int32(max_lines)),
        "drop_too_long": jnp.sum(drop_too_long, axis=1).astype(jnp.int32),
        "drop_no_term": jnp.sum(drop_no_term, axis=1).astype(jnp.int32),
        "drop_few_words": jnp.sum(drop_few_words, axis=1).astype(jnp.int32),
        "line_overflow": (n_lines1 > max_lines) | gap_overflow,
    }
    return stats, c2_cps, c2_len
