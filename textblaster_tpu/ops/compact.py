"""Device-side compaction: masked gather into a fresh packed tensor.

The reference's C4 filter physically rewrites document strings (drops lines,
removes citation spans, rejoins — c4_filters.rs:195-258).  On device the same
effect is a *compaction*: given a keep-mask over ``[B, L]`` codepoints,
move the kept chars to the front of a new ``[B, L]`` tensor and recompute
lengths.  Downstream filter kernels then run on the compacted batch exactly as
they would on any packed batch — sequential pipeline semantics preserved
without leaving the device (SURVEY.md §7 "content rewriting" hard part).

Two implementations behind :func:`textblaster_tpu.ops.device.use_sort_tables`:
an XLA scatter (fast on CPU, serialized on TPU) and a sorted partition on the
VMEM bitonic network (TPU).  Also used by the language-ID kernel to build its
normalized letters-and-boundaries stream.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device import use_sort_tables
from .pallas_sort import sort2

__all__ = ["compact"]

_I32_MAX = np.int32(2**31 - 1)


def compact(
    cps: jax.Array, keep: jax.Array, mesh=None
) -> Tuple[jax.Array, jax.Array]:
    """Pack kept chars to the row starts.

    Args:
      cps:  ``[B, L]`` int32 codepoints.
      keep: ``[B, L]`` bool; True chars survive, order preserved.
      mesh: data-axis mesh for the TPU sort path (pallas under shard_map).

    Returns:
      ``(new_cps [B, L] int32 zero-padded, new_lengths [B] int32)``.
    """
    b, length = cps.shape

    if use_sort_tables():
        new_lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
        # Stable partition by sort: key = original position for kept chars,
        # INT32_MAX for dropped — kept chars land at the row start in order.
        # Codepoints are non-negative, satisfying sort2's payload contract.
        pos = jnp.broadcast_to(
            jnp.arange(length, dtype=jnp.int32)[None, :], (b, length)
        )
        key = jnp.where(keep, pos, _I32_MAX)
        val = jnp.where(keep, cps, 0)
        padded = 1 << (length - 1).bit_length()
        if padded != length:
            pad = ((0, 0), (0, padded - length))
            key = jnp.pad(key, pad, constant_values=_I32_MAX)
            val = jnp.pad(val, pad)
        s_key, s_val = sort2(key, val, mesh=mesh)
        new_cps = jnp.where(s_key[:, :length] != _I32_MAX, s_val[:, :length], 0)
        return new_cps, new_lengths

    # Flat scatter; dropped chars route to a trash slot past the real data.
    # (Byte-identical to the pre-gating trace so the CPU compile cache and
    # tuned CPU-backend record are preserved.)
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    new_lengths = jnp.max(jnp.where(keep, new_pos + 1, 0), axis=1)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    flat_idx = jnp.where(keep, rows * length + new_pos, b * length)
    out = jnp.zeros(b * length + 1, dtype=cps.dtype)
    out = out.at[flat_idx.reshape(-1)].set(cps.reshape(-1), mode="drop")
    return out[:-1].reshape(b, length), new_lengths.astype(jnp.int32)
