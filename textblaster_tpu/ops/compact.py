"""Device-side compaction: masked gather into a fresh packed tensor.

The reference's C4 filter physically rewrites document strings (drops lines,
removes citation spans, rejoins — c4_filters.rs:195-258).  On device the same
effect is a *compaction*: given a keep-mask over ``[B, L]`` codepoints,
scatter the kept chars to the front of a new ``[B, L]`` tensor and recompute
lengths.  Downstream filter kernels then run on the compacted batch exactly as
they would on any packed batch — sequential pipeline semantics preserved
without leaving the device (SURVEY.md §7 "content rewriting" hard part).

Also used by the language-ID kernel to build its normalized
letters-and-boundaries stream.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["compact"]


def compact(cps: jax.Array, keep: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Pack kept chars to the row starts.

    Args:
      cps:  ``[B, L]`` int32 codepoints.
      keep: ``[B, L]`` bool; True chars survive, order preserved.

    Returns:
      ``(new_cps [B, L] int32 zero-padded, new_lengths [B] int32)``.
    """
    b, length = cps.shape
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    new_lengths = jnp.max(jnp.where(keep, new_pos + 1, 0), axis=1)

    # Flat scatter; dropped chars route to a trash slot past the real data.
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    flat_idx = jnp.where(keep, rows * length + new_pos, b * length)
    out = jnp.zeros(b * length + 1, dtype=cps.dtype)
    out = out.at[flat_idx.reshape(-1)].set(cps.reshape(-1), mode="drop")
    return out[:-1].reshape(b, length), new_lengths.astype(jnp.int32)
