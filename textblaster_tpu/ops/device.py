"""Shared device primitives: class tables, segmented scans, hashing.

These are the building blocks of every filter kernel (SURVEY.md §7 stage 2):
a byte-class precompute (here: codepoint-class gather over the same table the
host oracle uses, so host and device classify identically), segmented
associative scans for per-word / per-line / per-paragraph aggregates, and
rolling hashes for duplicate detection.

All kernels operate on ``[B, L]`` codepoint tensors with a validity mask;
reductions are along axis 1.  Scans use ``jax.lax.associative_scan``, which
XLA lowers to log-depth work-efficient trees on the VPU.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import chartables as ct
from ..utils.text import _MID_ALL, _MID_LETTER, _MID_NUM, _MID_NUM_LET

__all__ = [
    "class_table",
    "lower_table",
    "classify",
    "utf8_width",
    "isin_sorted",
    "seg_scan_add",
    "seg_scan_or",
    "seg_scan_max",
    "latch_scan",
    "use_sort_tables",
    "rev",
    "ALNUM",
    "ALPHA",
    "DIGIT",
    "WS",
    "PUNCT",
    "LOWER",
    "UPPER",
    "EXTEND",
    "MID_LETTER_CPS",
    "MID_NUM_CPS",
    "MID_ALL_CPS",
    "word_base",
    "word_mask",
    "HASH_MUL",
]

ALNUM = ct.ALNUM
ALPHA = ct.ALPHA
DIGIT = ct.DIGIT
WS = ct.WS
PUNCT = ct.PUNCT
LOWER = ct.LOWER
UPPER = ct.UPPER
EXTEND = ct.EXTEND

HASH_MUL = np.int32(31)  # polynomial rolling-hash multiplier (int32 wraparound)


@lru_cache(maxsize=1)
def _class_table_np() -> np.ndarray:
    return ct.char_table()


@lru_cache(maxsize=1)
def _lower_table_np() -> np.ndarray:
    table = np.arange(ct._MAX_CP, dtype=np.int32)
    for cp in range(ct._MAX_CP):
        low = chr(cp).lower()
        if len(low) == 1 and ord(low) < ct._MAX_CP:
            table[cp] = ord(low)
    return table


def class_table() -> jax.Array:
    """The host classification table (``[0x40000] uint8``).  Materialized per
    trace as an XLA constant (cached host-side; never cache traced arrays)."""
    return jnp.asarray(_class_table_np())


def lower_table() -> jax.Array:
    """Codepoint -> lowercase codepoint (identity where ``str.lower`` is not
    a single char).  ``[0x40000] int32``."""
    return jnp.asarray(_lower_table_np())


def classify(cps: jax.Array) -> jax.Array:
    """Gather char classes; indices clipped like the host ``classify``,
    with the same plane-14 EXTEND range check."""
    cls = class_table()[jnp.minimum(cps, ct._MAX_CP - 1)]
    plane14 = (cps >= ct._PLANE14_LO) & (cps < ct._PLANE14_HI)
    return jnp.where(plane14, jnp.uint8(EXTEND), cls)


def utf8_width(cps: jax.Array) -> jax.Array:
    """UTF-8 encoded byte width of each codepoint (1/2/3/4) — recovers the
    reference's byte-length semantics (text.rs:203,230,252) from codepoints."""
    w = jnp.where(cps < 0x80, 1, jnp.where(cps < 0x800, 2, jnp.where(cps < 0x10000, 3, 4)))
    return w.astype(jnp.int32)


def isin_sorted(cps: jax.Array, sorted_vals) -> jax.Array:
    """Membership of each element in a small sorted codepoint set."""
    sorted_vals = jnp.asarray(sorted_vals)
    idx = jnp.searchsorted(sorted_vals, cps)
    idx = jnp.minimum(idx, sorted_vals.shape[0] - 1)
    return sorted_vals[idx] == cps


# Plain numpy at module scope: a jnp.asarray here would initialize a JAX
# backend at import time (observed hanging the whole process when the remote
# axon chip is claimed by another process).  jnp converts these per trace.
MID_LETTER_CPS = np.sort(
    np.array([ord(c) for c in (_MID_LETTER | _MID_NUM_LET)], dtype=np.int32)
)
MID_NUM_CPS = np.sort(
    np.array([ord(c) for c in (_MID_NUM | _MID_NUM_LET)], dtype=np.int32)
)
MID_ALL_CPS = np.sort(np.array([ord(c) for c in _MID_ALL], dtype=np.int32))


# --- Segmented scans ---------------------------------------------------------
# State (v, r): r = "resets here".  Composition is the standard segmented-scan
# monoid; associative, so any scan schedule computes the same values.
#
# Three schedules are provided:
#
# * ``assoc`` — ``jax.lax.associative_scan`` (work-efficient odd/even
#   recursion).  Its stride-2 slices relayout on TPU's tiled [sublane, lane]
#   layouts, which makes each of the log L levels far more expensive than its
#   FLOPs suggest.
# * ``shift`` — Hillis-Steele doubling: level ``d`` combines position ``i``
#   with ``i - d`` via a pad+slice shift (contiguous, layout-preserving).
#   O(L log L) work instead of O(L), but every step is a cheap contiguous
#   move — the TPU-friendly schedule.
# * ``chunk`` — blocked three-phase scan: reshape ``[B, L]`` to chunks
#   ``[C, B, n]``, one ``lax.scan`` over the C in-chunk positions (carry
#   ``[B, n]`` — every row and chunk advances in lockstep), a tiny
#   cross-chunk prefix over ``n``, and one broadcast combine.  ~O(2L) work
#   and ~4 full-array memory passes versus shift's log L — the candidate
#   replacement wherever scan passes dominate; kept opt-in until measured
#   on silicon (microbench3).
#
# ``TEXTBLAST_SCAN_IMPL`` (assoc|shift|chunk) pins one; default picks by
# backend at trace time (shift on tpu-like backends, assoc elsewhere).


def _seg_add_op(a, b):
    av, ar = a
    bv, br = b
    return jnp.where(br, bv, av + bv), ar | br


def _seg_or_op(a, b):
    av, ar = a
    bv, br = b
    return jnp.where(br, bv, av | bv), ar | br


def _seg_max_op(a, b):
    av, ar = a
    bv, br = b
    return jnp.where(br, bv, jnp.maximum(av, bv)), ar | br


def _latch_op(a, b):
    # "Rightmost set value" monoid: b wins where it is set.
    av, ar = a
    bv, br = b
    return jnp.where(br, bv, av), ar | br


def _scan_impl() -> str:
    import os

    impl = os.environ.get("TEXTBLAST_SCAN_IMPL", "")
    if impl in ("shift", "assoc", "chunk"):
        return impl
    if jax.default_backend() in ("tpu", "axon"):
        # Silicon-measured default is the shift schedule; the round-5 window
        # banked >1x records with it and chunk is unmeasured on TPU.
        return "shift"
    # XLA:CPU: the blocked chunk schedule wins decisively at the (new)
    # cache-resident batch sizes — full config best-of-3 2.68 s vs 3.60 s
    # (assoc) at batch 64, longdoc 0.79 -> 0.93 vs oracle at batch 16.
    return "chunk"


def _use_shift_scan() -> bool:
    return _scan_impl() == "shift"


def shift_scan_tuple(op, identities, xs, axis: int = 1):
    """Inclusive scan of a TUPLE state under associative ``op`` via the
    contiguous-shift (Hillis-Steele) schedule.

    ``op`` maps ``(left_state, right_state)`` tuples to a state tuple, where
    the left operand is the earlier prefix.  ``identities`` gives ``op``'s
    identity per component: a scalar, or an array broadcastable to a
    ``[B, d, ...]`` pad block.  The one scan-schedule implementation shared
    by the segmented scans, :func:`assoc_scan1`, and the fused polynomial
    hashes (stats._poly_hash_many).
    """
    if axis != 1:
        xs = tuple(jnp.moveaxis(x, axis, 1) for x in xs)
    length = xs[0].shape[1]

    def pad_block(x, ident, d):
        blk = x[:, :d]
        if isinstance(ident, (int, bool, np.integer, np.bool_)):
            pad = jnp.full_like(blk, ident)
        else:
            pad = jnp.broadcast_to(ident, blk.shape).astype(x.dtype)
        return jnp.concatenate([pad, x[:, :-d]], axis=1)

    d = 1
    while d < length:
        shifted = tuple(
            pad_block(x, ident, d) for x, ident in zip(xs, identities)
        )
        xs = op(shifted, xs)
        d *= 2
    if axis != 1:
        xs = tuple(jnp.moveaxis(x, 1, axis) for x in xs)
    return xs


def _ident_block(ident, like: jax.Array, shape) -> jax.Array:
    if isinstance(ident, (int, bool, np.integer, np.bool_)):
        return jnp.full(shape, ident, dtype=like.dtype)
    return jnp.broadcast_to(ident, shape).astype(like.dtype)


def chunk_scan_tuple(op, identities, xs, axis: int = 1, chunk_size: int = 0):
    """Inclusive tuple-state scan via the blocked three-phase schedule (see
    scan notes above): one ``lax.scan`` over in-chunk positions with a
    ``[B, n_chunks]`` carry, a small cross-chunk prefix, one combine."""
    import os

    if chunk_size <= 0:
        # Backend-conditional default.  XLA:CPU (measured at cache-resident
        # batch sizes): chunk 64 beats 128 on both the short-doc regime
        # (2.59 s vs 2.70 s full-pipeline pass) and scan-bound longdoc
        # (1.25x vs 1.11x the oracle); 32 ties 64, 256 is clearly worse.
        # Accelerators keep 128 — the schedule only runs there under the
        # opt-in TEXTBLAST_SCAN_IMPL=chunk A/B, and 64 is unmeasured on
        # silicon (halved per-step work vs doubled trip count lands
        # differently off-cache).
        env = os.environ.get("TEXTBLAST_SCAN_CHUNK")
        if env:
            chunk_size = int(env)
        else:
            chunk_size = 64 if jax.default_backend() == "cpu" else 128
    if axis != 1:
        xs = tuple(jnp.moveaxis(x, axis, 1) for x in xs)
    b, length = xs[0].shape[0], xs[0].shape[1]
    if length <= 2 * chunk_size:
        out = shift_scan_tuple(op, identities, xs, axis=1)
        return out if axis == 1 else tuple(jnp.moveaxis(x, 1, axis) for x in out)
    n = -(-length // chunk_size)
    pad = n * chunk_size - length

    xs3 = []
    for x, ident in zip(xs, identities):
        if pad:
            blk = _ident_block(ident, x, (b, pad) + x.shape[2:])
            x = jnp.concatenate([x, blk], axis=1)
        x = x.reshape((b, n, chunk_size) + x.shape[2:])
        xs3.append(jnp.moveaxis(x, 2, 0))  # [C, b, n, *rest]
    xs3 = tuple(xs3)

    init = tuple(
        _ident_block(ident, x, (x.shape[1], x.shape[2]) + x.shape[3:])
        for x, ident in zip(xs3, identities)
    )

    def step(carry, xc):
        new = op(carry, xc)
        return new, new

    _, ys = jax.lax.scan(step, init, xs3)  # each [C, b, n, *rest]

    # Cross-chunk exclusive prefix of the chunk summaries (tiny: [b, n]).
    sums = tuple(y[-1] for y in ys)
    inc = shift_scan_tuple(op, identities, sums, axis=1)
    exc = tuple(
        jnp.concatenate(
            [_ident_block(ident, i, (b, 1) + i.shape[2:]), i[:, :-1]], axis=1
        )
        for i, ident in zip(inc, identities)
    )
    exc_b = tuple(jnp.broadcast_to(e, y.shape) for e, y in zip(exc, ys))
    final = op(exc_b, ys)

    outs = []
    for f in final:
        f = jnp.moveaxis(f, 0, 2).reshape((b, n * chunk_size) + f.shape[3:])
        outs.append(f[:, :length])
    outs = tuple(outs)
    return outs if axis == 1 else tuple(jnp.moveaxis(x, 1, axis) for x in outs)


def _seg_scan(op, identity, values: jax.Array, reset: jax.Array, axis: int):
    # Dispatch accounting for bench's fused-vs-staged A/B (no-op unless a
    # count_scan_dispatches scope is active).  Imported lazily: device is
    # imported by pallas_scan's consumers, never the other way around.
    from .pallas_scan import record_scan_dispatch

    record_scan_dispatch("lax_scan")
    impl = _scan_impl()
    if impl == "shift":
        # Virtual elements left of position 0 are (op identity, reset=True):
        # the identity keeps in-range prefixes exact, the True seals the
        # boundary for later levels.
        v, _ = shift_scan_tuple(op, (identity, True), (values, reset), axis)
        return v
    if impl == "chunk":
        # The chunk schedule needs the TRUE left identity (reset=False):
        # its identities seed every chunk's carry and the cross-chunk
        # prefix, where a sealing True would cut segments at chunk
        # boundaries (shift's virtual elements sit only left of position 0,
        # where sealing is harmless).
        v, _ = chunk_scan_tuple(op, (identity, False), (values, reset), axis)
        return v
    out, _ = jax.lax.associative_scan(op, (values, reset), axis=axis)
    return out


def assoc_scan1(op, identity, x: jax.Array, axis: int = 1) -> jax.Array:
    """Inclusive scan of a single array under an arbitrary associative ``op``,
    using the backend-appropriate schedule (see scan notes above).

    ``identity`` is ``op``'s identity: a scalar, or an array broadcastable to
    a ``[B, d, ...]`` pad block (e.g. an iota for function-composition scans).
    """
    from .pallas_scan import record_scan_dispatch

    record_scan_dispatch("lax_scan")
    impl = _scan_impl()
    if impl == "assoc":
        return jax.lax.associative_scan(op, x, axis=axis)

    def tuple_op(a, b):
        return (op(a[0], b[0]),)

    if impl == "chunk":
        return chunk_scan_tuple(tuple_op, (identity,), (x,), axis)[0]
    return shift_scan_tuple(tuple_op, (identity,), (x,), axis)[0]


def seg_scan_add(values: jax.Array, reset: jax.Array, axis: int = 1) -> jax.Array:
    """Inclusive segmented sum along ``axis``; ``reset[i]`` starts a segment."""
    return _seg_scan(_seg_add_op, 0, values, reset, axis)


def seg_scan_or(values: jax.Array, reset: jax.Array, axis: int = 1) -> jax.Array:
    return _seg_scan(_seg_or_op, 0, values, reset, axis)


def seg_scan_max(values: jax.Array, reset: jax.Array, axis: int = 1) -> jax.Array:
    return _seg_scan(_seg_max_op, np.iinfo(np.int32).min, values, reset, axis)


def latch_scan(values: jax.Array, set_mask: jax.Array, axis: int = 1) -> jax.Array:
    """Inclusive "hold" scan: at each position, the value of the most recent
    position where ``set_mask`` is True (0 before any set position).  A reset
    is expressed by a set position carrying the fill value."""
    return _seg_scan(_latch_op, 0, values, set_mask, axis)


def use_sort_tables() -> bool:
    """Whether per-segment tables are built scatter-free (one position sort +
    gathers) instead of by XLA scatter.  XLA:TPU serializes scatters into
    per-element loops — the round-3 on-chip profile's prime suspect — while
    XLA:CPU handles the unique-index scatters well (the tuned CPU-backend
    record keeps its byte-identical traces and warm compile cache).
    ``TEXTBLAST_TABLE_IMPL`` (sort|scatter) pins one; default picks by
    backend at trace time, mirroring ``_use_shift_scan``."""
    import os

    impl = os.environ.get("TEXTBLAST_TABLE_IMPL", "")
    if impl == "sort":
        return True
    if impl == "scatter":
        return False
    return jax.default_backend() in ("tpu", "axon")


def rev(x: jax.Array, axis: int = 1) -> jax.Array:
    return jnp.flip(x, axis=axis)


def word_base(cps: jax.Array, cls: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Raw pre-WB4 wordness plus the Extend mask — the elementwise half of
    :func:`word_mask`, exposed so the dependency-fused chain kernel can run
    the WB4 hold scan in-kernel (stats.structure's depfuse path).

    A char is word-raw if alphanumeric/underscore, or a UAX#29-lite mid
    character flanked by the right neighbor classes.
    """
    word = ((cls & ALNUM) != 0) | (cps == ord("_"))
    prev_cls = jnp.pad(cls[:, :-1], ((0, 0), (1, 0)))
    next_cls = jnp.pad(cls[:, 1:], ((0, 0), (0, 1)))
    letter_ok = (
        isin_sorted(cps, MID_LETTER_CPS)
        & ((prev_cls & ALPHA) != 0)
        & ((next_cls & ALPHA) != 0)
    )
    num_ok = (
        isin_sorted(cps, MID_NUM_CPS)
        & ((prev_cls & DIGIT) != 0)
        & ((next_cls & DIGIT) != 0)
    )
    word = word | letter_ok | num_ok
    ext = (cls & EXTEND) != 0
    return word, ext


def word_mask(cps: jax.Array, cls: jax.Array) -> jax.Array:
    """In-word mask — the device twin of ``utils.text._word_mask``.

    UAX#29 WB4 (lite): Extend/Format chars inherit the wordness of the
    nearest preceding non-Extend char (utils.text._attach_extend twin).
    ``word`` is always False at Extend positions, so a segmented or-scan
    that RESETS at non-Extend positions holds each word flag through the
    following Extend run (leading Extend runs hold 0).
    """
    word, ext = word_base(cps, cls)
    held = seg_scan_or(word.astype(jnp.int32), ~ext)
    return jnp.where(ext, held > 0, word)
