"""Packed document batches for device execution.

The device-side document store (SURVEY.md §7 stage 1): a batch of documents
becomes one dense ``[B, L] int32`` codepoint tensor plus per-document lengths.
Codepoints (UTF-32) rather than UTF-8 bytes are the device representation:
every filter decision is defined over *characters* (char classes, char
counts), so decoding once on the host (a single C-speed ``str.encode``) keeps
the kernels branch-free; the reference's byte-length quirks are recovered on
device from the codepoint values (1/2/3/4-byte UTF-8 width is a pure function
of the codepoint).

Batches are length-bucketed into a small set of static shapes so XLA compiles
one program per bucket (SURVEY.md §5 "ragged data on fixed shapes").
Documents longer than the largest bucket are flagged for the host fallback
path rather than truncated.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..data_model import TextDocument

__all__ = [
    "PackedBatch",
    "DEFAULT_BUCKETS",
    "PACK_MARGIN",
    "pack_documents",
    "pack_documents_loop",
    "iter_packed_batches",
]

# Bucket char capacities.  Most CC documents are < 8k chars; the tail gets the
# big bucket and true outliers (>64k chars) fall back to the host oracle.
DEFAULT_BUCKETS: Tuple[int, ...] = (512, 2048, 8192, 32768, 65536)

#: Kernels need a little headroom past the content (e.g. the language-ID
#: stream wraps the text in boundary markers), so a bucket admits documents
#: only up to this many chars below its capacity.
PACK_MARGIN = 4


@dataclass
class PackedBatch:
    """One fixed-shape device batch.

    ``cps``    — ``[B, L] int32`` codepoints, zero-padded past ``lengths``.
    ``lengths`` — ``[B] int32`` document char counts.
    ``valid``  — ``[B] bool``; False rows are padding documents.
    ``docs``   — the host-side documents, index-aligned with rows.
    """

    cps: np.ndarray
    lengths: np.ndarray
    valid: np.ndarray
    docs: List[TextDocument]

    @property
    def batch_size(self) -> int:
        return self.cps.shape[0]

    @property
    def max_len(self) -> int:
        return self.cps.shape[1]


def _encode(text: str) -> np.ndarray:
    if not text:
        return np.empty(0, dtype=np.int32)
    return np.frombuffer(text.encode("utf-32-le"), dtype=np.uint32).astype(np.int32)


def pack_documents_loop(
    docs: Sequence[TextDocument],
    batch_size: int,
    max_len: int,
) -> PackedBatch:
    """Per-document reference packer (one ``str.encode`` per row).

    Kept as the oracle for the vectorized ``pack_documents``: the property
    test asserts both produce byte-identical ``cps/lengths/valid``.
    """
    n = len(docs)
    assert n <= batch_size
    cps = np.zeros((batch_size, max_len), dtype=np.int32)
    lengths = np.zeros(batch_size, dtype=np.int32)
    valid = np.zeros(batch_size, dtype=bool)
    for i, doc in enumerate(docs):
        arr = _encode(doc.content)
        assert arr.shape[0] <= max_len, "over-length document reached the packer"
        cps[i, : arr.shape[0]] = arr
        lengths[i] = arr.shape[0]
        valid[i] = True
    return PackedBatch(cps=cps, lengths=lengths, valid=valid, docs=list(docs))


def pack_documents(
    docs: Sequence[TextDocument],
    batch_size: int,
    max_len: int,
) -> PackedBatch:
    """Pack documents into one ``[batch_size, max_len]`` tensor.

    Rows beyond ``len(docs)`` are zero padding with ``valid=False``.  Callers
    are responsible for routing over-length documents elsewhere.

    Vectorized: one concatenated ``encode("utf-32-le")`` for the whole batch
    (C speed, releases the GIL) plus a boolean-mask scatter, instead of a
    Python-level encode/copy per document.  ``len(str)`` equals the UTF-32
    codepoint count and utf-32-le carries no BOM, so the flat buffer's
    row-major scatter order is exactly the concatenation order.
    """
    n = len(docs)
    assert n <= batch_size
    cps = np.zeros((batch_size, max_len), dtype=np.int32)
    lengths = np.zeros(batch_size, dtype=np.int32)
    valid = np.zeros(batch_size, dtype=bool)
    if n:
        texts = [doc.content for doc in docs]
        counts = np.fromiter((len(t) for t in texts), dtype=np.int64, count=n)
        assert counts.max(initial=0) <= max_len, (
            "over-length document reached the packer"
        )
        flat = np.frombuffer(
            "".join(texts).encode("utf-32-le"), dtype="<u4"
        ).astype(np.int32)
        mask = np.arange(max_len, dtype=np.int64)[None, :] < counts[:, None]
        cps[:n][mask] = flat
        lengths[:n] = counts
        valid[:n] = True
    return PackedBatch(cps=cps, lengths=lengths, valid=valid, docs=list(docs))


def iter_packed_batches(
    docs: Iterator[TextDocument],
    batch_size: int = 256,
    buckets: Sequence[int] = DEFAULT_BUCKETS,
    host_tail_max: int = 0,
    route_fn=None,
    pack_fn=pack_documents,
    geometry=None,
    overflow_flush: int = 64,
) -> Iterator[Tuple[Optional[PackedBatch], List[TextDocument]]]:
    """Group a document stream into per-bucket batches.

    Yields ``(packed_batch, host_fallback_docs)`` pairs.  Documents longer
    than the largest bucket are returned in the fallback list (processed by
    the host oracle); everything else lands in the smallest bucket that fits.
    ``route_fn(doc) -> bool`` marks additional host-oracle documents (e.g.
    dictionary-script or astral rows, ops/pipeline.py): they join the same
    interleaved fallback stream, so their host processing overlaps in-flight
    device batches instead of serializing ahead of the first dispatch; the
    fallback list is flushed every ``overflow_flush`` documents.

    ``geometry`` (an ``ops.geometry.DeviceGeometry``) supersedes
    ``buckets``/``batch_size`` and assigns each bucket its own row count, so
    wide buckets dispatch fewer rows and narrow buckets more — equalizing
    padded-lane volume per dispatch.  Without it, behavior is the uniform
    seed geometry: one ``batch_size`` for every bucket.

    End-of-stream handling: a device program computes every padded row, so
    per-bucket partial flushes waste most of their cost.  Leftovers from all
    buckets are merged (sorted by length) and regrouped greedily: a group
    flushes once it reaches the batch size of the bucket its longest (most
    recent) document needs — with a uniform geometry this degenerates to
    exactly the historical ``batch_size``-sized slices.  Each group is
    packed at the smallest bucket that fits its longest document — one
    near-full batch instead of several near-empty ones.  Groups of at most
    ``host_tail_max`` documents are handed back as fallback docs: below
    that size the (bit-exact) host oracle is cheaper than any padded device
    batch.  ``host_tail_max`` may be a per-bucket mapping — with unequal
    row budgets the "below ~a fraction of a batch" cutoff must follow the
    group's own bucket, not one global row count.
    """
    if geometry is not None:
        buckets = tuple(geometry.buckets)
        rows_for = {b: geometry.batch_for(b) for b in buckets}
    else:
        buckets = tuple(sorted(buckets))
        rows_for = {b: batch_size for b in buckets}
    if isinstance(host_tail_max, dict):
        tail_for = {b: int(host_tail_max.get(b, 0)) for b in buckets}
    else:
        tail_for = {b: int(host_tail_max) for b in buckets}
    margin = PACK_MARGIN
    largest = buckets[-1] - margin
    pending: dict[int, List[TextDocument]] = {b: [] for b in buckets}
    overflow: List[TextDocument] = []

    for doc in docs:
        n_chars = len(doc.content)
        if n_chars > largest or (route_fn is not None and route_fn(doc)):
            overflow.append(doc)
            if len(overflow) >= overflow_flush:
                yield None, overflow
                overflow = []
            continue
        for b in buckets:
            if n_chars <= b - margin:
                pending[b].append(doc)
                if len(pending[b]) >= rows_for[b]:
                    batch_docs, pending[b] = pending[b], []
                    yield pack_fn(
                        batch_docs, batch_size=rows_for[b], max_len=b
                    ), []
                break

    leftovers = [d for b in buckets for d in pending[b]]
    leftovers.sort(key=lambda d: len(d.content))
    group: List[TextDocument] = []
    group_bucket = buckets[0]
    for doc in leftovers:
        need = next(b for b in buckets if len(doc.content) <= b - margin)
        # Ascending lengths mean `need` only grows and (with equalized
        # geometry) its row budget only shrinks; flush when the group
        # already fills the incoming document's budget.
        if group and len(group) >= rows_for[need]:
            if len(group) <= tail_for[group_bucket]:
                yield None, group
            else:
                yield pack_fn(
                    group, batch_size=rows_for[group_bucket], max_len=group_bucket
                ), []
            group = []
        group.append(doc)
        group_bucket = need
        if len(group) >= rows_for[need]:
            if len(group) <= tail_for[need]:
                yield None, group
            else:
                yield pack_fn(group, batch_size=rows_for[need], max_len=need), []
            group = []
    if group:
        if len(group) <= tail_for[group_bucket]:
            yield None, group
        else:
            yield pack_fn(
                group, batch_size=rows_for[group_bucket], max_len=group_bucket
            ), []
    if overflow:
        yield None, overflow
