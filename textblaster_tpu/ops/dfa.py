"""Small DFAs over codepoint rows via associative function composition.

The reference's regexes on the hot path (the citation pattern
``\\[\\d+(?:,\\s*\\d+)*\\]``, c4_filters.rs:33; the sentence-boundary rules)
become tiny DFAs here.  A DFA step is a gather through a per-char transition
row; runs of steps compose associatively (``t_ab = t_b[t_a]``), so the whole
row is evaluated with ``lax.associative_scan`` in log depth — no sequential
scan, XLA-friendly (SURVEY.md §7 "regexes on device").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .device import assoc_scan1, latch_scan, use_sort_tables
from .pallas_scan import dfa_compose_scan, pallas_scan_ok

__all__ = ["dfa_packed_fns", "dfa_states", "citation_spans"]


def dfa_packed_fns(char_classes: jax.Array, transition: np.ndarray) -> jax.Array:
    """Nibble-packed per-char transition maps for a <= 8-state DFA.

    This is exactly the operand stream :func:`dfa_states` composes (state
    ``s``'s successor in bits ``4s..4s+3``), exposed so multi-pass chain
    programs (pallas_scan.chain_scan) can run the DFA composition as one
    group of a larger kernel and derive downstream operands from the packed
    state in-register.  ``(packed >> (4 * start_state)) & 15`` recovers the
    inclusive state stream.
    """
    n_states = transition.shape[1]
    if n_states > 8:
        raise ValueError("packed DFA maps require <= 8 states")
    packed_rows = np.zeros(transition.shape[0], dtype=np.int64)
    for s in range(n_states):
        packed_rows |= transition[:, s].astype(np.int64) << (4 * s)
    table = jnp.asarray(packed_rows.astype(np.int32))
    return table[char_classes]


def dfa_states(
    char_classes: jax.Array, transition: np.ndarray, start_state: int = 0
) -> jax.Array:
    """Inclusive per-position DFA state along axis 1.

    Args:
      char_classes: ``[B, L] int32`` — per-char input symbol in ``[0, S)``.
      transition:   ``[S, N] -> N`` numpy table: next state per (symbol, state).
      start_state:  initial state before position 0.

    Returns:
      ``[B, L] int32`` — state *after* consuming each char.

    For <= 8 states the per-char state maps are nibble-packed into one int32
    (state ``s``'s successor in bits ``4s..4s+3``) and composed with
    elementwise shifts — no gathers, which cost far more than ALU on both
    XLA:CPU and TPU.  Larger automata fall back to the gather composition.
    """
    n_states = transition.shape[1]
    if n_states <= 8:
        fns = dfa_packed_fns(char_classes, transition)  # [B, L] packed maps

        def compose(a, b):
            # (b . a)(s) = b[a[s]]: route each of a's nibbles through b.
            out = jnp.zeros_like(a)
            for s in range(n_states):
                nib = (a >> (4 * s)) & 15
                out = out | (((b >> (nib << 2)) & 15) << (4 * s))
            return out

        # Identity function map: nibble s holds s.
        ident = 0
        for s in range(n_states):
            ident |= s << (4 * s)
        if pallas_scan_ok(*fns.shape):
            # Blocked VMEM kernel — same int32 composition, bit-identical
            # (pallas_scan module docstring; parity fuzzed in tests).  Under
            # mesh_tracing(mesh) the kernel dispatch shard_maps itself over
            # the data axis, so mesh programs keep this path too.
            packed = dfa_compose_scan(fns, n_states)
        else:
            packed = assoc_scan1(compose, np.int32(ident), fns, axis=1)
        return (packed >> (4 * start_state)) & 15

    table = jnp.asarray(transition, dtype=jnp.int32)  # [S, N]
    # Per-char transition row: f_i : state -> state, shape [B, L, N].
    fns = table[char_classes]

    def compose(a, b):
        # Apply a then b: (b . a)(s) = b[a[s]].
        return jnp.take_along_axis(b, a, axis=-1)

    composed = assoc_scan1(
        compose, jnp.arange(transition.shape[1], dtype=jnp.int32), fns, axis=1
    )
    return composed[..., start_state]


# Citation DFA symbols: 0=other, 1='[', 2=digit, 3=',', 4=space, 5=']'.
# States: 0=dead/outside, 1=after '[', 2=in digits, 3=after comma (spaces ok),
# 4=accept (just consumed ']' after digits).
_CIT_N = 5
_CIT_T = np.zeros((6, _CIT_N), dtype=np.int32)
# other: kill any progress
_CIT_T[0, :] = 0
# '[': always (re)start a candidate
_CIT_T[1, :] = 1
# digit: valid after '[', digit, comma-space; else dead
_CIT_T[2, :] = [0, 2, 2, 2, 0]
# ',': valid within digits
_CIT_T[3, :] = [0, 0, 3, 0, 0]
# space: valid after comma (\s* between comma and digits)
_CIT_T[4, :] = [0, 0, 0, 3, 0]
# ']': accept after >=1 digit
_CIT_T[5, :] = [0, 0, 4, 0, 0]


def citation_spans(cps: jax.Array, digit_mask: jax.Array, ws_mask: jax.Array) -> jax.Array:
    """Deletion mask for Wikipedia-style citations ``[1]``, ``[2, 3]``.

    Matches the reference regex ``\\[\\d+(?:,\\s*\\d+)*\\]`` over each row and
    returns a ``[B, L] bool`` mask marking every char inside a match
    (brackets included).

    ``\\s`` here is the regex-semantics whitespace of the reference engine
    (Unicode White_Space), supplied by ``ws_mask``.
    """
    sym = jnp.zeros_like(cps)
    sym = jnp.where(digit_mask, 2, sym)
    sym = jnp.where(cps == ord("["), 1, sym)
    sym = jnp.where(cps == ord(","), 3, sym)
    sym = jnp.where(ws_mask & (sym == 0), 4, sym)
    sym = jnp.where(cps == ord("]"), 5, sym)

    states = dfa_states(sym, _CIT_T)
    accept = states == 4  # position of each closing ']'

    # Span start = the most recent '[' (inside a match no other '[' occurs,
    # because '[' resets the candidate — so the nearest preceding '[' is the
    # match opener).  Mark spans with a +1/-1 difference array and a cumsum.
    positions = jnp.arange(cps.shape[1], dtype=jnp.int32)[None, :]
    lb_pos = jnp.where(cps == ord("["), positions, -1)
    last_lb = assoc_scan1(jnp.maximum, np.int32(-1), lb_pos, axis=1)

    b, length = cps.shape

    if use_sort_tables():
        # Scatter-free span fill (the TPU path): spans never overlap ('['
        # resets the candidate), so position p is inside a span iff the
        # NEAREST accept at/after p opened at or before p.  A reversed latch
        # scan carries each accept's span start (biased +1 so 0 = "no accept
        # follows") back over the positions it covers.
        start1 = jnp.where(accept, last_lb + 1, 0)
        na = jnp.flip(latch_scan(jnp.flip(start1, 1), jnp.flip(accept, 1)), 1)
        return (na > 0) & (positions >= na - 1)

    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    starts = jnp.where(accept, last_lb, -1)

    flat_start = jnp.where(accept, rows * (length + 1) + starts, b * (length + 1))
    flat_end = jnp.where(accept, rows * (length + 1) + positions + 1, b * (length + 1))
    flat = jnp.zeros(b * (length + 1) + 1, dtype=jnp.int32)
    flat = flat.at[flat_start.reshape(-1)].add(
        jnp.where(accept, 1, 0).reshape(-1), mode="drop"
    )
    flat = flat.at[flat_end.reshape(-1)].add(
        jnp.where(accept, -1, 0).reshape(-1), mode="drop"
    )
    diff = flat[:-1].reshape(b, length + 1)
    return jnp.cumsum(diff[:, :length], axis=1) > 0
