"""Language-ID device kernel.

The device twin of :class:`textblaster_tpu.models.langid.LangIdModel`: build
the normalized letters-and-boundaries stream with a compaction, hash trigrams,
gather the quantized log-prob table, and sum int32 scores per document.
Integer accumulation makes the scores *bit-identical* to the host model —
confidence/decision logic runs host-side from the same numbers.

This is the one dense "model" in the system (SURVEY.md §7 item 5): scoring is
a ``[65536, 5]`` embedding-style gather + segmented reduction, which XLA maps
onto the TPU's vector unit with the table resident in HBM.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..models.langid import TABLE_SIZE, get_model
from .compact import compact
from .device import ALPHA, classify, lower_table
from .stats import _poly_hash, _shift_l, _shift_r

__all__ = ["langid_scores"]


def _table_q() -> jax.Array:
    return jnp.asarray(get_model().table_q)  # [TABLE_SIZE, 5] int32


def langid_scores(
    cps: jax.Array, lengths: jax.Array, mesh=None
) -> Tuple[jax.Array, jax.Array]:
    """Per-document quantized language scores.

    Returns ``(scores_q [B, 5] int32, n_grams [B] int32)``; rows with
    ``n_grams == 0`` are undetectable (letterless).
    """
    _, length = cps.shape
    mask = jnp.arange(length, dtype=jnp.int32)[None, :] < lengths[:, None]

    lt = lower_table()
    low = jnp.where(mask, lt[jnp.minimum(cps, lt.shape[0] - 1)], 0)
    letter = ((classify(low) & ALPHA) != 0) & mask

    # Collapse non-letter runs to single boundary markers (value 0), keeping
    # the first char of each run; wrap the stream in boundaries like the host
    # _normalize_codepoints.
    nonletter = mask & ~letter
    first_of_run = nonletter & ~_shift_r(nonletter, False)
    keep = letter | first_of_run
    vals = jnp.where(letter, low, 0)
    norm, nlen = compact(vals, keep, mesh=mesh)

    # Leading boundary: prepend 0 unless the stream already starts with one.
    starts_with_letter = norm[:, 0] != 0
    shifted = jnp.concatenate([jnp.zeros_like(norm[:, :1]), norm[:, :-1]], axis=1)
    norm = jnp.where(starts_with_letter[:, None], shifted, norm)
    nlen = nlen + jnp.where(starts_with_letter & (nlen > 0), 1, 0)

    # Trailing boundary: the padded buffer is already 0, so just extend the
    # length when the last element is a letter.
    last = jnp.take_along_axis(
        norm, jnp.maximum(nlen[:, None] - 1, 0), axis=1
    )[:, 0]
    nlen = jnp.minimum(
        nlen + jnp.where((last != 0) & (nlen > 0), 1, 0), jnp.int32(length)
    )

    c1 = norm
    c2 = jnp.concatenate([norm[:, 1:], jnp.zeros_like(norm[:, :1])], axis=1)
    c3 = jnp.concatenate([norm[:, 2:], jnp.zeros_like(norm[:, :2])], axis=1)
    h = (c1 * 961 + c2 * 31 + c3) & (TABLE_SIZE - 1)

    tri_valid = (
        jnp.arange(length, dtype=jnp.int32)[None, :] < jnp.maximum(nlen - 2, 0)[:, None]
    )
    table = _table_q()
    rows = table[h]  # [B, L, 5]
    scores = jnp.sum(
        jnp.where(tri_valid[..., None], rows, 0), axis=1, dtype=jnp.int32
    )

    # Whole-word hash features (models.langid._word_hash_vec twin): the
    # rolling hash h = h*31 + c of each boundary-delimited word, via the
    # shared segmented affine scan; int32 wraparound == the host's mod 2^32.
    in_word = norm != 0  # zero-padded past nlen, so no extra mask needed
    word_start = in_word & ~_shift_r(in_word, False)
    word_end = in_word & ~_shift_l(in_word, False)
    wh = _poly_hash(norm, in_word, word_start) & (TABLE_SIZE - 1)
    wrows = table[wh]  # [B, L, 5]
    scores = scores + jnp.sum(
        jnp.where(word_end[..., None], wrows, 0), axis=1, dtype=jnp.int32
    )
    n_words = jnp.sum(word_end, axis=1).astype(jnp.int32)

    n_grams = (jnp.maximum(nlen - 2, 0) + n_words).astype(jnp.int32)
    return scores, n_grams
