"""C4 bad-words matching on device — the decision, not just a prefilter.

The reference scans every document with one big case-insensitive alternation
regex per language (c4_filters.rs:431-447).  A sequential automaton is the
wrong shape for a TPU (state-to-state dependencies serialize the scan), so
the device twin is a **parallel window test**: one pair of prefix polynomial
hashes over the lowercased row (independent multipliers 31 and 1000003), then
for each distinct pattern length an O(1) double window-hash
(prefix-difference) checked against the per-length (h1, h2)-keyed pattern
table, plus word-boundary masks for non-CJK languages (c4_filters.rs:433-439:
CJK patterns get no ``\\W`` anchors).  Every window of every length is tested
simultaneously on the VPU.

Exactness: a true regex match always hits (hashes are computed from the same
codepoints the pattern hashes used; boundary classes mirror ``\\w`` via the
shared char table).  A spurious hit requires a simultaneous collision in two
independent 32-bit hashes — ~2^-64 per (window, pattern) pair, the same
negligible-collision class the duplicate tables already document
(:mod:`.stats`).  The host therefore trusts the device verdict: non-matching
documents never touch the host regex, and matching documents only draw the
seeded keep-fraction (VERDICT r3 item 6).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device import ALNUM, classify, lower_table
from .stats import _first_col, _poly_hash, _shift_r

__all__ = [
    "BadwordTables",
    "badwords_matches",
    "badwords_matches_multi",
    "MAX_PATTERN_CPS",
]

#: Patterns longer than this (in codepoints) disqualify device execution —
#: real LDNOOBW entries are far shorter.
MAX_PATTERN_CPS = 48

#: Second, independent window-hash multiplier (odd, so invertible mod 2^32).
MUL2 = 1000003


def _hash_cps(cps: Sequence[int], mul: int) -> int:
    """Host twin of the device window hash (int32 wraparound)."""
    h = 0
    for c in cps:
        h = (h * mul + c) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def _pow_i32(mul: int, n: int) -> int:
    p = pow(mul, n, 1 << 32)
    return p - (1 << 32) if p >= (1 << 31) else p


class BadwordTables(NamedTuple):
    """Per-length (h1, h2)-keyed pattern tables for one language's list."""

    lengths: Tuple[int, ...]
    tables1: Tuple[np.ndarray, ...]  # int32 h1, sorted, one per length
    tables2: Tuple[np.ndarray, ...]  # int32 h2, aligned with tables1
    max_dup: int  # most patterns sharing one h1 within a length
    check_boundaries: bool  # False for CJK languages (ja/th/zh)

    @classmethod
    def build(
        cls, words: Sequence[str], check_boundaries: bool
    ) -> Optional["BadwordTables"]:
        """None if any pattern is empty/too long (caller falls back to host)."""
        by_len: Dict[int, List[Tuple[int, int]]] = {}
        for w in words:
            cps = [ord(c) for c in w.lower()]
            if not cps or len(cps) > MAX_PATTERN_CPS:
                return None
            by_len.setdefault(len(cps), []).append(
                (_hash_cps(cps, 31), _hash_cps(cps, MUL2))
            )
        if not by_len:
            return None
        lengths = tuple(sorted(by_len))
        t1s, t2s = [], []
        max_dup = 1
        for n in lengths:
            pairs = sorted(set(by_len[n]))
            h1 = np.array([p[0] for p in pairs], dtype=np.int32)
            h2 = np.array([p[1] for p in pairs], dtype=np.int32)
            _, counts = np.unique(h1, return_counts=True)
            max_dup = max(max_dup, int(counts.max()))
            t1s.append(h1)
            t2s.append(h2)
        return cls(
            lengths=lengths,
            tables1=tuple(t1s),
            tables2=tuple(t2s),
            max_dup=max_dup,
            check_boundaries=check_boundaries,
        )


def _isin2(w1, w2, t1, t2, max_dup: int):
    """Membership of (w1, w2) pairs in the aligned (t1-sorted) pair table."""
    m = t1.shape[0]
    idx = jnp.searchsorted(t1, w1)
    hit = jnp.zeros(w1.shape, dtype=bool)
    for k in range(max_dup):
        j = jnp.minimum(idx + k, m - 1)
        hit = hit | ((t1[j] == w1) & (t2[j] == w2))
    return hit


def _window_context(cps: jax.Array, lengths: jax.Array) -> dict:
    """Per-row scans shared by every language's table test: lowercased chars,
    both prefix hashes, and the ``\\w`` boundary masks."""
    _, length = cps.shape
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]
    mask = pos < lengths[:, None]

    lt = lower_table()
    low = jnp.where(mask, lt[jnp.minimum(cps, lt.shape[0] - 1)], 0)

    first = _first_col(mask)
    h1 = _poly_hash(low, mask, first)
    h2 = _poly_hash(low, mask, first, mul=MUL2)

    wordch = ((classify(low) & ALNUM) != 0) | (low == ord("_"))
    return {
        "pos": pos,
        "lengths": lengths,
        "h1": h1,
        "h2": h2,
        "h1_prev": _shift_r(h1, 0),  # hash(low[0..i)) at position i
        "h2_prev": _shift_r(h2, 0),
        "nonword_before": ~_shift_r(wordch, False),  # row start => boundary
        "after_pad": jnp.pad(wordch[:, 1:], ((0, 0), (0, 1))),
        "n_rows": cps.shape[0],
        "length": length,
    }


def _match_with_context(ctx: dict, tables: BadwordTables) -> jax.Array:
    pos, lengths, length = ctx["pos"], ctx["lengths"], ctx["length"]
    match = jnp.zeros(ctx["n_rows"], dtype=bool)
    for n, t1, t2 in zip(tables.lengths, tables.tables1, tables.tables2):
        if n > length:
            continue
        # Window [i, i+n): hash = h[i+n-1] - h[i-1] * mul^n  (int32 wrap).
        w1 = jnp.pad(
            ctx["h1"][:, n - 1 :], ((0, 0), (0, n - 1))
        ) - ctx["h1_prev"] * jnp.int32(_pow_i32(31, n))
        w2 = jnp.pad(
            ctx["h2"][:, n - 1 :], ((0, 0), (0, n - 1))
        ) - ctx["h2_prev"] * jnp.int32(_pow_i32(MUL2, n))
        ok = (pos + n) <= lengths[:, None]
        hit = _isin2(w1, w2, jnp.asarray(t1), jnp.asarray(t2), tables.max_dup) & ok
        if tables.check_boundaries:
            # Char after the window: position i+n (row end => boundary).
            after_word = jnp.pad(
                ctx["after_pad"][:, n - 1 :], ((0, 0), (0, n - 1))
            ) & ((pos + n) < lengths[:, None])
            hit = hit & ctx["nonword_before"] & ~after_word
        match = match | jnp.any(hit, axis=1)
    return match


def badwords_matches(
    cps: jax.Array, lengths: jax.Array, tables: BadwordTables
) -> jax.Array:
    """``[B] bool`` — the regex-match verdict per document (see module
    docstring for the 2^-64 collision caveat)."""
    return _match_with_context(_window_context(cps, lengths), tables)


def badwords_matches_multi(
    cps: jax.Array, lengths: jax.Array, tables_by_lang: dict
) -> dict:
    """Match verdicts for several languages' tables, sharing the hash scans
    (the scans dominate; per-language window tests are cheap)."""
    ctx = _window_context(cps, lengths)
    return {
        lang: _match_with_context(ctx, tables)
        for lang, tables in sorted(tables_by_lang.items())
    }
