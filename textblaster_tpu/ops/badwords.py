"""C4 bad-words candidate detection on device.

The reference scans every document with one big case-insensitive alternation
regex per language (c4_filters.rs:431-447).  On device that scan becomes a
**rolling-hash membership test**: one prefix polynomial hash over the
lowercased row, then for each distinct pattern length an O(1) window-hash
(prefix-difference) checked against the sorted hash table of that length's
patterns, plus word-boundary masks for non-CJK languages
(c4_filters.rs:433-439: CJK patterns get no ``\\W`` anchors).

The kernel is *candidate-exact in the safe direction*: a true regex match is
always flagged (the hash is computed from the same codepoints the pattern
hash used; boundary classes mirror ``\\w`` via the shared char table), while
hash collisions can only over-flag.  The host finalizer runs the real regex
filter on flagged documents only — so final decisions equal the reference's,
and the expensive scan is skipped for the (vast) majority of clean documents.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device import ALNUM, classify, isin_sorted, lower_table
from .stats import _first_col, _poly_hash, _shift_r

__all__ = ["BadwordTables", "badwords_candidates", "MAX_PATTERN_CPS"]

#: Patterns longer than this (in codepoints) disqualify device execution —
#: real LDNOOBW entries are far shorter.
MAX_PATTERN_CPS = 48


def _hash_cps(cps: Sequence[int]) -> int:
    """Host twin of the device window hash (int32 wraparound, mul 31)."""
    h = 0
    for c in cps:
        h = (h * 31 + c) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def _pow31(n: int) -> int:
    p = pow(31, n, 1 << 32)
    return p - (1 << 32) if p >= (1 << 31) else p


class BadwordTables(NamedTuple):
    """Per-length sorted hash tables for one language's pattern list."""

    lengths: Tuple[int, ...]
    tables: Tuple[np.ndarray, ...]  # sorted int32 hashes, one per length
    check_boundaries: bool  # False for CJK languages (ja/th/zh)

    @classmethod
    def build(
        cls, words: Sequence[str], check_boundaries: bool
    ) -> Optional["BadwordTables"]:
        """None if any pattern is empty/too long (caller falls back to host)."""
        by_len: Dict[int, List[int]] = {}
        for w in words:
            cps = [ord(c) for c in w.lower()]
            if not cps or len(cps) > MAX_PATTERN_CPS:
                return None
            by_len.setdefault(len(cps), []).append(_hash_cps(cps))
        if not by_len:
            return None
        lengths = tuple(sorted(by_len))
        tables = tuple(
            np.unique(np.array(by_len[n], dtype=np.int32)) for n in lengths
        )
        return cls(lengths=lengths, tables=tables, check_boundaries=check_boundaries)


def badwords_candidates(
    cps: jax.Array, lengths: jax.Array, tables: BadwordTables
) -> jax.Array:
    """``[B] bool`` — document contains a window whose lowercased content
    hash matches a pattern of that length (with boundary masks unless CJK)."""
    _, length = cps.shape
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]
    mask = pos < lengths[:, None]

    lt = lower_table()
    low = jnp.where(mask, lt[jnp.minimum(cps, lt.shape[0] - 1)], 0)

    # Inclusive prefix hash over the whole row: h[i] = hash(low[0..=i]).
    h = _poly_hash(low, mask, _first_col(mask))
    h_prev = _shift_r(h, 0)  # hash(low[0..i)) at position i

    if tables.check_boundaries:
        # Regex \w ≈ alphanumeric or underscore (shared char table semantics).
        wordch = ((classify(low) & ALNUM) != 0) | (low == ord("_"))
        nonword_before = ~_shift_r(wordch, False)  # start-of-row => boundary
        after_pad = jnp.pad(wordch[:, 1:], ((0, 0), (0, 1)))
    else:
        nonword_before = None
        after_pad = None

    match = jnp.zeros(cps.shape[0], dtype=bool)
    for n, table in zip(tables.lengths, tables.tables):
        if n > length:
            continue
        # Window [i, i+n): hash = h[i+n-1] - h[i-1] * 31^n  (int32 wrap).
        h_end = jnp.pad(h[:, n - 1 :], ((0, 0), (0, n - 1)))
        w = h_end - h_prev * jnp.int32(_pow31(n))
        ok = (pos + n) <= lengths[:, None]
        hit = isin_sorted(w, jnp.asarray(table)) & ok
        if tables.check_boundaries:
            # Char after the window: position i+n (row end => boundary).
            after_word = jnp.pad(
                after_pad[:, n - 1 :], ((0, 0), (0, n - 1))
            ) & ((pos + n) < lengths[:, None])
            hit = hit & nonword_before & ~after_word
        match = match | jnp.any(hit, axis=1)
    return match
