"""C4 bad-words matching on device — the decision, not just a prefilter.

The reference scans every document with one big case-insensitive alternation
regex per language (c4_filters.rs:431-447).  A sequential automaton is the
wrong shape for a TPU (state-to-state dependencies serialize the scan), so
the device twin is a **parallel window test**: one pair of prefix polynomial
hashes over the lowercased row (independent multipliers 31 and 1000003), then
for each distinct pattern length an O(1) double window-hash
(prefix-difference) checked against the per-length (h1, h2)-keyed pattern
table, plus word-boundary masks for non-CJK languages (c4_filters.rs:433-439:
CJK patterns get no ``\\W`` anchors).  Every window of every length is tested
simultaneously on the VPU.

Exactness: a true regex match always hits (hashes are computed from the same
codepoints the pattern hashes used; boundary classes mirror ``\\w`` via the
shared char table).  A spurious hit requires a simultaneous collision in two
independent 32-bit hashes — ~2^-64 per (window, pattern) pair, the same
negligible-collision class the duplicate tables already document
(:mod:`.stats`).  The host therefore trusts the device verdict: non-matching
documents never touch the host regex, and matching documents only draw the
seeded keep-fraction (VERDICT r3 item 6).

Case-folding exactness (ADVICE r4): ``re.IGNORECASE`` equates a handful of
codepoint pairs that single-char lowercasing cannot (``ſ``/``s``, ``ı``/``i``,
``µ``/``μ``, …: CPython's ``_equivalences`` table), and a few codepoints have
multi-char lowers (``İ``) the device's char→char table maps to identity.
Rather than documenting a silent false-negative class, the kernel *routes
around it*: pattern lists containing fold-divergent codepoints disqualify
device tables entirely (``BadwordTables.build`` → None → host regex), and
rows containing a text-side hazard codepoint are flagged per row
(``fold_hazard``) and re-decided by the host regex — the same escape hatch
uncompiled languages use.  Both sets are computed from the running
interpreter's own folding behavior, so the guarantee tracks the oracle.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device import ALNUM, classify, lower_table
from .stats import _first_col, _poly_hash, _shift_r

__all__ = [
    "BadwordTables",
    "badwords_matches",
    "badwords_matches_multi",
    "MAX_PATTERN_CPS",
]

#: Patterns longer than this (in codepoints) disqualify device execution —
#: real LDNOOBW entries are far shorter.
MAX_PATTERN_CPS = 48

#: Second, independent window-hash multiplier (odd, so invertible mod 2^32).
MUL2 = 1000003

# re.IGNORECASE's extra single-char equivalences beyond str.lower (CPython
# sre; imported from the interpreter when exposed so the set tracks the
# oracle's actual behavior, with the full CPython-3.12 table as fallback).
_EQUIV_FALLBACK = (
    (0x69, 0x131),            # i, dotless i
    (0x73, 0x17F),            # s, long s
    (0xB5, 0x3BC),            # micro sign, greek mu
    (0x345, 0x3B9, 0x1FBE),   # ypogegrammeni, iota, prosgegrammeni
    (0x390, 0x1FD3),          # iota dialytika+tonos / +oxia
    (0x3B0, 0x1FE3),          # upsilon dialytika+tonos / +oxia
    (0x3B2, 0x3D0),           # beta / beta symbol
    (0x3B5, 0x3F5),           # epsilon / lunate epsilon
    (0x3B8, 0x3D1),           # theta / theta symbol
    (0x3BA, 0x3F0),           # kappa / kappa symbol
    (0x3C0, 0x3D6),           # pi / omega pi
    (0x3C1, 0x3F1),           # rho / rho symbol
    (0x3C2, 0x3C3),           # final sigma / sigma
    (0x3C6, 0x3D5),           # phi / phi symbol
    (0x432, 0x1C80),          # cyrillic ve / rounded ve
    (0x434, 0x1C81),          # cyrillic de / long-legged de
    (0x43E, 0x1C82),          # cyrillic o / narrow o
    (0x441, 0x1C83),          # cyrillic es / wide es
    (0x442, 0x1C84, 0x1C85),  # cyrillic te / tall te / three-legged te
    (0x44A, 0x1C86),          # cyrillic hard sign / tall hard sign
    (0x463, 0x1C87),          # cyrillic yat / tall yat
    (0x1C88, 0xA64B),         # cyrillic unblended uk / monograph uk
    (0x1E61, 0x1E9B),         # s with dot above / long s with dot above
    (0xFB05, 0xFB06),         # latin small ligature st variants
)


def _equivalence_classes():
    # 3.12+: re._casefix._EXTRA_CASES (cp -> equivalent lowered cps; 50
    # entries incl. Greek variant letters and final sigma).  Older: the
    # _equivalences tuple in the sre compiler.  Both are the exact tables
    # the running re module matches with.
    try:
        from re import _casefix  # type: ignore[attr-defined]

        return tuple(
            (k, *v) for k, v in sorted(_casefix._EXTRA_CASES.items())
        )
    except Exception:  # noqa: BLE001
        pass
    try:
        from re._compiler import _equivalences  # type: ignore[attr-defined]

        return tuple(_equivalences)
    except Exception:  # noqa: BLE001
        try:
            from sre_compile import _equivalences  # type: ignore[attr-defined]

            return tuple(_equivalences)
        except Exception:  # noqa: BLE001
            return _EQUIV_FALLBACK


def _table_lower(cp: int) -> int:
    """The device lower table's mapping (identity for multi-char lowers)."""
    low = chr(cp).lower()
    return ord(low) if len(low) == 1 else cp


@lru_cache(maxsize=1)
def _fold_partners() -> Tuple[Dict[int, Tuple[int, ...]], frozenset]:
    """(partner map in table-lower space, the "common" codepoints).

    The device lowers text through the char→char table (:func:`lower_table`);
    ``re.IGNORECASE`` lowers both sides AND applies the sre equivalence
    classes.  A device miss therefore needs a *pair*: a pattern codepoint and
    a text codepoint the regex folds together but the table lowers
    differently.  ``partners[x]`` lists the table-lower-space codepoints the
    regex equates with ``x`` despite distinct table lowers — built from the
    equivalence classes plus the multi-char-lower codepoints (``İ`` is a
    table identity but regex-equal to ``i``).

    "Common" codepoints are ASCII or have an uppercase pre-image under
    single-char lower (``σ`` ← ``Σ``); their partner rows cannot be
    hazard-flagged without forfeiting the fast path for ordinary text, so a
    pattern whose divergence partner is common disqualifies its whole list
    instead (``ſtop`` would need every ``s`` row host-routed).  Rare partners
    (``ſ``, ``ı``, ``İ``, the historic Cyrillic letterforms) are cheap to
    flag per-row, so lists whose divergences are all rare-sided stay
    device-compiled with a per-list hazard set (``BadwordTables.hazard_cps``).
    """
    from ..utils import chartables as ct

    max_cp = ct._MAX_CP
    partners: Dict[int, set] = {}

    def _link(a: int, b: int) -> None:
        la, lb = _table_lower(a), _table_lower(b)
        if la != lb and la < max_cp and lb < max_cp:
            partners.setdefault(la, set()).add(lb)
            partners.setdefault(lb, set()).add(la)

    for cls in _equivalence_classes():
        for i, a in enumerate(cls):
            for b in cls[i + 1 :]:
                _link(a, b)
    # Multi-char lowers: regex folds them via simple per-char tolower (first
    # char of the full lower); the table keeps them as identities.
    for cp in range(max_cp):
        low = chr(cp).lower()
        if len(low) != 1:
            _link(cp, ord(low[0]))

    # Common = ASCII, or some *other* codepoint single-char-lowers to it
    # (i.e. it has an uppercase form in ordinary text).
    has_preimage = np.zeros(max_cp, dtype=bool)
    for cp in range(max_cp):
        lcp = _table_lower(cp)
        if lcp != cp and lcp < max_cp:
            has_preimage[lcp] = True
    # Greek final sigma ς (U+03C2) has no uppercase pre-image (Σ lowers to
    # σ), but it ends nearly every Greek word — hazard-flagging it would
    # silently host-re-decide almost every Greek row, which is worse than
    # honestly disqualifying the (σ-containing) list to the whole-list host
    # fallback.  Treat it as common despite the pre-image test.
    common = frozenset(
        x
        for x in {p for v in partners.values() for p in v} | set(partners)
        if x < 0x80 or has_preimage[x] or x == 0x3C2
    )
    return (
        {k: tuple(sorted(v)) for k, v in partners.items()},
        common,
    )


def _hash_cps(cps: Sequence[int], mul: int) -> int:
    """Host twin of the device window hash (int32 wraparound)."""
    h = 0
    for c in cps:
        h = (h * mul + c) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def _pow_i32(mul: int, n: int) -> int:
    p = pow(mul, n, 1 << 32)
    return p - (1 << 32) if p >= (1 << 31) else p


class BadwordTables(NamedTuple):
    """Per-length (h1, h2)-keyed pattern tables for one language's list."""

    lengths: Tuple[int, ...]
    tables1: Tuple[np.ndarray, ...]  # int32 h1, sorted, one per length
    tables2: Tuple[np.ndarray, ...]  # int32 h2, aligned with tables1
    max_dup: int  # most patterns sharing one h1 within a length
    check_boundaries: bool  # False for CJK languages (ja/th/zh)
    #: Table-lower-space codepoints whose presence in a TEXT row voids the
    #: device verdict for this list (IGNORECASE folds them into a pattern
    #: codepoint the char→char table cannot — see _fold_partners).
    hazard_cps: Tuple[int, ...] = ()

    @classmethod
    def build(
        cls, words: Sequence[str], check_boundaries: bool
    ) -> Optional["BadwordTables"]:
        """None if any pattern is empty/too long, contains a codepoint whose
        lowercase is multi-char (hash length would diverge from the table's),
        or fold-diverges against a *common* text codepoint (caller falls back
        to host — see module docstring)."""
        partners, fold_common = _fold_partners()
        hazard: set = set()
        by_len: Dict[int, List[Tuple[int, int]]] = {}
        for w in words:
            if any(len(c.lower()) != 1 for c in w):
                return None
            for c in w.lower():
                for p in partners.get(_table_lower(ord(c)), ()):
                    if p in fold_common:
                        return None
                    hazard.add(p)
            cps = [ord(c) for c in w.lower()]
            if not cps or len(cps) > MAX_PATTERN_CPS:
                return None
            by_len.setdefault(len(cps), []).append(
                (_hash_cps(cps, 31), _hash_cps(cps, MUL2))
            )
        if not by_len:
            return None
        lengths = tuple(sorted(by_len))
        t1s, t2s = [], []
        max_dup = 1
        for n in lengths:
            pairs = sorted(set(by_len[n]))
            h1 = np.array([p[0] for p in pairs], dtype=np.int32)
            h2 = np.array([p[1] for p in pairs], dtype=np.int32)
            _, counts = np.unique(h1, return_counts=True)
            max_dup = max(max_dup, int(counts.max()))
            t1s.append(h1)
            t2s.append(h2)
        return cls(
            lengths=lengths,
            tables1=tuple(t1s),
            tables2=tuple(t2s),
            max_dup=max_dup,
            check_boundaries=check_boundaries,
            hazard_cps=tuple(sorted(hazard)),
        )


def _isin2(w1, w2, t1, t2, max_dup: int):
    """Membership of (w1, w2) pairs in the aligned (t1-sorted) pair table."""
    m = t1.shape[0]
    idx = jnp.searchsorted(t1, w1)
    hit = jnp.zeros(w1.shape, dtype=bool)
    for k in range(max_dup):
        j = jnp.minimum(idx + k, m - 1)
        hit = hit | ((t1[j] == w1) & (t2[j] == w2))
    return hit


def _window_context(cps: jax.Array, lengths: jax.Array) -> dict:
    """Per-row scans shared by every language's table test: lowercased chars,
    both prefix hashes, and the ``\\w`` boundary masks."""
    _, length = cps.shape
    pos = jnp.arange(length, dtype=jnp.int32)[None, :]
    mask = pos < lengths[:, None]

    lt = lower_table()
    low = jnp.where(mask, lt[jnp.minimum(cps, lt.shape[0] - 1)], 0)

    first = _first_col(mask)
    h1 = _poly_hash(low, mask, first)
    h2 = _poly_hash(low, mask, first, mul=MUL2)

    wordch = ((classify(low) & ALNUM) != 0) | (low == ord("_"))
    return {
        "low": low,
        "mask": mask,
        "pos": pos,
        "lengths": lengths,
        "h1": h1,
        "h2": h2,
        "h1_prev": _shift_r(h1, 0),  # hash(low[0..i)) at position i
        "h2_prev": _shift_r(h2, 0),
        "nonword_before": ~_shift_r(wordch, False),  # row start => boundary
        "after_pad": jnp.pad(wordch[:, 1:], ((0, 0), (0, 1))),
        "n_rows": cps.shape[0],
        "length": length,
    }


def _match_with_context(ctx: dict, tables: BadwordTables) -> jax.Array:
    pos, lengths, length = ctx["pos"], ctx["lengths"], ctx["length"]
    match = jnp.zeros(ctx["n_rows"], dtype=bool)
    for n, t1, t2 in zip(tables.lengths, tables.tables1, tables.tables2):
        if n > length:
            continue
        # Window [i, i+n): hash = h[i+n-1] - h[i-1] * mul^n  (int32 wrap).
        w1 = jnp.pad(
            ctx["h1"][:, n - 1 :], ((0, 0), (0, n - 1))
        ) - ctx["h1_prev"] * jnp.int32(_pow_i32(31, n))
        w2 = jnp.pad(
            ctx["h2"][:, n - 1 :], ((0, 0), (0, n - 1))
        ) - ctx["h2_prev"] * jnp.int32(_pow_i32(MUL2, n))
        ok = (pos + n) <= lengths[:, None]
        hit = _isin2(w1, w2, jnp.asarray(t1), jnp.asarray(t2), tables.max_dup) & ok
        if tables.check_boundaries:
            # Char after the window: position i+n (row end => boundary).
            after_word = jnp.pad(
                ctx["after_pad"][:, n - 1 :], ((0, 0), (0, n - 1))
            ) & ((pos + n) < lengths[:, None])
            hit = hit & ctx["nonword_before"] & ~after_word
        match = match | jnp.any(hit, axis=1)
    return match


def _hazard_rows(ctx: dict, hazard_cps) -> jax.Array:
    """``[B] bool`` — rows containing any of the (few) hazard codepoints, in
    table-lower space.  Empty hazard sets (the common case: e.g. no pattern
    uses ``s``'s partner ``ſ`` unless some pattern contains ``s`` — which
    English lists do, giving {ſ}) compile to a constant False."""
    hz = jnp.zeros(ctx["n_rows"], dtype=bool)
    for cp in hazard_cps:
        hz = hz | jnp.any((ctx["low"] == jnp.int32(cp)) & ctx["mask"], axis=1)
    return hz


def badwords_matches(
    cps: jax.Array, lengths: jax.Array, tables: BadwordTables
) -> Tuple[jax.Array, jax.Array]:
    """``([B] bool match, [B] bool fold_hazard)`` per document.

    The match verdict equals the reference regex's on every row whose hazard
    flag is False (module docstring: 2^-64 collision caveat).  Hazard rows
    contain a codepoint IGNORECASE folds into a pattern codepoint the
    char→char lower table cannot — the caller must re-decide those rows with
    the host regex."""
    ctx = _window_context(cps, lengths)
    return _match_with_context(ctx, tables), _hazard_rows(ctx, tables.hazard_cps)


def badwords_matches_multi(
    cps: jax.Array, lengths: jax.Array, tables_by_lang: dict
) -> Tuple[dict, dict]:
    """(per-language match verdicts, per-language ``[B] bool`` hazard rows).

    Verdicts for several languages' tables share the hash scans (the scans
    dominate; per-language window tests are cheap).  A hazard row contains a
    codepoint whose IGNORECASE folding the char→char lower table cannot
    express *for that language's pattern list*; its verdict must come from
    the host regex (module docstring)."""
    ctx = _window_context(cps, lengths)
    per_lang = {
        lang: _match_with_context(ctx, tables)
        for lang, tables in sorted(tables_by_lang.items())
    }
    # Hazards are per-language: a row quoting historic Cyrillic must only be
    # host-routed when decided AGAINST a list whose patterns fold into those
    # codepoints, not because some other language's table is loaded.
    # Identical hazard sets share one computed array (common case: every
    # Latin list hazards exactly {ſ, ı, İ}).
    by_set: Dict[Tuple[int, ...], jax.Array] = {}
    hazards = {}
    for lang, tables in sorted(tables_by_lang.items()):
        key = tuple(tables.hazard_cps)
        if key not in by_set:
            by_set[key] = _hazard_rows(ctx, key)
        hazards[lang] = by_set[key]
    return per_lang, hazards
