"""Occupancy-aware device geometry: buckets + per-bucket batch sizes.

The device executes ragged UTF-8 on fixed shapes (SURVEY.md §5), so two
numbers govern occupancy: the bucket ladder (how much each row is padded)
and the rows per dispatch (how much work each program instance carries).
The seed geometry was corpus-blind — ``DEFAULT_BUCKETS`` is a hardcoded
ladder and one batch size serves every bucket — so a short-doc corpus burns
most of its padded codepoint lanes and a long-doc corpus dispatches
oversized batches.  This module makes geometry a first-class, data-derived
object:

* :class:`DeviceGeometry` — an immutable (buckets, per-bucket batch sizes)
  pair every layer threads through (packer, compiled pipeline, checkpoint
  cursor, multi-host negotiation).  ``DeviceGeometry.uniform`` reproduces
  the seed behavior exactly, so defaults stay byte-identical.
* :func:`choose_buckets` — histogram-calibrated bucket boundaries that
  minimize padded-codepoint waste under a max-programs budget (dynamic
  program over quantized length candidates; exact for the sample).
* :func:`equalized_batch_sizes` — ``B_b ∝ lane_budget / L_b`` rounded to
  multiples of 8, backend-aware like the seed knee heuristic, so every
  dispatch carries roughly the same padded-lane volume instead of one row
  count serving 512-char and 65536-char programs alike.
* :class:`LengthReservoir` / :func:`length_histogram` — deterministic
  sampling for the calibration pass; the fixed-bin histogram is the
  allgather payload multi-host runs merge so every process derives the
  *identical* geometry (lockstep dispatch must agree on shapes).

The persistent XLA compilation cache keys on program shapes, so each chosen
geometry reuses its compiled programs across runs for free.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from .packing import PACK_MARGIN

__all__ = [
    "DeviceGeometry",
    "LengthReservoir",
    "choose_buckets",
    "equalized_batch_sizes",
    "calibrate_geometry",
    "length_histogram",
    "geometry_from_histogram",
    "HIST_BIN_EDGES",
    "CALIBRATION_SAMPLE",
]

#: Documents sampled by the calibration pass before geometry is frozen.
CALIBRATION_SAMPLE = 8192

#: Default ceiling on the number of buckets (== compiled programs per phase).
MAX_PROGRAMS = 6

#: Fixed log-spaced histogram bin edges (upper-inclusive), shared by every
#: process of a multi-host job: the allgather payload must be shape-stable
#: and identical across hosts for the merged geometry to be identical.
#: Covers 64 chars .. 1M chars in ~quarter-octave steps.
HIST_BIN_EDGES: Tuple[int, ...] = tuple(
    int(round(64 * (2 ** (i / 4)))) for i in range(57)
)


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


@dataclass(frozen=True)
class DeviceGeometry:
    """Immutable device geometry: sorted bucket lengths + aligned batch sizes.

    ``buckets[i]`` is a char capacity; a document of ``n`` chars lands in the
    smallest bucket with ``n <= bucket - PACK_MARGIN`` (same admission rule
    as the packer).  ``batch_sizes[i]`` is the row count of that bucket's
    compiled program.  ``source`` records provenance: ``default`` (seed
    heuristic), ``explicit`` (operator flags), or ``auto`` (calibrated).
    """

    buckets: Tuple[int, ...]
    batch_sizes: Tuple[int, ...]
    source: str = "default"

    def __post_init__(self) -> None:
        if not self.buckets:
            raise ValueError("DeviceGeometry: buckets cannot be empty")
        if len(self.buckets) != len(self.batch_sizes):
            raise ValueError(
                "DeviceGeometry: buckets and batch_sizes must align "
                f"({len(self.buckets)} vs {len(self.batch_sizes)})"
            )
        if tuple(sorted(self.buckets)) != tuple(self.buckets):
            raise ValueError("DeviceGeometry: buckets must be sorted ascending")
        if len(set(self.buckets)) != len(self.buckets):
            raise ValueError("DeviceGeometry: buckets must be unique")
        if any(b < 64 for b in self.buckets):
            raise ValueError("DeviceGeometry: buckets must be >= 64 chars")
        if any(n < 1 for n in self.batch_sizes):
            raise ValueError("DeviceGeometry: batch sizes must be >= 1")

    @classmethod
    def uniform(
        cls,
        buckets: Sequence[int],
        batch_size: int,
        source: str = "default",
    ) -> "DeviceGeometry":
        """The seed behavior: one batch size for every bucket."""
        bs = tuple(sorted(buckets))
        return cls(buckets=bs, batch_sizes=(int(batch_size),) * len(bs), source=source)

    # --- lookups -----------------------------------------------------------

    def bucket_for(self, n_chars: int) -> Optional[int]:
        """Smallest bucket admitting ``n_chars``, or None (host fallback)."""
        for b in self.buckets:
            if n_chars <= b - PACK_MARGIN:
                return b
        return None

    def batch_for(self, bucket: int) -> int:
        """Rows per dispatch for ``bucket`` (exact bucket length required)."""
        try:
            return self.batch_sizes[self.buckets.index(bucket)]
        except ValueError:
            raise KeyError(f"no bucket of length {bucket} in {self.buckets}") from None

    @property
    def max_batch(self) -> int:
        return max(self.batch_sizes)

    @property
    def largest(self) -> int:
        return self.buckets[-1]

    def with_batch_multiple(self, mult: int) -> "DeviceGeometry":
        """Round every batch size up to a multiple of ``mult`` (mesh runs
        need the global batch divisible by the device count)."""
        if mult <= 1:
            return self
        return DeviceGeometry(
            buckets=self.buckets,
            batch_sizes=tuple(
                max(mult, _round_up(n, mult)) for n in self.batch_sizes
            ),
            source=self.source,
        )

    # --- identity ----------------------------------------------------------

    def to_dict(self) -> Dict[str, object]:
        return {
            "buckets": list(self.buckets),
            "batch_sizes": list(self.batch_sizes),
            "source": self.source,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "DeviceGeometry":
        return cls(
            buckets=tuple(int(b) for b in d["buckets"]),
            batch_sizes=tuple(int(n) for n in d["batch_sizes"]),
            source=str(d.get("source", "default")),
        )

    def fingerprint(self) -> str:
        """Stable hash of the shape-determining fields (source excluded:
        the same shapes compile to the same programs however chosen)."""
        blob = json.dumps(
            {"buckets": list(self.buckets), "batch_sizes": list(self.batch_sizes)},
            sort_keys=True,
        ).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()[:16]

    def describe(self) -> str:
        pairs = ", ".join(
            f"{b}x{n}" for b, n in zip(self.buckets, self.batch_sizes)
        )
        return f"[{pairs}] ({self.source})"


# --- batch sizing -----------------------------------------------------------


def _lane_budget(backend: Optional[str] = None) -> Tuple[int, int, int]:
    """(lane budget, min rows, max rows) for the backend.

    Mirrors the seed ``default_batch_size`` knee heuristic: XLA:CPU is
    cache-residency-bound at ~128k int32 lanes per batch; accelerators
    amortize per-dispatch cost (the remote tunnel's ~66 ms round trip) and
    carry ~2M lanes (~8 MB int32)."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    if backend == "cpu":
        return 64 * 2048, 8, 256
    return 1024 * 2048, 64, 1024


def equalized_batch_sizes(
    buckets: Sequence[int],
    backend: Optional[str] = None,
    lane_budget: Optional[int] = None,
) -> Tuple[int, ...]:
    """Work-equalized rows per bucket: ``B_b ∝ lane_budget / L_b``.

    Rounded down to multiples of 8 (sublane-friendly and a whole multiple of
    the test meshes' 8 virtual devices), clamped to the backend's row range,
    so every dispatch carries roughly the same padded-lane volume instead of
    the seed's one-row-count-for-all-widths."""
    budget, lo, hi = _lane_budget(backend)
    if lane_budget is not None:
        budget = lane_budget
    sizes = []
    for b in sorted(buckets):
        n = max(lo, min(hi, budget // int(b)))
        n = max(8, (n // 8) * 8)
        sizes.append(n)
    return tuple(sizes)


# --- bucket calibration -----------------------------------------------------


def choose_buckets(
    lengths: Sequence[int],
    weights: Optional[Sequence[float]] = None,
    max_programs: int = MAX_PROGRAMS,
    round_to: int = 64,
    min_bucket: int = 128,
    max_candidates: int = 512,
) -> Tuple[int, ...]:
    """Bucket boundaries minimizing padded-codepoint waste for a length
    sample, using at most ``max_programs`` buckets.

    Candidates are sampled lengths (plus the packer margin) rounded up to
    ``round_to``; the dynamic program is exact over that candidate set:
    ``dp[k][j]`` = minimal waste of covering every doc ≤ candidate ``j``
    with ``k`` buckets whose largest is ``j``.  ``weights`` lets a merged
    histogram stand in for raw lengths (multi-host calibration).

    Deterministic: same sample (or histogram) → same ladder, which is what
    lets every host of an SPMD job derive the geometry independently.
    """
    if max_programs < 1:
        raise ValueError("max_programs must be >= 1")
    ls = np.asarray([int(l) for l in lengths], dtype=np.int64)
    if ls.size == 0:
        raise ValueError("choose_buckets: empty length sample")
    w = (
        np.ones(ls.size, dtype=np.float64)
        if weights is None
        else np.asarray(weights, dtype=np.float64)
    )
    if w.shape != ls.shape:
        raise ValueError("choose_buckets: weights must align with lengths")
    order = np.argsort(ls, kind="stable")
    ls, w = ls[order], w[order]

    # Candidate capacities: every doc must fit under bucket - PACK_MARGIN.
    need = np.maximum(ls + PACK_MARGIN, min_bucket)
    while True:
        cands = np.unique((np.ceil(need / round_to) * round_to).astype(np.int64))
        if cands.size <= max_candidates:
            break
        round_to *= 2
    k_buckets = min(max_programs, cands.size)

    # Docs ordered by candidate assignment: doc i belongs to the smallest
    # candidate >= need[i].  Every candidate is some doc's rounded need, so
    # every candidate index has weight.  Prefix sums give O(1) segment waste.
    idx = np.searchsorted(cands, need, side="left")
    counts = np.bincount(idx, weights=w, minlength=cands.size)
    len_sums = np.bincount(idx, weights=w * ls, minlength=cands.size)
    C = np.concatenate([[0.0], np.cumsum(counts)])
    S = np.concatenate([[0.0], np.cumsum(len_sums)])

    # W[i, j] (i <= j): waste of assigning docs with candidate index in
    # [i, j] to bucket cands[j].  nC <= max_candidates so nC^2 floats fit.
    nC = cands.size
    candf = cands.astype(np.float64)
    W = (C[None, 1:] - C[:-1, None]) * candf[None, :] - (S[None, 1:] - S[:-1, None])

    # dp[j] at level k: minimal waste covering docs [0..j] with exactly k
    # buckets, the largest being cands[j].  Level 1 is W[0, :]; level k
    # extends level k-1 via dp_new[j] = min_{i<j} dp[i] + W[i+1, j].
    dp = W[0].copy()
    parents = []  # parents[k-2][j] = best i for level k ending at j
    ii = np.arange(nC - 1)[:, None]
    jj = np.arange(nC)[None, :]
    for _ in range(2, k_buckets + 1):
        total = np.where(ii + 1 <= jj, dp[:-1, None] + W[1:, :], np.inf)
        best_i = np.argmin(total, axis=0)
        dp = total[best_i, np.arange(nC)]
        parents.append(best_i)

    # The largest bucket must admit the longest doc, i.e. end at the last
    # candidate.  More distinct buckets never increase waste, so take the
    # full budget and backtrack.
    j = nC - 1
    picks = [j]
    for parent in reversed(parents):
        j = int(parent[j])
        picks.append(j)
    return tuple(int(cands[p]) for p in sorted(picks))


def calibrate_geometry(
    lengths: Sequence[int],
    weights: Optional[Sequence[float]] = None,
    max_programs: int = MAX_PROGRAMS,
    backend: Optional[str] = None,
) -> DeviceGeometry:
    """Histogram-calibrated geometry: waste-minimizing buckets + work-
    equalized per-bucket batch sizes.  Deterministic in the sample."""
    buckets = choose_buckets(lengths, weights=weights, max_programs=max_programs)
    return DeviceGeometry(
        buckets=buckets,
        batch_sizes=equalized_batch_sizes(buckets, backend=backend),
        source="auto",
    )


# --- sampling ---------------------------------------------------------------


class LengthReservoir:
    """Seeded reservoir sampler over document lengths.

    Deterministic for a given (seed, stream): calibration must be
    reproducible so a re-run over the same corpus derives the same geometry
    (and therefore hits the same persistent compile-cache entries)."""

    def __init__(self, capacity: int = CALIBRATION_SAMPLE, seed: int = 0x6E0) -> None:
        if capacity < 1:
            raise ValueError("reservoir capacity must be >= 1")
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self._sample: list[int] = []
        self.n_seen = 0

    def add(self, length: int) -> None:
        self.n_seen += 1
        if len(self._sample) < self.capacity:
            self._sample.append(int(length))
            return
        j = int(self._rng.integers(0, self.n_seen))
        if j < self.capacity:
            self._sample[j] = int(length)

    def lengths(self) -> Tuple[int, ...]:
        return tuple(self._sample)


def length_histogram(
    lengths: Sequence[int], edges: Sequence[int] = HIST_BIN_EDGES
) -> np.ndarray:
    """Counts per fixed bin (upper-inclusive; overflow lands in the last
    bin).  The multi-host allgather payload — identical shape on every host
    by construction, so the merged histogram (elementwise sum) is the same
    array on every process."""
    e = np.asarray(edges, dtype=np.int64)
    ls = np.asarray([int(l) for l in lengths], dtype=np.int64)
    idx = np.searchsorted(e, ls, side="left")
    idx = np.minimum(idx, e.size - 1)
    return np.bincount(idx, minlength=e.size).astype(np.int64)


def geometry_from_histogram(
    hist: np.ndarray,
    edges: Sequence[int] = HIST_BIN_EDGES,
    max_programs: int = MAX_PROGRAMS,
    backend: Optional[str] = None,
) -> DeviceGeometry:
    """Geometry from a (possibly merged) fixed-bin histogram.  Each bin is
    represented by its upper edge — the conservative choice: a bucket sized
    for the representative admits every doc in the bin."""
    hist = np.asarray(hist, dtype=np.float64)
    e = np.asarray(edges, dtype=np.int64)
    if hist.shape != e.shape:
        raise ValueError("histogram does not match the bin edges")
    nz = hist > 0
    if not nz.any():
        raise ValueError("geometry_from_histogram: empty histogram")
    return calibrate_geometry(
        e[nz].tolist(),
        weights=hist[nz].tolist(),
        max_programs=max_programs,
        backend=backend,
    )
