"""The compiled filter pipeline: config -> one XLA program per shape bucket.

This is the device replacement for the reference's executor + worker loop
(SURVEY.md §7 stage 3): the whole filter chain is traced once into a single
``jit`` function mapping a packed batch to per-filter integer statistics.
Sequential observable semantics (a doc filtered at step k gets no step-k+1
metadata; C4's rewrite feeds downstream steps) are preserved by:

* computing every step's stats unconditionally on device (masked work is
  free compared to divergent control flow — XLA semantics), and
* resolving order, short-circuiting, metadata stamping, and reason-string
  formatting on the host from the integer stats, with float64 arithmetic
  identical to the oracle filters'.

Steps with no device kernel (TokenCounter; C4BadWordsFilter when no local
word list is available) run as host oracle steps.  If they appear as a suffix
of the config, the device prefix still runs compiled; any other placement
falls back to the host executor for the whole pipeline.  Documents that
overflow kernel table bounds (pathological line/word counts) are re-run on
the host oracle — the outlier path SURVEY.md §5 calls for.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time as _time_mod
from collections import deque
from functools import lru_cache, partial
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..config.pipeline import (
    OverlapConfig,
    PipelineConfig,
    ResilienceConfig,
    StepConfig,
)
from ..data_model import ProcessingOutcome, TextDocument
from ..errors import PipelineError, RetryExhaustedError
from ..filters.c4_quality import CITATION_RE
from ..filters.common import fmt2, fmt4, rust_bool, rust_float, rust_lines
from ..filters.gopher_quality import DEFAULT_STOP_WORDS
from ..filters.fineweb_quality import DEFAULT_STOP_CHARS
from ..models.langid import ISO_TO_NAME, LANGUAGES, NAME_TO_ISO, LangIdModel
from ..orchestration import execute_processing_pipeline
from ..pipeline_builder import build_pipeline_from_config
from ..resilience.breaker import CircuitBreaker
from ..resilience.faults import FAULTS
from ..resilience.retry import RetryPolicy
from ..resilience.watchdog import WATCHDOG
from ..utils.metrics import FILTER_DROP_PREFIX, METRICS
from ..utils.profiler import PROFILER
from ..utils.telemetry import TELEMETRY
from ..utils.events import EVENTS
from ..utils.trace import TRACER
from ..utils.overlap import prefetch_iter
from .badwords import badwords_matches_multi
from .langid_tpu import langid_scores
from .geometry import DeviceGeometry
from .packing import (
    DEFAULT_BUCKETS,
    PACK_MARGIN,
    PackedBatch,
    iter_packed_batches,
    pack_documents,
)
from .stats import (
    C4Params,
    c4_stage,
    fineweb_stats,
    gopher_quality_stats,
    gopher_rep_stats,
    hash_string,
    structure,
)

logger = logging.getLogger(__name__)

__all__ = ["CompiledPipeline", "process_documents_device", "device_step_types"]

_DEVICE_STEPS = {
    "LanguageDetectionFilter",
    "GopherRepetitionFilter",
    "GopherQualityFilter",
    "C4QualityFilter",
    "FineWebQualityFilter",
    "C4BadWordsFilter",
}

_CJK_BADWORDS_LANGS = ("ja", "th", "zh")  # c4_filters.rs:70

#: Window sentinel: the breaker refused this batch's dispatch — the drain
#: sends it straight to the host rung without recording a breaker failure
#: (the device was never asked, so there is nothing new to count).
_BREAKER_OPEN = object()


def device_step_types() -> frozenset:
    return frozenset(_DEVICE_STEPS)


@lru_cache(maxsize=64)
def _badwords_tables_cached(default_language: str, cache_base_path, stat_key):
    from ..filters.c4_badwords import load_local_badwords
    from .badwords import BadwordTables

    words = load_local_badwords(default_language, cache_base_path)
    if not words:
        # Unavailable or empty: the host filter owns the semantics
        # (download, passed_no_regex, fail_on_missing_language).
        return None
    return BadwordTables.build(
        words, check_boundaries=default_language not in _CJK_BADWORDS_LANGS
    )


def _badwords_list_stat(default_language: str, cache_base_path):
    """(mtime_ns, size) of the on-disk list, or None when absent — part of
    the cache key so a list that appears or changes during a long-lived
    process is observed instead of a stale table (or stale None) sticking
    for the process lifetime."""
    import os

    from ..filters.c4_badwords import local_badwords_path

    path = local_badwords_path(default_language, cache_base_path)
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_mtime_ns, st.st_size)


def _badwords_tables(step: StepConfig):
    """BadwordTables for the step's default language from local lists only,
    or None (-> host execution).  Cached per (lang, cache path, file stat);
    the cache also makes the `_step_on_device` check and `_build_fn` see one
    consistent value even if the on-disk list disappears between them."""
    p = step.params
    stat_key = _badwords_list_stat(p.default_language, p.cache_base_path)
    return _badwords_tables_cached(p.default_language, p.cache_base_path, stat_key)


def _badwords_all_tables(step: StepConfig) -> Dict[str, object]:
    """Tables for EVERY language with a locally available list (vendored or
    cache dir) — one device pass then decides docs of all these languages,
    not just the default (VERDICT r3 weak #7).  Languages without local
    lists keep full host semantics (download / passed_no_regex /
    fail_on_missing_language)."""
    from ..filters.c4_badwords import BADWORDS_LANGS

    p = step.params
    out: Dict[str, object] = {}
    for lang in BADWORDS_LANGS:
        stat_key = _badwords_list_stat(lang, p.cache_base_path)
        if stat_key is None:
            continue
        tables = _badwords_tables_cached(lang, p.cache_base_path, stat_key)
        if tables is not None:
            out[lang] = tables
    return out


def _step_on_device_base(step: StepConfig) -> bool:
    """Device eligibility from config alone (no filesystem consulted)."""
    return step.type in _DEVICE_STEPS


def _step_on_device(step: StepConfig) -> bool:
    if not _step_on_device_base(step):
        return False
    if step.type == "C4BadWordsFilter" and _badwords_tables(step) is None:
        return False
    return True


def _table_sizes(length: int) -> Tuple[int, int]:
    """(max line/para slots, max word slots) for a bucket of ``length``.

    Word slots assume >= 4 chars per word+separator on average; denser docs
    hit ``word_overflow`` and take the (counted, bit-exact) host fallback.
    The cap halves the duplicate-table sort volume vs ``length // 2``."""
    max_lines = min(length, max(128, length // 8))
    max_words = min(16384, max(256, length // 4))
    return max_lines, max_words


class _Decision:
    """Host-side result for one step on one doc."""

    __slots__ = ("passed", "reason", "stamps", "extra")

    def __init__(self, passed: bool, reason: str = "", stamps=None, extra=None):
        self.passed = passed
        self.reason = reason
        self.stamps = stamps or []  # list[(key, value)] in stamp order
        self.extra = extra


class _StepEval:
    """Batch-vectorized verdicts for one step (see finalizer section notes)."""

    __slots__ = (
        "passed",
        "overflow",
        "decide",
        "pass_stamps",
        "pass_stamp_fn",
        "c4_line_keep",
        "c4_n_lines",
        "c4_rewrite_identity",
        "badwords_matches",
        "badwords_default_language",
        "badwords_fold_hazard",
    )

    def __init__(self, passed, decide, pass_stamps, overflow=None):
        self.passed = passed
        self.overflow = overflow
        self.decide = decide
        # Constant stamps for passing rows; None means even passing rows need
        # decide() (per-row stamp values or host-side work) — unless
        # pass_stamp_fn supplies the per-row stamps from batch-precomputed
        # arrays (the assemble_phase fast path).
        self.pass_stamps = pass_stamps
        self.pass_stamp_fn = None
        self.c4_line_keep = None
        self.c4_n_lines = None
        self.c4_rewrite_identity = None
        self.badwords_matches = None
        self.badwords_default_language = None
        self.badwords_fold_hazard = None


def default_batch_size(buckets=DEFAULT_BUCKETS) -> int:
    """Rows per device batch when the caller didn't choose.

    XLA:CPU throughput is cache-residency-bound: per-op working sets beyond
    the L2 fall to memory bandwidth, and the measured knee on the bench box
    is ~128k int32 lanes per batch — dropping the full-pipeline batch from
    1024 to 64 rows at 2048-char buckets took a pass from 6.5 s to 3.3 s
    (oracle 6.0 s), flipping every sub-1.0 bench config above the oracle.
    Accelerators amortize the per-dispatch cost (the remote TPU tunnel's
    ~66 ms round trip especially) and keep the round-1024 heuristic, scaled
    down for very wide buckets so a batch stays ~8 MB.
    """
    max_bucket = max(buckets)
    if jax.default_backend() == "cpu":
        return max(8, min(256, (64 * 2048) // max_bucket))
    return max(64, min(1024, (1024 * 2048) // max_bucket))


def record_occupancy(batch: PackedBatch) -> None:
    """Occupancy telemetry for one device dispatch (see utils/metrics.py):
    real codepoints vs padded lanes actually computed, plus a per-bucket
    dispatch counter.  Called at every dispatch seam (single-host
    ``dispatch_batch``, the multi-host lockstep loop) so the waste ratio in
    the CLI/bench reports reflects what the device really executed."""
    rows, length = batch.cps.shape
    METRICS.inc("occupancy_device_batches_total")
    METRICS.inc("occupancy_padded_lanes_total", float(rows) * float(length))
    METRICS.inc("occupancy_real_codepoints_total", float(int(batch.lengths.sum())))
    METRICS.inc(f"occupancy_dispatches_bucket_{length}")


# Step types that cheaply kill many documents: a phase boundary after them
# lets the runner repack survivors and skip the expensive downstream kernels
# for already-filtered rows — the device analogue of the host executor's
# short-circuit (executor.rs:30-57).
_PHASE_BOUNDARY_AFTER = frozenset({"LanguageDetectionFilter", "GopherQualityFilter"})

def _wire_u16() -> bool:
    """uint16 device uploads (see CompiledPipeline.__init__ note).

    ``TEXTBLAST_WIRE=u16|cp32`` pins it; the default is u16 on accelerator
    backends (halves the dominant tunnel transfer) and cp32 on CPU (no
    transfer to save; the widen would be pure cost)."""
    import os

    w = os.environ.get("TEXTBLAST_WIRE", "")
    if w == "u16":
        return True
    if w == "cp32":
        return False
    return jax.default_backend() in ("tpu", "axon")


# Steps whose decisions depend on word segmentation (word counts, stop
# words, word n-gram tables, words-per-line) — the steps that force
# dictionary-script documents onto the host oracle (see __init__).
_WORD_TABLE_STEPS = frozenset(
    {
        "GopherRepetitionFilter",
        "GopherQualityFilter",
        "C4QualityFilter",
        "FineWebQualityFilter",
    }
)


def _split_phases(steps: List[StepConfig]) -> List[List[int]]:
    phases: List[List[int]] = []
    cur: List[int] = []
    for i, s in enumerate(steps):
        cur.append(i)
        if s.type in _PHASE_BOUNDARY_AFTER and i < len(steps) - 1:
            phases.append(cur)
            cur = []
    if cur:
        phases.append(cur)
    return phases or [[]]


@dataclasses.dataclass
class WarmupStats:
    """Timing breakdown of one ``warmup_parallel`` call.

    ``total_s`` is wall time; ``trace_s``/``compile_s``/``cache_load_s``
    attribute where it went (compile_s is summed across pool threads, so it
    can exceed total_s on multi-core).  ``float(stats)`` is ``total_s`` for
    drop-in use where the old float return was consumed."""

    total_s: float = 0.0
    trace_s: float = 0.0
    compile_s: float = 0.0
    cache_load_s: float = 0.0
    programs: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    cache_stores: int = 0

    def __float__(self) -> float:
        return self.total_s

    def to_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


def _toggle_xla_compilation_cache(on: bool) -> bool:
    """Flip ``jax_enable_compilation_cache`` and force jax to notice.

    jax memoizes ``is_cache_used()`` (module globals ``_cache_checked`` /
    ``_cache_used``) the first time any compile consults the cache, so a
    plain config update after that point is silently ignored.
    ``reset_cache()`` clears the memo.  Returns True iff the flag changed."""
    try:
        if bool(jax.config.jax_enable_compilation_cache) == on:
            return False
        jax.config.update("jax_enable_compilation_cache", on)
    except AttributeError:  # pragma: no cover - very old jax
        return False
    try:
        from jax._src import compilation_cache as _xla_cc

        _xla_cc.reset_cache()
    except Exception:  # pragma: no cover - private API drift
        pass
    return True


def should_warmup(warmup: Optional[bool] = None) -> bool:
    """Resolve the warmup tri-state: explicit flag > ``TEXTBLAST_WARMUP``
    env > backend default (accelerators warm — cold remote compiles
    dominate startup; CPU stays lazy — first-dispatch compiles there are
    cheap and a warm AOT cache makes them cheaper)."""
    if warmup is not None:
        return warmup
    env = os.environ.get("TEXTBLAST_WARMUP", "").lower()
    if env:
        return env not in ("0", "off", "false")
    return jax.default_backend() in ("tpu", "axon")


def maybe_warmup(
    pipeline: "CompiledPipeline", warmup: Optional[bool] = None
) -> Optional[WarmupStats]:
    """Warm ``pipeline`` when the resolved policy says so (see
    :func:`should_warmup`); every runner entry point (streaming,
    checkpointed, multi-host) funnels through this so the AOT executable
    cache is consulted uniformly.  Returns the stats, or None if skipped."""
    if pipeline.fully_host or not pipeline.device_steps:
        METRICS.set("pipeline_warmup_done", 1)
        return None
    if not should_warmup(warmup):
        METRICS.set("pipeline_warmup_done", 1)
        return None
    ws = pipeline.warmup_parallel()
    METRICS.set("pipeline_warmup_done", 1)
    logger.info(
        "warmup: %d programs in %.2fs (trace %.2fs, compile %.2fs, "
        "cache-load %.2fs, %d/%d AOT hits)",
        ws.programs, ws.total_s, ws.trace_s, ws.compile_s,
        ws.cache_load_s, ws.cache_hits, ws.programs,
    )
    if EVENTS.enabled:
        EVENTS.emit("warmup_complete", programs=ws.programs,
                    total_s=round(ws.total_s, 3), cache_hits=ws.cache_hits,
                    cache_misses=ws.cache_misses, compile_s=round(ws.compile_s, 3))
    return ws


class CompiledPipeline:
    """A pipeline config compiled for device execution."""

    def __init__(
        self,
        config: PipelineConfig,
        buckets=DEFAULT_BUCKETS,
        batch_size: Optional[int] = None,
        mesh=None,
        phase_split: bool = True,
        geometry: Optional[DeviceGeometry] = None,
    ) -> None:
        self.config = config
        self.mesh = mesh
        if geometry is not None:
            # Calibrated (or checkpoint-recorded) geometry supersedes the
            # buckets/batch_size knobs; mesh runs need every per-bucket batch
            # divisible by the device count.
            if mesh is not None:
                geometry = geometry.with_batch_multiple(mesh.devices.size)
            self.geometry = geometry
        else:
            bs = tuple(sorted(buckets))
            if not batch_size:  # None or 0 — CLI passes ints through unguarded
                batch_size = default_batch_size(bs)
            if mesh is not None:
                n_dev = mesh.devices.size
                batch_size = max(n_dev, (batch_size // n_dev) * n_dev)
            src = "default" if batch_size == default_batch_size(bs) else "explicit"
            self.geometry = DeviceGeometry.uniform(bs, batch_size, source=src)
        self.buckets = self.geometry.buckets
        # The representative (largest) per-dispatch row count: chunk sizing,
        # host-tail thresholds, and multi-host sharding key off it.
        self.batch_size = self.geometry.max_batch

        steps = list(config.pipeline)
        n_device = 0
        # Badwords tables are resolved ONCE here and carried on the instance:
        # _build_fn may run much later (first batch of a new bucket length),
        # and the on-disk list can have changed or vanished by then — the
        # plan must use exactly the tables this placement decision saw.
        self._badwords_device_tables: Dict[int, object] = {}
        for s in steps:
            if s.type == "C4BadWordsFilter" and _step_on_device_base(s):
                if _badwords_tables(s) is None:  # default language must exist
                    break
                self._badwords_device_tables[n_device] = _badwords_all_tables(s)
            elif not _step_on_device(s):
                break
            n_device += 1
        self.device_steps = steps[:n_device]
        self.host_steps = steps[n_device:]
        # Host-only fallback when un-kerneled steps precede device steps.
        self.fully_host = any(_step_on_device(s) for s in self.host_steps)

        # Documents containing dictionary-segmented scripts (Han/kana/Thai…)
        # are decided by the host oracle whenever a word-table kernel is in
        # the pipeline: the host word splitter now approximates ICU's
        # dictionary segmentation for those scripts (utils/cjk.py), which
        # the kernels' UAX#29-lite run-whole tables cannot express.  Routing
        # is a correctness fallback (counts in worker_host_fallback_total),
        # the same pattern as kernel-table overflows.
        self._route_dict_scripts = any(
            s.type in _WORD_TABLE_STEPS for s in self.device_steps
        )

        # Wire format: accelerator uploads dominate TPU pass time (round-5
        # window: ~0.5 s of a 1.7 s c4 pass was the 32 MB int32 upload at
        # ~65 MB/s), and BMP codepoints fit uint16 exactly.  Rows containing
        # supplementary-plane chars (emoji etc.) are routed to the host
        # oracle instead — decisions stay bit-identical, attribution is the
        # fallback counter.  Meshes keep int32 (multi-host sharding layers
        # are not wire-bound the same way; one format keeps lockstep simple).
        self.wire_u16 = self.mesh is None and _wire_u16()

        # Multi-phase short-circuiting: always on single-controller runs
        # (including single-process meshes — one controller dispatches for
        # every local device, so there is no lockstep problem and the v5e-8
        # north-star config gets the phasing win).  Multi-PROCESS SPMD jobs
        # must dispatch identical program sequences; run_local_shard
        # (parallel/multihost.py) makes that safe by negotiating per-phase
        # round counts over allgather, so phases stay enabled there too.
        # TEXTBLAST_PHASES=off (or phase_split=False) pins the single fused
        # program.
        import os as _os

        if phase_split and _os.environ.get("TEXTBLAST_PHASES") != "off":
            self.phases = _split_phases(self.device_steps)
            # A content-REWRITING step in a non-final phase would make later
            # phases' host-fallback reruns re-run the rewrite on already
            # rewritten content; bit-exactness would then rest on the rewrite
            # being idempotent (plausible, unverified — ADVICE r3).  Only
            # split when every rewriting step sits in the final phase.
            if any(
                self.device_steps[i].type == "C4QualityFilter"
                for ph in self.phases[:-1]
                for i in ph
            ):
                self.phases = [list(range(len(self.device_steps)))]
        else:
            self.phases = [list(range(len(self.device_steps)))]

        self._host_executor = None
        self._host_suffix_executor = None
        self._jitted: Dict[Tuple, Callable] = {}
        self._badwords_steps: Dict[int, object] = {}

        # Degradation ladder state (see _execute_packed): retry the batch ->
        # split it in half -> rerun the docs on the host oracle, with a
        # breaker that abandons the device path for the run after N
        # consecutive batches fell all the way to the host rung.
        rc = getattr(config, "resilience", None) or ResilienceConfig()
        self._retry = RetryPolicy.from_config(rc)
        self._breaker = CircuitBreaker(
            rc.breaker_threshold,
            cooldown_s=getattr(rc, "breaker_cooldown_s", 0.0),
        )
        self._split_retry = rc.split_retry

        # Overlapped host pipeline (see process_chunk): depth of the device
        # in-flight window and the pack-stage thread pool.  Mesh runs stay
        # serial (lockstep dispatch must not reorder across hosts).
        self._overlap = getattr(config, "overlap", None) or OverlapConfig()
        self._pack_pool_obj = None

    def _badwords_host_step(self, idx: int):
        """The real host C4BadWordsFilter for device step ``idx`` — runs only
        on kernel-flagged candidates (shared regex cache + RNG across docs)."""
        if idx not in self._badwords_steps:
            from ..pipeline_builder import build_step

            self._badwords_steps[idx] = build_step(self.device_steps[idx])
        return self._badwords_steps[idx]

    # --- host executors -----------------------------------------------------

    @property
    def host_executor(self):
        if self._host_executor is None:
            self._host_executor = build_pipeline_from_config(self.config)
        return self._host_executor

    @property
    def host_suffix_executor(self):
        if self._host_suffix_executor is None:
            from ..executor import PipelineExecutor
            from ..pipeline_builder import build_step

            self._host_suffix_executor = PipelineExecutor(
                [build_step(s) for s in self.host_steps]
            )
        return self._host_suffix_executor

    # --- device program -----------------------------------------------------

    def _build_fn(self, length: int, phase: int = 0, jit: bool = True) -> Callable:
        max_lines, max_words = _table_sizes(length)
        plans = []
        for i in self.phases[phase]:
            step = self.device_steps[i]
            p = step.params
            if step.type == "LanguageDetectionFilter":
                plans.append(("langid", i, None))
            elif step.type == "GopherQualityFilter":
                stop_words = (
                    p.stop_words if p.stop_words is not None else list(DEFAULT_STOP_WORDS)
                )
                hashes = tuple(sorted({hash_string(w) for w in stop_words}))
                plans.append(("gopher_quality", i, hashes))
            elif step.type == "GopherRepetitionFilter":
                plans.append(
                    (
                        "gopher_rep",
                        i,
                        (
                            tuple(n for n, _ in p.top_n_grams),
                            tuple(n for n, _ in p.dup_n_grams),
                        ),
                    )
                )
            elif step.type == "C4QualityFilter":
                plans.append(
                    (
                        "c4",
                        i,
                        C4Params(
                            split_paragraph=p.split_paragraph,
                            remove_citations=p.remove_citations,
                            filter_no_terminal_punct=p.filter_no_terminal_punct,
                            min_num_sentences=p.min_num_sentences,
                            min_words_per_line=p.min_words_per_line,
                            max_word_length=p.max_word_length,
                            filter_lorem_ipsum=p.filter_lorem_ipsum,
                            filter_javascript=p.filter_javascript,
                            filter_curly_bracket=p.filter_curly_bracket,
                            filter_policy=p.filter_policy,
                        ),
                    )
                )
            elif step.type == "FineWebQualityFilter":
                stop_chars = (
                    tuple(sorted(p.stop_chars))
                    if p.stop_chars is not None
                    else tuple(sorted(DEFAULT_STOP_CHARS))
                )
                plans.append(("fineweb", i, (stop_chars, p.short_line_length)))
            elif step.type == "C4BadWordsFilter":
                plans.append(("badwords", i, self._badwords_device_tables[i]))

        # Mosaic pallas_call has no GSPMD partitioning rule, so multi-device
        # programs run the sort kernels under shard_map over the data axis —
        # the stats entry points take the mesh explicitly (pallas_sort.sort2).
        mesh = self.mesh if self.mesh is not None and self.mesh.devices.size > 1 else None

        # Unit hashes are consumed only by the Gopher steps; phases without
        # them (e.g. the c4+fineweb phase) skip both polynomial-hash scans.
        needs_hashes = any(
            kind in ("gopher_quality", "gopher_rep") for kind, _, _ in plans
        )

        def fn(cps, lengths):
            if self.mesh is not None:
                # Bare pallas_call has no GSPMD rule: tracing under
                # mesh_tracing(mesh) makes every scan kernel dispatch through
                # shard_map over the data axis instead (the pallas_sort.sort2
                # pattern), so mesh programs keep the Pallas scans.  A mesh
                # without a usable data axis still declines to the lax scans.
                from .pallas_scan import mesh_tracing

                with mesh_tracing(self.mesh):
                    return inner(cps, lengths)
            return inner(cps, lengths)

        def inner(cps, lengths):
            if self.wire_u16:
                # Wire is uint16; every kernel computes in int32.  The widen
                # fuses into the first consumer on device.
                cps = cps.astype(jnp.int32)
            out: Dict[str, jax.Array] = {}
            state = {"cps": cps, "lengths": lengths, "st": None}

            def get_structure():
                if state["st"] is None:
                    state["st"] = structure(
                        state["cps"], state["lengths"], with_hashes=needs_hashes
                    )
                return state["st"]

            return _eval_plans(plans, state, out, get_structure, max_lines, max_words)

        def _eval_plans(plans, state, out, get_structure, max_lines, max_words):
            for kind, i, arg in plans:
                if kind == "langid":
                    scores, n_grams = langid_scores(
                        state["cps"], state["lengths"], mesh=mesh
                    )
                    out[f"{i}:scores"] = scores
                    out[f"{i}:n_grams"] = n_grams
                elif kind == "gopher_quality":
                    for k, v in gopher_quality_stats(get_structure(), arg).items():
                        out[f"{i}:{k}"] = v
                elif kind == "gopher_rep":
                    top_ns, dup_ns = arg
                    stats = gopher_rep_stats(
                        get_structure(), top_ns, dup_ns, max_lines, max_words,
                        mesh=mesh,
                    )
                    for k, v in stats.items():
                        out[f"{i}:{k}"] = v
                elif kind == "c4":
                    stats, new_cps, new_lengths = c4_stage(
                        state["cps"], state["lengths"], arg, max_lines, mesh=mesh
                    )
                    for k, v in stats.items():
                        out[f"{i}:{k}"] = v
                    # Downstream steps see the rewritten batch (sequential
                    # pipeline semantics — executor.rs:30-57 analogue).
                    state.update(cps=new_cps, lengths=new_lengths, st=None)
                elif kind == "fineweb":
                    stop_chars, short_len = arg
                    fw = fineweb_stats(
                        get_structure(), stop_chars, max_lines, short_len, mesh=mesh
                    )
                    for k, v in fw.items():
                        out[f"{i}:{k}"] = v
                elif kind == "badwords":
                    per_lang, per_hazard = badwords_matches_multi(
                        state["cps"], state["lengths"], arg
                    )
                    for lang, m in per_lang.items():
                        out[f"{i}:match:{lang}"] = m
                        out[f"{i}:hazard:{lang}"] = per_hazard[lang]
            return out

        if not jit:
            # Raw traceable fn (scan_dispatch_counts traces it under
            # jax.eval_shape to count dispatches without compiling).
            return fn
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.mesh import DATA_AXIS, batch_sharding

            # Outputs must stay data-sharded (leading dim on the data axis,
            # trailing dims replicated): without out_shardings XLA may pick a
            # replicated layout, and the multi-host path reads each process's
            # addressable rows as *its* documents' stats — replication would
            # silently hand every host process-0's rows.
            out_sharding = NamedSharding(self.mesh, PartitionSpec(DATA_AXIS))
            return jax.jit(
                fn,
                in_shardings=(
                    batch_sharding(self.mesh, 2),
                    batch_sharding(self.mesh, 1),
                ),
                out_shardings=out_sharding,
            )
        if jax.default_backend() in ("tpu", "axon"):
            # Each dispatch uploads fresh numpy arrays, so the input buffers
            # are never reused host-side: donating them lets XLA alias the
            # [B, L] codepoint upload into scratch instead of holding both
            # live — with a K-deep in-flight window the biggest buffer would
            # otherwise exist K+1 times.  CPU stays undonated (XLA:CPU often
            # can't use the donation and warns per call).
            return jax.jit(fn, donate_argnums=(0, 1))
        return jax.jit(fn)

    def _fn_for(
        self, length: int, phase: int = 0, rows: Optional[int] = None
    ) -> Callable:
        """Program for one (bucket length, phase) — and, for the ladder's
        split rung, a separate cache entry per non-standard row count:
        ``warmup_parallel`` installs AOT executables fixed to the bucket's
        geometry rows, which a half-sized batch must never hit."""
        if rows is not None and rows != self.geometry.batch_for(length):
            key = (length, phase, rows)
        else:
            key = (length, phase)
        if key not in self._jitted:
            self._jitted[key] = self._build_fn(length, phase)
        return self._jitted[key]

    def scan_dispatch_counts(
        self, length: int, phase: int = 0, rows: Optional[int] = None
    ) -> Dict[str, int]:
        """Per-kind scan dispatch counts for one traced (bucket, phase)
        program: "fused" / "pallas_scan" kernel calls and "lax_scan"
        schedules.  A multi-pass dependency chain (``chain_scan``, the
        ``TEXTBLAST_DEPFUSE`` path) books as ONE "fused" dispatch however
        many passes and groups it carries — the whole point of the chain is
        that its intermediate streams never leave VMEM, so one kernel launch
        is the honest count.  Traces the raw program under
        ``jax.eval_shape`` (no compile, no device execution), so bench's
        BENCH_FUSED / BENCH_DEPFUSE A/Bs can report how many dispatches the
        fused megakernel and the dependency chains removed."""
        from .pallas_scan import count_scan_dispatches

        rows = rows or self.geometry.batch_for(length)
        raw = self._build_fn(length, phase, jit=False)
        wire = jnp.uint16 if self.wire_u16 else jnp.int32
        cps = jax.ShapeDtypeStruct((rows, length), wire)
        lens = jax.ShapeDtypeStruct((rows,), jnp.int32)
        with count_scan_dispatches() as counts:
            jax.eval_shape(raw, cps, lens)
        return dict(counts)

    @staticmethod
    def _split_rows(full: int) -> int:
        """Row count the degradation ladder's split rung packs each half to:
        half the batch, rounded UP to the 8-row sublane tile so the split
        program keeps the (fused) Pallas kernels — ``pallas_scan_ok`` /
        ``fused_scan_ok`` require rows % 8 == 0, and pack_documents already
        pads rows beyond the doc count."""
        from .pallas_sort import ROWS

        half = (full + 1) // 2
        return min(full, ((half + ROWS - 1) // ROWS) * ROWS)

    def _warmup_jobs(self, include_split_rows: bool = True):
        """``(program key, length, phase, rows)`` tuples warmup must cover:
        every (bucket, phase) at geometry rows — plus the degradation
        ladder's half-split row count, which ``_execute_packed`` packs both
        halves to and ``_fn_for`` keys separately.  Without pre-seeding,
        those programs (fused-kernel variants included — the split rows are
        ROWS-aligned via ``_split_rows`` so they trace the same fused path,
        multi-pass ``chain_scan`` chains and all; the depfuse/staged choice
        itself is an env knob, fingerprinted by the AOT cache via
        ``_TRACE_ENV_KNOBS``, so each setting pre-seeds its own executables)
        always compiled cold *mid-incident*, stacking a 15-29 s compile
        stall on top of whatever fault tripped the split."""
        jobs = []
        for length in self.buckets:
            full = self.geometry.batch_for(length)
            variants = [full]
            sub = self._split_rows(full)
            if (
                include_split_rows
                and self._split_retry
                and self.mesh is None
                and sub != full
            ):
                variants.append(sub)
            for phase in range(len(self.phases)):
                for rows in variants:
                    key = (length, phase) if rows == full else (length, phase, rows)
                    jobs.append((key, length, phase, rows))
        return jobs

    def warmup_parallel(
        self,
        max_workers: int = 8,
        aot_cache=None,
        include_split_rows: bool = True,
    ) -> "WarmupStats":
        """Install every warmup program (see ``_warmup_jobs``), cheapest
        source first: serialized AOT executable cache, else trace + compile.

        **AOT cache.**  Each program is first looked up in the serialized
        executable store (``utils.compile_cache.AOTExecutableCache``),
        keyed by geometry + filter-config fingerprints, jax version,
        backend, topology, shape, and the trace-shaping env knobs.  A hit
        deserializes a finished executable — no trace, no lower, no
        compile — so a warm start loads every (bucket, phase) program in
        well under a second instead of the 15-29 s cold path.  Misses are
        compiled and stored back.  ``TEXTBLAST_NO_COMPILE_CACHE=1``
        bypasses both directions; pass ``aot_cache`` to use a specific
        store (bench A/B, tests).

        **Compile pool.**  Tracing is Python (GIL-bound) and happens
        serially up front; XLA compilation releases the GIL — and on the
        remote-tunnel TPU backend happens on the far side — so N in-flight
        compiles cost ~the slowest one instead of the sum (the round-3
        cold bench spent 459 s compiling programs one at a time).

        On accelerator backends each pool thread also fires ONE throwaway
        execution of its program (zero-filled batch): the first dispatch
        pays a load/setup cost the compile does not (round-5 TPU window:
        ``warmup_s`` 97 s vs ``warmup_compile_s`` 25.6 — ~4.8 s x 15
        programs of first-dispatch overhead).  CPU backends skip it: no
        remote load to hide, and a full-batch execution costs real pass
        time.

        Returns a :class:`WarmupStats` breakdown (``float()`` of it is
        total wall seconds).
        """
        import time as _time
        from concurrent.futures import ThreadPoolExecutor
        from threading import Lock

        import numpy as _np

        from ..utils.compile_cache import (
            AOTExecutableCache,
            config_fingerprint,
            program_cache_key,
        )
        from ..utils.profiler import program_cost

        stats = WarmupStats()
        t0 = _time.perf_counter()
        warm_dispatch = self.mesh is None and jax.default_backend() != "cpu"
        wire = jnp.uint16 if self.wire_u16 else jnp.int32
        wire_name = "uint16" if self.wire_u16 else "int32"
        backend = jax.default_backend()
        n_devices = self.mesh.devices.size if self.mesh is not None else 1

        cache = aot_cache if aot_cache is not None else AOTExecutableCache()
        try:
            cfg_fp = config_fingerprint(self.config)
            geo_fp = self.geometry.fingerprint()
        except Exception as e:  # pragma: no cover - exotic config objects
            logger.warning("AOT cache disabled (unfingerprintable): %s", e)
            cache = None

        def cache_key(length, phase, rows):
            return program_cache_key(
                config_fp=cfg_fp,
                geometry_fp=geo_fp,
                backend=backend,
                length=length,
                phase=phase,
                rows=rows,
                wire=wire_name,
                n_devices=n_devices,
                mesh=self.mesh is not None,
            )

        # Serial front half: AOT-cache loads, then traces for the misses.
        to_compile = []  # (key, length, rows, lowered, aot_key)
        loaded = []  # (key, length, rows, compiled) — warm-dispatch only
        for key, length, phase, rows in self._warmup_jobs(include_split_rows):
            if key in self._jitted and not hasattr(self._jitted[key], "lower"):
                continue  # already an installed executable
            stats.programs += 1
            aot_key = None
            if cache is not None:
                aot_key = cache_key(length, phase, rows)
                t = _time.perf_counter()
                compiled = cache.load(aot_key)
                stats.cache_load_s += _time.perf_counter() - t
                if compiled is not None:
                    stats.cache_hits += 1
                    self._jitted[key] = compiled
                    if PROFILER.enabled:
                        # Cost model survives the cache hit: the sidecar
                        # holds the numbers captured at compile time; a
                        # missing sidecar (pre-profiler entry) falls back
                        # to re-analyzing the deserialized executable and
                        # backfills the sidecar for the next warm start.
                        cost = cache.load_cost(aot_key)
                        source = "aot-sidecar"
                        if cost is None:
                            cost = program_cost(compiled)
                            source = "aot-recompute"
                            if cost is not None:
                                cache.store_cost(aot_key, cost)
                        PROFILER.record_program_cost(
                            length, phase, rows, cost, source
                        )
                    if warm_dispatch:
                        loaded.append((key, length, rows, compiled))
                    continue
                stats.cache_misses += 1
            fn = self._fn_for(length, phase, rows=rows)
            cps = jax.ShapeDtypeStruct((rows, length), wire)
            lens = jax.ShapeDtypeStruct((rows,), jnp.int32)
            t = _time.perf_counter()
            lowered = fn.lower(cps, lens)
            stats.trace_s += _time.perf_counter() - t
            to_compile.append((key, length, rows, lowered, aot_key))

        lock = Lock()

        def dispatch_zero(compiled, length, rows):
            wire_np = _np.uint16 if self.wire_u16 else _np.int32
            z = jnp.asarray(_np.zeros((rows, length), dtype=wire_np))
            zl = jnp.asarray(_np.zeros((rows,), dtype=_np.int32))
            jax.block_until_ready(compiled(z, zl))

        def compile_one(item):
            # The remote-tunnel compile service drops connections under load
            # ("response body closed before all bytes were read" killed the
            # first round-5 TPU bench run outright).  A transient transport
            # failure must cost a retry, not the benchmark: back off and
            # re-issue the compile; the lowered IR is reusable.  Genuine
            # compile errors (shape/VMEM) repeat identically and surface on
            # the final attempt.
            key, length, rows, lowered, aot_key = item
            last = None
            t = _time.perf_counter()
            for attempt in range(4):
                try:
                    compiled = lowered.compile()
                    break
                except Exception as e:  # noqa: BLE001
                    last = e
                    if attempt < 3:
                        _time.sleep(2.0 * (attempt + 1))
            else:
                raise last
            with lock:
                stats.compile_s += _time.perf_counter() - t
            if PROFILER.enabled:
                cost = program_cost(compiled)
                PROFILER.record_program_cost(
                    key[0], key[1], rows, cost, "compile"
                )
                if cache is not None and aot_key is not None and cost:
                    cache.store_cost(aot_key, cost)
            if cache is not None and aot_key is not None:
                if cache.store(aot_key, compiled):
                    with lock:
                        stats.cache_stores += 1
            if warm_dispatch:
                dispatch_zero(compiled, length, rows)
            return key, compiled

        def load_one(item):
            key, length, rows, compiled = item
            dispatch_zero(compiled, length, rows)

        # Compiles that will be stored must NOT be served by XLA's own
        # persistent compilation cache: cache-served executables serialize
        # without their kernel object code (deserialize fails "Symbols not
        # found" on XLA:CPU), so the AOT store would fill with entries every
        # future process evicts.  Flipping the enable flag alone is not
        # enough — jax memoizes is_cache_used() at first compile — so the
        # memo must be reset around the toggle.  Nothing else compiles
        # during warmup; everything is restored before the first dispatch.
        xla_cache_disabled = False
        if cache is not None and to_compile:
            xla_cache_disabled = _toggle_xla_compilation_cache(False)
        try:
            with ThreadPoolExecutor(max_workers=max_workers) as pool:
                if loaded:
                    list(pool.map(load_one, loaded))
                for key, compiled in pool.map(compile_one, to_compile):
                    self._jitted[key] = compiled
        finally:
            if xla_cache_disabled:
                _toggle_xla_compilation_cache(True)
        stats.total_s = _time.perf_counter() - t0
        return stats

    def register_installed_costs(
        self, include_split_rows: bool = True
    ) -> int:
        """Re-register the installed executables' static cost models with
        the PROFILER — for observers armed AFTER warmup (bench A/B,
        tests): the warmup seams only capture when profiling was on at
        compile/load time, and a second ``warmup_parallel`` skips programs
        that are already installed.  Returns the number registered."""
        from ..utils.profiler import program_cost

        n = 0
        for key, length, phase, rows in self._warmup_jobs(
            include_split_rows
        ):
            fn = self._jitted.get(key)
            if fn is None or hasattr(fn, "lower"):
                continue  # missing, or still a jitted wrapper (no analysis)
            cost = program_cost(fn)
            if cost:
                PROFILER.record_program_cost(
                    length, phase, rows, cost, "installed"
                )
                n += 1
        return n

    # --- host finalizers ----------------------------------------------------
    #
    # Threshold logic is evaluated ONCE per batch in vectorized numpy (float64
    # ratios from the device's integer stats — identical arithmetic to the
    # oracle filters'); per-row Python runs only to format reason strings and
    # stamps for rows that need them.  The per-batch eval objects carry:
    #   passed [B] bool   — step verdict per row (badwords: provisional)
    #   overflow [B] bool — row hit a kernel table bound (host-oracle rerun)
    #   pass_stamps       — constant stamps for passing rows (None: per-row)
    #   decide(row, doc)  — full _Decision (fail rows / per-row-stamp steps)

    def _eval_step(self, step: StepConfig, idx: int, stats: Dict[str, np.ndarray]):
        try:
            fn = _EVALS[step.type]
        except KeyError:
            raise PipelineError(f"no finalizer for step {step.type}") from None
        return fn(self, step, idx, stats)

    def _eval_langid(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        scores = np.asarray(stats[f"{idx}:scores"])
        n_grams = np.asarray(stats[f"{idx}:n_grams"]).astype(np.int64)
        best, conf = LangIdModel.decide_batch(scores, n_grams)

        valid = n_grams > 0
        allowed = [c for c in p.allowed_languages if c in ISO_TO_NAME]
        lang_allowed = np.array(
            [NAME_TO_ISO[lang] in allowed for lang in LANGUAGES], dtype=bool
        )[best]
        conf_ok = conf >= p.min_confidence
        passed = valid & lang_allowed & conf_ok
        joined = "; ".join(allowed)

        def decide(row: int, doc: TextDocument) -> _Decision:
            if not valid[row]:
                return _Decision(False, "Language could not be confidently detected")
            stamps = [
                ("Detected language", LANGUAGES[best[row]]),
                ("Detected language confidence", rust_float(conf[row])),
            ]
            if not lang_allowed[row]:
                return _Decision(
                    False,
                    f'Document is not any of the following languages: "{joined}"',
                    stamps,
                )
            if not conf_ok[row]:
                return _Decision(
                    False,
                    "Language detection confidence is not satified: "
                    f"{rust_float(conf[row])} < {rust_float(p.min_confidence)}",
                    stamps,
                )
            return _Decision(True, stamps=stamps)

        # Langid stamps are per-row even on pass (detected language + conf),
        # but their values come straight from the batch arrays: a stamp
        # function (vectorized language-name take, same rust_float formatting
        # as decide) lets passing rows skip decide() entirely.
        ev = _StepEval(passed=passed, decide=decide, pass_stamps=None)
        lang_names = np.asarray(LANGUAGES, dtype=object)[best]

        def pass_stamp_fn(row: int, doc: TextDocument) -> None:
            doc.metadata["Detected language"] = lang_names[row]
            doc.metadata["Detected language confidence"] = rust_float(conf[row])

        ev.pass_stamp_fn = pass_stamp_fn
        return ev

    def _eval_gopher_rep(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        g = lambda key: np.asarray(stats[f"{idx}:{key}"]).astype(np.int64)  # noqa: E731
        overflow = np.asarray(stats[f"{idx}:seg_overflow"], dtype=bool) | np.asarray(
            stats[f"{idx}:word_overflow"], dtype=bool
        )
        trimmed = g("trimmed_len")
        empty = trimmed == 0
        char_len = np.maximum(trimmed, 1).astype(np.float64)

        # (cond [B], ratio [B], reason template parts) per check, in the
        # oracle's check order.
        checks = []

        def add(cond, ratio, label, thr):
            checks.append((cond, ratio, label, thr))

        ratio = g("para_dup_elems") / np.maximum(g("n_paragraphs"), 1)
        if p.dup_para_frac is not None:
            add(ratio > p.dup_para_frac, ratio, "dup_para_frac", p.dup_para_frac)
        ratio = g("para_dup_bytes") / char_len
        if p.dup_para_char_frac is not None:
            add(
                ratio > p.dup_para_char_frac,
                ratio,
                "dup_para_char_frac",
                p.dup_para_char_frac,
            )
        ratio = g("line_dup_elems") / np.maximum(g("n_lines"), 1)
        if p.dup_line_frac is not None:
            add(ratio > p.dup_line_frac, ratio, "dup_line_frac", p.dup_line_frac)
        ratio = g("line_dup_bytes") / char_len
        if p.dup_line_char_frac is not None:
            add(
                ratio > p.dup_line_char_frac,
                ratio,
                "dup_line_char_frac",
                p.dup_line_char_frac,
            )
        for n, thr in p.top_n_grams:
            if n > 0:
                ratio = g(f"top_{n}") / char_len
                add(ratio > thr, ratio, f"top_{n}_gram", thr)
        for n, thr in p.dup_n_grams:
            if n > 0:
                ratio = g(f"dup_{n}") / char_len
                add(ratio > thr, ratio, f"duplicated_{n}_n_grams", thr)

        any_cond = empty.copy()
        for cond, _, _, _ in checks:
            any_cond |= cond
        passed = ~any_cond

        def decide(row: int, doc: TextDocument) -> _Decision:
            if empty[row]:
                return _Decision(
                    False,
                    "skipping empty content",
                    [
                        ("gopher_repetition_filter_status", "filtered"),
                        ("gopher_repetition_filter_reason", "skipping empty content"),
                    ],
                )
            reasons = [
                f"{label} (ratio {fmt2(ratio[row])}, max {fmt2(thr)})"
                for cond, ratio, label, thr in checks
                if cond[row]
            ]
            rs = "; ".join(reasons)
            return _Decision(
                False,
                rs,
                [
                    ("gopher_repetition_filter_status", "filtered"),
                    ("gopher_repetition_filter_reasons", rs),
                ],
            )

        return _StepEval(
            passed=passed,
            overflow=overflow,
            decide=decide,
            pass_stamps=(("gopher_repetition_filter_status", "passed"),),
        )

    def _eval_gopher_quality(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        g = lambda key: np.asarray(stats[f"{idx}:{key}"]).astype(np.int64)  # noqa: E731
        n_non_symbol = g("n_non_symbol")
        n_words = g("n_words")
        sum_len = g("sum_word_len")
        avg = np.zeros(len(n_words), dtype=np.float64)
        np.divide(sum_len, n_non_symbol, out=avg, where=n_non_symbol > 0)
        n_total = np.maximum(n_words, 1).astype(np.float64)
        hash_ratio = g("hash_count") / n_total
        ellipsis_ratio = g("ellipsis_units") / n_total
        n_lines_f = np.maximum(g("n_lines"), 1).astype(np.float64)
        bullet_ratio = g("bullet_lines") / n_lines_f
        ell_lines_ratio = g("ellipsis_lines") / n_lines_f
        alpha_ratio = g("alpha_words") / n_total
        stop_count = g("stop_words")

        # (cond [B], reason_fn(row) -> str) in the oracle's check order.
        checks = []
        if p.min_doc_words is not None:
            checks.append(
                (
                    n_non_symbol < p.min_doc_words,
                    lambda r: f"gopher_short_doc ({n_non_symbol[r]} non-symbol words, "
                    f"required {p.min_doc_words})",
                )
            )
        if p.max_doc_words is not None:
            checks.append(
                (
                    n_non_symbol > p.max_doc_words,
                    lambda r: f"gopher_long_doc ({n_non_symbol[r]} non-symbol words, "
                    f"max {p.max_doc_words})",
                )
            )
        if p.min_avg_word_length is not None:

            def _below_avg(r: int) -> str:
                suffix = (
                    " - 0 non-symbol words"
                    if n_non_symbol[r] == 0 and p.min_avg_word_length > 0.0
                    else ""
                )
                return (
                    f"gopher_below_avg_threshold (avg len {fmt2(avg[r])}, "
                    f"required {fmt2(p.min_avg_word_length)}{suffix})"
                )

            checks.append((avg < p.min_avg_word_length, _below_avg))
        if p.max_avg_word_length is not None:
            checks.append(
                (
                    (n_non_symbol > 0) & (avg > p.max_avg_word_length),
                    lambda r: f"gopher_above_avg_threshold (avg len {fmt2(avg[r])}, "
                    f"max {fmt2(p.max_avg_word_length)})",
                )
            )
        if p.max_symbol_word_ratio is not None:
            checks.append(
                (
                    hash_ratio > p.max_symbol_word_ratio,
                    lambda r: f"gopher_too_many_hashes (ratio {fmt2(hash_ratio[r])}, "
                    f"max {fmt2(p.max_symbol_word_ratio)})",
                )
            )
            checks.append(
                (
                    ellipsis_ratio > p.max_symbol_word_ratio,
                    lambda r: "gopher_too_many_ellipsis_units "
                    f"(ratio {fmt2(ellipsis_ratio[r])}, "
                    f"max {fmt2(p.max_symbol_word_ratio)})",
                )
            )
        if p.max_bullet_lines_ratio is not None:
            checks.append(
                (
                    bullet_ratio > p.max_bullet_lines_ratio,
                    lambda r: f"gopher_too_many_bullets (ratio {fmt2(bullet_ratio[r])}, "
                    f"max {fmt2(p.max_bullet_lines_ratio)})",
                )
            )
        if p.max_ellipsis_lines_ratio is not None:
            checks.append(
                (
                    ell_lines_ratio > p.max_ellipsis_lines_ratio,
                    lambda r: "gopher_too_many_end_ellipsis_lines "
                    f"(ratio {fmt2(ell_lines_ratio[r])}, "
                    f"max {fmt2(p.max_ellipsis_lines_ratio)})",
                )
            )
        if p.max_non_alpha_words_ratio is not None:
            checks.append(
                (
                    alpha_ratio < p.max_non_alpha_words_ratio,
                    lambda r: "gopher_below_alpha_threshold "
                    f"(alpha ratio {fmt2(alpha_ratio[r])}, "
                    f"required min {fmt2(p.max_non_alpha_words_ratio)})",
                )
            )
        if p.min_stop_words is not None and p.min_stop_words > 0:
            checks.append(
                (
                    stop_count < p.min_stop_words,
                    lambda r: f"gopher_too_few_stop_words (found {stop_count[r]}, "
                    f"required {p.min_stop_words})",
                )
            )

        any_cond = np.zeros(len(n_words), dtype=bool)
        for cond, _ in checks:
            any_cond |= cond
        passed = ~any_cond

        def decide(row: int, doc: TextDocument) -> _Decision:
            rs = "; ".join(fn(row) for cond, fn in checks if cond[row])
            return _Decision(
                False,
                rs,
                [
                    ("gopher_quality_filter_status", "filtered"),
                    ("gopher_quality_filter_reasons", rs),
                ],
            )

        return _StepEval(
            passed=passed,
            decide=decide,
            pass_stamps=(("gopher_quality_filter_status", "passed"),),
        )

    def _eval_c4(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        overflow = np.asarray(stats[f"{idx}:line_overflow"], dtype=bool)
        rewrite_identity = np.asarray(stats[f"{idx}:rewrite_identity"], dtype=bool)
        lorem = np.asarray(stats[f"{idx}:has_lorem"], dtype=bool)
        curly = np.asarray(stats[f"{idx}:has_curly"], dtype=bool)
        early = lorem | curly
        n_sent = np.asarray(stats[f"{idx}:n_sentences"]).astype(np.int64)
        n_lines = np.asarray(stats[f"{idx}:n_lines"]).astype(np.int64)
        line_keep = np.asarray(stats[f"{idx}:line_keep"])
        drops = [
            (np.asarray(stats[f"{idx}:{key}"]).astype(np.int64), name)
            for key, name in (
                ("drop_too_long", "line-filter-too_long_word"),
                ("drop_no_term", "line-filter-no_terminal_punc"),
                ("drop_few_words", "line-filter-too_few_words"),
            )
        ]
        few_sent = (
            (n_sent < p.min_num_sentences)
            if p.min_num_sentences > 0
            else np.zeros(len(n_sent), dtype=bool)
        )
        passed = ~early & ~few_sent

        def decide(row: int, doc: TextDocument) -> _Decision:
            if early[row]:
                reasons = []
                if lorem[row]:
                    reasons.append("lorem_ipsum")
                if curly[row]:
                    reasons.append("curly_bracket")
                rs = "; ".join(reasons)
                return _Decision(
                    False,
                    rs,
                    [("c4_filter_status", "filtered"), ("c4_filter_reasons", rs)],
                    extra={"rewrite": False},
                )
            rs = (
                f"too_few_sentences (found {n_sent[row]}, "
                f"required {p.min_num_sentences})"
            )
            stamps = [("c4_filter_status", "filtered"), ("c4_filter_reasons", rs)]
            stamps += [(name, str(c[row])) for c, name in drops if c[row] > 0]
            return _Decision(
                False,
                rs,
                stamps,
                extra={
                    "rewrite": not rewrite_identity[row],
                    "keep_mask": line_keep[row][: n_lines[row]],
                },
            )

        ev = _StepEval(
            passed=passed,
            overflow=overflow,
            decide=decide,
            pass_stamps=(("c4_filter_status", "passed"),),
        )
        ev.c4_line_keep = line_keep
        ev.c4_n_lines = n_lines
        ev.c4_rewrite_identity = rewrite_identity
        return ev

    def _eval_badwords(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        matches = {
            lang: np.asarray(stats[f"{idx}:match:{lang}"], dtype=bool)
            for lang in self._badwords_device_tables.get(idx, {})
        }
        hazards = {
            lang: np.asarray(stats[f"{idx}:hazard:{lang}"], dtype=bool)
            for lang in self._badwords_device_tables.get(idx, {})
        }

        def decide(row: int, doc: TextDocument) -> _Decision:
            # The device kernel delivers the regex-match verdict for every
            # language with local tables (ops/badwords.py — a spurious match
            # needs a double 32-bit hash collision, ~2^-64).  Matched docs
            # only draw the keep fraction here; docs in uncompiled languages
            # run the full host filter (download / passed_no_regex /
            # fail_on_missing_language, c4_filters.rs:456-552).  Seeded
            # keep-fraction draws are per-document (hash of seed + doc id),
            # independent of batch order (filters/c4_badwords.py RNG note).
            from ..errors import DocumentFiltered

            host_step = self._badwords_host_step(idx)
            doc_lang = doc.metadata.get("language", p.default_language)
            m = matches.get(doc_lang)
            if m is not None and hazards[doc_lang][row]:
                # Observability: host-regex re-decisions for fold-hazard
                # rows are host-path work (one regex search, not a full
                # pipeline rerun) — counted under their own name so bench
                # honesty metrics stay complete.
                METRICS.inc("worker_fold_hazard_rows_total")
            if m is None or hazards[doc_lang][row]:
                # Uncompiled language, or the row contains a codepoint whose
                # IGNORECASE folding this language's table cannot express
                # (ops/badwords.py module docstring) — the host regex decides.
                try:
                    host_step.process(doc)  # stamps metadata itself
                except DocumentFiltered as e:
                    return _Decision(False, e.reason)
                return _Decision(True)
            if not m[row]:
                doc.metadata["c4_badwords_filter_status"] = "passed"
                return _Decision(True)
            if (
                p.keep_fraction > 0.0
                and host_step._keep_draw(doc.id) < p.keep_fraction
            ):
                doc.metadata["c4_badwords_filter_status"] = "passed_kept_by_fraction"
                return _Decision(True)
            reason = "document_removed_with_badwords"
            doc.metadata["c4_badwords_filter_status"] = "filtered"
            doc.metadata["c4_badwords_filter_reason"] = reason
            return _Decision(False, reason)

        # passed is never consulted for badwords evals: _assemble_row's
        # badwords branch short-circuits on badwords_matches before the
        # generic ev.passed path.
        ev = _StepEval(passed=None, decide=decide, pass_stamps=None)
        ev.badwords_matches = matches
        ev.badwords_default_language = p.default_language
        ev.badwords_fold_hazard = hazards
        return ev

    def _eval_fineweb(self, step: StepConfig, idx: int, stats) -> "_StepEval":
        p = step.params
        overflow = np.asarray(stats[f"{idx}:line_overflow"], dtype=bool)
        g = lambda key: np.asarray(stats[f"{idx}:{key}"]).astype(np.int64)  # noqa: E731
        n_lines = g("n_nonblank_lines")
        empty = n_lines == 0
        nl_f = np.maximum(n_lines, 1).astype(np.float64)
        punct_ratio = g("lines_ending_stop") / nl_f
        punct_fail = (punct_ratio < p.line_punct_thr) & ~(
            (punct_ratio == 0.0) & p.line_punct_exclude_zero
        )
        short_ratio = g("short_lines") / nl_f
        short_fail = short_ratio > p.short_line_thr
        total_chars = g("total_chars_no_newline")
        dup_ratio = np.zeros(len(n_lines), dtype=np.float64)
        np.divide(g("dup_line_bytes"), total_chars, out=dup_ratio, where=total_chars > 0)
        dup_fail = dup_ratio > p.char_duplicates_ratio
        n_words = g("n_words")
        newlines = g("newline_count")
        list_ratio = np.zeros(len(n_lines), dtype=np.float64)
        np.divide(newlines, n_words, out=list_ratio, where=n_words > 0)
        list_fail = np.where(
            n_words == 0, newlines > 0, list_ratio > p.new_line_ratio
        )
        passed = ~(empty | punct_fail | short_fail | dup_fail | list_fail)

        def decide(row: int, doc: TextDocument) -> _Decision:
            def fail(reason, outcome_reason=""):
                return _Decision(
                    False,
                    outcome_reason or reason,
                    [
                        ("fineweb_filter_status", "filtered"),
                        ("fineweb_filter_reason", reason),
                    ],
                )

            # First failing check wins (fineweb_quality.rs check order).
            if empty[row]:
                return fail("empty document", outcome_reason="empty")
            if punct_fail[row]:
                return fail(
                    f"line_punct_ratio: {fmt4(punct_ratio[row])} < threshold "
                    f"{fmt4(p.line_punct_thr)} (exclude_zero: "
                    f"{rust_bool(p.line_punct_exclude_zero)})"
                )
            if short_fail[row]:
                return fail(
                    f"short_line_ratio: {fmt4(short_ratio[row])} > threshold "
                    f"{fmt4(p.short_line_thr)}"
                )
            if dup_fail[row]:
                return fail(
                    f"char_dup_ratio: {fmt4(dup_ratio[row])} > threshold "
                    f"{fmt4(p.char_duplicates_ratio)}"
                )
            if n_words[row] == 0:
                return fail("list_ratio_no_words (newlines present but no words)")
            return fail(
                f"list_ratio: {fmt4(list_ratio[row])} > threshold "
                f"{fmt4(p.new_line_ratio)}"
            )

        return _StepEval(passed=passed, overflow=overflow, decide=decide, pass_stamps=())

    # --- batch processing ---------------------------------------------------

    def _rewrite_c4(self, doc: TextDocument, step: StepConfig, keep_mask) -> None:
        """Apply the device line-keep mask to rebuild C4's rewritten content —
        the string half of c4_filters.rs:192-258; decisions came from device.
        Units are lines (split_paragraph) or sentences (c4_filters.rs:150-156)."""
        if step.params.split_paragraph:
            lines = rust_lines(doc.content)
        else:
            from ..utils.text import split_into_sentences

            lines = split_into_sentences(doc.content)
        n = len(keep_mask)
        if step.params.remove_citations:
            # CITATION_RE can only match where a '[' exists — skip the regex
            # for the (overwhelmingly common) bracket-free lines.
            kept = [
                CITATION_RE.sub("", s) if "[" in s else s
                for i, line in enumerate(lines)
                if i < n and keep_mask[i]
                for s in (line.strip(),)
            ]
        else:
            kept = [
                line.strip() for i, line in enumerate(lines) if i < n and keep_mask[i]
            ]
        doc.content = "\n".join(kept).strip()

    def dispatch_batch(
        self, batch: PackedBatch, phase: int = 0
    ) -> Dict[str, jax.Array]:
        """Launch the compiled program for a batch and return the on-device
        stats WITHOUT blocking (JAX async dispatch) — the caller overlaps the
        previous batch's host-side assembly with this batch's device compute
        (the double-buffered feed SURVEY.md §2.5 maps prefetch/QoS onto)."""
        if WATCHDOG.enabled:
            # Beat scope lets an injected device hang (chaos kind "hang")
            # be rescued by the stage deadline on this thread; disabled,
            # the seam pays exactly this one attribute check.
            with WATCHDOG.stage_beat("device_fetch"):
                FAULTS.fire("device.execute")
        else:
            FAULTS.fire("device.execute")
        record_occupancy(batch)
        if TELEMETRY.enabled:
            TELEMETRY.mark("dispatch", (d.id for d in batch.docs))
        with TRACER.span(
            "device_dispatch",
            {"bucket": batch.max_len, "rows": batch.batch_size,
             "phase": phase},
        ):
            fn = self._fn_for(batch.max_len, phase, rows=batch.batch_size)
            if self.mesh is not None:
                from ..parallel.mesh import shard_batch

                cps, lengths = shard_batch(
                    self.mesh, batch.cps, batch.lengths
                )
            else:
                cps, lengths = batch.cps, batch.lengths
                if self.wire_u16:
                    # Astral rows were routed to the host oracle upstream
                    # (process_chunk); a slip here would truncate silently,
                    # so guard with one cheap vectorized check.
                    if int(cps.max(initial=0)) >= 0x10000:
                        raise RuntimeError(
                            "astral codepoint reached the uint16 wire — "
                            "routing invariant broken"
                        )
                    cps = cps.astype(np.uint16)
            return fn(cps, lengths)

    def dispatch_lockstep(
        self, batch: PackedBatch, phase: int, sharding2, sharding1
    ) -> Dict[str, jax.Array]:
        """Launch one multi-host lockstep round (async) from this process's
        local rows of the global batch.

        The multi-host analogue of :meth:`dispatch_batch`: the fault seam the
        negotiated guard wraps (``FAULTS`` site ``"multihost.round"`` fires
        here, so chaos tests can fail the launch on one host only), but the
        arrays are assembled per-process (``make_array_from_process_local_data``
        against the caller's global shardings) and occupancy is NOT recorded —
        the caller records it once per round so negotiated re-dispatches don't
        skew the telemetry.  ``batch`` is any pre-packed ``PackedBatch`` —
        the lockstep window packs rounds ahead on the shared pack pool and
        hands the resolved batches here, so this seam must stay pack-free.

        Fires ``"device.execute"`` too (the same device-dispatch seam as
        :meth:`dispatch_batch`), so hang chaos armed on the device seam
        lands on the lockstep path as well and escalates through the
        negotiated local-fault verdict."""
        if WATCHDOG.enabled:
            with WATCHDOG.stage_beat("device_fetch"):
                FAULTS.fire("device.execute")
                FAULTS.fire("multihost.round")
        else:
            FAULTS.fire("device.execute")
            FAULTS.fire("multihost.round")
        if TELEMETRY.enabled:
            TELEMETRY.mark("dispatch", (d.id for d in batch.docs))
        with TRACER.span(
            "device_dispatch",
            {"bucket": batch.max_len, "rows": batch.batch_size,
             "phase": phase, "lockstep": True},
        ):
            fn = self._fn_for(batch.max_len, phase)
            g_cps = jax.make_array_from_process_local_data(
                sharding2, batch.cps
            )
            g_len = jax.make_array_from_process_local_data(
                sharding1, batch.lengths
            )
            return fn(g_cps, g_len)

    # --- degradation ladder -------------------------------------------------

    def _device_fetch(
        self, batch: PackedBatch, phase: int, inflight=None
    ) -> Dict[str, np.ndarray]:
        """Dispatch + transfer for one batch under the device RetryPolicy.

        ``inflight`` is an already-dispatched stats tree (the overlap path):
        the first attempt only has to fetch it; every re-attempt re-dispatches
        from scratch.  Returns host-side numpy stats (``jax.device_get`` on
        numpy is identity, so ``assemble_phase`` takes them unchanged).
        """
        import time

        first = [inflight]

        def attempt() -> Dict[str, np.ndarray]:
            stats = first[0]
            first[0] = None
            if stats is None:
                stats = self.dispatch_batch(batch, phase)
            if TELEMETRY.enabled:
                TELEMETRY.mark("device_wait", (d.id for d in batch.docs))
            if WATCHDOG.enabled:
                # Deadline-bounded readiness poll so the blocking
                # device_get below cannot wedge this rank; a StallError
                # here enters the same retry → ladder path as a raised
                # device fault.
                WATCHDOG.wait_device_ready(
                    "device_fetch", jax.tree_util.tree_leaves(stats)
                )
            t0 = time.perf_counter()
            try:
                with TRACER.span(
                    "device_wait",
                    {"bucket": batch.max_len, "phase": phase},
                ) as sp:
                    out = jax.device_get(stats)
                    if PROFILER.enabled:
                        # Duration must be taken inside the span: the event
                        # is emitted at __exit__, so args attached later
                        # would miss the trace.
                        sp.add_args(
                            PROFILER.record_dispatch(
                                batch.max_len,
                                phase,
                                batch.batch_size,
                                time.perf_counter() - t0,
                            )
                        )
                    return out
            finally:
                # Time blocked on device results (transfer + any compute not
                # yet finished).  Identity-fast for already-numpy stats, so
                # re-attempts after a host-side fetch don't double-count.
                METRICS.inc(
                    "stage_device_wait_seconds", time.perf_counter() - t0
                )

        return self._retry.run(attempt, seam="device")

    def _host_rerun(self, docs: List[TextDocument]) -> List[ProcessingOutcome]:
        """Bottom rung: the full host-oracle pipeline, bit-identical to the
        device path by the same contract the overflow fallback relies on
        (docs are re-stamped identically even mid-phase)."""
        outcomes: List[ProcessingOutcome] = []
        for doc in docs:
            METRICS.inc("resilience_ladder_host_total")
            outcome = execute_processing_pipeline(self.host_executor, doc)
            if outcome is not None:
                outcomes.append(outcome)
        return outcomes

    def _execute_packed(
        self, batch: PackedBatch, phase: int, inflight=None
    ) -> Tuple[List[ProcessingOutcome], List[TextDocument]]:
        """One packed batch through the degradation ladder.

        Rungs: (1) retry the whole batch under the device RetryPolicy;
        (2) split it in half and retry each half — OOM recovery, and a
        bisection that saves the healthy half of a poisoned batch; (3) rerun
        the documents on the host oracle.  Deterministic errors (fatal per
        the classifier) propagate immediately — the ladder only absorbs
        transient device faults.  The circuit breaker counts batches that
        fell to the host rung; once tripped, the run stays on the host
        backend (no more device dispatches to time out on) until the
        half-open cooldown grants a probe.
        """
        if inflight is _BREAKER_OPEN or (
            inflight is None and not self._breaker.allow_request()
        ):
            # The breaker refused the dispatch (window sentinel) or refuses
            # the re-dispatch now: host rung, with no breaker recording —
            # the device was never asked.
            return self._host_rerun(batch.docs), []
        try:
            stats = self._device_fetch(batch, phase, inflight)
        except RetryExhaustedError:
            pass  # descend the ladder below
        else:
            self._breaker.record_success()
            return self.assemble_phase(batch, stats, phase)

        fell_to_host = False
        outcomes: List[ProcessingOutcome] = []
        survivors: List[TextDocument] = []
        if self._split_retry and self.mesh is None and len(batch.docs) > 1:
            # Split rung.  Both halves pack to the same padded row count so
            # they share one traced program shape (a fresh jit entry — the
            # warmup's AOT executables are fixed to the full batch size).
            METRICS.inc("resilience_ladder_split_total")
            TRACER.instant(
                "ladder_split", {"bucket": batch.max_len, "phase": phase}
            )
            if EVENTS.enabled:
                EVENTS.emit("ladder_split", batch=batch.max_len,
                            depth=len(batch.docs), phase=phase)
            sub_rows = self._split_rows(batch.batch_size)
            mid = (len(batch.docs) + 1) // 2
            for part in (batch.docs[:mid], batch.docs[mid:]):
                if not part:
                    continue
                sub = pack_documents(part, sub_rows, batch.max_len)
                try:
                    stats = self._device_fetch(sub, phase)
                except RetryExhaustedError:
                    fell_to_host = True
                    outcomes.extend(self._host_rerun(part))
                else:
                    o, s = self.assemble_phase(sub, stats, phase)
                    outcomes.extend(o)
                    survivors.extend(s)
        else:
            fell_to_host = True
            outcomes.extend(self._host_rerun(batch.docs))

        if fell_to_host:
            TRACER.instant(
                "ladder_host", {"bucket": batch.max_len, "phase": phase}
            )
            if EVENTS.enabled:
                EVENTS.emit("ladder_host", batch=batch.max_len, phase=phase)
            self._breaker.record_failure("device batch fell to host rung")
        else:
            self._breaker.record_success()
        return outcomes, survivors

    def assemble_phase(
        self,
        batch: PackedBatch,
        device_stats: Dict[str, jax.Array],
        phase: int = 0,
    ) -> Tuple[List[ProcessingOutcome], List[TextDocument]]:
        """Blocking half for one phase: transfer stats, resolve
        order/short-circuit/reason strings per document.

        Returns ``(outcomes, survivors)``: outcomes are final (filtered docs,
        host-fallback reruns, and — on the last phase — passes); survivors
        are documents that passed a non-final phase and continue to the next.
        """
        if TELEMETRY.enabled:
            TELEMETRY.mark("assemble", (d.id for d in batch.docs))
        # ONE bundled transfer: on the remote-tunnel TPU backend each per-key
        # np.asarray is its own synchronous round trip (~0.7s/key measured,
        # 48 keys = 35s/batch); jax.device_get moves the whole tree in one
        # call (93ms measured for the same batch).
        stats = jax.device_get(device_stats)
        # Rows where any step hit a kernel table bound rerun the host oracle.
        # Phase-boundary note: a doc overflowing in a later phase carries the
        # earlier phases' metadata stamps; the full-pipeline host rerun
        # re-stamps the identical values (device/host stamp parity), so the
        # outcome is still bit-identical to a pure host run.
        n_rows = len(batch.docs)
        step_ids = self.phases[phase]
        evals = [
            (self.device_steps[i], self._eval_step(self.device_steps[i], i, stats))
            for i in step_ids
        ]
        overflow_any = np.zeros(n_rows, dtype=bool)
        for _, ev in evals:
            if ev.overflow is not None:
                overflow_any |= ev.overflow[:n_rows]
        last = phase == len(self.phases) - 1
        outcomes: List[ProcessingOutcome] = []
        survivors: List[TextDocument] = []
        # Vectorized pass-row fast path: one batch-level AND of every step's
        # verdict finds the rows that pass the whole phase; their only side
        # effects are metadata pass-stamps (constant, or per-row via a
        # batch-precomputed stamp function), so they skip the per-row
        # decide() walk.  Rows that fail, overflow, need a non-identity C4
        # rewrite, or hit a step without a batch verdict (badwords: the
        # doc's language is only known per row) keep the per-row path.
        fast_mask = None
        if n_rows:
            fast_mask = ~overflow_any
            for _, ev in evals:
                if ev.passed is None or (
                    ev.pass_stamps is None and ev.pass_stamp_fn is None
                ):
                    fast_mask = None
                    break
                fast_mask &= ev.passed[:n_rows]
                if ev.c4_line_keep is not None:
                    fast_mask &= ev.c4_rewrite_identity[:n_rows]
        for row, doc in enumerate(batch.docs):
            if fast_mask is not None and fast_mask[row]:
                # Passed every step: stamp in step order, exactly what
                # _assemble_row's pass branches would have written.
                for _, ev in evals:
                    if ev.pass_stamps is not None:
                        for k, v in ev.pass_stamps:
                            doc.metadata[k] = v
                    else:
                        ev.pass_stamp_fn(row, doc)
                if not last:
                    survivors.append(doc)
                    continue
                if self.host_steps:
                    outcome = execute_processing_pipeline(
                        self.host_suffix_executor, doc
                    )
                else:
                    outcome = ProcessingOutcome.success(doc)
            elif overflow_any[row]:
                METRICS.inc("worker_host_fallback_total")
                outcome = execute_processing_pipeline(self.host_executor, doc)
            else:
                outcome = self._assemble_row(evals, row, doc)
                if outcome is None:  # passed every step of this phase
                    if not last:
                        survivors.append(doc)
                        continue
                    if self.host_steps:
                        outcome = execute_processing_pipeline(
                            self.host_suffix_executor, doc
                        )
                    else:
                        outcome = ProcessingOutcome.success(doc)
            if outcome is not None:  # hard error -> no outcome (reference quirk)
                outcomes.append(outcome)
        return outcomes, survivors

    def phase_previewable(self, phase: int) -> bool:
        """True when every step of ``phase`` carries a full batch verdict
        mask, so the phase's survivor count is derivable from device stats
        alone (:meth:`preview_phase_survivors`).

        Config-derived only — every lockstep host answers identically for
        the same config, which is what lets the speculative phase barrier
        (parallel/multihost.py) treat previewability as shared state
        without exchanging it.  Badwords is out (per-row host regex +
        keep-fraction RNG, ``passed=None``); C4 is out because its rewrite
        re-routes survivors by post-rewrite length (and a non-final C4
        phase is impossible anyway — the constructor collapses those)."""
        return all(
            self.device_steps[i].type in _PREVIEWABLE_STEPS
            for i in self.phases[phase]
        )

    def preview_phase_survivors(
        self,
        batch: PackedBatch,
        device_stats: Dict[str, jax.Array],
        phase: int,
    ) -> int:
        """Exact survivor count for one resolved round of a previewable
        non-final phase — the batch-vectorized half of
        :meth:`assemble_phase` without any per-row work or side effects.

        The speculative phase barrier posts these counts piggybacked on
        the tail verdict exchange, so the next phase's round schedule can
        be negotiated in the same allgather the tail flags ride.  A row
        survives iff it overflowed no kernel table and passed every step
        of the phase — identical to the rows ``assemble_phase`` appends to
        ``survivors``, which the barrier asserts after assembly.  The
        stats tree must already be host-side (``_timed_stats`` output);
        evaluating it here and again in ``assemble_phase`` is safe because
        the step finalizers are pure over the stats arrays."""
        assert self.phase_previewable(phase), (
            "preview_phase_survivors called on a non-previewable phase — "
            "the barrier must gate on phase_previewable or hosts desync "
            "on the exchange vector width"
        )
        stats = jax.device_get(device_stats)
        n_rows = len(batch.docs)
        mask = np.ones(n_rows, dtype=bool)
        for i in self.phases[phase]:
            ev = self._eval_step(self.device_steps[i], i, stats)
            if ev.overflow is not None:
                mask &= ~ev.overflow[:n_rows]
            mask &= ev.passed[:n_rows]
        return int(mask.sum())

    def assemble_batch(
        self, batch: PackedBatch, device_stats: Dict[str, jax.Array]
    ) -> List[ProcessingOutcome]:
        """Single-phase form (the multi-host lockstep path): every device
        step evaluated from one program's stats."""
        assert len(self.phases) == 1, "assemble_batch requires a single-phase pipeline"
        outcomes, _ = self.assemble_phase(batch, device_stats, 0)
        return outcomes

    def process_batch(self, batch: PackedBatch) -> List[ProcessingOutcome]:
        assert len(self.phases) == 1
        return self.assemble_batch(batch, self.dispatch_batch(batch))

    def _timed_pack(
        self, docs: List[TextDocument], batch_size: int, max_len: int
    ) -> PackedBatch:
        """``pack_documents`` with the pack-stage wall clock attached.

        Runs once per batch on the pack pool's hot path — the clock comes
        from the module-scope import, not a per-call ``import time``."""
        if TELEMETRY.enabled:
            TELEMETRY.mark("pack", (d.id for d in docs))
        t0 = _time_mod.perf_counter()
        try:
            with TRACER.span(
                "pack", {"rows": len(docs), "bucket": max_len}
            ):
                return pack_documents(
                    docs, batch_size=batch_size, max_len=max_len
                )
        finally:
            METRICS.inc("stage_pack_seconds", _time_mod.perf_counter() - t0)

    def _pack_pool(self):
        # One process-wide pool shared with the multi-host lockstep window
        # (utils/overlap.py) — pack work releases the GIL, and every caller
        # resolves its own futures FIFO, so sharing changes no ordering.
        if self._pack_pool_obj is None:
            from ..utils.overlap import shared_pack_pool

            self._pack_pool_obj = shared_pack_pool(
                max(1, self._overlap.pack_workers)
            )
        return self._pack_pool_obj

    def _packed_source(self, docs_iter, host_tail_max, route_fn, overlapped):
        """The packer stage for one phase.

        Serial: the grouping generator inline, packing on the caller's
        thread.  Overlapped: the generator runs ahead on a prefetch thread
        and each ``pack`` is a thread-pool future (the encode/scatter work
        releases the GIL), so grouping+packing of batch i+1.. overlap the
        caller's dispatch/assembly of batch i.  Either way items arrive in
        the generator's order — the overlap changes timing, never sequence.

        Returns ``(iterable of (batch_or_future, fallback_docs), close_fn)``.
        """
        kwargs = dict(
            geometry=self.geometry,
            host_tail_max=host_tail_max,
            route_fn=route_fn,
            overflow_flush=max(1, self._overlap.overflow_flush),
        )
        if not overlapped:
            gen = iter_packed_batches(docs_iter, pack_fn=self._timed_pack, **kwargs)
            return gen, lambda: None
        pool = self._pack_pool()

        def submit(docs, batch_size, max_len):
            return pool.submit(
                self._timed_pack, docs, batch_size=batch_size, max_len=max_len
            )

        gen = iter_packed_batches(docs_iter, pack_fn=submit, **kwargs)
        pf = prefetch_iter(
            gen, depth=max(2, self._overlap.pack_workers + 1), block=1
        )
        return pf, pf.close

    def _dispatch_window(self, batch: PackedBatch, phase: int, no_overlap: bool):
        """Breaker-gated async dispatch for the in-flight window.

        Returns the in-flight stats tree, ``None`` on a retryable launch
        failure (the drain's ladder re-dispatches from scratch), or the
        ``_BREAKER_OPEN`` sentinel when the breaker refused the request.
        Deterministic errors propagate — the ladder only absorbs transient
        device faults.
        """
        if not self._breaker.allow_request():
            return _BREAKER_OPEN
        try:
            stats = self.dispatch_batch(batch, phase)
            if no_overlap:
                if WATCHDOG.enabled:
                    WATCHDOG.wait_device_ready(
                        "device_fetch", jax.tree_util.tree_leaves(stats)
                    )
                jax.block_until_ready(stats)
            return stats
        except Exception as e:  # noqa: BLE001
            if self._retry.classify(e) != "retryable":
                raise
            WATCHDOG.escalated(e)
            # Failed launch: hand the batch to the ladder with nothing in
            # flight (its first retry attempt re-dispatches).
            logger.warning("Device dispatch failed (phase %d): %s", phase, e)
            return None

    def process_chunk(self, docs: List[TextDocument]) -> Iterator[ProcessingOutcome]:
        """Run one chunk of documents through every phase, repacking the
        survivors between phases (device-side short-circuit).

        Batches ride a FIFO in-flight window ``pipeline_depth`` deep: batch
        i's host assembly/post-passes run while batches i+1..i+K compute on
        the device.  Outcomes are emitted in the strict FIFO order of the
        packer's output items at EVERY depth — the window moves the waits,
        never the sequence — so serial (depth 1, or --no-overlap) and
        overlapped runs produce byte-identical outcome streams by
        construction.
        """
        import os
        import time

        debug = os.environ.get("TEXTBLAST_PHASE_DEBUG") == "1"
        no_overlap = os.environ.get("TEXTBLAST_NO_OVERLAP") == "1"
        overlapped = (
            self._overlap.enabled and not no_overlap and self.mesh is None
        )
        depth = max(1, self._overlap.pipeline_depth) if overlapped else 1
        current: List[TextDocument] = docs
        if self._route_dict_scripts or self.wire_u16:
            from ..utils.cjk import has_astral, has_dict_script

            route_dict = self._route_dict_scripts
            route_astral = self.wire_u16
            # Routing decisions recorded at route_fn time (the packer calls
            # route_fn once per non-over-length doc), so the fallback
            # classification below reuses them instead of re-running the
            # has_dict_script/has_astral scans on every fallback doc.
            routed: Dict[int, bool] = {}

            def _host_routed(doc: TextDocument) -> bool:
                decision = (route_dict and has_dict_script(doc.content)) or (
                    route_astral and has_astral(doc.content)
                )
                routed[id(doc)] = decision
                return decision

        else:
            _host_routed = None
            routed = {}
        for phase in range(len(self.phases)):
            t0 = time.perf_counter()
            timing = {"dispatch": 0.0, "drain": 0.0}
            n_in, n_batches = len(current), 0
            survivors: List[TextDocument] = []
            # FIFO window entries: ("batch", (batch, stats)) dispatched and
            # awaiting assembly, or ("host", docs) fallback groups awaiting
            # their host-oracle pass.  ``inflight`` counts batch entries only.
            window: deque = deque()
            inflight = 0
            # Host-oracle threshold for leftover groups: the first phase's
            # program is cheap (it exists to kill docs early), so the device
            # wins even for small groups; later phases carry the expensive
            # kernels and the (bit-exact) host oracle wins below ~half a
            # batch.  Mesh runs keep every doc on device (shard accounting),
            # and TEXTBLAST_HOST_TAILS=off pins tails to the device too (the
            # parity suites use it so device kernels decide every doc).
            if self.mesh is None and os.environ.get("TEXTBLAST_HOST_TAILS") != "off":
                # Per-bucket: the cutoff tracks each bucket's own row budget
                # (with a uniform geometry this is the historical scalar).
                div = 16 if phase == 0 else 2
                host_tail_max = {
                    b: self.geometry.batch_for(b) // div
                    for b in self.geometry.buckets
                }
            else:
                host_tail_max = 0
            over_length = self.buckets[-1] - PACK_MARGIN
            # Phase 0 only: later phases' survivors already passed it.
            route = _host_routed if phase == 0 else None

            def _process_fallback(fallback_docs):
                outs = []
                for doc in fallback_docs:
                    # Over-length and routed (dict-script/astral) docs are
                    # genuine fallbacks; leftover tail groups are deliberate
                    # routing — count them apart so the bench's honesty
                    # metric stays meaningful.
                    if len(doc.content) > over_length or (
                        route is not None and routed.get(id(doc), False)
                    ):
                        METRICS.inc("worker_host_fallback_total")
                    else:
                        METRICS.inc("worker_host_tail_total")
                    outcome = execute_processing_pipeline(self.host_executor, doc)
                    if outcome is not None:
                        outs.append(outcome)
                return outs

            def _drain_front():
                nonlocal inflight
                kind, payload = window.popleft()
                ta = time.perf_counter()
                with TRACER.span("post", {"kind": kind, "phase": phase}):
                    if kind == "batch":
                        inflight -= 1
                        METRICS.set("inflight_batches", inflight)
                        TRACER.counter("inflight_batches", inflight)
                        b, stats = payload
                        outcomes, alive = self._execute_packed(b, phase, stats)
                        survivors.extend(alive)
                    else:
                        outcomes = _process_fallback(payload)
                dt = time.perf_counter() - ta
                timing["drain"] += dt
                METRICS.inc("stage_post_seconds", dt)
                return outcomes

            src, src_close = self._packed_source(
                iter(current),
                host_tail_max=host_tail_max,
                route_fn=route,
                overlapped=overlapped,
            )
            try:
                for item, fallback in src:
                    if item is not None:
                        # Overlapped items are pack futures; resolving here
                        # keeps FIFO order (futures complete out of order,
                        # but we only ever wait on the oldest).
                        if hasattr(item, "result"):
                            if WATCHDOG.enabled:
                                WATCHDOG.wait("pack_wait", item.done)
                            batch = item.result()
                        else:
                            batch = item
                        if overlapped:
                            METRICS.set("queue_depth_pack", src.qsize())
                            TRACER.counter("queue_depth_pack", src.qsize())
                        n_batches += 1
                        td = time.perf_counter()
                        with TRACER.span(
                            "dispatch",
                            {"bucket": batch.max_len,
                             "rows": batch.batch_size, "phase": phase},
                        ):
                            stats = self._dispatch_window(
                                batch, phase, no_overlap
                            )
                        dt = time.perf_counter() - td
                        timing["dispatch"] += dt
                        METRICS.inc("stage_dispatch_seconds", dt)
                        window.append(("batch", (batch, stats)))
                        inflight += 1
                        METRICS.set("inflight_batches", inflight)
                        TRACER.counter("inflight_batches", inflight)
                    if fallback:
                        window.append(("host", fallback))
                    # Host groups at the front never block on the device —
                    # draining them early IS the read/post overlap; batch
                    # entries drain once more than ``depth`` are in flight.
                    while window and (
                        window[0][0] == "host" or inflight > depth
                    ):
                        yield from _drain_front()
                while window:
                    yield from _drain_front()
            finally:
                src_close()
                METRICS.set("inflight_batches", 0)
            if debug:
                print(
                    f"[phase {phase}] docs={n_in} batches={n_batches} "
                    f"survivors={len(survivors)} depth={depth} "
                    f"{time.perf_counter()-t0:.2f}s "
                    f"(dispatch {timing['dispatch']:.2f}s "
                    f"drain {timing['drain']:.2f}s)",
                    flush=True,
                )
            current = survivors
            if not current:
                break

    _BADWORDS_PASS_STAMPS = (("c4_badwords_filter_status", "passed"),)

    def _assemble_row(
        self, evals, row: int, doc: TextDocument
    ) -> Optional[ProcessingOutcome]:
        """Walk one row through this phase's steps; ``None`` means it passed
        them all (the caller decides success vs next-phase survival)."""
        for step, ev in evals:
            if ev.badwords_matches is not None:
                # Fast path for non-matching docs of any device-compiled
                # language (the common case — no host work at all); matches
                # and uncompiled languages go through decide().
                doc_lang = doc.metadata.get("language", ev.badwords_default_language)
                m = ev.badwords_matches.get(doc_lang)
                if (
                    m is not None
                    and not m[row]
                    and not ev.badwords_fold_hazard[doc_lang][row]
                ):
                    for k, v in self._BADWORDS_PASS_STAMPS:
                        doc.metadata[k] = v
                    continue
            elif ev.passed[row] and ev.pass_stamps is not None:
                for k, v in ev.pass_stamps:
                    doc.metadata[k] = v
                if ev.c4_line_keep is not None and not ev.c4_rewrite_identity[row]:
                    # Identity rewrites (every line kept, already trimmed —
                    # the common clean-text case) skip the per-doc Python
                    # string rebuild; the device proved content equality.
                    self._rewrite_c4(
                        doc, step, ev.c4_line_keep[row][: ev.c4_n_lines[row]]
                    )
                continue
            decision = ev.decide(row, doc)
            for k, v in decision.stamps:
                doc.metadata[k] = v
            if ev.c4_line_keep is not None and decision.extra is not None:
                if decision.extra.get("rewrite"):
                    self._rewrite_c4(doc, step, decision.extra["keep_mask"])
            if not decision.passed:
                # Funnel attribution: the device-path twin of the host seam
                # in orchestration.execute_processing_pipeline — together
                # the only two creators of FILTERED outcomes.
                METRICS.inc(FILTER_DROP_PREFIX + step.type)
                return ProcessingOutcome.filtered(doc, decision.reason)
        return None


#: Step types whose batch eval always yields a full per-row verdict mask
#: (``_StepEval.passed`` is an array, never None) — the set
#: ``phase_previewable`` checks.  Badwords decides per-row on the host;
#: C4 rewrites survivor content.
_PREVIEWABLE_STEPS = frozenset(
    {
        "LanguageDetectionFilter",
        "GopherRepetitionFilter",
        "GopherQualityFilter",
        "FineWebQualityFilter",
    }
)

_EVALS = {
    "LanguageDetectionFilter": CompiledPipeline._eval_langid,
    "GopherRepetitionFilter": CompiledPipeline._eval_gopher_rep,
    "GopherQualityFilter": CompiledPipeline._eval_gopher_quality,
    "C4QualityFilter": CompiledPipeline._eval_c4,
    "C4BadWordsFilter": CompiledPipeline._eval_badwords,
    "FineWebQualityFilter": CompiledPipeline._eval_fineweb,
}


def process_documents_device(
    config: PipelineConfig,
    docs: Iterable[Union[TextDocument, PipelineError]],
    device_batch: Optional[int] = None,
    on_read_error=None,
    buckets=DEFAULT_BUCKETS,
    mesh=None,
    pipeline: Optional[CompiledPipeline] = None,
    geometry: Optional[DeviceGeometry] = None,
    warmup: Optional[bool] = None,
) -> Iterator[ProcessingOutcome]:
    """Device-backed processing loop: packs the stream into bucketed batches,
    runs the compiled pipeline, assembles outcomes in input order per batch.

    Outcome **ordering** is deterministic but not input order: documents are
    grouped by length bucket and emitted in the packer's strict FIFO item
    order, with up to ``overlap.pipeline_depth`` batches in flight (assembly
    of batch k overlaps device compute of batches k+1..k+K).  The order is
    identical at every depth — serial and overlapped runs produce the same
    outcome stream.  Output row order is NOT contractual — the reference has
    none either (its results queue returns worker-completion order,
    producer_logic.rs:141-176); tests compare outputs as id-keyed sets.

    Pass a prebuilt ``pipeline`` to reuse its compiled programs across
    multiple streams (the checkpointed runner processes one chunk per call)."""
    if pipeline is None:
        pipeline = CompiledPipeline(
            config,
            buckets=buckets,
            batch_size=device_batch,
            mesh=mesh,
            geometry=geometry,
        )
        # Remote/TPU compiles are the dominant cold-start cost and run
        # serially if left to first dispatch; compile everything concurrently
        # up front — a populated AOT executable cache makes this a sub-second
        # load instead of a 15-29 s compile.
        maybe_warmup(pipeline, warmup)

    if pipeline.fully_host or not pipeline.device_steps:
        if pipeline.device_steps and pipeline.fully_host:
            logger.warning(
                "Pipeline has un-kerneled steps before device steps; "
                "running fully on host."
            )
        from ..orchestration import process_documents_host

        yield from process_documents_host(
            pipeline.host_executor, docs, on_read_error=on_read_error
        )
        return

    def doc_stream():
        for item in docs:
            if isinstance(item, PipelineError):
                logger.warning("Error reading document for task. Skipping. %s", item)
                if on_read_error is not None:
                    on_read_error(item)
                continue
            yield item

    # Macro-chunks through the phased pipeline: each chunk runs phase by
    # phase with survivors repacked between phases, and one batch in flight
    # per phase (assembly overlaps device compute).  Larger chunks amortize
    # the partial batches each phase flushes at its end.
    from itertools import islice

    chunk_size = max(4 * pipeline.batch_size, 4096)
    stream = doc_stream()
    while True:
        chunk = list(islice(stream, chunk_size))
        if not chunk:
            break
        yield from pipeline.process_chunk(chunk)
